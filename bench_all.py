"""Multi-config benchmark suite (BASELINE.json tracked configs).

Prints one JSON line per config. `bench.py` stays the driver's headline
single-line contract; this script covers the wider matrix: 125M ZeRO-0,
350M ZeRO-2/3, decode latency.
"""
from __future__ import annotations

import functools
import json
import os
import tempfile
import time

import numpy as np

# tp_decode_bench needs the virtual 8-device CPU mesh (same forcing as
# tests/conftest.py); the flag only affects the HOST platform backend,
# so it is a no-op on real TPU runs.  Must land before the first jax
# backend use — every bench imports jax lazily, so module top is safe.
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()


def _bench_artifact_dir() -> str:
    """Where serving benches drop their merged fleet trace artifacts
    (override with DSTPU_BENCH_ARTIFACTS)."""
    d = os.environ.get("DSTPU_BENCH_ARTIFACTS") or os.path.join(
        tempfile.gettempdir(), f"dstpu_bench_{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _obs_block(art_dir: str) -> dict:
    """Observability block the serving benches share: tracing +
    request waterfalls + metrics + the host/device overlap profiler."""
    return {"tracing": {"enabled": True, "output_dir": art_dir},
            "request_tracing": {"enabled": True},
            "metrics": {"enabled": True},
            "overlap": {"enabled": True}}


def _overlap_columns(kind: str = "serving") -> dict:
    """Host/device overlap summary for the bench JSON line, read from
    the overlap profiler's registry histograms."""
    from deepspeed_tpu.observability import get_registry
    reg = get_registry()
    h_plan = reg.histogram(f"dstpu_{kind}_host_plan_seconds")
    h_wait = reg.histogram(f"dstpu_{kind}_device_wait_seconds")
    h_frac = reg.histogram(f"dstpu_{kind}_overlap_frac_dist")
    return {"host_plan_ms_p50": round(h_plan.quantile(0.5) * 1e3, 3),
            "device_wait_ms_p50": round(h_wait.quantile(0.5) * 1e3, 3),
            "overlap_frac_p50": round(h_frac.quantile(0.5), 4),
            "iterations": h_frac.count}


def train_bench(size: str, micro: int, seq: int, zero_stage: int,
                iters: int = 10, **cfg_kw):
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config
    from deepspeed_tpu.profiling.flops_profiler import chip_peak_flops

    cfg = gpt2_config(size, max_seq_len=seq, remat="full",
                      attn_impl="flash", loss_chunk=256, **cfg_kw)
    model = TransformerLM(cfg)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": zero_stage},
        "gradient_clipping": 1.0, "steps_per_print": 0})
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(0, cfg.vocab_size, (micro, seq),
                                     dtype=np.int32)}
    m = engine.train_step(batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        m = engine.train_step(batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    tok = micro * seq * iters / dt
    n = engine.num_parameters()
    fpt = 6 * n + 12 * cfg.num_layers * cfg.d_model * seq
    mfu = tok * fpt / chip_peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": f"gpt2_{size}_zero{zero_stage}_tokens_per_sec_per_chip",
        "value": round(tok, 1), "unit": "tokens/s",
        "mfu": round(mfu, 4), "vs_baseline": round(mfu / 0.45, 4)}),
        flush=True)


def train_3d_bench(size: str = "125m", seq: int = 128,
                   micro_batches: int = 4, micro: int = 2, iters: int = 3,
                   shapes=((1, 1, 8), (2, 2, 2), (4, 2, 1)), **cfg_kw):
    """3D-parallel train sweep over (pp, tp, dp) mesh shapes on one chip
    budget (docs/training_perf.md "3D parallelism"). Per shape:

      - tokens/s/chip — the comparable throughput number;
      - measured bubble fraction — the pipeline engine's two-point slope
        fit over the compiled schedule (pp >= 2 only; the 1F1B number
        should sit well under gpipe's (S-1)/(M+S-1));
      - per-chip param+optimizer resident bytes — summed from the placed
        arrays' actual shard shapes, i.e. what the (pipe, model) param
        split x ZeRO data sharding really left on one chip;
      - stage-boundary ppermute volume per step per chip — analytic:
        every schedule tick rotates one [micro_local, seq, d_model]
        activation (1F1B also rotates the cotangent), so
        volume = transfers/step x micro_local x seq x d_model x 2B.
    """
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    ndev = jax.device_count()

    def _shard_bytes(tree):
        tot = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            sh = getattr(leaf, "sharding", None)
            if sh is None or not hasattr(leaf, "shape"):
                continue
            tot += int(np.prod(sh.shard_shape(leaf.shape))) * \
                leaf.dtype.itemsize
        return tot

    for pp, tp, dp in shapes:
        name = f"train3d_{size}_pp{pp}_tp{tp}_dp{dp}"
        if pp * tp * dp != ndev:
            print(json.dumps({
                "metric": name, "skipped":
                f"shape needs {pp * tp * dp} devices, have {ndev}"}),
                flush=True)
            continue
        cfg = gpt2_config(size, max_seq_len=seq, **cfg_kw)
        model = TransformerLM(cfg)
        m_count = micro_batches if pp > 1 else 1
        tb = micro * m_count * dp
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_batch_size": tb,
            "gradient_accumulation_steps": m_count,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 6e-4, "weight_decay": 0.1}},
            "zero_optimization": {"stage": 1 if dp > 1 else 0},
            "mesh": {"pipe": pp, "model": tp, "data": dp},
            "gradient_clipping": 1.0, "steps_per_print": 0},
            rng=jax.random.PRNGKey(0))
        rs = np.random.RandomState(0)
        batch = {"input_ids": rs.randint(0, cfg.vocab_size, (tb, seq),
                                         dtype=np.int32)}
        mt = engine.train_step(batch)
        float(mt["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            mt = engine.train_step(batch)
        float(mt["loss"])
        dt = (time.perf_counter() - t0) / iters
        row = {"metric": name,
               "value": round(tb * seq / dt / ndev, 1),
               "unit": "tokens/s/chip",
               "loss": round(float(mt["loss"]), 4),
               "per_chip_state_bytes":
               _shard_bytes(engine.state.get("params"))
               + _shard_bytes(engine.state.get("opt"))}
        if pp > 1:
            probe = engine.measure_bubble_fraction(repeats=1)
            row["bubble_frac"] = round(probe["bubble_frac"], 4)
            row["schedule"] = probe["schedule"]
            act_bytes = np.dtype(engine.compute_dtype).itemsize
            transfers = (4 * (m_count + pp - 1)
                         if engine.schedule == "1f1b"
                         else 2 * (m_count + pp - 1))
            row["ppermute_bytes_per_step"] = int(
                transfers * micro * seq * cfg.d_model * act_bytes)
        print(json.dumps(row), flush=True)


def decode_bench(size: str = "125m", batch: int = 4, prompt: int = 64,
                 new: int = 64):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    cfg = gpt2_config(size, max_seq_len=prompt + new, attn_impl="flash",
                      dtype=jnp.bfloat16)
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "bfloat16", "max_out_tokens": prompt + new})
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch, prompt), dtype=np.int32)
    for _ in range(3):
        eng.generate(ids, max_new_tokens=new, temperature=0.0)
    stats = eng.latency_stats()
    print(json.dumps({
        "metric": f"gpt2_{size}_decode_p50_ms_per_token",
        "value": round(stats["p50_ms"], 3), "unit": "ms",
        "p90_ms": round(stats["p90_ms"], 3),
        # decode-only since PR 4 (prefill now reported as TTFT instead
        # of being amortized into the per-token number)
        "ttft_p50_ms": round(stats["ttft_p50_ms"], 3),
        "decode_tokens_per_sec": round(stats["tokens_per_sec"], 1)}),
        flush=True)


def serving_decode_bench(size: str = "125m", slots: int = 8,
                         prompt: int = 128, new: int = 128):
    """Continuous-batching serving throughput (inference/serving/):
    `slots` concurrent streams through the single-trace batched decode
    step + paged KV pool, vs the single-stream decode baseline the
    `gpt2_*_decode_p50_ms_per_token` metric tracks."""
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    cfg = gpt2_config(size, max_seq_len=prompt + new, attn_impl="flash",
                      dtype=jnp.bfloat16)
    block = 32
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "bfloat16", "max_out_tokens": prompt + new,
        "temperature": 0.0,
        "serving": {"enabled": True, "kv_block_size": block,
                    "num_kv_blocks":
                        slots * ((prompt + new) // block + 1) + 8,
                    "max_batch_slots": slots}})
    srv = eng.serving_engine()
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size, (prompt,)).tolist()
               for _ in range(2 * slots)]
    # warm the compiled programs (prefill bucket + decode step)
    srv.submit(prompts[0], max_new_tokens=4)
    srv.run(max_steps=50)
    itl = srv._m_itl            # decode-iteration wall-time histogram
    warm_sum, warm_n = itl.sum, itl.count   # exclude warmup+compile iters
    t0 = time.perf_counter()
    reqs = [srv.submit(p, max_new_tokens=new) for p in prompts]
    srv.run(max_steps=100 * len(prompts) * new)
    dt = time.perf_counter() - t0
    toks = sum(len(r.output) for r in reqs)
    iter_ms = ((itl.sum - warm_sum) / max(itl.count - warm_n, 1)) * 1e3
    n_req = len(prompts) + 1                 # incl. the warmup request
    lc = srv.lifecycle_counts
    print(json.dumps({
        "metric": "decode_batched_tokens_per_sec",
        "value": round(toks / dt, 1), "unit": "tokens/s",
        "slots": slots, "requests": len(prompts),
        "prompt": prompt, "new": new,
        "decode_iter_mean_ms": round(iter_ms, 3),
        "preemptions": srv.scheduler.preemption_count,
        # lifecycle rates (docs/serving.md "Failure handling &
        # overload") — the acceptance instrument for SLO work: a bench
        # run that sheds/expires/quarantines is overloaded or broken,
        # and these make it visible next to the throughput number
        "shed_rate": round(lc["shed"] / n_req, 3),
        "timeout_rate": round(lc["timed_out"] / n_req, 3),
        "quarantine_rate": round(lc["quarantined"] / n_req, 3),
        "cancelled": lc["cancelled"], "failed": lc["failed"],
        "decode_builds": srv.decode_builds}), flush=True)


def tp_decode_bench(slots: int = 8, prompt: int = 24, new: int = 32):
    """Tensor-parallel paged serving over the (data, model) mesh
    (docs/serving.md "Tensor-parallel serving"), swept over model ∈
    {1, 2, 4} with data = 8 / model on the forced 8-device CPU mesh —
    the MULTICHIP_* trajectory's serving row.  Reports per mesh shape:
    end-to-end serving tokens/s, the measured PER-CHIP KV pool bytes
    (must fall as 1/model), and the per-token collective volume the
    model axis costs (bytes psummed per layer x layers; zero at
    model=1).  CPU wall-times only order WITHIN this sweep — the
    numbers that transfer to TPU are the bytes columns."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    if len(jax.devices()) < 8:
        print(json.dumps({"metric": "serving_tp_tokens_per_sec",
                          "skipped": f"{len(jax.devices())} devices"}),
              flush=True)
        return
    # CPU-sized toy (the tier-1 test model): the sweep is about mesh
    # SHAPES, not model scale
    cfg = gpt2_config("125m", num_layers=4, d_model=64, num_heads=4,
                      max_seq_len=prompt + new + 8, vocab_size=256,
                      dtype=jnp.float32)
    params = TransformerLM(cfg).init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, cfg.vocab_size, (prompt,)).tolist()
               for _ in range(2 * slots)]
    for model_size in (1, 2, 4):
        eng = ds.init_inference(TransformerLM(cfg), params=params, config={
            "dtype": "float32", "max_out_tokens": prompt + new + 8,
            "temperature": 0.0, "replace_with_kernel_inject": False,
            "serving": {"enabled": True, "kv_block_size": 8,
                        "num_kv_blocks": slots * ((prompt + new) // 8 + 1)
                        + 8,
                        "max_batch_slots": slots,
                        "prefill_chunk_tokens": 32,
                        "mesh": {"data": 8 // model_size,
                                 "model": model_size}}})
        srv = eng.serving_engine()
        srv.submit(prompts[0], max_new_tokens=2)    # compile off-clock
        srv.run(max_steps=50)
        t0 = time.perf_counter()
        reqs = [srv.submit(p, max_new_tokens=new) for p in prompts]
        srv.run(max_steps=100 * len(prompts) * new)
        dt = time.perf_counter() - t0
        toks = sum(len(r.output) for r in reqs)
        psum_b = srv.tp_psum_bytes_per_token_layer
        print(json.dumps({
            "metric": "serving_tp_tokens_per_sec",
            "value": round(toks / dt, 1), "unit": "tokens/s",
            "mesh": {"data": 8 // model_size, "model": model_size},
            "slots": slots,
            "kv_pool_bytes_per_chip": srv.kv_pool_bytes,
            "psum_bytes_per_token_layer": psum_b,
            "psum_bytes_per_token": psum_b * cfg.num_layers,
            "decode_builds": srv.decode_builds}), flush=True)


def prefix_cache_bench(size: str = "125m", slots: int = 8,
                       n_req: int = 8, system: int = 384, user: int = 32,
                       new: int = 32):
    """Shared-prefix serving (the 'millions of users behind one system
    prompt' shape): ``n_req`` requests share a ``system``-token prompt
    and differ only in a short user tail.  Round 1 (cold) prefills the
    shared prefix from scratch; round 2 (warm) hits the committed
    blocks parked in the allocator's LRU — warm TTFT must sit
    measurably below cold, and the hit-rate counter proves WHY."""
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    total = system + user + new
    cfg = gpt2_config(size, max_seq_len=total, attn_impl="flash",
                      dtype=jnp.bfloat16)
    block = 32
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "bfloat16", "max_out_tokens": total, "temperature": 0.0,
        "serving": {"enabled": True, "kv_block_size": block,
                    # concurrent footprint + headroom so the shared
                    # blocks survive the LRU between rounds
                    "num_kv_blocks":
                        slots * ((total + 1) // block + 2)
                        + system // block + 8,
                    "max_batch_slots": slots,
                    "prefill_chunk_tokens": 256}})
    srv = eng.serving_engine()
    rs = np.random.RandomState(0)
    shared = rs.randint(0, cfg.vocab_size, (system,)).tolist()
    # compile the mixed program off the clock (distinct prompt so its
    # blocks neither pollute the cache rounds nor hit them)
    srv.submit(rs.randint(0, cfg.vocab_size, (8,)).tolist(),
               max_new_tokens=2)
    srv.run(max_steps=500)

    def one_round():
        reqs = [srv.submit(
            shared + rs.randint(0, cfg.vocab_size, (user,)).tolist(),
            max_new_tokens=new) for _ in range(n_req)]
        srv.run(max_steps=200 * n_req * new)
        ttfts = [r.first_token_time - r.submit_time for r in reqs]
        hits = sum(r.cache_hit_tokens for r in reqs)
        return float(np.percentile(ttfts, 50) * 1e3), hits

    cold_p50, cold_hits = one_round()
    warm_p50, warm_hits = one_round()
    prompt_tokens = n_req * (system + user)
    print(json.dumps({
        "metric": "serving_prefix_cache_warm_ttft_p50_ms",
        "value": round(warm_p50, 2), "unit": "ms",
        "ttft_p50_cold_ms": round(cold_p50, 2),
        "warm_vs_cold": round(warm_p50 / max(cold_p50, 1e-9), 3),
        "prefix_cache_hit_rate": round(warm_hits / prompt_tokens, 3),
        "cold_round_hit_rate": round(cold_hits / prompt_tokens, 3),
        "shared_tokens": system, "requests": n_req,
        "evictions": srv.allocator.evictions_total,
        "shed_rate": round(srv.lifecycle_counts["shed"] / (2 * n_req + 1),
                           3),
        "timeout_rate": round(
            srv.lifecycle_counts["timed_out"] / (2 * n_req + 1), 3),
        "quarantine_rate": round(
            srv.lifecycle_counts["quarantined"] / (2 * n_req + 1), 3),
        "decode_builds": srv.decode_builds}), flush=True)


def tiered_prefix_cache_bench(size: str = "125m", slots: int = 8,
                              n_req: int = 8, system: int = 384,
                              user: int = 32, new: int = 32,
                              block: int = 32,
                              dram_budget: int = 1 << 28, **cfg_kw):
    """Tiered prefix cache under memory pressure: the same shared-prefix
    shape as ``prefix_cache_bench``, but after the HBM-warm round a
    flood of distinct filler prompts cycles the paged pool's LRU so the
    shared chain is *demoted* to the host tier (int8 at rest).  Round 3
    then hits host, holds in PROMOTING while blocks scatter back, and
    its TTFT answers the tentpole question: is promote-from-DRAM
    measurably cheaper than recompute?  Target: host-warm p50 < 0.5x
    cold p50."""
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    total = system + user + new
    cfg_kw.setdefault("dtype", jnp.bfloat16)
    cfg_kw.setdefault("attn_impl", "flash")
    cfg = gpt2_config(size, max_seq_len=total, **cfg_kw)
    # same headroom math as prefix_cache_bench: shared blocks survive
    # rounds 1->2 in the LRU; the filler flood is sized off this pool
    # so eviction pressure is explicit, not accidental
    nb = (slots * ((total + 1) // block + 2) + system // block + 8)
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "bfloat16" if cfg_kw["dtype"] == jnp.bfloat16
                 else "float32",
        "max_out_tokens": total, "temperature": 0.0,
        "serving": {"enabled": True, "kv_block_size": block,
                    "num_kv_blocks": nb,
                    "max_batch_slots": slots,
                    "prefill_chunk_tokens": 256,
                    # int8 pool => byte-exact at rest, so the host
                    # round trip costs zero extra fidelity
                    "kv_cache_bits": 8,
                    "host_cache": {"enabled": True,
                                   "dram_budget_bytes": dram_budget}}})
    srv = eng.serving_engine()
    rs = np.random.RandomState(0)
    shared = rs.randint(0, cfg.vocab_size, (system,)).tolist()
    srv.submit(rs.randint(0, cfg.vocab_size, (8,)).tolist(),
               max_new_tokens=2)
    srv.run(max_steps=500)

    def one_round():
        reqs = [srv.submit(
            shared + rs.randint(0, cfg.vocab_size, (user,)).tolist(),
            max_new_tokens=new) for _ in range(n_req)]
        srv.run(max_steps=400 * n_req * new)
        ttfts = [r.first_token_time - r.submit_time for r in reqs]
        hbm = sum(r.cache_hit_tokens for r in reqs)
        return float(np.percentile(ttfts, 50) * 1e3), hbm

    cold_p50, _ = one_round()
    hbm_p50, hbm_hits = one_round()

    # flood: enough distinct `system`-length prompts to cycle every LRU
    # slot at least twice -> the shared chain demotes to the host tier
    fillers = 2 * (nb // max(1, system // block)) + slots
    for _ in range(fillers):
        srv.submit(rs.randint(0, cfg.vocab_size, (system,)).tolist(),
                   max_new_tokens=2)
        srv.run(max_steps=40 * system)
    spills = srv.host_cache.spills_total

    host_tok0 = srv.allocator.host_hit_tokens_total
    promo0, psec0 = srv.host_counts["promoted_blocks"], srv.promote_seconds
    host_p50, host_round_hits = one_round()
    promoted = srv.host_counts["promoted_blocks"] - promo0
    psec = srv.promote_seconds - psec0
    host_hit_tok = srv.allocator.host_hit_tokens_total - host_tok0

    prompt_tokens = n_req * (system + user)
    print(json.dumps({
        "metric": "serving_tiered_prefix_cache_host_warm_ttft_p50_ms",
        "value": round(host_p50, 2), "unit": "ms",
        "ttft_p50_cold_ms": round(cold_p50, 2),
        "ttft_p50_hbm_warm_ms": round(hbm_p50, 2),
        "host_warm_vs_cold": round(host_p50 / max(cold_p50, 1e-9), 3),
        "target_host_warm_vs_cold": 0.5,
        "hbm_hit_rate": round(hbm_hits / prompt_tokens, 3),
        "host_hit_rate": round(host_hit_tok / prompt_tokens, 3),
        # total hit tokens in round 3 (HBM residue + host-claimed)
        "host_round_total_hit_rate": round(
            host_round_hits / prompt_tokens, 3),
        "tier_hits": dict(srv.host_cache.hits_total),
        "spills": spills, "filler_requests": fillers,
        "promoted_blocks": promoted,
        "promote_mb_s": round(
            promoted * srv.host_cache.entry_nbytes / max(psec, 1e-9)
            / 1e6, 2),
        "host_entry_bytes": srv.host_cache.entry_nbytes,
        "promote_failures": srv.host_counts["promote_failures"],
        "spill_failures": srv.host_counts["spill_failures"],
        "decode_builds": srv.decode_builds}), flush=True)


def paged_decode_attention_bench(slots: int = 8, heads: int = 16,
                                 d: int = 128, cache: int = 16384,
                                 block: int = 256, iters: int = 20):
    """Batched paged decode-attention kernel at serving shapes: `slots`
    ragged sequences (cache/2 .. cache tokens) through one kernel
    dispatch. Achieved GB/s counts only the VALID kv bytes each slot
    actually attends — the block tables mean padding is never read."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer.paged_decode_attention import (
        paged_decode_attention)

    rs = np.random.RandomState(0)
    pages = cache // block
    nb = slots * pages + 1
    lens = np.linspace(cache // 2, cache, slots).astype(np.int32)
    bt = np.zeros((slots, pages), np.int32)
    free = 1
    for i, ln in enumerate(lens):
        n = -(-int(ln) // block)
        bt[i, :n] = np.arange(free, free + n)
        free += n
    q = jnp.asarray(rs.randn(slots, heads, d), jnp.bfloat16)
    pk = jnp.asarray(rs.randn(nb, block, heads, d), jnp.bfloat16)
    pv = jnp.asarray(rs.randn(nb, block, heads, d), jnp.bfloat16)
    lens_j = jnp.asarray(lens)
    bt_j = jnp.asarray(bt)
    # pools ride as ARGUMENTS (closing over them would bake ~GiB of pool
    # data into the executable as constants — decode16k_bench ditto)
    f = jax.jit(lambda q, pk, pv: paged_decode_attention(q, pk, pv,
                                                         lens_j, bt_j))
    o = f(q, pk, pv)
    o.block_until_ready()
    qq = q
    t0 = time.perf_counter()
    for _ in range(iters):
        # roll q each dispatch: additive eps-perturbations underflow in
        # bf16 (bit-identical input → the tunnel elides the dispatch,
        # the r3 chain flaw) — same discipline as blocksparse_bench
        qq = jnp.roll(qq, 1, axis=1)
        o = f(qq, pk, pv)
    o.block_until_ready()
    ms = (time.perf_counter() - t0) / iters * 1000
    valid_gb = float(lens.sum()) * heads * d * 2 * 2 / 2**30
    print(json.dumps({
        "metric": "decode_attention_batched_gbps",
        "value": round(valid_gb / (ms / 1000), 1), "unit": "GB/s",
        "ms": round(ms, 3), "slots": slots,
        "valid_kv_gib": round(valid_gb, 2),
        "cache_tokens": [int(x) for x in lens]}), flush=True)


def hbm_ceiling_probe() -> float:
    """Measured HBM bandwidth ceiling (bf16 elementwise chain, best of
    8 — same discipline as bench.py measure_roofline): the denominator
    of every roofline_frac this file emits."""
    import jax
    import jax.numpy as jnp
    a = jnp.asarray(np.random.default_rng(0).standard_normal(
        1 << 26, dtype=np.float32), jnp.bfloat16)

    @jax.jit
    def ew_chain(a):
        return jax.lax.fori_loop(
            0, 20, lambda i, a: a * 1.0000001 + 0.0000001, a)

    y = ew_chain(a)
    y.block_until_ready()
    best = float("inf")
    for _ in range(8):
        t0 = time.perf_counter()
        y = ew_chain(y)
        y.block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return 2 * a.nbytes * 20 / best / 2**30


def decode16k_bench(batch: int = 4, heads: int = 16, d: int = 128,
                    cache: int = 16384, iters: int = 20,
                    hbm_gbps: float = 0.0):
    """Chunked decode-attention kernel at a 16k KV cache (the workspace
    the single-block kernel could not serve — VERDICT r2 weak #5).
    ISSUE 8 reworked the kernel's compute onto the MXU (batched matvec
    scores, broadcastable [H,1] softmax state); roofline_frac against
    the probed HBM ceiling is the acceptance metric."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.transformer.decode_attention import (
        decode_attention)

    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(batch, heads, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(batch, cache, heads, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(batch, cache, heads, d), jnp.bfloat16)
    # calls are data-CHAINED (q depends on the previous output): the
    # tunnel elides repeated identical dispatches, which would otherwise
    # report physically impossible times
    f = jax.jit(lambda q, k, v, n: decode_attention(q, k, v, n))
    o = f(q, k, v, cache)
    o.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        o = f(q + 1e-6 * o, k, v, cache)
    o.block_until_ready()
    ms = (time.perf_counter() - t0) / iters * 1000
    gb = (k.nbytes + v.nbytes) / 2**30
    gbps = gb / (ms / 1000)
    print(json.dumps({
        "metric": "decode_attention_ms_16k_cache",
        "value": round(ms, 3), "unit": "ms",
        "kv_gib": round(gb, 2),
        "achieved_gbps": round(gbps, 1),
        "roofline_frac": round(gbps / hbm_gbps, 3) if hbm_gbps else None,
        "hbm_ceiling_gbps": round(hbm_gbps, 1) if hbm_gbps else None}),
        flush=True)


def paged_decode_roofline_sweep(hbm_gbps: float, slots: int = 8,
                                heads: int = 16, d: int = 128,
                                cache: int = 16384, iters: int = 16):
    """ISSUE 8 roofline sweep: the paged decode kernel across pages-
    per-program (double-buffer group width) x block size x kv bits.
    Each point reports the bytes that ACTUALLY cross HBM (compressed
    values + scales at 8/4-bit) and its fraction of the probed
    ceiling; ``kv_blocks_capacity_effective`` records how many pool
    blocks the bf16 pool's HBM budget admits at each width — the
    concurrency side of the quantization win."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.serving.block_allocator import (
        blocks_for_budget, kv_block_bytes)
    from deepspeed_tpu.ops.quantizer import kv_quantize
    from deepspeed_tpu.ops.transformer.paged_decode_attention import (
        paged_decode_attention)

    rs = np.random.RandomState(0)
    best = None
    for block in (64, 256):
        pages = cache // block
        nb = slots * pages + 1
        lens = np.linspace(cache // 2, cache, slots).astype(np.int32)
        bt = np.zeros((slots, pages), np.int32)
        free = 1
        for i, ln in enumerate(lens):
            n = -(-int(ln) // block)
            bt[i, :n] = np.arange(free, free + n)
            free += n
        q = jnp.asarray(rs.randn(slots, heads, d), jnp.bfloat16)
        pk16 = jnp.asarray(rs.randn(nb, block, heads, d), jnp.bfloat16)
        pv16 = jnp.asarray(rs.randn(nb, block, heads, d), jnp.bfloat16)
        lens_j, bt_j = jnp.asarray(lens), jnp.asarray(bt)
        for bits in (0, 8, 4):
            if bits:
                pk, ks = kv_quantize(pk16, bits)
                pv, vs = kv_quantize(pv16, bits)
            else:
                pk, pv, ks, vs = pk16, pv16, None, None
            # bytes one dispatch actually reads: each slot's valid rows,
            # values + scales, k and v — kv_block_bytes at block_size 1
            # IS the per-row rule (pinned against init_paged_cache)
            gb = float(lens.sum()) * kv_block_bytes(1, heads, d,
                                                    bits) / 2**30
            for pp in (1, 4, 8):
                if pp > pages:
                    continue
                # pools AND scales ride as arguments (closing over them
                # would bake them into the executable as constants —
                # the decode16k_bench discipline)
                kern = functools.partial(paged_decode_attention,
                                         kv_bits=bits,
                                         pages_per_program=pp)
                f = jax.jit(lambda q, pk, pv, ks, vs, kern=kern:
                            kern(q, pk, pv, lens_j, bt_j,
                                 k_scale=ks, v_scale=vs))
                qq = q
                o = f(qq, pk, pv, ks, vs)
                o.block_until_ready()
                t0 = time.perf_counter()
                for _ in range(iters):
                    qq = jnp.roll(qq, 1, axis=1)   # genuinely new input
                    o = f(qq, pk, pv, ks, vs)
                o.block_until_ready()
                ms = (time.perf_counter() - t0) / iters * 1000
                gbps = gb / (ms / 1000)
                point = {
                    "metric": "paged_decode_roofline_point",
                    "block": block, "pages_per_program": pp,
                    "kv_bits": bits, "ms": round(ms, 3),
                    "hbm_gib_moved": round(gb, 3),
                    "achieved_gbps": round(gbps, 1),
                    "roofline_frac": round(gbps / hbm_gbps, 3)
                    if hbm_gbps else None}
                print(json.dumps(point), flush=True)
                if bits == 0 and (best is None
                                  or ms < best["ms"]):
                    best = point
    budget = 512 * kv_block_bytes(16, heads, d)
    print(json.dumps({
        "metric": "kv_blocks_capacity_effective",
        "unit": "blocks@same_hbm_budget",
        "budget_bf16_blocks": 512,
        "value": {str(b): blocks_for_budget(budget, 16, heads, d, b)
                  for b in (0, 8, 4)},
        "best_bf16_point": best}), flush=True)


def blocksparse_bench(seq: int = 8192, heads: int = 8, d: int = 128,
                      iters: int = 8):
    """Block-sparse flash vs dense flash at long sequence — the nnz win
    (VERDICT r2 #10). Sliding-window layout, fwd+bwd timed."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import (
        LocalSlidingWindowSparsityConfig, blocksparse_attention_bthd)
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bthd)

    # block 512 / window 3 measured fastest on v5e (128-blocks are grid-
    # overhead-bound); the nnz win grows with seq as dense goes quadratic
    scfg = LocalSlidingWindowSparsityConfig(
        num_heads=heads, block=512, num_sliding_window_blocks=3)

    def run(f, q, k, v):
        # Every dispatch must see a GENUINELY distinct input: additive
        # eps-perturbations underflow in bf16 (input bit-identical →
        # the tunnel elides the dispatch; r3's chain had this flaw), so
        # roll the query each iteration. Sync by fetching a reduction —
        # block_until_ready returns early on this backend.
        loss = jax.jit(jax.grad(lambda q: jnp.sum(f(q, k, v) ** 2)))
        g = loss(q)
        float(jnp.sum(jnp.abs(g.astype(jnp.float32))))
        qq = q
        t0 = time.perf_counter()
        for _ in range(iters):
            qq = jnp.roll(qq, 1, axis=1)
            g = loss(qq)
        float(jnp.sum(jnp.abs(g.astype(jnp.float32))))
        return (time.perf_counter() - t0) / iters * 1000

    res = {}
    for s in (seq, 2 * seq):
        rng = np.random.RandomState(0)
        mk = lambda: jnp.asarray(  # noqa: E731
            rng.randn(1, s, heads, d), jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        res[s] = (
            run(lambda q, k, v: blocksparse_attention_bthd(q, k, v, scfg),
                q, k, v),
            run(lambda q, k, v: flash_attention_bthd(q, k, v), q, k, v))
    bs_ms, fl_ms = res[2 * seq]
    print(json.dumps({
        "metric": "blocksparse_attn_fwdbwd_ms_seq16k",
        "value": round(bs_ms, 2), "unit": "ms",
        "flash_dense_ms": round(fl_ms, 2),
        "speedup_vs_flash": round(fl_ms / bs_ms, 2),
        "seq8k_ms": round(res[seq][0], 2),
        "seq8k_flash_ms": round(res[seq][1], 2),
        "layout_density": round(2 / (2 * seq // 512), 3)}), flush=True)


def diffusion_bench(iters: int = 4):
    """SD-v1.5-geometry UNet denoising step latency (BASELINE.md tracked
    config 'Stable-Diffusion inference with kernel injection'): full
    320/640/1280/1280 UNet at 64x64 latents with CFG (batch doubles),
    77-token text context, bf16."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.diffusion import (UNet2DCondition,
                                                UNetConfig)
    cfg = UNetConfig(dtype=jnp.bfloat16)
    unet = UNet2DCondition(cfg)
    params = jax.jit(unet.init)(jax.random.PRNGKey(0))
    step = jax.jit(unet.apply)
    lat = jnp.zeros((2, 64, 64, 4), jnp.bfloat16)      # CFG pair
    ctx = jnp.zeros((2, 77, 768), jnp.bfloat16)
    t = jnp.array([500, 500], jnp.int32)
    out = step(params, lat, t, ctx)
    np.asarray(jax.device_get(out[0, 0, 0]))           # sync barrier
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(params, out, t, ctx)
    np.asarray(jax.device_get(out[0, 0, 0]))
    ms = (time.perf_counter() - t0) / iters * 1e3
    print(json.dumps({
        "metric": "sd15_unet_step_latency", "value": round(ms, 1),
        "unit": "ms", "latents": "2x64x64x4 (cfg pair)",
        "steps_per_sec": round(1000.0 / ms, 2),
        "est_50step_image_s": round(ms * 50 / 1000.0, 1)}), flush=True)


def host_offload_bench(seq: int = 8192, iters: int = 2):
    """Host activation checkpointing ladder (reference cpu_checkpointing,
    `activation_checkpointing/checkpointing.py:485`): at a long sequence,
    find the largest micro-batch trainable under remat='full' (residual
    stash in HBM) vs remat='host_offload' (stash in pinned host DRAM) —
    the long-sequence memory lever Infinity doesn't cover."""
    import gc

    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    # the tunnel reports HBM exhaustion as an opaque compile-helper 500
    # ("XLA:TPU compile permanent error. Ran out of memory in hbm" only
    # reaches the terminal's stderr) — for THIS ladder, where the only
    # varied quantity is memory, classify it as OOM
    oom_markers = ("RESOURCE_EXHAUSTED", "Out of memory", "OOM",
                   "Ran out of memory", "remote_compile")

    def try_step(remat, micro):
        # deep-narrow: the residual stash (L x d bytes/token) dominates
        # the per-layer recompute working set (~12 x d bytes/token), so
        # spilling the stash to host moves the trainable-batch ceiling —
        # the regime host activation checkpointing exists for
        cfg = gpt2_config("125m", max_seq_len=seq, remat=remat,
                          num_layers=48, d_model=512, num_heads=8,
                          attn_impl="flash", loss_chunk=256)
        conf = {"train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "AdamW", "params": {"lr": 6e-4}},
                "bf16": {"enabled": True}, "steps_per_print": 0}
        rs = np.random.RandomState(0)
        b = {"input_ids": rs.randint(0, cfg.vocab_size, (micro, seq),
                                     dtype=np.int32)}
        try:
            eng, _, _, _ = ds.initialize(model=TransformerLM(cfg),
                                         config=conf)
            fn = eng._build_train_step()
            ma = fn.lower(eng.state,
                          {"input_ids": b["input_ids"][None]}
                          ).compile().memory_analysis()
            mem = {"hbm_temp_gib": round(ma.temp_size_in_bytes / 2**30, 2),
                   "host_temp_gib": round(
                       getattr(ma, "host_temp_size_in_bytes", 0) / 2**30,
                       2)}
            m = eng.train_step(b)
            float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(iters):
                m = eng.train_step(b)
            float(m["loss"])
            tput = micro * seq * iters / (time.perf_counter() - t0)
            del eng
            gc.collect()
            return tput, mem
        except Exception as e:
            if any(s in str(e) for s in oom_markers):
                gc.collect()
                return None, None
            raise

    results = {}
    for remat in ("full", "host_offload"):
        fit, tput, mem = 0, None, None
        for micro in (16, 32):
            t, ma = try_step(remat, micro)
            if t is None:
                break
            fit, tput, mem = micro, t, ma
        results[remat] = {"max_micro": fit,
                          "tokens_per_sec": round(tput or 0.0, 1),
                          "memory": mem}
    print(json.dumps({
        "metric": "host_act_ckpt_max_tokens",
        "value": results["host_offload"]["max_micro"] * seq,
        "unit": "tokens/batch", "seq": seq,
        "full_remat": results["full"],
        "host_offload": results["host_offload"]}), flush=True)


def wire_bench(mb: int = 32):
    """Measured host<->device wire roofline — the hard bound on every
    offload design on this machine; reported in-band so offload numbers
    can be judged against hardware reality (VERDICT r2 weak #1)."""
    import jax
    import jax.numpy as jnp
    x = np.ones((mb << 20,), np.uint8)
    jax.device_put(x[:1 << 20]).block_until_ready()   # warm the path
    t0 = time.perf_counter()
    d = jax.device_put(x)
    d.block_until_ready()
    h2d = mb / 1024 / (time.perf_counter() - t0)
    y = (jnp.asarray(d) + 1).block_until_ready()
    t0 = time.perf_counter()
    np.asarray(y)
    d2h = mb / 1024 / (time.perf_counter() - t0)
    print(json.dumps({"metric": "wire_bandwidth", "value": round(d2h, 4),
                      "unit": "GB/s_d2h", "h2d_gbps": round(h2d, 3),
                      "d2h_gbps": round(d2h, 4)}), flush=True)
    return h2d, d2h


def offload_bench(iters: int = 3):
    """ZeRO-Offload tier 1 (host-DRAM optimizer, pipelined sweep) vs the
    same model in-HBM. Model sized to the measured wire: the offload step
    moves 4 bytes/param f32 grads down + 2 bytes/param bf16 up."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    cfg = gpt2_config("125m", max_seq_len=256, num_layers=4, d_model=512,
                      num_heads=8, loss_chunk=256, attn_impl="flash")
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(0, cfg.vocab_size, (8, 256),
                                     dtype=np.int32)}

    def run(zero):
        conf = {"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 6e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": zero, "steps_per_print": 0}
        eng, _, _, _ = ds.initialize(model=TransformerLM(cfg), config=conf)
        m = eng.train_step(batch)
        float(m["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            m = eng.train_step(batch)
        float(m["loss"])
        return 8 * 256 * iters / (time.perf_counter() - t0)

    base = run({"stage": 0})
    off = run({"stage": 0, "offload_optimizer": {"device": "cpu"}})
    # r5: the tier-1 grad wire rides the Infinity codec (offload_wire_bits)
    off1 = run({"stage": 0, "offload_optimizer": {"device": "cpu"},
                "offload_wire_bits": 1})
    print(json.dumps({
        "metric": "offload_tier1_tokens_per_sec",
        "value": round(off1, 1), "unit": "tokens/s",
        "in_hbm_tokens_per_sec": round(base, 1),
        "uncompressed_wire_tokens_per_sec": round(off, 1),
        "wire1bit_speedup": round(off1 / off, 2),
        "offload_vs_hbm": round(off1 / base, 4)}), flush=True)


def infinity_bench(h2d_gbps: float, d2h_gbps: float):
    """peak-params-per-chip: train the largest ladder config whose
    (wire-bound) step fits the time budget, with ZeRO-Infinity layer
    streaming. Also projects every larger config against host RAM and the
    measured wire so capability vs. tunnel-constraint is explicit."""
    import os

    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config
    from deepspeed_tpu.models.transformer import GPT2_SIZES, TransformerConfig

    budget = float(os.environ.get("DSTPU_INFINITY_BUDGET_S", "900"))
    seq = 512
    try:
        avail = int(next(l for l in open("/proc/meminfo")
                         if "MemAvailable" in l).split()[1]) * 1024
    except Exception:
        avail = 64 << 30
    hbm = 16 << 30   # v5e

    ladder = ["350m", "760m", "1.3b", "2.7b", "6.7b", "13b"]
    wire_bits = 1                  # stochastic-sign D2H grad wire (16x)
    live_budget = int(4e9)         # device layer-cache params (8 GiB bf16)
    projections = {}
    chosen = None
    for name in ladder:
        c = TransformerConfig(**{"max_seq_len": seq, **GPT2_SIZES[name]})
        p = c.num_params()
        host = 14 * p               # 2 bf16 store + 12 opt state
        # step wire: fwd uploads every layer (2 bytes/param bf16); the
        # backward re-uses the device layer cache up to live_budget and
        # re-uploads the rest; grads cross D2H at wire_bits/8 bytes/param
        per_layer = p / max(c.num_layers, 1)
        cached = min(c.num_layers, int(live_budget // per_layer))
        h2d_bytes = 2 * p + 2 * p * (1 - cached / max(c.num_layers, 1))
        d2h_bytes = p * wire_bits / 8
        est = (d2h_bytes / (d2h_gbps * 2**30 + 1) +
               h2d_bytes / (h2d_gbps * 2**30 + 1) + 16 * p / (3 * 2**30))
        fits_ram = host < avail * 0.85
        projections[name] = {
            "params_b": round(p / 1e9, 2),
            "host_gib": round(host / 2**30, 1),
            "est_step_s": round(est, 1),
            "hbm_equiv": round(16 * p / hbm, 2),   # on-device Adam bytes
            "fits": bool(fits_ram and est < budget)}
        if fits_ram and est < budget:
            chosen = name
    if chosen is None:
        chosen = "350m"

    cfg = gpt2_config(chosen, max_seq_len=seq, loss_chunk=256,
                      attn_impl="flash")
    conf = {"train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 6e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 3, "infinity_host_init": True,
                "offload_wire_bits": wire_bits,
                "max_live_parameters": live_budget,
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"}},
            "steps_per_print": 0}
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(0, cfg.vocab_size, (1, seq),
                                     dtype=np.int32)}
    eng, _, _, _ = ds.initialize(model=TransformerLM(cfg), config=conf)
    t0 = time.perf_counter()
    m = eng.train_step(batch)
    step1 = time.perf_counter() - t0
    steps, elapsed = 1, step1
    if elapsed + step1 < budget:      # a compile-free step fits too
        t0 = time.perf_counter()
        m = eng.train_step(batch)
        step_t = time.perf_counter() - t0
        steps += 1
    else:
        step_t = step1                # includes compile; flagged below
    p = eng.num_parameters()
    print(json.dumps({
        "metric": "peak_params_per_chip",
        "value": p, "unit": "params",
        "config": chosen,
        "tokens_per_sec": round(seq / step_t, 2),
        "step_seconds": round(step_t, 1),
        "includes_compile": steps == 1,
        "hbm_equivalent": round(16 * p / hbm, 2),
        "loss": round(float(m["loss"]), 3),
        "wire_d2h_gbps": round(d2h_gbps, 4),
        "wire_bits": wire_bits,
        "device_cache_layers": eng._infinity.max_live_layers,
        "projections": projections}), flush=True)


def multi_tenant_replay_bench(slots: int = 4, new: int = 16,
                              rounds: int = 60, spec_k: int = 1,
                              **model_kw):
    """Bursty 3-tenant replay through the SLO frontend (docs/serving.md
    "Sampling, streaming & multi-tenant SLOs"): an interactive tenant
    (4x weight, TTFT SLO) trickles short sampled prompts, a standard
    tenant submits steadily, and a batch tenant dumps two long-prompt
    bursts into a bounded queue — with the speculative lane armed.
    Reports per-tenant p50/p99 TTFT and inter-token latency, shed /
    timeout rates, and the draft acceptance rate: the fairness
    instrument — under the bursts the interactive percentiles should
    hold while the batch tenant absorbs the queueing and the sheds.

    An ``SloMonitor`` with bench-tight windows rides along: the SLO
    column reports how many burn-rate alerts fired, the time to the
    first alert, and the time the running p99 of the under-provisioned
    tenant's TTFT first showed the breach — the alert should win that
    race (docs/observability.md "SLO alerting")."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.inference.serving import (ServingFrontend,
                                                 SloMonitor, TenantSpec)
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    cfg = gpt2_config("125m", dtype=jnp.float32, **model_kw)
    art_dir = _bench_artifact_dir()
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "float32", "max_out_tokens": 128, "temperature": 0.0,
        "replace_with_kernel_inject": False,
        "observability": _obs_block(art_dir),
        "serving": {"enabled": True, "kv_block_size": 8,
                    "num_kv_blocks": 64, "max_batch_slots": slots,
                    "prefill_chunk_tokens": 32, "max_queue_depth": 6,
                    "spec_k": spec_k}})
    draft = TransformerLM(gpt2_config(
        "125m", dtype=jnp.float32, **dict(model_kw, num_layers=1)))
    srv = eng.serving_engine(draft_model=draft,
                             draft_params=draft.init(jax.random.PRNGKey(1)))
    # bench-tight burn-rate windows so a breach inside a ~seconds run
    # is observable; threshold 1.0 = burning the error budget at all
    slo_mon = SloMonitor(objective=0.9, fast_window_s=2.0,
                         slow_window_s=8.0, burn_threshold=1.0,
                         min_samples=3)
    alerts = []
    slo_mon.subscribe(lambda a: alerts.append(
        (time.perf_counter(), a)))
    fe = ServingFrontend(srv, slo=slo_mon)
    fe.register(TenantSpec("interactive", weight=4.0, ttft_slo_s=0.5))
    fe.register(TenantSpec("standard", weight=1.0))
    # the under-provisioned tenant: unit weight, a TTFT target its own
    # bursts cannot meet behind the bounded queue — the burn-rate alert
    # should fire here, and before the p99 shows it
    fe.register(TenantSpec("batch", weight=1.0, max_queue_share=0.5,
                           ttft_slo_s=0.3))
    tenants = ("interactive", "standard", "batch")
    ttft = {t: [] for t in tenants}
    itl = {t: [] for t in tenants}
    p99_breach = {"at": None}

    def hook(ev):
        if ev.token is None or ev.tenant not in ttft:
            return
        if ev.index == 0:
            ttft[ev.tenant].append(ev.time_s - ev.request.submit_time)
            # the histogram's view of the breach: first wall time the
            # running p99 of the batch tenant's completed TTFTs
            # exceeds its target
            if (ev.tenant == "batch" and p99_breach["at"] is None
                    and len(ttft["batch"]) >= 3
                    and float(np.percentile(ttft["batch"], 99)) > 0.3):
                p99_breach["at"] = time.perf_counter()
        elif ev.prev_time_s is not None:
            itl[ev.tenant].append(ev.time_s - ev.prev_time_s)

    srv.token_hooks.append(hook)
    fe.submit([1, 2, 3], max_new_tokens=4)      # warm the compile
    srv.run()
    rs = np.random.RandomState(7)
    reqs = {t: [] for t in tenants}

    def sub(tenant, plen, **kw):
        p = rs.randint(0, cfg.vocab_size, (plen,)).tolist()
        reqs[tenant].append(fe.submit(p, tenant=tenant,
                                      max_new_tokens=new, **kw))

    t0 = time.perf_counter()
    for r in range(rounds):
        if r % 3 == 0:
            sub("interactive", int(rs.randint(4, 9)),
                temperature=0.7, top_k=16, seed=100 + r)
        if r % 5 == 0:
            sub("standard", int(rs.randint(10, 14)))
        if r in (2, rounds // 2):               # the bursts
            for _ in range(5):
                sub("batch", int(rs.randint(20, 25)))
        srv.step()
    srv.run()
    dt = time.perf_counter() - t0
    # quiet tail: the load is gone, the fast window drains, and the
    # firing alerts must RESOLVE (the hysteresis edge of the state
    # machine) — bounded at ~2.5x the fast window
    quiet_deadline = time.perf_counter() + 2.5 * slo_mon.fast_window_s
    while (any(v["state"] == "firing"
               for v in slo_mon.snapshot().values())
           and time.perf_counter() < quiet_deadline):
        time.sleep(0.1)
        slo_mon.evaluate()

    def pcts(xs):
        if not xs:
            return {"p50_ms": None, "p99_ms": None}
        return {"p50_ms": round(float(np.percentile(xs, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(xs, 99)) * 1e3, 2)}

    sc = srv.spec_counts
    per_tenant = {}
    for t in tenants:
        rs_t = reqs[t]
        shed = sum(r.status.value == "shed" for r in rs_t)
        timed = sum(r.status.value == "timed_out" for r in rs_t)
        per_tenant[t] = {
            "requests": len(rs_t),
            "ttft": pcts(ttft[t]), "inter_token": pcts(itl[t]),
            "shed_rate": round(shed / max(len(rs_t), 1), 3),
            "timeout_rate": round(timed / max(len(rs_t), 1), 3),
            "tokens": sum(len(r.output) for r in rs_t)}
    fired = [(at, a) for at, a in alerts if a.state == "firing"]
    first_alert_s = round(fired[0][0] - t0, 3) if fired else None
    breach_s = round(p99_breach["at"] - t0, 3) \
        if p99_breach["at"] is not None else None
    # one merged trace artifact per run: flush the tracer (request
    # waterfalls + overlap iteration track ride along) and assemble
    from deepspeed_tpu.observability import FleetTraceAssembler, get_tracer
    tracer = get_tracer()
    trace_path = FleetTraceAssembler() \
        .add_file(tracer.flush(), label=f"rank{tracer.rank}") \
        .write(os.path.join(art_dir, "multi_tenant_fleet_trace.json"))
    print(json.dumps({
        "metric": "multi_tenant_replay",
        "value": round(sum(pt["tokens"] for pt in per_tenant.values())
                       / dt, 1),
        "unit": "tokens/s", "slots": slots, "rounds": rounds,
        "tenants": per_tenant, "spec_k": spec_k,
        "spec_proposed": sc["proposed"], "spec_accepted": sc["accepted"],
        "spec_acceptance_rate": round(
            sc["accepted"] / max(sc["proposed"], 1), 3),
        "slo": {
            "alerts_fired": len(fired),
            "alerts_resolved": sum(
                a.state == "resolved" for _, a in alerts),
            "time_to_first_alert_s": first_alert_s,
            "p99_breach_at_s": breach_s,
            "alert_before_p99": (first_alert_s is not None
                                 and (breach_s is None
                                      or first_alert_s <= breach_s)),
            "firing_now": sorted(
                k for k, v in slo_mon.snapshot().items()
                if v["state"] == "firing")},
        "overlap": _overlap_columns("serving"),
        "fleet_trace": trace_path,
        "decode_builds": srv.decode_builds}), flush=True)


def fleet_failover_bench(replicas: int = 2, rounds: int = 12,
                         new: int = 12, kill_at: int = 9, **model_kw):
    """Price the fleet failover path (docs/serving.md "Fleet serving &
    failover"): the same two-tenant wave runs twice across the replica
    fleet — once clean, once with a fatal ``serving.fleet.replica_step``
    killing one replica at a fixed site-call index mid-wave.  Reports
    the failover detection latency (kill -> first replayed token
    delivered past the dedup high-water mark), the replayed-token
    overhead the dedup swallowed, per-tenant p99 TTFT with vs without
    the kill, and ``decode_builds`` (must stay 1 per surviving replica
    — failover replays ride the existing compiled step, never a
    retrace).  Absolute latencies are only meaningful on TPU."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.inference.serving import FleetRouter, ReplicaState
    from deepspeed_tpu.models import TransformerLM, gpt2_config
    from deepspeed_tpu.runtime.resilience import (FaultInjector,
                                                  install_fault_injector)

    cfg = gpt2_config("125m", dtype=jnp.float32, **model_kw)
    tenants = ("interactive", "batch")

    def run(kill: bool):
        eng = ds.init_inference(TransformerLM(cfg), config={
            "dtype": "float32", "max_out_tokens": 64,
            "temperature": 0.0, "replace_with_kernel_inject": False,
            "serving": {"enabled": True, "kv_block_size": 8,
                        "num_kv_blocks": 64, "max_batch_slots": 4,
                        "prefill_chunk_tokens": 32,
                        "max_queue_depth": 32,
                        "fleet": {"enabled": True,
                                  "replicas": replicas}}})
        fleet = FleetRouter.from_engine(eng, rng=jax.random.PRNGKey(0))
        # warm every replica's compile before the clock (and before the
        # injector: warmup steps must not consume the kill index)
        for _ in range(replicas):
            fleet.submit([1, 2, 3], max_new_tokens=4)
        fleet.run()
        t_kill = {}
        for r in fleet.replicas:
            orig = r.mark_dead
            def dead(reason, _orig=orig):
                t_kill.setdefault("t", time.perf_counter())
                _orig(reason)
            r.mark_dead = dead
        fi = FaultInjector()
        if kill:
            fi.add_plan("serving.fleet.replica_step", "fatal",
                        at=kill_at)
        install_fault_injector(fi)
        try:
            rs = np.random.RandomState(11)
            ttft = {t: [] for t in tenants}
            first_replay = {}

            def hook(freq):
                def _cb(ev):
                    if ev.token is None:
                        return
                    if ev.index == 0:
                        ttft[ev.tenant].append(
                            ev.time_s - freq.submit_time)
                    if "t" in t_kill and freq.failovers:
                        first_replay.setdefault(
                            freq.req_id, time.perf_counter())
                return _cb

            reqs = []
            t0 = time.perf_counter()
            for i in range(rounds):
                plen = int(rs.randint(4, 9)) if i % 2 == 0 \
                    else int(rs.randint(16, 21))
                tenant = tenants[i % 2]
                p = rs.randint(0, cfg.vocab_size, (plen,)).tolist()
                freq = fleet.submit(p, max_new_tokens=new,
                                    tenant=tenant)
                freq.on_token = hook(freq)
                reqs.append(freq)
                fleet.pump()
            fleet.run()
            dt = time.perf_counter() - t0
            assert all(r.status is not None and r.status.value == "ok"
                       for r in reqs), "a request did not survive"
            dead = [r.replica_id for r in fleet.replicas
                    if r.state is ReplicaState.DEAD]
            detect_ms = None
            if "t" in t_kill and first_replay:
                detect_ms = round(
                    (min(first_replay.values()) - t_kill["t"]) * 1e3, 2)
            return {
                "tokens_per_sec": round(
                    sum(len(r.output) for r in reqs) / dt, 1),
                "ttft_p99_ms": {
                    t: round(float(np.percentile(ttft[t], 99)) * 1e3, 2)
                    for t in tenants if ttft[t]},
                "dead_replicas": dead,
                "failovers": fleet.fleet_counts["failovers"],
                "replayed_tokens": fleet.fleet_counts["replayed_tokens"],
                "failover_detect_ms": detect_ms,
                "decode_builds": [r.srv.decode_builds
                                  for r in fleet.replicas]}
        finally:
            install_fault_injector(FaultInjector())

    base = run(kill=False)
    killed = run(kill=True)
    assert all(b == 1 for b in killed["decode_builds"]), \
        "failover replay retraced a surviving replica"
    print(json.dumps({
        "metric": "fleet_failover",
        "value": killed["failover_detect_ms"], "unit": "ms",
        "replicas": replicas, "kill_at": kill_at,
        "dead_replica": (killed["dead_replicas"] or [None])[0],
        "failovers": killed["failovers"],
        "replayed_tokens": killed["replayed_tokens"],
        "tokens_per_sec": {"baseline": base["tokens_per_sec"],
                           "kill": killed["tokens_per_sec"]},
        "ttft_p99_ms": {"baseline": base["ttft_p99_ms"],
                        "kill": killed["ttft_p99_ms"]},
        "decode_builds": killed["decode_builds"]}), flush=True)


def disaggregated_fleet_bench(rounds: int = 18, new: int = 10,
                              chips: int = 3, burst: int = 4,
                              **model_kw):
    """Price the disaggregated prefill/decode split (docs/serving.md
    "Disaggregated fleet & autoscaling"): the same bursty two-tenant
    trace runs twice on the SAME chip budget — once on a uniform
    ``chips``-replica fleet, once on a 1-prefill + 1-decode split with
    the SLO/queue-driven autoscaler allowed to grow the decode class up
    to the budget.  An interactive tenant streams short prompts every
    round while a batch tenant dumps long-prompt prefill bursts; in the
    uniform fleet those prefill chunks ride the decode iterations and
    inflate everyone's TTFT, in the split fleet they land on the
    prefill worker and arrive at the decode class as claimable fabric
    chains.  Reports per-tenant p99 TTFT and decode tokens/s for both
    shapes — aggregate AND per decode-class chip (every uniform replica
    is decode-class but spends iterations on prefill chunks; that
    dilution is the interference disaggregation removes, so the
    per-chip number is the one the split should win) — plus the
    autoscaler's scale events against the wall time the uniform run's
    running p99 first showed the breach (the scale-up should win that
    race), and ``decode_builds`` per replica (must stay 1 — the handoff
    rides the compiled mixed program, never a retrace).  Absolute
    latencies are only meaningful on TPU."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.inference.serving import (FleetAutoscaler,
                                                 FleetRouter)
    from deepspeed_tpu.inference.serving.engine import ServingEngine
    from deepspeed_tpu.inference.serving.fleet.replica import ReplicaHandle
    from deepspeed_tpu.models import TransformerLM, gpt2_config
    from deepspeed_tpu.observability.slo import KIND_TTFT, SloMonitor

    cfg = gpt2_config("125m", dtype=jnp.float32, **model_kw)
    tenants = ("interactive", "batch")
    targets = {"interactive": 0.5, "batch": 1.5}

    art_dir = _bench_artifact_dir()

    def build(replicas, prefill_replicas):
        eng = ds.init_inference(TransformerLM(cfg), config={
            "dtype": "float32", "max_out_tokens": 64,
            "temperature": 0.0, "replace_with_kernel_inject": False,
            "observability": _obs_block(art_dir),
            "serving": {"enabled": True, "kv_block_size": 8,
                        "num_kv_blocks": 64, "max_batch_slots": 4,
                        "prefill_chunk_tokens": 8,
                        "max_queue_depth": 32,
                        "fleet": {"enabled": True, "replicas": replicas,
                                  "prefill_replicas": prefill_replicas},
                        "host_cache": {"enabled": True,
                                       "dram_budget_bytes": 1 << 24,
                                       "wire_bits": 0}}})
        fleet = FleetRouter.from_engine(eng, rng=jax.random.PRNGKey(0))
        return eng, fleet

    def run(split: bool):
        eng, fleet = build(chips if not split else 2,
                           0 if not split else 1)
        # warm every replica's compile before the clock; the 12-token
        # prompt crosses a block boundary so the split fleet's warm-up
        # also runs the publish→claim→promote handoff once
        for _ in range(len(fleet.replicas)):
            fleet.submit(list(range(1, 13)), max_new_tokens=4)
        fleet.run()
        auto = None
        spawned = []
        if split:
            mon = SloMonitor(objective=0.9, fast_window_s=2.0,
                             slow_window_s=8.0, burn_threshold=1.0,
                             min_samples=3, time_fn=time.perf_counter)

            def spawn(role):
                srv = ServingEngine(
                    eng, rng=jax.random.PRNGKey(2 + len(spawned)),
                    shared_host_cache=fleet.shared_host_cache,
                    role=role)
                srv.publisher_id = f"as{len(spawned)}-{role}"
                h = ReplicaHandle(f"as{len(spawned)}-{role}", srv,
                                  role=role)
                spawned.append(h)
                return h

            auto = FleetAutoscaler(
                fleet, spawn, slo_monitor=mon, clock=time.perf_counter,
                chip_budget=chips, scale_up_cooldown_s=0.5,
                scale_down_cooldown_s=2.0, queue_high=3.0,
                queue_low=1.0, quiet_s=1.0)
        rs = np.random.RandomState(11)
        ttft = {t: [] for t in tenants}
        breach = {}

        def hook(freq, tenant):
            def _cb(ev):
                if ev.token is None or ev.index != 0:
                    return
                lat = ev.time_s - freq.submit_time
                ttft[tenant].append(lat)
                if split:
                    mon.observe(tenant, KIND_TTFT, lat, targets[tenant])
                elif (tenant not in breach and len(ttft[tenant]) >= 3
                      and float(np.percentile(ttft[tenant], 99))
                      > targets[tenant]):
                    # the uniform run's histogram view of the breach:
                    # the "would-be" timestamp the split fleet's
                    # scale-up must beat
                    breach[tenant] = time.perf_counter()
            return _cb

        reqs = []
        t0 = time.perf_counter()
        for i in range(rounds):
            p = rs.randint(0, cfg.vocab_size,
                           (int(rs.randint(4, 9)),)).tolist()
            freq = fleet.submit(p, max_new_tokens=new,
                                tenant="interactive")
            freq.on_token = hook(freq, "interactive")
            reqs.append(freq)
            if i in (2, rounds // 2):       # the prefill bursts
                for _ in range(burst):
                    p = rs.randint(0, cfg.vocab_size,
                                   (int(rs.randint(36, 45)),)).tolist()
                    freq = fleet.submit(p, max_new_tokens=6,
                                        tenant="batch")
                    freq.on_token = hook(freq, "batch")
                    reqs.append(freq)
            fleet.pump()
            if auto is not None:
                auto.tick()
        fleet.run()
        dt = time.perf_counter() - t0
        assert all(r.status is not None and r.status.value == "ok"
                   for r in reqs), "a request did not survive the trace"
        builds = [r.srv.decode_builds for r in fleet.replicas
                  if r.srv.decode_builds]
        assert all(b == 1 for b in builds), \
            "the disaggregated handoff retraced a replica"
        decode_chips = max(
            1, sum(r.role != "prefill" for r in fleet.replicas))
        tok_s = sum(len(r.output) for r in reqs) / dt
        # one merged fleet trace per run: every leg's waterfall under
        # its fleet trace id, flow arrows chaining the handoffs
        shape = "split" if split else "uniform"
        trace_path = fleet.export_fleet_trace(os.path.join(
            art_dir, f"disagg_fleet_trace_{shape}.json"))
        fleet.export_fleet_metrics(prometheus_path=os.path.join(
            art_dir, f"disagg_fleet_{shape}.prom"))
        out = {
            "replicas": [(r.replica_id, r.role) for r in fleet.replicas],
            "decode_tokens_per_sec": round(tok_s, 1),
            "decode_tokens_per_sec_per_decode_chip": round(
                tok_s / decode_chips, 1),
            "ttft_p99_ms": {
                t: round(float(np.percentile(ttft[t], 99)) * 1e3, 2)
                for t in tenants if ttft[t]},
            "overlap": _overlap_columns("serving"),
            "fleet_trace": trace_path,
            "decode_builds": builds}
        if split:
            out["handoffs"] = fleet.fleet_counts["handoffs"]
            out["fabric"] = {
                "published": fleet.shared_host_cache.published_total,
                "claim_hits": sum(
                    fleet.shared_host_cache.hits_total.values())}
            out["scale_events"] = [
                {"at_s": round(e["t"] - t0, 3), "action": e["action"],
                 "role": e["role"], "reason": e["reason"]}
                for e in (auto.events if auto else [])]
            # close the loop: quiet tail scale-down + orphan hygiene
            deadline = time.perf_counter() + 3.0
            while (auto and auto.counts["scale_ups"]
                   and not auto.counts["scale_downs"]
                   and time.perf_counter() < deadline):
                time.sleep(0.2)
                fleet.pump()
                auto.tick()
            fleet.reap_orphans()
            assert fleet.shared_host_cache.published_entries() == 0, \
                "orphaned fabric entries survived the drain"
            out["scale_downs"] = auto.counts["scale_downs"] if auto else 0
        else:
            out["p99_breach_at_s"] = {
                t: round(breach[t] - t0, 3) for t in breach}
        return out

    uniform = run(split=False)
    disagg = run(split=True)
    ups = [e for e in disagg["scale_events"] if e["action"] == "up"]
    first_up_s = ups[0]["at_s"] if ups else None
    breach_s = min(uniform["p99_breach_at_s"].values(), default=None) \
        if uniform["p99_breach_at_s"] else None
    print(json.dumps({
        "metric": "disaggregated_fleet",
        "value": disagg["ttft_p99_ms"].get("interactive"),
        "unit": "ms", "chips": chips, "rounds": rounds,
        "uniform": uniform, "disagg": disagg,
        "scale_up_before_breach": (
            first_up_s is not None
            and (breach_s is None or first_up_s <= breach_s)),
        "first_scale_up_s": first_up_s,
        "uniform_breach_s": breach_s,
        "disagg_wins_ttft": (
            uniform["ttft_p99_ms"].get("interactive", 0)
            > disagg["ttft_p99_ms"].get("interactive", float("inf"))),
        "disagg_wins_decode_throughput": (
            disagg["decode_tokens_per_sec_per_decode_chip"]
            > uniform["decode_tokens_per_sec_per_decode_chip"])}),
        flush=True)


def main():
    import jax
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        train_bench("125m", 64, 1024, 0)
        train_bench("350m", 16, 1024, 2, iters=6)
        train_bench("350m", 16, 1024, 3, iters=6)
        train_3d_bench("350m", seq=1024, micro=8, iters=4)
        decode_bench()
        hbm = hbm_ceiling_probe()
        decode16k_bench(hbm_gbps=hbm)
        serving_decode_bench()
        multi_tenant_replay_bench(spec_k=3)
        fleet_failover_bench()
        disaggregated_fleet_bench()
        prefix_cache_bench()
        tiered_prefix_cache_bench()
        paged_decode_attention_bench()
        paged_decode_roofline_sweep(hbm)
        blocksparse_bench()
        diffusion_bench()
        host_offload_bench()
        h2d, d2h = wire_bench()
        offload_bench()
        infinity_bench(h2d, d2h)
    else:
        train_bench("125m", 2, 128, 0, iters=3, num_layers=4, d_model=256,
                    num_heads=8)
        # (pp, tp, dp) sweep on the forced 8-device CPU mesh: shape and
        # bubble-measurement coverage, not absolute throughput
        import jax.numpy as jnp
        train_3d_bench(seq=32, micro=1, iters=2, num_layers=4, d_model=32,
                       num_heads=4, vocab_size=64, dtype=jnp.float32)
        # the (data, model) serving sweep runs on the forced 8-device
        # CPU mesh — mesh-shape coverage, not absolute throughput
        tp_decode_bench()
        multi_tenant_replay_bench(num_layers=2, d_model=64, num_heads=4,
                                  vocab_size=256, max_seq_len=128)
        # failover pricing on the same tiny model: the detection/replay
        # numbers rank the path's overheads, not TPU latency
        fleet_failover_bench(num_layers=2, d_model=64, num_heads=4,
                             vocab_size=256, max_seq_len=128)
        # uniform-vs-disaggregated on the same chip budget: CPU smoke
        # checks the scale-up-beats-breach race and handoff hygiene,
        # not absolute latency
        disaggregated_fleet_bench(rounds=10, new=8,
                                  num_layers=2, d_model=64, num_heads=4,
                                  vocab_size=256, max_seq_len=128)
        # tiny-model tier sweep: exercises spill -> host -> promote on
        # the interpret-mode kernels; ratios are indicative only on CPU
        import jax.numpy as jnp
        tiered_prefix_cache_bench(
            slots=4, n_req=4, system=48, user=8, new=8, block=8,
            dram_budget=1 << 26, num_layers=2, d_model=64, num_heads=4,
            vocab_size=256, dtype=jnp.float32, attn_impl="xla")


if __name__ == "__main__":
    main()
