"""Multi-config benchmark suite (BASELINE.json tracked configs).

Prints one JSON line per config. `bench.py` stays the driver's headline
single-line contract; this script covers the wider matrix: 125M ZeRO-0,
350M ZeRO-2/3, decode latency.
"""
from __future__ import annotations

import json
import time

import numpy as np


def train_bench(size: str, micro: int, seq: int, zero_stage: int,
                iters: int = 10, **cfg_kw):
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config
    from deepspeed_tpu.profiling.flops_profiler import chip_peak_flops

    cfg = gpt2_config(size, max_seq_len=seq, remat="full",
                      attn_impl="flash", loss_chunk=256, **cfg_kw)
    model = TransformerLM(cfg)
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": zero_stage},
        "gradient_clipping": 1.0, "steps_per_print": 0})
    rs = np.random.RandomState(0)
    batch = {"input_ids": rs.randint(0, cfg.vocab_size, (micro, seq),
                                     dtype=np.int32)}
    m = engine.train_step(batch)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        m = engine.train_step(batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    tok = micro * seq * iters / dt
    n = engine.num_parameters()
    fpt = 6 * n + 12 * cfg.num_layers * cfg.d_model * seq
    mfu = tok * fpt / chip_peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": f"gpt2_{size}_zero{zero_stage}_tokens_per_sec_per_chip",
        "value": round(tok, 1), "unit": "tokens/s",
        "mfu": round(mfu, 4), "vs_baseline": round(mfu / 0.45, 4)}),
        flush=True)


def decode_bench(size: str = "125m", batch: int = 4, prompt: int = 64,
                 new: int = 64):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    cfg = gpt2_config(size, max_seq_len=prompt + new, attn_impl="flash",
                      dtype=jnp.bfloat16)
    eng = ds.init_inference(TransformerLM(cfg), config={
        "dtype": "bfloat16", "max_out_tokens": prompt + new})
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch, prompt), dtype=np.int32)
    for _ in range(3):
        eng.generate(ids, max_new_tokens=new, temperature=0.0)
    stats = eng.latency_stats()
    print(json.dumps({
        "metric": f"gpt2_{size}_decode_p50_ms_per_token",
        "value": round(stats["p50_ms"], 3), "unit": "ms",
        "p90_ms": round(stats["p90_ms"], 3),
        "decode_tokens_per_sec": round(stats["tokens_per_sec"], 1)}),
        flush=True)


def main():
    import jax
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu:
        train_bench("125m", 64, 1024, 0)
        train_bench("350m", 16, 1024, 2, iters=6)
        train_bench("350m", 16, 1024, 3, iters=6)
        decode_bench()
    else:
        train_bench("125m", 2, 128, 0, iters=3, num_layers=4, d_model=256,
                    num_heads=8)


if __name__ == "__main__":
    main()
