"""One-off diagnostic: true kernel times with in-program chaining.

Itemizes the blocksparse ~100ms floor (VERDICT r4 weak #3) and the decode
bandwidth (weak #4) by timing each kernel inside a single compiled
fori_loop — no per-dispatch tunnel latency in the measurement at all.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def sync(a):
    leaf = jax.tree_util.tree_leaves(a)[0]
    np.asarray(jax.device_get(leaf.reshape(-1)[:1]))


def timed(fn, *args, reps=3, inner=64):
    r = fn(*args)
    sync(r)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        sync(r)
        best = min(best, time.perf_counter() - t0)
    return best / inner * 1000


def decode_diag():
    from deepspeed_tpu.ops.transformer.decode_attention import (
        decode_attention)
    b, h, d, cache = 4, 16, 128, 16384
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, h, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(b, cache, h, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(b, cache, h, d), jnp.bfloat16)

    @jax.jit
    def chain(q, k, v):
        def body(i, qq):
            return decode_attention(qq, k, v, cache)
        return jax.lax.fori_loop(0, 64, body, q)

    ms = timed(chain, q, k, v)
    gb = (k.nbytes + v.nbytes) / 2**30
    print(json.dumps({"kernel": "decode_16k", "ms": round(ms, 3),
                      "achieved_gbps": round(gb / (ms / 1e3), 1)}),
          flush=True)


def attn_diag():
    from deepspeed_tpu.ops.sparse_attention import (
        LocalSlidingWindowSparsityConfig, blocksparse_attention_bthd)
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bthd)
    heads, d = 8, 128

    def run_case(name, f, s, fwd_only=False):
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)
        k = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)
        v = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)

        if fwd_only:
            @jax.jit
            def chain(q, k, v):
                def body(i, qq):
                    o = f(qq, k, v)
                    return o.astype(qq.dtype)
                return jax.lax.fori_loop(0, 64, body, q)
        else:
            g = jax.grad(lambda q, k, v: jnp.sum(
                f(q, k, v).astype(jnp.float32) ** 2))

            @jax.jit
            def chain(q, k, v):
                def body(i, qq):
                    return g(qq, k, v).astype(qq.dtype)
                return jax.lax.fori_loop(0, 64, body, q)

        ms = timed(chain, q, k, v)
        print(json.dumps({"kernel": name, "seq": s, "ms": round(ms, 2)}),
              flush=True)
        return ms

    for s in (2048, 4096, 8192, 16384):
        scfg = LocalSlidingWindowSparsityConfig(
            num_heads=heads, block=512, num_sliding_window_blocks=3)
        bs = lambda q, k, v: blocksparse_attention_bthd(q, k, v, scfg)  # noqa
        fl = lambda q, k, v: flash_attention_bthd(q, k, v, causal=True)  # noqa
        run_case("blocksparse_fwd", bs, s, fwd_only=True)
        run_case("blocksparse_fwdbwd", bs, s)
        run_case("flash_fwd", fl, s, fwd_only=True)
        run_case("flash_fwdbwd", fl, s)


if __name__ == "__main__":
    decode_diag()
    attn_diag()
