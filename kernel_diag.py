"""One-off diagnostic: true kernel times with in-program chaining.

Itemizes the blocksparse ~100ms floor (VERDICT r4 weak #3) and the decode
bandwidth (weak #4) by timing each kernel inside a single compiled
fori_loop — no per-dispatch tunnel latency in the measurement at all.
"""
import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def sync(a):
    leaf = jax.tree_util.tree_leaves(a)[0]
    np.asarray(jax.device_get(leaf.reshape(-1)[:1]))


def timed(fn, *args, reps=3, inner=64):
    r = fn(*args)
    sync(r)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        sync(r)
        best = min(best, time.perf_counter() - t0)
    return best / inner * 1000


def decode_diag():
    from deepspeed_tpu.ops.transformer.decode_attention import (
        decode_attention)
    b, h, d, cache = 4, 16, 128, 16384
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, h, d), jnp.bfloat16)
    k = jnp.asarray(rs.randn(b, cache, h, d), jnp.bfloat16)
    v = jnp.asarray(rs.randn(b, cache, h, d), jnp.bfloat16)

    @jax.jit
    def chain(q, k, v):
        def body(i, qq):
            return decode_attention(qq, k, v, cache)
        return jax.lax.fori_loop(0, 64, body, q)

    ms = timed(chain, q, k, v)
    gb = (k.nbytes + v.nbytes) / 2**30
    print(json.dumps({"kernel": "decode_16k", "ms": round(ms, 3),
                      "achieved_gbps": round(gb / (ms / 1e3), 1)}),
          flush=True)


def paged_decode_diag():
    """True paged-kernel times with in-program chaining: the ISSUE 8
    roofline levers (pages-per-program, kv bits) isolated from dispatch
    latency — one compiled fori_loop per configuration."""
    from deepspeed_tpu.inference.serving.block_allocator import (
        kv_block_bytes)
    from deepspeed_tpu.ops.quantizer import kv_quantize
    from deepspeed_tpu.ops.transformer.paged_decode_attention import (
        paged_decode_attention)
    slots, h, d, cache, block = 8, 16, 128, 16384, 256
    rs = np.random.RandomState(0)
    pages = cache // block
    nb = slots * pages + 1
    lens = jnp.full((slots,), cache, jnp.int32)
    bt = jnp.asarray(
        np.arange(1, nb).reshape(slots, pages), jnp.int32)
    q = jnp.asarray(rs.randn(slots, h, d), jnp.bfloat16)
    pk16 = jnp.asarray(rs.randn(nb, block, h, d), jnp.bfloat16)
    pv16 = jnp.asarray(rs.randn(nb, block, h, d), jnp.bfloat16)
    for bits in (0, 8, 4):
        if bits:
            pk, ks = kv_quantize(pk16, bits)
            pv, vs = kv_quantize(pv16, bits)
        else:
            pk, pv, ks, vs = pk16, pv16, None, None
        # per-row values+scales bytes via the pinned sizing rule
        gb = float(slots * cache) * kv_block_bytes(1, h, d, bits) / 2**30
        for pp in (1, 4, 8):

            @jax.jit
            def chain(q, pk, pv, ks, vs, pp=pp, bits=bits):
                def body(i, qq):
                    return paged_decode_attention(
                        qq, pk, pv, lens, bt, k_scale=ks, v_scale=vs,
                        kv_bits=bits, pages_per_program=pp)
                return jax.lax.fori_loop(0, 16, body, q)

            ms = timed(chain, q, pk, pv, ks, vs, inner=16)
            print(json.dumps({
                "kernel": "paged_decode_16k", "kv_bits": bits,
                "pages_per_program": pp, "ms": round(ms, 3),
                "achieved_gbps": round(gb / (ms / 1e3), 1)}),
                flush=True)


def attn_diag():
    from deepspeed_tpu.ops.sparse_attention import (
        LocalSlidingWindowSparsityConfig, blocksparse_attention_bthd)
    from deepspeed_tpu.ops.transformer.flash_attention import (
        flash_attention_bthd)
    heads, d = 8, 128

    def run_case(name, f, s, fwd_only=False):
        rs = np.random.RandomState(0)
        q = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)
        k = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)
        v = jnp.asarray(rs.randn(1, s, heads, d), jnp.bfloat16)

        if fwd_only:
            @jax.jit
            def chain(q, k, v):
                def body(i, qq):
                    o = f(qq, k, v)
                    return o.astype(qq.dtype)
                return jax.lax.fori_loop(0, 64, body, q)
        else:
            g = jax.grad(lambda q, k, v: jnp.sum(
                f(q, k, v).astype(jnp.float32) ** 2))

            @jax.jit
            def chain(q, k, v):
                def body(i, qq):
                    return g(qq, k, v).astype(qq.dtype)
                return jax.lax.fori_loop(0, 64, body, q)

        ms = timed(chain, q, k, v)
        print(json.dumps({"kernel": name, "seq": s, "ms": round(ms, 2)}),
              flush=True)
        return ms

    for s in (2048, 4096, 8192, 16384):
        scfg = LocalSlidingWindowSparsityConfig(
            num_heads=heads, block=512, num_sliding_window_blocks=3)
        bs = lambda q, k, v: blocksparse_attention_bthd(q, k, v, scfg)  # noqa
        fl = lambda q, k, v: flash_attention_bthd(q, k, v, causal=True)  # noqa
        run_case("blocksparse_fwd", bs, s, fwd_only=True)
        run_case("blocksparse_fwdbwd", bs, s)
        run_case("flash_fwd", fl, s, fwd_only=True)
        run_case("flash_fwdbwd", fl, s)


if __name__ == "__main__":
    decode_diag()
    paged_decode_diag()
    attn_diag()
