"""Mixture-of-Experts: top-k gating, expert-parallel dispatch, PR-MoE.

Counterpart of `/root/reference/deepspeed/moe/` re-designed for SPMD: expert
weights are a stacked [E, ...] pytree sharded over the ``expert`` mesh axis,
and the dispatch/combine all_to_alls are emitted by GSPMD from sharding
constraints instead of hand-issued collectives.
"""
from .layer import MoEConfig, MoELayer, mlp_expert
from .sharded_moe import GateOutput, capacity, gate, top1_gating, top2_gating

__all__ = ["MoEConfig", "MoELayer", "mlp_expert", "GateOutput", "capacity",
           "gate", "top1_gating", "top2_gating"]
