"""Top-k gating + expert dispatch (GShard-style), TPU-native.

Behavioral counterpart of the reference's gating
(`/root/reference/deepspeed/moe/sharded_moe.py:177` top1gating, `:278`
top2gating, `:439` MOELayer.forward). Redesign notes:

  - The reference computes ``capacity`` from runtime tensor shapes and
    branches on it; here capacity is STATIC (derived from the traced token
    count), so the whole gate compiles into one XLA program with fixed
    shapes — no dynamic-shape recompiles.
  - Dispatch/combine are the same einsums as the reference
    (``sec,sm->ecm`` / ``sec,ecm->sm``); sharding constraints on the
    [E, C, M] dispatched tensor make GSPMD emit the all_to_all over the
    ``expert`` mesh axis that the reference issues by hand
    (`sharded_moe.py:89` _AllToAll autograd function).
  - Random Token Selection (`use_rts`, reference `:254`) and the RSample
    noisy gate (`:185`) take an explicit rng key — omitted key = the
    deterministic variants (drop-by-token-order), which is also what the
    reference does at eval.
  - Everything runs in fp32 regardless of the activation dtype, like the
    reference ("everything is in fp32 in this function").

Gating tensor shapes follow the GShard paper / reference notation:
S = tokens, E = experts, C = per-expert capacity, M = model dim.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GateOutput(NamedTuple):
    l_aux: jnp.ndarray            # scalar load-balance loss
    combine_weights: jnp.ndarray  # [S, E, C] fp32
    dispatch_mask: jnp.ndarray    # [S, E, C] bool
    exp_counts: jnp.ndarray       # [E] int32 — tokens routed per expert
                                  # (pre-drop), the reference's exp_counts


def capacity(num_tokens: int, num_experts: int, capacity_factor: float,
             min_capacity: int) -> int:
    """Static per-expert capacity (reference `_capacity`,
    `sharded_moe.py:163`)."""
    cap = int(math.ceil((num_tokens / num_experts) * capacity_factor))
    return max(cap, min_capacity)


def _rank_within_expert(mask: jnp.ndarray,
                        priority: Optional[jnp.ndarray]) -> jnp.ndarray:
    """Position of each selected token within its expert's queue.

    ``priority`` None → token order (cumsum, the reference's non-RTS path);
    else higher priority wins a capacity slot (RTS: uniform noise).
    Returns [S, E] int32; meaningless where mask == 0."""
    if priority is None:
        return jnp.cumsum(mask, axis=0) - 1
    # Rank selected tokens by descending priority via double argsort.
    keyed = jnp.where(mask > 0, priority, -jnp.inf)
    order = jnp.argsort(-keyed, axis=0)
    return jnp.argsort(order, axis=0).astype(jnp.int32)


def _combine_tensors(gates_masked: jnp.ndarray, locations_s: jnp.ndarray,
                     cap: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    loc_sc = jax.nn.one_hot(locations_s, cap, dtype=jnp.float32)  # [S, C]
    combine = jnp.einsum("se,sc->sec", gates_masked, loc_sc)
    return combine, combine > 0


def top1_gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
                min_capacity: int = 4,
                used_token: Optional[jnp.ndarray] = None,
                noisy_gate_policy: Optional[str] = None,
                drop_tokens: bool = True, use_rts: bool = True,
                rng: Optional[jax.Array] = None) -> GateOutput:
    """Switch-style top-1 routing (reference `top1gating`,
    `sharded_moe.py:177`).

    ``drop_tokens=False`` is intentionally unsupported here: it requires a
    data-dependent capacity (runtime max of exp_counts), which XLA cannot
    compile without dynamic shapes — raise and tell the user to bound
    capacity_factor instead.
    """
    if not drop_tokens:
        raise ValueError(
            "drop_tokens=False needs data-dependent shapes under jit; raise "
            "capacity_factor (e.g. to num_experts) for the same effect")
    s, e = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    cap = capacity(s, e, capacity_factor, min_capacity)

    route_logits = logits
    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("noisy_gate_policy='RSample' needs an rng key")
        rng, sub = jax.random.split(rng)
        route_logits = logits + jax.random.gumbel(sub, logits.shape)
        indices1 = jnp.argmax(route_logits, axis=1)
    else:
        indices1 = jnp.argmax(gates, axis=1)
    mask1 = jax.nn.one_hot(indices1, e, dtype=jnp.int32)
    if used_token is not None:
        mask1 = mask1 * used_token[:, None].astype(jnp.int32)

    exp_counts = jnp.sum(mask1, axis=0)

    # load-balance aux loss: sum(mean-prob * mean-assignment) * E
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.sum(me * ce) * e

    prio = None
    if use_rts:
        if rng is None:
            prio = None   # deterministic fallback: token order
        else:
            prio = jax.random.uniform(rng, mask1.shape)
    locations1 = _rank_within_expert(mask1, prio)
    mask1 = mask1 * (locations1 < cap).astype(jnp.int32)
    if prio is not None:
        # re-pack surviving tokens contiguously into capacity slots
        locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations1_s = jnp.sum(locations1 * mask1, axis=1)

    gates_masked = gates * mask1.astype(jnp.float32)
    combine, dispatch = _combine_tensors(gates_masked, locations1_s, cap)
    # zero the slots of dropped tokens (one_hot of garbage locations is
    # already masked because gates_masked is 0 there)
    return GateOutput(l_aux, combine, dispatch, exp_counts)


def top2_gating(logits: jnp.ndarray, capacity_factor: float = 1.0,
                min_capacity: int = 4,
                rng: Optional[jax.Array] = None) -> GateOutput:
    """GShard top-2 routing (reference `top2gating`, `sharded_moe.py:278`).

    Second expert picked by gumbel-max when ``rng`` given (the reference
    always samples); deterministic second-argmax otherwise.
    """
    s, e = logits.shape
    logits = logits.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    cap = capacity(s, e, capacity_factor * 2.0, min_capacity)

    indices1 = jnp.argmax(gates, axis=1)
    mask1 = jax.nn.one_hot(indices1, e, dtype=jnp.int32)

    logits2 = logits
    if rng is not None:
        logits2 = logits + jax.random.gumbel(rng, logits.shape)
    logits_except1 = jnp.where(mask1 > 0, -jnp.inf, logits2)
    indices2 = jnp.argmax(logits_except1, axis=1)
    mask2 = jax.nn.one_hot(indices2, e, dtype=jnp.int32)

    locations1 = jnp.cumsum(mask1, axis=0) - 1
    locations2 = jnp.cumsum(mask2, axis=0) - 1
    # second-choice tokens queue behind ALL first-choice tokens
    locations2 = locations2 + jnp.sum(mask1, axis=0, keepdims=True)

    exp_counts = jnp.sum(mask1, axis=0)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1.astype(jnp.float32), axis=0)
    l_aux = jnp.mean(me * ce) * e * e

    mask1 = mask1 * (locations1 < cap).astype(jnp.int32)
    mask2 = mask2 * (locations2 < cap).astype(jnp.int32)
    locations1_s = jnp.sum(locations1 * mask1, axis=1)
    locations2_s = jnp.sum(locations2 * mask2, axis=1)

    gates1_s = jnp.einsum("se,se->s", gates, mask1.astype(jnp.float32))
    gates2_s = jnp.einsum("se,se->s", gates, mask2.astype(jnp.float32))
    denom = jnp.maximum(gates1_s + gates2_s, jnp.finfo(jnp.float32).eps)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    combine1, _ = _combine_tensors(
        gates1_s[:, None] * mask1.astype(jnp.float32), locations1_s, cap)
    combine2, _ = _combine_tensors(
        gates2_s[:, None] * mask2.astype(jnp.float32), locations2_s, cap)
    combine = combine1 + combine2
    return GateOutput(l_aux, combine, combine > 0, exp_counts)


def gate(logits: jnp.ndarray, k: int, capacity_factor: float = 1.0,
         min_capacity: int = 4, rng: Optional[jax.Array] = None,
         noisy_gate_policy: Optional[str] = None,
         use_rts: bool = True) -> GateOutput:
    """k-dispatch front door (reference TopKGate.forward,
    `sharded_moe.py:389`; k ∈ {1, 2} like the reference)."""
    if k == 1:
        return top1_gating(logits, capacity_factor, min_capacity,
                           noisy_gate_policy=noisy_gate_policy,
                           use_rts=use_rts, rng=rng)
    if k == 2:
        return top2_gating(logits, capacity_factor, min_capacity, rng=rng)
    raise ValueError(f"Only top-1 and top-2 gating supported, got k={k}")
