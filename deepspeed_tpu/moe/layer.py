"""MoE layer: gate → dispatch → experts → combine, over the ``expert`` axis.

Role-equivalent of the reference ``MoE`` / ``MOELayer`` / ``Experts``
(`/root/reference/deepspeed/moe/layer.py:15`, `sharded_moe.py:439`,
`moe/experts.py:9`). TPU-native shape of the design:

  - Expert weights carry a leading ``E`` (num_experts) axis sharded over the
    ``expert`` mesh axis — the reference's ``num_local_experts`` is simply
    E / ep_size shards of that axis, and its per-group expert process groups
    (`utils/groups.py:109` _create_expert_and_data_parallel) collapse into
    the one mesh.
  - The [E, C, M] dispatched tensor is sharding-constrained to
    P('expert', ...); with tokens sharded over the data-like axes, GSPMD
    lowers the dispatch/combine einsums into exactly the all_to_all pair the
    reference issues by hand (`sharded_moe.py:89` _AllToAll).
  - Expert gradients need no special buckets (reference engine.py:2428
    _reduce_expert_gradients): grads of expert-sharded params are reduced
    over the remaining axes automatically by GSPMD's partitioner.
  - PR-MoE residual experts (`layer.py` use_residual, arXiv 2201.05596):
    a dense MLP branch mixed per-token via a learned 2-way coefficient.

The expert itself is pluggable as an (init, apply) pair like everything in
`models/layers.py`; `mlp_expert` is the standard FFN expert.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models import layers as L
from ..parallel.topology import EXPERT_AXIS
from .sharded_moe import GateOutput, gate as topk_gate


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Mirrors the reference MoE.__init__ surface (`moe/layer.py:15`)."""
    num_experts: int = 1
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    use_residual: bool = False
    noisy_gate_policy: Optional[str] = None
    use_rts: bool = True
    aux_loss_coef: float = 0.01


def mlp_expert(d_model: int, d_ff: int, activation: str = "gelu",
               use_bias: bool = True, depth_scale: Optional[int] = None):
    """Standard FFN expert (init, apply) pair.

    ``depth_scale`` — total transformer depth; the residual-branch output
    projection then uses the GPT-2 scaled init (0.02/sqrt(2L)) exactly like
    the dense blocks' fc_out, keeping residual variance depth-controlled."""
    def init(rng, dtype=jnp.float32):
        k1, k2 = jax.random.split(rng)
        if depth_scale:
            out_kernel = L.scaled_init(k2, (d_ff, d_model), 0.02,
                                       depth_scale, dtype)
        else:
            out_kernel = L.normal_init(k2, (d_ff, d_model), 0.02, dtype)
        p = {"fc_in": L.dense_init(k1, d_model, d_ff, use_bias, 0.02, dtype),
             "fc_out": {"kernel": out_kernel}}
        if use_bias:
            p["fc_out"]["bias"] = jnp.zeros((d_model,), dtype)
        return p

    def apply(p, x):
        h = L.dense_apply(p["fc_in"], x)
        h = L.ACT_FNS[activation](h)
        return L.dense_apply(p["fc_out"], h)

    def specs():
        sp = {"fc_in": {"kernel": P(None, "model")},
              "fc_out": {"kernel": P("model", None)}}
        if use_bias:
            sp["fc_in"]["bias"] = P("model")
            sp["fc_out"]["bias"] = P(None)
        return sp

    return init, apply, specs


class MoELayer:
    """Functional MoE layer.

    ``init(rng)`` → params; ``apply(params, x, rng=None, train=True)`` →
    (y, l_aux, exp_counts). x: [..., M] (any leading batch dims).
    """

    def __init__(self, d_model: int, config: MoEConfig,
                 expert: Optional[Tuple[Callable, Callable, Callable]] = None,
                 d_ff: Optional[int] = None,
                 constrain: Optional[Callable] = None,
                 depth_scale: Optional[int] = None):
        self.d_model = d_model
        self.config = config
        self.expert_init, self.expert_apply, self.expert_specs = (
            expert if expert is not None
            else mlp_expert(d_model, d_ff or 4 * d_model,
                            depth_scale=depth_scale))
        self.constrain = constrain or (lambda x, spec=None: x)

    def init(self, rng, dtype=jnp.float32) -> Dict:
        c = self.config
        kg, ke, kr, kc = jax.random.split(rng, 4)
        # gate weights stay fp32 — routing decisions are precision-critical
        # (reference keeps the whole gate in fp32)
        params = {
            "gate": {"kernel": L.normal_init(kg, (self.d_model, c.num_experts),
                                             0.02, jnp.float32)},
            "experts": jax.vmap(lambda k: self.expert_init(k, dtype))(
                jax.random.split(ke, c.num_experts)),
        }
        if c.use_residual:
            params["residual_mlp"] = self.expert_init(kr, dtype)
            params["coefficient"] = L.dense_init(kc, self.d_model, 2, True,
                                                 0.02, dtype)
        return params

    def partition_specs(self) -> Dict:
        """Experts shard over 'expert' on the leading E axis (+ TP inside
        each expert over 'model'); gate + residual replicate over 'expert'."""
        exp = self.expert_specs()
        specs = {
            "gate": {"kernel": P(None, None)},
            "experts": jax.tree_util.tree_map(
                lambda sp: P(EXPERT_AXIS, *sp), exp,
                is_leaf=lambda x: isinstance(x, P)),
        }
        if self.config.use_residual:
            specs["residual_mlp"] = exp
            specs["coefficient"] = {"kernel": P(None, None),
                                    "bias": P(None)}
        return specs

    _warned_no_rts_rng = False

    def apply(self, params, x, rng: Optional[jax.Array] = None,
              train: bool = True):
        c = self.config
        if (train and c.use_rts and rng is None
                and not MoELayer._warned_no_rts_rng):
            # trace-time, once: RTS without a key degrades to deterministic
            # drop-by-token-order — legal, but the user asked for randomness
            from ..utils.logging import logger
            logger.warning(
                "MoE use_rts=True but no rng provided (pass batch['moe_rng'] "
                "through the engine); token selection is deterministic")
            MoELayer._warned_no_rts_rng = True
        orig_shape = x.shape
        m = orig_shape[-1]
        tokens = x.reshape(-1, m)                       # [S, M]
        s = tokens.shape[0]

        logits = jnp.einsum("sm,me->se", tokens.astype(jnp.float32),
                            params["gate"]["kernel"])
        out: GateOutput = topk_gate(
            logits, c.k,
            c.capacity_factor if train else c.eval_capacity_factor,
            c.min_capacity, rng=rng,
            noisy_gate_policy=c.noisy_gate_policy if train else None,
            use_rts=c.use_rts and train)

        # dispatch: [S,E,C] x [S,M] -> [E,C,M]; constraining to
        # P('expert',...) makes GSPMD emit the token all_to_all here.
        dispatched = jnp.einsum(
            "sec,sm->ecm", out.dispatch_mask.astype(x.dtype), tokens)
        dispatched = self.constrain(dispatched, P(EXPERT_AXIS, None, None))
        expert_out = jax.vmap(self.expert_apply)(params["experts"],
                                                 dispatched)   # [E, C, M]
        expert_out = self.constrain(expert_out, P(EXPERT_AXIS, None, None))
        # combine: the reverse all_to_all
        combined = jnp.einsum("sec,ecm->sm",
                              out.combine_weights.astype(x.dtype), expert_out)

        if c.use_residual:
            # PR-MoE (reference layer.py use_residual + moe/experts residual
            # path): out = moe(x)*w0 + mlp(x)*w1, per-token softmax mix
            mlp_out = self.expert_apply(params["residual_mlp"], tokens)
            coef = jax.nn.softmax(
                L.dense_apply(params["coefficient"], tokens).astype(
                    jnp.float32), axis=-1)
            combined = (combined * coef[:, 0:1].astype(x.dtype)
                        + mlp_out * coef[:, 1:2].astype(x.dtype))

        return (combined.reshape(orig_shape), out.l_aux, out.exp_counts)
