"""Autotuning — counterpart of `/root/reference/deepspeed/autotuning/`."""
from .autotuner import Autotuner

__all__ = ["Autotuner"]
