"""Autotuning — counterpart of `/root/reference/deepspeed/autotuning/`."""
from .autotuner import Autotuner
from .scheduler import ResourceManager

__all__ = ["Autotuner", "ResourceManager"]
