"""Single autotuning experiment, run in its own process.

The subprocess half of the experiment scheduler (reference: the launcher
job each `autotuning/scheduler.py` slot sshes out — here a plain child
process). Reads a JSON spec, builds the model + engine, times a few
steps, writes a JSON result; every failure mode is converted into a
result file (oom/error) or a nonzero exit the ResourceManager maps to
"crash"."""
from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Attempting to allocate")


def lm_factory(config_dict: Dict[str, Any]):
    """Default factory: TransformerLM from a JSON-safe config dict
    (dtype fields as strings)."""
    import jax.numpy as jnp
    from ..models.transformer import TransformerConfig, TransformerLM
    d = dict(config_dict)
    dtypes = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
              "float16": jnp.float16}
    for k in ("dtype", "param_dtype"):
        if isinstance(d.get(k), str):
            d[k] = dtypes[d[k]]
    return TransformerLM(TransformerConfig(**d))


def _resolve(path: str):
    mod, _, name = path.partition(":")
    import importlib
    return getattr(importlib.import_module(mod), name)


def run(spec: Dict[str, Any]) -> Dict[str, Any]:
    fault = spec.get("inject_fault")
    if fault == "crash":
        sys.exit(41)
    if fault == "hang":
        time.sleep(3600)
    import numpy as np
    import deepspeed_tpu as ds
    factory = _resolve(spec.get(
        "model_factory", "deepspeed_tpu.autotuning.exp_runner:lm_factory"))
    model = factory(spec["model_config"])
    try:
        engine, _, _, _ = ds.initialize(model=model, config=spec["cfg"])
        seq = int(spec.get("seq")
                  or getattr(model.config, "max_seq_len", 128))
        vocab = int(getattr(model.config, "vocab_size", 1024))
        rs = np.random.RandomState(0)
        batch = {"input_ids": rs.randint(
            0, vocab, (engine.train_batch_size, seq), dtype=np.int32)}
        m = engine.train_step(batch)
        float(m["loss"])
        steps = int(spec.get("steps", 3))
        t0 = time.perf_counter()
        for _ in range(steps):
            m = engine.train_step(batch)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        out = {"status": "ok",
               "samples_per_sec": engine.train_batch_size / dt,
               "step_seconds": dt, "detail": ""}
        if spec.get("profile_phases"):
            # per-phase attribution of THIS trial via the shared roofline
            # engine (profiling/phase_bench.py). Timing-only unless the
            # spec carries probed ceilings — re-probing the roofline per
            # experiment would dominate the trial. Best-effort: a profile
            # failure must not fail a measured experiment.
            try:
                from ..profiling.phase_bench import phase_breakdown
                out["phases"] = phase_breakdown(
                    engine, model, batch, seq, dt,
                    spec.get("gemm_tflops"), spec.get("hbm_gbps"),
                    inner=2, reps=1, do_feed_registry=False)
            except Exception as e:
                out["phases"] = {"error":
                                 f"{type(e).__name__}: {str(e)[:200]}"}
        return out
    except Exception as e:  # classified, not propagated
        status = ("oom" if any(s in str(e) for s in _OOM_MARKERS)
                  else "error")
        return {"status": status, "samples_per_sec": None,
                "detail": f"{type(e).__name__}: {str(e)[:300]}"}


def main() -> None:
    with open(sys.argv[1]) as f:
        spec = json.load(f)
    result = run(spec)
    with open(spec["result_path"], "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
