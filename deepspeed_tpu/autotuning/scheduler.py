"""Experiment scheduler: autotuning candidates as isolated subprocess jobs.

Role-equivalent of the reference ``ResourceManager``
(`/root/reference/deepspeed/autotuning/scheduler.py:28`): there,
experiments are launched as ssh/pdsh launcher jobs across nodes with a
slot pool and early termination; here each experiment is a local
subprocess running `autotuning/exp_runner.py` — crash/timeout isolation
means a candidate that OOMs the whole process, deadlocks, or segfaults
costs one job, not the tune (the round-3 verdict's gap #3: an in-process
candidate crash killed the whole tune).

A job spec is a JSON dict:
  {"cfg": <engine config>, "model_factory": "pkg.mod:callable",
   "model_config": {...}, "steps": 3, "seq": 64,
   "result_path": "...", "inject_fault": None|"crash"|"hang",
   "timeout_s": <optional per-spec override of the pool timeout>}

``inject_fault`` is a chaos hook honoured by the runner (used by the
fault-isolation tests; the reference has no in-band fault injection —
SURVEY §5.3 — this framework treats it as part of the contract).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils.logging import logger


class ResourceManager:
    """Run job specs over a bounded pool of subprocess slots."""

    def __init__(self, slots: int = 1, timeout_s: float = 600.0,
                 env: Optional[Dict[str, str]] = None,
                 poll_s: float = 0.2):
        self.slots = max(1, int(slots))
        self.timeout_s = float(timeout_s)
        self.env = dict(env or {})
        self.poll_s = poll_s

    def _launch(self, spec_path: str,
                log_path: str) -> subprocess.Popen:
        """Child output goes to a per-job LOG FILE, not a pipe: a verbose
        experiment would fill the ~64KiB pipe buffer, block mid-run, and
        get misclassified as a timeout (advisor r4, low)."""
        env = dict(os.environ)
        env.update(self.env)
        logf = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.exp_runner", spec_path],
                stdout=logf, stderr=subprocess.STDOUT, env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
        finally:
            logf.close()      # the child holds its own fd from here
        return proc

    def run(self, specs: List[Dict[str, Any]],
            workdir: str) -> List[Dict[str, Any]]:
        """Execute all specs; returns one result dict per spec (same
        order): {"status": ok|oom|error|crash|timeout, "samples_per_sec",
        "detail"}."""
        os.makedirs(workdir, exist_ok=True)
        results: List[Optional[Dict[str, Any]]] = [None] * len(specs)
        pending = deque()
        for i, spec in enumerate(specs):
            spec = dict(spec)
            spec.setdefault("result_path",
                            os.path.join(workdir, f"result_{i}.json"))
            sp = os.path.join(workdir, f"spec_{i}.json")
            with open(sp, "w") as f:
                json.dump(spec, f)
            lp = os.path.join(workdir, f"job_{i}.log")
            budget = float(spec.get("timeout_s", self.timeout_s))
            pending.append((i, sp, spec["result_path"], lp, budget))
        running: Dict[int, Any] = {}

        def tail(log_path: str, n: int = 300) -> str:
            try:
                with open(log_path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - n))
                    return f.read().decode(errors="replace")
            except OSError:
                return ""

        def harvest(i, proc, result_path, log_path, timed_out=False,
                    budget=None):
            if timed_out:
                proc.kill()
                proc.wait()
                results[i] = {"status": "timeout", "samples_per_sec": None,
                              "detail": (f"killed after {budget}s; "
                                         f"{tail(log_path)}")}
                return
            proc.wait()
            if os.path.exists(result_path):
                with open(result_path) as f:
                    results[i] = json.load(f)
            else:
                results[i] = {
                    "status": "crash", "samples_per_sec": None,
                    "detail": (f"exit={proc.returncode}; "
                               f"{tail(log_path)}")}

        while pending or running:
            while pending and len(running) < self.slots:
                i, sp, rp, lp, budget = pending.popleft()
                proc = self._launch(sp, lp)
                running[i] = (proc, rp, lp, time.monotonic(), budget)
                logger.info(f"autotune scheduler: job {i} launched "
                            f"(pid {proc.pid}, "
                            f"{len(running)}/{self.slots} slots)")
            done = []
            for i, (proc, rp, lp, t0, budget) in running.items():
                if proc.poll() is not None:
                    harvest(i, proc, rp, lp)
                    done.append(i)
                elif time.monotonic() - t0 > budget:
                    harvest(i, proc, rp, lp, timed_out=True, budget=budget)
                    done.append(i)
            for i in done:
                running.pop(i)
                logger.info(f"autotune scheduler: job {i} -> "
                            f"{results[i]['status']}")
            if running and not done:
                time.sleep(self.poll_s)
        return [r for r in results]
