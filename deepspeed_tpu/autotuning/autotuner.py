"""Autotuner: config-space search over measured train steps.

Role-equivalent of the reference autotuner
(`/root/reference/deepspeed/autotuning/autotuner.py:421` Autotuner.tune,
tuners in `autotuning/tuner/`): generate experiments over the tuning
space, run a few measured steps each, and pick the fastest config.
Redesign notes:

  - The reference schedules experiments as separate launcher jobs across
    nodes (ResourceManager); here each experiment is an engine build + a
    few steps in-process — on TPU the "job" boundary is just a new jit.
  - Tuner strategies: grid (exhaustive) and model_based (cost-model-
    pruned: skip configs whose predicted memory exceeds HBM), mirroring
    index_based/model_based tuners.
  - The space covers the knobs that actually move THIS framework's bench
    (VERDICT r2 weak #7): micro-batch x ZeRO stage x remat policy x
    loss-chunk x optimizer offload. OOM failures are classified apart
    from real errors, and an OOM at micro-batch m prunes every larger
    micro-batch of the same (stage, remat, chunk, offload) combination.
"""
from __future__ import annotations

import copy
import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import logger

DEFAULT_MICRO_BATCHES = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Attempting to allocate")


def _is_oom(exc: BaseException) -> bool:
    return any(m in str(exc) for m in _OOM_MARKERS)


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 micro_batches: Sequence[int] = DEFAULT_MICRO_BATCHES,
                 zero_stages: Sequence[int] = DEFAULT_ZERO_STAGES,
                 remat_policies: Optional[Sequence[str]] = None,
                 loss_chunks: Optional[Sequence[int]] = None,
                 offload_options: Sequence[bool] = (False,),
                 steps_per_trial: int = 3, tuner_type: str = "model_based",
                 hbm_bytes: Optional[int] = None):
        self.model = model
        self.base_config = base_config
        self.micro_batches = sorted(micro_batches)
        self.zero_stages = list(zero_stages)
        # model-side dims: None = keep the model's current setting
        self.remat_policies = list(remat_policies) if remat_policies \
            else [None]
        self.loss_chunks = list(loss_chunks) if loss_chunks else [None]
        self.offload_options = list(offload_options)
        self.steps_per_trial = steps_per_trial
        self.tuner_type = tuner_type
        self.hbm_bytes = hbm_bytes
        self.results: List[Dict[str, Any]] = []

    # -- experiment generation (reference exps generation) -----------------
    def generate_experiments(self) -> List[Dict[str, Any]]:
        exps = []
        for mb, stage, remat, chunk, offload in itertools.product(
                self.micro_batches, self.zero_stages, self.remat_policies,
                self.loss_chunks, self.offload_options):
            cfg = copy.deepcopy(self.base_config)
            cfg["train_micro_batch_size_per_gpu"] = mb
            cfg.pop("train_batch_size", None)
            cfg.setdefault("zero_optimization", {})["stage"] = stage
            if offload:
                cfg["zero_optimization"]["offload_optimizer"] = {
                    "device": "cpu"}
            else:
                # the non-offload arm must actually BE non-offloaded even
                # when base_config carries an offload block
                cfg["zero_optimization"].pop("offload_optimizer", None)
            model_kw = {}
            if remat is not None:
                model_kw["remat"] = remat
            if chunk is not None:
                model_kw["loss_chunk"] = chunk
            exps.append({"cfg": cfg, "model_kw": model_kw,
                         "key": (stage, remat, chunk, offload), "mb": mb})
        if self.tuner_type == "model_based":
            exps = [e for e in exps
                    if self._predict_fits(e["cfg"], e["model_kw"])]
        return exps

    def _predict_fits(self, cfg: Dict[str, Any],
                      model_kw: Optional[Dict[str, Any]] = None) -> bool:
        """Cost-model pruning (reference model_based_tuner): param + opt +
        activation memory estimate against HBM."""
        if self.hbm_bytes is None:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            self.hbm_bytes = stats.get("bytes_limit", 16 * 2 ** 30) or \
                16 * 2 ** 30
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            return True
        n = mcfg.num_params() if hasattr(mcfg, "num_params") else 0
        stage = cfg.get("zero_optimization", {}).get("stage", 0)
        offload = (cfg.get("zero_optimization", {})
                   .get("offload_optimizer") or {}).get("device") == "cpu"
        import jax
        dp = max(jax.device_count(), 1) if stage else 1
        # bf16 params + f32 master/m/v (sharded by stage>=1, or in host
        # DRAM when offloaded) + grads
        opt_bytes = 0 if offload else (n * 12) / (dp if stage >= 1 else 1)
        state = n * 2 + opt_bytes + n * 4 / (dp if stage >= 2 else 1)
        mb = cfg.get("train_micro_batch_size_per_gpu", 1)
        remat = (model_kw or {}).get("remat", getattr(mcfg, "remat", "none"))
        # no remat: ~4 live tensors per layer; remat keeps ~the per-layer
        # block inputs plus one layer's working set
        eff_layers = (mcfg.num_layers * 4 if remat in (None, "none")
                      else mcfg.num_layers + 4)
        acts = mb * mcfg.max_seq_len * mcfg.d_model * 2 * eff_layers
        return (state + acts) * 1.3 < self.hbm_bytes

    def _build_model(self, model_kw: Dict[str, Any]):
        if not model_kw:
            return self.model
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            raise ValueError(
                f"model-side tuning dims {list(model_kw)} need a model "
                f"with a dataclass config (got {type(self.model).__name__})")
        return type(self.model)(dataclasses.replace(mcfg, **model_kw),
                                getattr(self.model, "constrain", None))

    # -- measurement -------------------------------------------------------
    def _measure(self, exp: Dict[str, Any],
                 batch_fn: Callable[[int], Dict]):
        """→ (samples_per_sec | None, status in ok|oom|error)."""
        import deepspeed_tpu as ds
        cfg = exp["cfg"]
        try:
            model = self._build_model(exp["model_kw"])
            engine, _, _, _ = ds.initialize(model=model,
                                            config=copy.deepcopy(cfg))
            batch = batch_fn(engine.train_batch_size)
            m = engine.train_step(batch)
            float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                m = engine.train_step(batch)
            float(m["loss"])
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return engine.train_batch_size / dt, "ok"
        except Exception as e:
            status = "oom" if _is_oom(e) else "error"
            log = logger.warning if status == "error" else logger.info
            log(f"autotune experiment {status} "
                f"(mb={cfg.get('train_micro_batch_size_per_gpu')}, "
                f"zero={cfg.get('zero_optimization', {}).get('stage')}, "
                f"model_kw={exp['model_kw']}): "
                f"{type(e).__name__}: {str(e)[:120]}")
            return None, status

    def tune(self, batch_fn: Callable[[int], Dict]) -> Dict[str, Any]:
        """Run all experiments; return the best config (highest
        samples/sec). ``batch_fn(global_batch_size)`` supplies data."""
        exps = self.generate_experiments()
        logger.info(f"autotuning over {len(exps)} experiments")
        best, best_tput, best_kw = None, -1.0, {}
        oom_floor: Dict[Any, int] = {}   # combo key -> smallest OOM mb
        for exp in exps:
            key, mb = exp["key"], exp["mb"]
            if key in oom_floor and mb >= oom_floor[key]:
                status, tput = "pruned_oom", None
            else:
                tput, status = self._measure(exp, batch_fn)
                if status == "oom":
                    oom_floor[key] = min(mb, oom_floor.get(key, mb))
            self.results.append({
                "micro_batch": mb,
                "zero_stage": exp["cfg"]["zero_optimization"]["stage"],
                **exp["model_kw"],
                "offload": bool(exp["cfg"]["zero_optimization"].get(
                    "offload_optimizer")),
                "status": status,
                "samples_per_sec": tput})
            if tput is not None and tput > best_tput:
                best, best_tput, best_kw = exp["cfg"], tput, exp["model_kw"]
        if best is None:
            raise RuntimeError("every autotuning experiment failed")
        logger.info(
            f"autotune best: mb={best['train_micro_batch_size_per_gpu']} "
            f"zero={best['zero_optimization']['stage']} "
            f"model_kw={best_kw} ({best_tput:.1f} samples/s)")
        best = copy.deepcopy(best)
        if best_kw:
            best["_model_overrides"] = dict(best_kw)
        return best

    # -- scheduled (subprocess) tuning -------------------------------------
    def _make_specs(self, seq: Optional[int] = None,
                    steps: Optional[int] = None) -> List[Dict[str, Any]]:
        """Job specs for the experiment scheduler: the in-process
        model-based pruner stays the PROPOSAL stage; measurement moves to
        isolated subprocesses."""
        mcfg = getattr(self.model, "config", None)
        if mcfg is None or not dataclasses.is_dataclass(mcfg):
            raise ValueError(
                "scheduled tuning needs a model with a dataclass config "
                "(serialized into the job spec)")
        base = dataclasses.asdict(mcfg)
        for k in ("dtype", "param_dtype"):
            if k in base and not isinstance(base[k], str):
                base[k] = np.dtype(base[k]).name   # JSON-safe dtype name
        specs = []
        for exp in self.generate_experiments():
            mc = dict(base)
            mc.update(exp["model_kw"])
            specs.append({
                "cfg": exp["cfg"], "model_config": mc,
                "steps": steps or self.steps_per_trial,
                "seq": seq,
                "meta": {"mb": exp["mb"],
                         "zero_stage": exp["cfg"]["zero_optimization"]
                         ["stage"],
                         "offload": bool(exp["cfg"]["zero_optimization"]
                                         .get("offload_optimizer")),
                         **exp["model_kw"]}})
        return specs

    def tune_scheduled(self, workdir: str, slots: int = 1,
                       timeout_s: float = 600.0,
                       env: Optional[Dict[str, str]] = None,
                       seq: Optional[int] = None,
                       specs: Optional[List[Dict[str, Any]]] = None
                       ) -> Dict[str, Any]:
        """Reference `Autotuner.tune` (`autotuner.py:421`) semantics:
        experiments run as scheduler jobs with crash/timeout isolation
        and parallel slots; returns the best config and stores a ranked
        report in ``self.results`` (+ ``<workdir>/autotune_report.json``).
        """
        import json
        import os
        from .scheduler import ResourceManager
        specs = specs if specs is not None else self._make_specs(seq=seq)
        # smallest micro-batches first: cheap failures surface early
        order = sorted(range(len(specs)),
                       key=lambda i: specs[i]["meta"]["mb"])
        specs = [specs[i] for i in order]
        logger.info(f"scheduled autotuning: {len(specs)} jobs, "
                    f"{slots} slots, timeout {timeout_s}s")
        rm = ResourceManager(slots=slots, timeout_s=timeout_s, env=env)
        results = rm.run(specs, workdir)
        self.results = []
        for idx, (spec, res) in enumerate(zip(specs, results)):
            # spec_index pins the result row to its exact spec: meta-dict
            # matching could return a DIFFERENT config that shares the
            # same coarse meta (advisor r4, low)
            self.results.append({**spec["meta"], "spec_index": idx,
                                 "status": res["status"],
                                 "samples_per_sec": res.get(
                                     "samples_per_sec"),
                                 "detail": res.get("detail", "")})
        ranked = sorted((r for r in self.results
                         if r["samples_per_sec"] is not None),
                        key=lambda r: -r["samples_per_sec"])
        with open(os.path.join(workdir, "autotune_report.json"),
                  "w") as f:
            json.dump({"ranked": ranked, "all": self.results}, f,
                      indent=1)
        if not ranked:
            raise RuntimeError(
                "every scheduled autotuning experiment failed — see "
                f"{workdir}/autotune_report.json")
        best_meta = ranked[0]
        # the winning config is the MEASURED spec, recovered by index
        spec = specs[best_meta["spec_index"]]
        best = copy.deepcopy(spec["cfg"])
        kw = {k: v for k, v in best_meta.items()
              if k not in ("mb", "zero_stage", "offload", "status",
                           "samples_per_sec", "detail", "spec_index")}
        if kw:
            best["_model_overrides"] = kw
        logger.info(f"scheduled autotune best: {best_meta}")
        return best

    @staticmethod
    def apply_best(model, best_config: Dict[str, Any]):
        """Split tune()'s result into (model, engine_config): model-side
        winning knobs (remat/loss_chunk under "_model_overrides") are
        applied by rebuilding the model; the returned config is clean for
        ds.initialize. Skipping this and passing tune()'s raw dict keeps
        the ORIGINAL model settings and will not reproduce the measured
        throughput."""
        cfg = copy.deepcopy(best_config)
        overrides = cfg.pop("_model_overrides", None)
        if overrides:
            mcfg = getattr(model, "config", None)
            if mcfg is None:
                raise ValueError(
                    "best config carries model overrides but the model has "
                    "no dataclass config to apply them to")
            model = type(model)(dataclasses.replace(mcfg, **overrides),
                                getattr(model, "constrain", None))
        return model, cfg
