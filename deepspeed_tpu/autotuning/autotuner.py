"""Autotuner: config-space search over measured train steps.

Role-equivalent of the reference autotuner
(`/root/reference/deepspeed/autotuning/autotuner.py:421` Autotuner.tune,
tuners in `autotuning/tuner/`): generate experiments over the
(micro-batch, ZeRO-stage) space, run a few measured steps each, and pick
the fastest config. Redesign notes:

  - The reference schedules experiments as separate launcher jobs across
    nodes (ResourceManager); here each experiment is an engine build + a
    few steps in-process — on TPU the "job" boundary is just a new jit.
  - Tuner strategies: grid (exhaustive) and model_based (cost-model-
    pruned: skip configs whose predicted memory exceeds HBM), mirroring
    index_based/model_based tuners.
"""
from __future__ import annotations

import copy
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import logger

DEFAULT_MICRO_BATCHES = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 micro_batches: Sequence[int] = DEFAULT_MICRO_BATCHES,
                 zero_stages: Sequence[int] = DEFAULT_ZERO_STAGES,
                 steps_per_trial: int = 3, tuner_type: str = "model_based",
                 hbm_bytes: Optional[int] = None):
        self.model = model
        self.base_config = base_config
        self.micro_batches = list(micro_batches)
        self.zero_stages = list(zero_stages)
        self.steps_per_trial = steps_per_trial
        self.tuner_type = tuner_type
        self.hbm_bytes = hbm_bytes
        self.results: List[Dict[str, Any]] = []

    # -- experiment generation (reference exps generation) -----------------
    def generate_experiments(self) -> List[Dict[str, Any]]:
        exps = []
        for mb, stage in itertools.product(self.micro_batches,
                                           self.zero_stages):
            cfg = copy.deepcopy(self.base_config)
            cfg["train_micro_batch_size_per_gpu"] = mb
            cfg.pop("train_batch_size", None)
            cfg.setdefault("zero_optimization", {})["stage"] = stage
            exps.append(cfg)
        if self.tuner_type == "model_based":
            exps = [c for c in exps if self._predict_fits(c)]
        return exps

    def _predict_fits(self, cfg: Dict[str, Any]) -> bool:
        """Cost-model pruning (reference model_based_tuner): param + opt +
        activation memory estimate against HBM."""
        if self.hbm_bytes is None:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            self.hbm_bytes = stats.get("bytes_limit", 16 * 2 ** 30) or \
                16 * 2 ** 30
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            return True
        n = mcfg.num_params() if hasattr(mcfg, "num_params") else 0
        stage = cfg.get("zero_optimization", {}).get("stage", 0)
        import jax
        dp = max(jax.device_count(), 1) if stage else 1
        # bf16 params + f32 master/m/v (sharded by stage>=1) + grads
        state = n * 2 + (n * 12) / (dp if stage >= 1 else 1) + n * 4 / (
            dp if stage >= 2 else 1)
        mb = cfg.get("train_micro_batch_size_per_gpu", 1)
        acts = mb * mcfg.max_seq_len * mcfg.d_model * 2 * \
            (mcfg.num_layers * 4)
        return (state + acts) * 1.3 < self.hbm_bytes

    # -- measurement -------------------------------------------------------
    def _measure(self, cfg: Dict[str, Any],
                 batch_fn: Callable[[int], Dict]) -> Optional[float]:
        import deepspeed_tpu as ds
        try:
            engine, _, _, _ = ds.initialize(model=self.model,
                                            config=copy.deepcopy(cfg))
            batch = batch_fn(engine.train_batch_size)
            m = engine.train_step(batch)
            float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                m = engine.train_step(batch)
            float(m["loss"])
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return engine.train_batch_size / dt
        except Exception as e:
            logger.warning(f"autotune experiment failed "
                           f"(mb={cfg.get('train_micro_batch_size_per_gpu')}"
                           f", zero={cfg.get('zero_optimization')}): "
                           f"{type(e).__name__}: {str(e)[:120]}")
            return None

    def tune(self, batch_fn: Callable[[int], Dict]) -> Dict[str, Any]:
        """Run all experiments; return the best config (highest
        samples/sec). ``batch_fn(global_batch_size)`` supplies data."""
        exps = self.generate_experiments()
        logger.info(f"autotuning over {len(exps)} experiments")
        best, best_tput = None, -1.0
        for cfg in exps:
            tput = self._measure(cfg, batch_fn)
            self.results.append({
                "micro_batch": cfg.get("train_micro_batch_size_per_gpu"),
                "zero_stage": cfg["zero_optimization"]["stage"],
                "samples_per_sec": tput})
            if tput is not None and tput > best_tput:
                best, best_tput = cfg, tput
        if best is None:
            raise RuntimeError("every autotuning experiment failed")
        logger.info(
            f"autotune best: mb={best['train_micro_batch_size_per_gpu']} "
            f"zero={best['zero_optimization']['stage']} "
            f"({best_tput:.1f} samples/s)")
        return best
