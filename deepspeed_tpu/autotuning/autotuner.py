"""Autotuner: config-space search over measured train steps.

Role-equivalent of the reference autotuner
(`/root/reference/deepspeed/autotuning/autotuner.py:421` Autotuner.tune,
tuners in `autotuning/tuner/`): generate experiments over the tuning
space, run a few measured steps each, and pick the fastest config.
Redesign notes:

  - The reference schedules experiments as separate launcher jobs across
    nodes (ResourceManager); here each experiment is an engine build + a
    few steps in-process — on TPU the "job" boundary is just a new jit.
  - Tuner strategies: grid (exhaustive) and model_based (cost-model-
    pruned: skip configs whose predicted memory exceeds HBM), mirroring
    index_based/model_based tuners.
  - The space covers the knobs that actually move THIS framework's bench
    (VERDICT r2 weak #7): micro-batch x ZeRO stage x remat policy x
    loss-chunk x optimizer offload x offload wire-bits x mesh shape —
    where mesh shapes may be legacy (data, model) pairs or joint
    (pipe, model, data) 3D points, pruned by per-chip state bytes
    (params/dp-shard + optimizer moments + largest remat-window
    activation), stage divisibility and head/vocab divisibility. OOM
    failures are classified apart from real errors, and an OOM at
    micro-batch m prunes every larger micro-batch of the same
    (stage, remat, chunk, offload, bits, mesh) combination.
  - The winner can be exported per hardware profile
    (:meth:`Autotuner.export_best`) as a self-contained JSON the master
    ``DeepSpeedConfig`` parses directly: model-side knobs land in the
    ``training`` block, which the engine applies itself
    (``runtime/engine.py`` ``_apply_training_overrides``,
    docs/training_perf.md "Autotuner feedback loop").
"""
from __future__ import annotations

import copy
import dataclasses
import itertools
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils.logging import logger

DEFAULT_MICRO_BATCHES = (1, 2, 4, 8, 16, 32, 64)
DEFAULT_ZERO_STAGES = (0, 1, 2, 3)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "Attempting to allocate")


def _is_oom(exc: BaseException) -> bool:
    return any(m in str(exc) for m in _OOM_MARKERS)


def hardware_profile() -> str:
    """Stable key for "the hardware this search ran on": device kind x
    device count (e.g. ``tpu-v4-x8``, ``cpu-x1``). Best-config files are
    per-profile — a winner tuned behind one chip count is not evidence
    about another."""
    import jax
    d = jax.devices()[0]
    kind = str(getattr(d, "device_kind", "") or d.platform)
    kind = "".join(c if c.isalnum() else "-" for c in kind.lower())
    while "--" in kind:
        kind = kind.replace("--", "-")
    return f"{kind.strip('-')}-x{jax.device_count()}"


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 micro_batches: Sequence[int] = DEFAULT_MICRO_BATCHES,
                 zero_stages: Sequence[int] = DEFAULT_ZERO_STAGES,
                 remat_policies: Optional[Sequence[str]] = None,
                 loss_chunks: Optional[Sequence[int]] = None,
                 offload_options: Sequence[bool] = (False,),
                 offload_bits: Sequence[int] = (0,),
                 mesh_shapes: Optional[Sequence[Sequence[int]]] = None,
                 steps_per_trial: int = 3, tuner_type: str = "model_based",
                 hbm_bytes: Optional[int] = None):
        self.model = model
        self.base_config = base_config
        self.micro_batches = sorted(micro_batches)
        self.zero_stages = list(zero_stages)
        # model-side dims: None = keep the model's current setting
        self.remat_policies = list(remat_policies) if remat_policies \
            else [None]
        self.loss_chunks = list(loss_chunks) if loss_chunks else [None]
        self.offload_options = list(offload_options)
        # D2H wire compression for the offloaded-optimizer arm only
        # (zero_optimization.offload_wire_bits): a non-offload run has no
        # wire, so bits there would just duplicate experiments
        self.offload_bits = sorted(set(offload_bits)) or [0]
        # mesh shapes: 2-tuples are (data, model); 3-tuples are
        # (pipe, model, data) — the joint 3D search. None entries/default
        # = keep the base config's mesh. Infeasible shapes (device count,
        # stage/head/vocab divisibility, per-chip state bytes) are pruned
        # at generation time, not failed at measure time.
        self.mesh_shapes = ([tuple(m) for m in mesh_shapes]
                           if mesh_shapes else [None])
        self.steps_per_trial = steps_per_trial
        self.tuner_type = tuner_type
        self.hbm_bytes = hbm_bytes
        self.results: List[Dict[str, Any]] = []

    # -- experiment generation (reference exps generation) -----------------
    def generate_experiments(self) -> List[Dict[str, Any]]:
        # offload arms carry the wire-bits dim; the non-offload arm is a
        # single point (no wire to compress)
        arms = []
        for offload in self.offload_options:
            if offload:
                arms.extend((True, b) for b in self.offload_bits)
            else:
                arms.append((False, 0))
        meshes = self.mesh_shapes
        if any(m is not None for m in meshes):
            import jax
            ndev = jax.device_count()
            kept = [m for m in meshes if self._mesh_feasible(m, ndev)]
            if len(kept) < len(meshes):
                logger.info(
                    f"autotune: pruned "
                    f"{len(meshes) - len(kept)} infeasible mesh shape(s) "
                    f"(device count / stage / head / vocab divisibility "
                    f"on {ndev} device(s))")
            meshes = kept or [None]
        exps = []
        for mb, stage, remat, chunk, (offload, bits), mesh in \
                itertools.product(
                    self.micro_batches, self.zero_stages,
                    self.remat_policies, self.loss_chunks, arms, meshes):
            cfg = copy.deepcopy(self.base_config)
            cfg["train_micro_batch_size_per_gpu"] = mb
            cfg.pop("train_batch_size", None)
            cfg.setdefault("zero_optimization", {})["stage"] = stage
            if offload:
                cfg["zero_optimization"]["offload_optimizer"] = {
                    "device": "cpu"}
                if bits:
                    cfg["zero_optimization"]["offload_wire_bits"] = bits
                else:
                    cfg["zero_optimization"].pop("offload_wire_bits",
                                                 None)
            else:
                # the non-offload arm must actually BE non-offloaded even
                # when base_config carries an offload block
                cfg["zero_optimization"].pop("offload_optimizer", None)
                cfg["zero_optimization"].pop("offload_wire_bits", None)
            if mesh is not None:
                m = {**(cfg.get("mesh") or {})}
                if len(mesh) == 2:
                    m.update({"data": mesh[0], "model": mesh[1]})
                else:   # (pipe, model, data): the joint 3D point
                    m.update({"pipe": mesh[0], "model": mesh[1],
                              "data": mesh[2]})
                    if mesh[0] > 1:
                        # pin the pipeline block so the exported winner
                        # declares its stage count (ds.initialize
                        # cross-checks it against the mesh)
                        pl = dict(cfg.get("pipeline") or {})
                        pl.setdefault("stages", mesh[0])
                        cfg["pipeline"] = pl
                cfg["mesh"] = m
            model_kw = {}
            if remat is not None:
                model_kw["remat"] = remat
            if chunk is not None:
                model_kw["loss_chunk"] = chunk
            exps.append({"cfg": cfg, "model_kw": model_kw,
                         "key": (stage, remat, chunk, offload, bits,
                                 mesh),
                         "mb": mb, "wire_bits": bits, "mesh": mesh})
        if self.tuner_type == "model_based":
            exps = [e for e in exps
                    if self._predict_fits(e["cfg"], e["model_kw"])]
        return exps

    def _mesh_feasible(self, m, ndev: int) -> bool:
        """Generation-time shape pruning: device count plus the hard
        divisibility walls a (pipe, model, data) point would hit at engine
        build (stage count into the layer scan, model shards into heads
        and vocab) — pruned here so the grid never wastes a measured trial
        on a config that cannot construct."""
        if m is None:
            return True
        if len(m) == 2:                      # legacy (data, model)
            return m[0] * m[1] <= ndev
        pp, tp, dp = m
        if pp * tp * dp != ndev:
            # a fully explicit 3D shape must tile the device array exactly
            return False
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            return True
        layers = getattr(mcfg, "scan_length",
                         getattr(mcfg, "num_layers", 0)) or 0
        if pp > 1 and (not layers or layers % pp):
            return False
        if tp > 1:
            if getattr(mcfg, "vocab_size", 0) % tp:
                return False
            if getattr(mcfg, "num_heads", 0) % tp:
                return False
            kv = getattr(mcfg, "kv_heads", 0) or 0
            if kv % tp:
                return False
        return True

    def per_chip_state_bytes(self, cfg: Dict[str, Any],
                             model_kw: Optional[Dict[str, Any]] = None
                             ) -> Optional[int]:
        """Estimated resident bytes on ONE chip under this config's
        (pipe, model, data) placement — the quantity the model-based
        pruner compares to HBM. None when the model has no introspectable
        config. Terms:

          - compute params: bf16, sharded over pipe (stage slices) and
            model (TP column/row splits) → ``2n / (pp·tp)``;
          - f32 master + Adam moments: 12 bytes on the same param shard,
            further over ``data`` at ZeRO >= 1; zero on-chip when the
            optimizer is offloaded to host DRAM;
          - grads: 4 bytes on the param shard, over ``data`` at ZeRO >= 2
            (reduce-scatter layout);
          - activations: the largest remat window — with remat only the
            per-layer block inputs of the layers this chip owns plus one
            layer's working set stay live; without it ~4 tensors per
            layer — plus the 1F1B ring of <= pp+1 in-flight
            stage-boundary buffers when pipelined.
        """
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            return None
        import jax
        ndev = max(jax.device_count(), 1)
        mesh = cfg.get("mesh") or {}
        pp = max(int(mesh.get("pipe", 1)), 1)
        tp = max(int(mesh.get("model", 1)), 1)
        dp = int(mesh.get("data", -1))
        if dp <= 0:     # -1 absorbs the remaining devices
            dp = max(ndev // (pp * tp), 1)
        dp *= max(int(mesh.get("dcn_data", 1)), 1) \
            * max(int(mesh.get("expert", 1)), 1)
        n = mcfg.num_params() if hasattr(mcfg, "num_params") else 0
        n_local = n / (pp * tp)
        stage = cfg.get("zero_optimization", {}).get("stage", 0)
        offload = (cfg.get("zero_optimization", {})
                   .get("offload_optimizer") or {}).get("device") == "cpu"
        opt = 0 if offload else n_local * 12 / (dp if stage >= 1 else 1)
        state = n_local * 2 + opt + n_local * 4 / (dp if stage >= 2 else 1)
        mb = cfg.get("train_micro_batch_size_per_gpu", 1) or 1
        remat = (model_kw or {}).get("remat", getattr(mcfg, "remat", "none"))
        layers = max(1, -(-int(getattr(mcfg, "num_layers", 1)) // pp))
        eff_layers = (layers * 4 if remat in (None, "none") else layers + 4)
        act_unit = mb * mcfg.max_seq_len * mcfg.d_model * 2
        acts = act_unit * eff_layers
        if pp > 1:
            acts += act_unit * (pp + 1)
        return int(state + acts)

    def _predict_fits(self, cfg: Dict[str, Any],
                      model_kw: Optional[Dict[str, Any]] = None) -> bool:
        """Cost-model pruning (reference model_based_tuner): per-chip
        param + optimizer + remat-window activation bytes against HBM."""
        if self.hbm_bytes is None:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            self.hbm_bytes = stats.get("bytes_limit", 16 * 2 ** 30) or \
                16 * 2 ** 30
        per_chip = self.per_chip_state_bytes(cfg, model_kw)
        if per_chip is None:
            return True
        return per_chip * 1.3 < self.hbm_bytes

    def _build_model(self, model_kw: Dict[str, Any]):
        if not model_kw:
            return self.model
        mcfg = getattr(self.model, "config", None)
        if mcfg is None:
            raise ValueError(
                f"model-side tuning dims {list(model_kw)} need a model "
                f"with a dataclass config (got {type(self.model).__name__})")
        return type(self.model)(dataclasses.replace(mcfg, **model_kw),
                                getattr(self.model, "constrain", None))

    # -- measurement -------------------------------------------------------
    def _measure(self, exp: Dict[str, Any],
                 batch_fn: Callable[[int], Dict]):
        """→ (samples_per_sec | None, status in ok|oom|error)."""
        import deepspeed_tpu as ds
        cfg = exp["cfg"]
        try:
            model = self._build_model(exp["model_kw"])
            engine, _, _, _ = ds.initialize(model=model,
                                            config=copy.deepcopy(cfg))
            batch = batch_fn(engine.train_batch_size)
            m = engine.train_step(batch)
            float(m["loss"])
            t0 = time.perf_counter()
            for _ in range(self.steps_per_trial):
                m = engine.train_step(batch)
            float(m["loss"])
            dt = (time.perf_counter() - t0) / self.steps_per_trial
            return engine.train_batch_size / dt, "ok"
        except Exception as e:
            status = "oom" if _is_oom(e) else "error"
            log = logger.warning if status == "error" else logger.info
            log(f"autotune experiment {status} "
                f"(mb={cfg.get('train_micro_batch_size_per_gpu')}, "
                f"zero={cfg.get('zero_optimization', {}).get('stage')}, "
                f"model_kw={exp['model_kw']}): "
                f"{type(e).__name__}: {str(e)[:120]}")
            return None, status

    def tune(self, batch_fn: Callable[[int], Dict]) -> Dict[str, Any]:
        """Run all experiments; return the best config (highest
        samples/sec). ``batch_fn(global_batch_size)`` supplies data."""
        exps = self.generate_experiments()
        logger.info(f"autotuning over {len(exps)} experiments")
        best, best_tput, best_kw = None, -1.0, {}
        oom_floor: Dict[Any, int] = {}   # combo key -> smallest OOM mb
        for exp in exps:
            key, mb = exp["key"], exp["mb"]
            if key in oom_floor and mb >= oom_floor[key]:
                status, tput = "pruned_oom", None
            else:
                tput, status = self._measure(exp, batch_fn)
                if status == "oom":
                    oom_floor[key] = min(mb, oom_floor.get(key, mb))
            self.results.append({
                "micro_batch": mb,
                "zero_stage": exp["cfg"]["zero_optimization"]["stage"],
                **exp["model_kw"],
                "offload": bool(exp["cfg"]["zero_optimization"].get(
                    "offload_optimizer")),
                "wire_bits": exp.get("wire_bits", 0),
                "mesh": list(exp["mesh"]) if exp.get("mesh") else None,
                "status": status,
                "samples_per_sec": tput})
            if tput is not None and tput > best_tput:
                best, best_tput, best_kw = exp["cfg"], tput, exp["model_kw"]
        if best is None:
            raise RuntimeError("every autotuning experiment failed")
        logger.info(
            f"autotune best: mb={best['train_micro_batch_size_per_gpu']} "
            f"zero={best['zero_optimization']['stage']} "
            f"model_kw={best_kw} ({best_tput:.1f} samples/s)")
        best = copy.deepcopy(best)
        if best_kw:
            best["_model_overrides"] = dict(best_kw)
        return best

    # -- scheduled (subprocess) tuning -------------------------------------
    def _make_specs(self, seq: Optional[int] = None,
                    steps: Optional[int] = None,
                    profile_phases: bool = False) -> List[Dict[str, Any]]:
        """Job specs for the experiment scheduler: the in-process
        model-based pruner stays the PROPOSAL stage; measurement moves to
        isolated subprocesses."""
        mcfg = getattr(self.model, "config", None)
        if mcfg is None or not dataclasses.is_dataclass(mcfg):
            raise ValueError(
                "scheduled tuning needs a model with a dataclass config "
                "(serialized into the job spec)")
        base = dataclasses.asdict(mcfg)
        for k in ("dtype", "param_dtype"):
            if k in base and not isinstance(base[k], str):
                base[k] = np.dtype(base[k]).name   # JSON-safe dtype name
        specs = []
        for exp in self.generate_experiments():
            mc = dict(base)
            mc.update(exp["model_kw"])
            specs.append({
                "cfg": exp["cfg"], "model_config": mc,
                "steps": steps or self.steps_per_trial,
                "seq": seq,
                "profile_phases": bool(profile_phases),
                "meta": {"mb": exp["mb"],
                         "zero_stage": exp["cfg"]["zero_optimization"]
                         ["stage"],
                         "offload": bool(exp["cfg"]["zero_optimization"]
                                         .get("offload_optimizer")),
                         "wire_bits": exp.get("wire_bits", 0),
                         "mesh": (list(exp["mesh"]) if exp.get("mesh")
                                  else None),
                         **exp["model_kw"]}})
        return specs

    def tune_scheduled(self, workdir: str, slots: int = 1,
                       timeout_s: float = 600.0,
                       env: Optional[Dict[str, str]] = None,
                       seq: Optional[int] = None,
                       specs: Optional[List[Dict[str, Any]]] = None,
                       profile_phases: bool = False
                       ) -> Dict[str, Any]:
        """Reference `Autotuner.tune` (`autotuner.py:421`) semantics:
        experiments run as scheduler jobs with crash/timeout isolation
        and parallel slots; returns the best config and stores a ranked
        report in ``self.results`` (+ ``<workdir>/autotune_report.json``).
        """
        import json
        import os
        from .scheduler import ResourceManager
        specs = specs if specs is not None else self._make_specs(
            seq=seq, profile_phases=profile_phases)
        # smallest micro-batches first: cheap failures surface early
        order = sorted(range(len(specs)),
                       key=lambda i: specs[i]["meta"]["mb"])
        specs = [specs[i] for i in order]
        logger.info(f"scheduled autotuning: {len(specs)} jobs, "
                    f"{slots} slots, timeout {timeout_s}s")
        rm = ResourceManager(slots=slots, timeout_s=timeout_s, env=env)
        results = rm.run(specs, workdir)
        self.results = []
        for idx, (spec, res) in enumerate(zip(specs, results)):
            # spec_index pins the result row to its exact spec: meta-dict
            # matching could return a DIFFERENT config that shares the
            # same coarse meta (advisor r4, low)
            row = {**spec["meta"], "spec_index": idx,
                   "status": res["status"],
                   "samples_per_sec": res.get("samples_per_sec"),
                   "detail": res.get("detail", "")}
            if res.get("phases"):   # optional per-phase profile
                row["phases"] = res["phases"]
            self.results.append(row)
        ranked = sorted((r for r in self.results
                         if r["samples_per_sec"] is not None),
                        key=lambda r: -r["samples_per_sec"])
        with open(os.path.join(workdir, "autotune_report.json"),
                  "w") as f:
            json.dump({"ranked": ranked, "all": self.results}, f,
                      indent=1)
        if not ranked:
            raise RuntimeError(
                "every scheduled autotuning experiment failed — see "
                f"{workdir}/autotune_report.json")
        best_meta = ranked[0]
        # the winning config is the MEASURED spec, recovered by index
        spec = specs[best_meta["spec_index"]]
        best = copy.deepcopy(spec["cfg"])
        # config-side dims (wire_bits, mesh) already live inside the
        # spec's cfg — only MODEL-side knobs become overrides
        kw = {k: v for k, v in best_meta.items()
              if k not in ("mb", "zero_stage", "offload", "wire_bits",
                           "mesh", "status", "samples_per_sec", "detail",
                           "spec_index", "phases")}
        if kw:
            best["_model_overrides"] = kw
        logger.info(f"scheduled autotune best: {best_meta}")
        return best

    @staticmethod
    def apply_best(model, best_config: Dict[str, Any]):
        """Split tune()'s result into (model, engine_config): model-side
        winning knobs (remat/loss_chunk under "_model_overrides") are
        applied by rebuilding the model; the returned config is clean for
        ds.initialize. Skipping this and passing tune()'s raw dict keeps
        the ORIGINAL model settings and will not reproduce the measured
        throughput."""
        cfg = copy.deepcopy(best_config)
        overrides = cfg.pop("_model_overrides", None)
        if overrides:
            mcfg = getattr(model, "config", None)
            if mcfg is None:
                raise ValueError(
                    "best config carries model overrides but the model has "
                    "no dataclass config to apply them to")
            model = type(model)(dataclasses.replace(mcfg, **overrides),
                                getattr(model, "constrain", None))
        return model, cfg

    @staticmethod
    def export_best(best_config: Dict[str, Any],
                    path: Optional[str] = None,
                    profile: Optional[str] = None):
        """Emit the winner as a self-contained per-hardware-profile JSON.

        The model-side winners (``remat`` / ``loss_chunk`` /
        ``fused_loss_head`` under ``_model_overrides``) move into the
        master config's ``training`` block, which the engine applies by
        rebuilding the model itself (``runtime/engine.py``
        ``_apply_training_overrides``) — the exported file feeds
        ``DeepSpeedConfig`` / ``ds.initialize`` directly, no
        :meth:`apply_best` step for the consumer. ``autotune_profile``
        records the hardware the search ran on (:func:`hardware_profile`)
        so best files for different chip counts coexist; it is metadata
        the config parser tolerates and ignores.

        ``path`` None → ``autotune_best_<profile>.json`` in the CWD; a
        directory → that file inside it. Returns ``(config, path)``.
        """
        import json
        import os
        cfg = copy.deepcopy(best_config)
        overrides = dict(cfg.pop("_model_overrides", None) or {})
        training = dict(cfg.get("training") or {})
        for k in ("remat", "loss_chunk", "fused_loss_head"):
            if k in overrides:
                training[k] = overrides.pop(k)
        if training:
            cfg["training"] = training
        if overrides:
            # knobs the training block cannot carry stay model overrides
            # for an explicit apply_best by the consumer
            cfg["_model_overrides"] = overrides
        prof = profile or hardware_profile()
        cfg["autotune_profile"] = prof
        if path is None:
            path = f"autotune_best_{prof}.json"
        elif os.path.isdir(path):
            path = os.path.join(path, f"autotune_best_{prof}.json")
        with open(path, "w") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)
        logger.info(f"autotune best config for {prof} -> {path}")
        return cfg, path
