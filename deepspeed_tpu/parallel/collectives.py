"""Exact-gradient collectives for manual shard_map regions.

The training-side tensor-parallel seam. Serving TP (inference/serving)
runs forward-only and uses raw ``lax.psum`` on block outputs; training
needs the *pair* of Megatron's conjugate operators so hand-driven
``jax.vjp`` chains (the 1F1B pipeline backward) and in-region autodiff
(the gpipe backward) both produce exact gradients under the legacy
fully-manual degradation of ``shard_map_compat`` (where every shard's
loss cotangent is seeded identically and a raw psum's transpose would
over-count replicated compute by the shard count):

  - :func:`copy_to` — Megatron's ``f``: identity forward, psum backward.
    Placed where a replicated tensor enters shard-local compute (the
    attention/MLP branch inputs, the vocab-projection input); the
    backward psum reassembles the full cotangent from per-shard partials.
  - :func:`reduce_from` — Megatron's ``g``: psum forward, identity
    backward. Placed where per-shard partial outputs rejoin the
    replicated stream (row-parallel matmul outputs, the vocab-parallel
    softmax statistics); the backward hands each shard the full
    cotangent unchanged — NOT the summed transpose a raw psum would
    apply.

Gradient calculus under this convention (validated to ~1e-7 against a
single-device reference on the 8-virtual-device CPU mesh):

  - model-sharded kernels (column/row splits, vocab-sharded embeddings)
    get EXACT shard-local gradients — no exit collective;
  - leaves consumed on the replicated stream (layernorms, positional
    embeddings applied after the embed psum) get FULL gradients on every
    shard — no exit collective;
  - replicated leaves consumed INSIDE a reduced term (the fused qkv
    kernel/bias entering via per-shard column gather, row-parallel
    output biases pre-divided by the shard count) get PARTIAL gradients
    — one exit psum over ``model`` (:func:`psum_tp_partials`) restores
    them.

The data axis composes on top: gradients leave the region through one
psum — or a ZeRO-2 reduce-scatter (:func:`reduce_over_data`) — over the
data-parallel axis product.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Sequence, Tuple, Union

import jax
import numpy as np

AxisName = Union[str, Tuple[str, ...]]

# gradient-reduce plan codes (grad_reduce_plan leaves must be pytree
# LEAVES so the plan tree zips against the grads tree): -1 = all-reduce,
# d >= 0 = reduce-scatter along dim d into the ZeRO-2 grad layout
REDUCE_PSUM = -1


@lru_cache(maxsize=None)
def copy_to(axis: AxisName):
    """Megatron ``f``: identity forward, psum-over-``axis`` backward."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (jax.lax.psum(g, axis),)

    f.defvjp(fwd, bwd)
    return f


@lru_cache(maxsize=None)
def reduce_from(axis: AxisName):
    """Megatron ``g``: psum-over-``axis`` forward, identity backward."""

    @jax.custom_vjp
    def f(x):
        return jax.lax.psum(x, axis)

    def fwd(x):
        return jax.lax.psum(x, axis), None

    def bwd(_, g):
        return (g,)

    f.defvjp(fwd, bwd)
    return f


# Transformer-block leaves whose gradients are PARTIAL per model shard
# under the copy_to/reduce_from convention (keyed on the trailing
# (module, weight) path pair, same addressing as the model's
# _SUFFIX_RULES): the fused qkv enters the region replicated and each
# shard gathers its own permuted columns (gradients are zero off-shard),
# and the row-parallel output biases are pre-divided by the shard count
# inside the reduced term.
TP_PARTIAL_SUFFIXES = frozenset({
    ("qkv", "kernel"), ("qkv", "bias"),
    ("out", "bias"), ("fc_out", "bias"),
})


def psum_tp_partials(tree, axis: AxisName):
    """Exit psum over the model axis for the partial-gradient leaf set."""
    def f(path, leaf):
        keys = tuple(getattr(p, "key", None) for p in path)
        if keys[-2:] in TP_PARTIAL_SUFFIXES:
            return jax.lax.psum(leaf, axis)
        return leaf
    return jax.tree_util.tree_map_with_path(f, tree)


def qkv_shard_columns(num_heads: int, num_kv_heads: int, head_dim: int,
                      model_shards: int) -> np.ndarray:
    """[model_shards, qkv_dim // model_shards] column indices: row ``s``
    is shard ``s``'s fused-qkv layout ``[q_s | k_s | v_s]`` drawn from
    the global ``[q(nh*hd) | k(nkv*hd) | v(nkv*hd)]`` packing.

    The fused qkv axis cannot tile contiguously over ``model`` (a plain
    split would hand shard 0 only q heads), so training regions take the
    kernel/bias in REPLICATED and gather these columns per shard inside
    the differentiated function — the gather's vjp scatters the partial
    gradients back into global layout, and the exit psum over ``model``
    (:func:`psum_tp_partials`) assembles them.  Same permutation math as
    serving's host-side ``_tp_qkv_perm`` prep, reshaped per shard."""
    nhl = num_heads // model_shards
    nkvl = num_kv_heads // model_shards
    rows = []
    for s in range(model_shards):
        rows.append(np.concatenate([
            np.arange(s * nhl * head_dim, (s + 1) * nhl * head_dim),
            num_heads * head_dim
            + np.arange(s * nkvl * head_dim, (s + 1) * nkvl * head_dim),
            (num_heads + num_kv_heads) * head_dim
            + np.arange(s * nkvl * head_dim, (s + 1) * nkvl * head_dim)]))
    return np.stack(rows).astype(np.int32)


def reduce_over_data(g, plan: int, data_axes: Sequence[str]):
    """Reduce one gradient leaf over the data-parallel axis product.

    ``plan`` (an int leaf from ``zero/sharding.grad_reduce_plan``):
    REDUCE_PSUM → all-reduce; ``d >= 0`` → ``psum_scatter`` along dim
    ``d``, landing the leaf directly in the ZeRO-2 sharded grad layout
    (the reference's reduce-scatter IPG path, stage_1_and_2.py:942)."""
    axes = tuple(data_axes)
    if not axes:
        return g
    if plan >= 0:
        return jax.lax.psum_scatter(g, axes, scatter_dimension=plan,
                                    tiled=True)
    return jax.lax.psum(g, axes)
