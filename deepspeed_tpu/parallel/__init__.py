"""3-axis parallel substrate: one mesh, three collective families.

The ``(pipe, model, data)`` product lives here — topology (the only
Mesh() owner in the tree, enforced by dstpu-lint MESH003), the
version-compat ``shard_map`` wrapper every manual region goes through,
and the exact-gradient collective pair (Megatron's f/g operators) the
3D training region is built from. The composition invariant: each
collective family owns one axis — ``ppermute`` moves stage-boundary
activations on ``pipe``, per-layer TP ``psum``s stay on ``model``, and
the gradient reduce(-scatter) stays on ``data`` — so no two families
ever contend for the same links.
"""
from .collectives import (REDUCE_PSUM, TP_PARTIAL_SUFFIXES, copy_to,
                          psum_tp_partials, qkv_shard_columns, reduce_from,
                          reduce_over_data)
from .shard_map_compat import shard_map
from .topology import (AXIS_ORDER, DATA_AXIS, DCN_DATA_AXIS, EXPERT_AXIS,
                       MODEL_AXIS, PIPE_AXIS, SEQUENCE_AXIS, MeshSpec,
                       PipeModelDataParallelTopology, ProcessTopology,
                       batch_sharding, build_mesh, dp_world_size,
                       mesh_topology, mp_world_size, named_sharding,
                       pp_world_size, replicated, resolve_mesh_spec)

__all__ = [
    "AXIS_ORDER", "DATA_AXIS", "DCN_DATA_AXIS", "EXPERT_AXIS",
    "MODEL_AXIS", "PIPE_AXIS", "SEQUENCE_AXIS", "MeshSpec",
    "PipeModelDataParallelTopology", "ProcessTopology", "REDUCE_PSUM",
    "TP_PARTIAL_SUFFIXES", "batch_sharding", "build_mesh", "copy_to",
    "dp_world_size", "mesh_topology", "mp_world_size", "named_sharding",
    "pp_world_size", "psum_tp_partials", "qkv_shard_columns",
    "reduce_from", "reduce_over_data", "replicated", "resolve_mesh_spec",
    "shard_map",
]
