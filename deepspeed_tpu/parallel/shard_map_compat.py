"""Version portability for ``shard_map`` (the ``pallas_compat`` of SPMD).

jax moved ``shard_map`` from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` and, in the same arc, replaced the
``auto=frozenset(...)`` parameter (axes NOT handled manually) with
``axis_names={...}`` (axes that ARE manual) and ``check_rep`` with
``check_vma``.  Depending on the pinned jax exactly one spelling works:
0.4.x ships only the experimental module, current jax only the
top-level form.  Calling ``jax.shard_map(...)`` directly therefore
raises ``AttributeError`` on 0.4.x — the same failure mode as the
``pltpu.TPUCompilerParams`` rename, and the one that silently broke
``ring_attention``/``ulysses_attention`` under the repo's CI jax.

All in-tree shard_map call sites route through :func:`shard_map` below
(``dstpu-lint`` MESH004 enforces this); new ones should too.  The
wrapper speaks the NEW vocabulary (``axis_names`` = manual axes,
``check`` = the rep/vma consistency check) and translates down when
needed.
"""
from __future__ import annotations

from typing import Iterable, Optional

import jax


def shard_map(f, mesh, in_specs, out_specs,
              axis_names: Optional[Iterable[str]] = None,
              check: bool = False):
    """``jax.shard_map`` under whichever API this jax exports.

    ``axis_names``: the mesh axes the function is MANUAL over (the rest
    stay GSPMD-auto); ``None`` — the ``jax.shard_map`` default — means
    manual over every mesh axis.  ``check``: the replication/VMA
    consistency check (``check_vma`` / ``check_rep`` depending on the
    jax generation) — off by default, matching every in-tree call site.

    Legacy degradation: when ``axis_names`` is a strict subset, 0.4.x
    is asked for the partial-manual form it cannot fully deliver —
    ``auto=`` regions there cannot lower ``axis_index``/``ppermute``
    ("PartitionId ... is not supported for SPMD partitioning"), which
    every in-tree partial-manual body uses.  So the legacy path always
    goes FULLY manual: axes the specs do not mention are replicated
    inside the region (same math, replicated compute over those axes).
    Sharding-constraint hints over those axes are dropped inside the
    region by ``zero/sharding.py constrain`` for the same reason.
    Current jax keeps the efficient partial-manual form.
    """
    manual = frozenset(mesh.axis_names if axis_names is None
                       else axis_names)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, axis_names=set(manual),
                             check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check,
                      auto=frozenset())
