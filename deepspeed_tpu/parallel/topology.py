"""Device mesh & process topology.

TPU-native replacement for the reference's process-group layer
(`/root/reference/deepspeed/utils/groups.py`,
`/root/reference/deepspeed/runtime/pipe/topology.py:9` ``ProcessTopology`` /
``:243`` ``PipeModelDataParallelTopology`` / ``:249``
``PipelineParallelGrid``): instead of building NCCL process groups per
parallel dimension, we build ONE `jax.sharding.Mesh` with named axes and
express every form of parallelism as sharding over those axes.

Axis names (canonical order, outermost → innermost):
    dcn_data — replicas across slices (DCN); collectives here are expensive
    pipe     — pipeline stages (ppermute ring)
    data     — data parallel / ZeRO sharding axis
    expert   — MoE expert parallel (usually folded into data)
    sequence — context parallelism (ring attention axis)
    model    — tensor parallel; innermost so its collectives ride ICI
               neighbors
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dcn_data", "pipe", "data", "expert", "sequence", "model")

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"
SEQUENCE_AXIS = "sequence"
DCN_DATA_AXIS = "dcn_data"


@dataclass(frozen=True)
class MeshSpec:
    """Resolved axis sizes. Product must equal device count."""
    dcn_data: int = 1
    pipe: int = 1
    data: int = 1
    expert: int = 1
    sequence: int = 1
    model: int = 1

    @property
    def sizes(self) -> Tuple[int, ...]:
        return tuple(getattr(self, a) for a in AXIS_ORDER)

    @property
    def world_size(self) -> int:
        return int(np.prod(self.sizes))


def resolve_mesh_spec(mesh_config, n_devices: int) -> MeshSpec:
    """Resolve -1 ("absorb remaining devices") axis sizes against n_devices."""
    sizes = {a: getattr(mesh_config, a, 1) for a in AXIS_ORDER}
    wild = [a for a, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wild}")
    fixed = int(np.prod([s for s in sizes.values() if s != -1]))
    if wild:
        if n_devices % fixed != 0:
            raise ValueError(
                f"Device count {n_devices} not divisible by fixed axes {fixed}")
        sizes[wild[0]] = n_devices // fixed
    spec = MeshSpec(**sizes)
    if spec.world_size != n_devices:
        raise ValueError(
            f"Mesh {sizes} covers {spec.world_size} devices, have {n_devices}")
    return spec


def build_mesh(mesh_config=None, devices: Optional[Sequence] = None) -> Mesh:
    """Build the global named mesh.

    Device order: `jax.devices()` on TPU enumerates chips so that adjacent
    indices are ICI neighbors; keeping ``model`` innermost gives TP the
    shortest links, then ``sequence``, etc. Multi-slice (dcn_data > 1) relies
    on devices being grouped by slice in the enumeration, which
    `jax.devices()` guarantees (slice-major order).
    """
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    if mesh_config is None:
        spec = MeshSpec(data=len(devices))
    else:
        spec = resolve_mesh_spec(mesh_config, len(devices))
    dev_array = devices.reshape(spec.sizes)
    return Mesh(dev_array, AXIS_ORDER)


class ProcessTopology:
    """Rank ↔ named-coordinate mapping over arbitrary axes.

    Same contract as the reference ``ProcessTopology``
    (`runtime/pipe/topology.py:9`): axes are named, ranks enumerate in
    row-major order of the axis list, and you can query coordinates, filter
    ranks by fixed coordinates, and list ranks along one axis. Used by the
    checkpoint-reshape library and the pipeline grid; at runtime the Mesh is
    authoritative.
    """

    def __init__(self, axes: List[str], dims: List[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have the same length")
        self.axes = list(axes)
        self.dims = list(dims)
        self._coord_to_rank: Dict[Tuple[int, ...], int] = {}
        for rank, coord in enumerate(itertools.product(*(range(d) for d in dims))):
            self._coord_to_rank[coord] = rank
        self._rank_to_coord = {r: c for c, r in self._coord_to_rank.items()}

    @property
    def world_size(self) -> int:
        return int(np.prod(self.dims))

    def get_rank(self, **coords) -> int:
        self._check_axes(coords)
        coord = tuple(coords[a] for a in self.axes)
        return self._coord_to_rank[coord]

    def get_coord(self, rank: int):
        coord = self._rank_to_coord[rank]
        return dict(zip(self.axes, coord))

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def get_axis_list(self, axis: str, idx: int) -> List[int]:
        """All ranks whose coordinate on `axis` equals idx."""
        ai = self.axes.index(axis)
        return sorted(r for c, r in self._coord_to_rank.items() if c[ai] == idx)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Groups of ranks that differ only along `axis` (the reference's
        process-group builder, `topology.py:188`)."""
        ai = self.axes.index(axis)
        groups: Dict[Tuple[int, ...], List[int]] = {}
        for coord, rank in sorted(self._coord_to_rank.items(), key=lambda kv: kv[1]):
            key = coord[:ai] + coord[ai + 1:]
            groups.setdefault(key, []).append(rank)
        return [sorted(g) for g in groups.values()]

    def filter_match(self, **filter_kwargs) -> List[int]:
        self._check_axes(filter_kwargs)
        out = []
        for coord, rank in self._coord_to_rank.items():
            d = dict(zip(self.axes, coord))
            if all(d[k] == v for k, v in filter_kwargs.items()):
                out.append(rank)
        return sorted(out)

    def _check_axes(self, coords) -> None:
        unknown = set(coords) - set(self.axes)
        if unknown:
            raise ValueError(f"Unknown axes {unknown}; have {self.axes}")

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeModelDataParallelTopology(ProcessTopology):
    """3D (pipe, data, model) topology — reference `topology.py:243`."""

    def __init__(self, num_pp: int, num_dp: int, num_mp: int):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


def mesh_topology(mesh: Mesh) -> ProcessTopology:
    """Derive a ProcessTopology from a Mesh (axes with size>1 only)."""
    axes = [a for a in mesh.axis_names if mesh.shape[a] > 1] or ["data"]
    dims = [mesh.shape[a] for a in axes]
    return ProcessTopology(axes, dims)


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------
def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Input batches shard over every data-like axis (pipe does NOT shard the
    batch — microbatching handles it)."""
    batch_axes = tuple(a for a in (DCN_DATA_AXIS, DATA_AXIS, EXPERT_AXIS)
                       if mesh.shape.get(a, 1) > 1)
    if not batch_axes:
        return replicated(mesh)
    return NamedSharding(mesh, P(batch_axes))


def dp_world_size(mesh: Mesh) -> int:
    return (mesh.shape.get(DATA_AXIS, 1) * mesh.shape.get(DCN_DATA_AXIS, 1)
            * mesh.shape.get(EXPERT_AXIS, 1))


def mp_world_size(mesh: Mesh) -> int:
    return mesh.shape.get(MODEL_AXIS, 1)


def pp_world_size(mesh: Mesh) -> int:
    return mesh.shape.get(PIPE_AXIS, 1)
