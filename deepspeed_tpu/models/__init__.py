from .transformer import (TransformerConfig, TransformerLM,  # noqa: F401
                          gpt2_config, neox_config)
