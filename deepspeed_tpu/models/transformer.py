"""Decoder-only transformer LM family (GPT-2 / GPT-NeoX style).

The flagship model family of the framework — the role the reference's fused
transformer layer + model zoo plays (`/root/reference/csrc/transformer/`,
`/root/reference/deepspeed/model_implementations/transformers/`), designed
TPU-first:

  - **scan over stacked layer params**: all blocks share one set of weights
    stacked on a leading ``L`` axis and run under `lax.scan`. One compiled
    block instead of L inlined copies (fast compiles), a natural remat
    boundary, and the unit at which ZeRO-3 gathers/releases params.
  - **remat policy** per config (`jax.checkpoint`) replaces the reference's
    activation-checkpointing reimplementation
    (`runtime/activation_checkpointing/checkpointing.py:498`).
  - **partition rules** produce a params-shaped PartitionSpec tree (TP over
    the ``model`` axis; ZeRO transforms these further over ``data``).
  - fp32 softmax/layernorm islands inside a bf16 activation stream — the same
    numeric contract as the reference's CUDA kernels.

Variants: ``gpt2`` (learned positions, serial residual), ``neox`` (rotary,
parallel residual — GPT-NeoX-20B architecture, the BASELINE.json 1.3B/20B
target family).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import layers as L


class PagedKVCache(NamedTuple):
    """One transformer layer's slice of the paged serving KV state —
    the marker type ``_attention`` dispatches on for the
    continuous-batching decode path (``inference/serving/``).

      k_pool / v_pool  [num_blocks, block, kv_heads, head_dim] — or,
                       with a quantized KV cache
                       (``serving.kv_cache_bits``), int8 pools at
                       ``head_dim`` (8-bit) / ``head_dim // 2``
                       (packed 4-bit) width
      block_tables     [B, pages] int32 (pool block ids; tail entries
                       hold the reserved null block 0)
      lens             [B] int32 — tokens ALREADY in the cache per slot
                       (the new token writes at position ``lens``;
                       0 = inactive slot)
      k_scale / v_scale  [num_blocks, block, kv_heads] f32 per-row
                       per-head dequant scales (None = bf16/f32 pools)
    """
    k_pool: Any
    v_pool: Any
    block_tables: Any
    lens: Any
    k_scale: Any = None
    v_scale: Any = None


class PagedMixedState(NamedTuple):
    """Paged serving state for the MIXED decode+chunked-prefill step
    (Sarathi-Serve style) — ``_attention`` dispatches on it when the
    serving engine coalesces one prompt chunk with the live decode
    slots into a single compiled program.

    On top of :class:`PagedKVCache`'s pool/tables/lens:

      dec_active   [B] int32 — 1 for slots decoding this iteration
                   (prefilling and empty slots are 0: their row of the
                   token batch is ignored and their KV write re-routes
                   to the null block)
      chunk_slot   int32 scalar — slot whose prompt chunk rides this
                   step (any value when chunk_len == 0)
      chunk_start  int32 scalar — absolute row of the chunk's first
                   token (== rows already present for that slot)
      chunk_len    int32 scalar — valid chunk tokens (0 = no prefill
                   work this dispatch)
      tables_g     [S, pages] int32 — the GLOBAL block tables when the
                   decode slots are sharded over the ``data`` mesh axis
                   (``block_tables``/``lens`` then hold this shard's
                   slot rows only, while ``chunk_slot`` stays a global
                   slot id); None on the single-shard path
      spec_active  [B] int32 — 1 for slots VERIFYING a speculative
                   draft run this iteration (the third lane of the
                   mixed step, docs/serving.md "Speculative decoding");
                   a speculating slot rides the spec rows INSTEAD of
                   the decode lane (its ``dec_active`` is 0).  None
                   when ``spec_width`` is 0.
      spec_width   static Python int — rows per slot in the spec lane
                   (draft length k + 1); 0 = no spec lane, the
                   pre-speculation program byte-identical
      k_scale / v_scale  per-row per-head dequant scales (see
                   :class:`PagedKVCache`; None = unquantized pools)
    """
    k_pool: Any
    v_pool: Any
    block_tables: Any
    lens: Any
    dec_active: Any
    chunk_slot: Any
    chunk_start: Any
    chunk_len: Any
    tables_g: Any = None
    spec_active: Any = None
    spec_width: int = 0
    k_scale: Any = None
    v_scale: Any = None


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50304
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    # grouped-query attention: kv heads < query heads (LLaMA-2/3 70B
    # family); 0 = MHA (kv heads == num_heads)
    num_kv_heads: int = 0
    d_model: int = 768
    d_ff: int = 0                      # 0 → 4 * d_model
    head_dim: int = 0                  # 0 → d_model // num_heads
    pos_embedding: str = "learned"     # learned | rotary | alibi | none
    # decoder (causal) vs encoder (bidirectional — the BERT family)
    causal: bool = True
    # pre-norm (GPT family: x + f(ln(x))) vs post-norm (BERT family:
    # ln(x + f(x)))
    norm_position: str = "pre"
    # final norm after the block stack (BERT has none)
    final_layernorm: bool = True
    # BLOOM-style layernorm on the embedding output (params["ln_embed"])
    embed_layernorm: bool = False
    # BERT token-type (segment) embeddings; 0 = none
    token_type_vocab: int = 0
    # BERT MLM prediction head: dense+act+LN transform before the tied
    # decoder, plus a decoder bias (params["mlm_head"])
    mlm_head: bool = False
    rotary_pct: float = 1.0
    rotary_base: float = 10000.0
    # True = GPT-J "rotate_every_two" pairing (the pre-existing default —
    # checkpoints trained before this knob keep their convention);
    # False = NeoX-family "rotate_half" (set by neox_config / HF import)
    rotary_interleaved: bool = True
    parallel_residual: bool = False    # NeoX-style x + attn(ln1 x) + mlp(ln2 x)
    norm_type: str = "layernorm"       # layernorm | rmsnorm
    activation: str = "gelu"
    # gated MLP (SwiGLU — the LLaMA family): act(gate(x)) * up(x) -> down;
    # adds a "fc_gate" kernel per block
    gated_mlp: bool = False
    use_bias: bool = True
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16          # activation dtype
    param_dtype: Any = jnp.float32
    remat: str = "none"                # none | full | dots_saveable | nothing_saveable
    attn_impl: str = "xla"     # xla | flash | ring | ulysses | blocksparse
    # attn_impl="blocksparse": an ops.sparse_attention.SparsityConfig
    # (Fixed/LocalSlidingWindow/BigBird/BSLongformer/Variable) — the layout
    # drives the Pallas block-sparse flash kernel
    # (ops/sparse_attention/blocksparse_flash.py)
    sparsity_config: Any = None
    # activation quantization seam (compression/compress.py
    # init_compression_model): fake-quantize the inputs of the qkv and
    # fc_in projections with STE. 0 = off.
    act_quant_bits: int = 0
    act_quant_symmetric: bool = False
    # static calibrated ranges (attn_in, mlp_in absmax) — empty = dynamic
    # per-tensor ranges. Per-SITE, shared across layers: the scanned block
    # compiles once for every layer, so per-layer ranges would need a
    # params seam (see compression.calibrate_activation_ranges).
    act_quant_ranges: tuple = ()
    layernorm_eps: float = 1e-5
    # Softmax logit scale: 0.0 → the usual 1/sqrt(head_dim); GPT-Neo
    # famously trains UNSCALED (reference policy `containers/gptneo.py:75`
    # passes scale_attention=False) — its HF import sets 1.0.
    attn_softmax_scale: float = 0.0
    # Per-layer attention pattern (the GPT-Neo family, reference
    # `containers/gptneo.py`): tuple of "global"/"local" per layer; local
    # layers see a trailing window of ``local_attention_window`` keys
    # (current token + W-1 predecessors). The pattern rides the layer scan
    # as a per-layer window operand, so the block still compiles ONCE —
    # heterogeneity is data, not code. Empty = all-global (default).
    attention_layers: tuple = ()
    local_attention_window: int = 256
    # Chunked cross-entropy: the [B,T,V] logits tensor is the largest HBM
    # object at vocab 50k; computing the loss in sequence chunks of this many
    # tokens (0 = off) keeps only [B,chunk,V] live, rematerializing per chunk
    # in backward.
    loss_chunk: int = 512
    # Analytic custom-VJP loss head (ops/transformer/fused_loss.py): the
    # backward recomputes chunk logits in-VJP and forms softmax−onehot
    # directly instead of materializing the [B,T,V] logit cotangent.
    # Ignored (autodiff path) for the MLM head and the vocab-sharded TP
    # head, which need the logits cotangent plumbing.
    fused_loss_head: bool = True
    # -- MoE (reference deepspeed/moe/layer.py:15 MoE surface) --------------
    moe_num_experts: int = 0           # 0 → dense model
    moe_freq: int = 2                  # 1 = every layer, 2 = every other
    moe_k: int = 1                     # top-1 or top-2 gating
    moe_capacity_factor: float = 1.0
    moe_eval_capacity_factor: float = 1.0
    moe_min_capacity: int = 4
    moe_use_residual: bool = False     # PR-MoE
    moe_noisy_gate_policy: Optional[str] = None
    moe_use_rts: bool = True
    moe_aux_loss_coef: float = 0.01
    moe_d_ff: int = 0                  # 0 → ff_dim

    @property
    def ff_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def moe_enabled(self) -> bool:
        return self.moe_num_experts > 0

    @property
    def scan_length(self) -> int:
        """Number of scanned superblocks (layers per superblock =
        ``moe_freq`` when MoE is on, else 1)."""
        if not self.moe_enabled:
            return self.num_layers
        if self.moe_freq not in (1, 2):
            raise ValueError("moe_freq must be 1 or 2")
        if self.num_layers % self.moe_freq:
            raise ValueError(
                f"num_layers ({self.num_layers}) must divide by moe_freq "
                f"({self.moe_freq})")
        return self.num_layers // self.moe_freq

    @property
    def attn_per_block(self) -> int:
        return self.moe_freq if self.moe_enabled else 1

    @property
    def hdim(self) -> int:
        return self.head_dim or self.d_model // self.num_heads

    @property
    def kv_heads(self) -> int:
        n = self.num_kv_heads or self.num_heads
        if self.num_heads % n:
            raise ValueError(f"num_heads {self.num_heads} must divide by "
                             f"num_kv_heads {n}")
        return n

    @property
    def qkv_dim(self) -> int:
        """Fused projection output: q heads + 2x kv heads."""
        return (self.num_heads + 2 * self.kv_heads) * self.hdim

    @property
    def rotary_dim(self) -> int:
        d = int(self.hdim * self.rotary_pct)
        return d - d % 2

    def num_params(self) -> int:
        d, f, v = self.d_model, self.ff_dim, self.vocab_size
        nhd = self.num_heads * self.hdim
        norm = 2 * d if self.norm_type == "layernorm" else d
        per_layer = d * self.qkv_dim + nhd * d + 2 * d * f + 2 * norm
        if self.gated_mlp:
            per_layer += d * f
        if self.use_bias:
            per_layer += self.qkv_dim + d + f + d
            if self.gated_mlp:
                per_layer += f
        emb = v * d + (self.max_seq_len * d if self.pos_embedding == "learned" else 0)
        head = 0 if self.tie_embeddings else d * v
        return self.num_layers * per_layer + emb + head + norm


GPT2_SIZES = {
    "125m": dict(num_layers=12, num_heads=12, d_model=768),
    "350m": dict(num_layers=24, num_heads=16, d_model=1024),
    "760m": dict(num_layers=24, num_heads=16, d_model=1536),
    "1.3b": dict(num_layers=24, num_heads=32, d_model=2048),
    "2.7b": dict(num_layers=32, num_heads=32, d_model=2560),
    "6.7b": dict(num_layers=32, num_heads=32, d_model=4096),
    "13b": dict(num_layers=40, num_heads=40, d_model=5120),
}
NEOX_SIZES = {
    "1.3b": dict(num_layers=24, num_heads=16, d_model=2048),
    "20b": dict(num_layers=44, num_heads=64, d_model=6144, rotary_pct=0.25),
}


def gpt2_config(size: str = "125m", **kw) -> TransformerConfig:
    return TransformerConfig(**{"pos_embedding": "learned",
                                "parallel_residual": False,
                                **GPT2_SIZES[size], **kw})


def neox_config(size: str = "1.3b", **kw) -> TransformerConfig:
    # rotate_half is the convention the real GPT-NeoX family uses
    # (architecture-fidelity fix; breaks rotary checkpoints from before the
    # rotary_interleaved knob existed)
    return TransformerConfig(**{"pos_embedding": "rotary",
                                "parallel_residual": True,
                                "rotary_interleaved": False,
                                **NEOX_SIZES[size], **kw})


class TransformerLM:
    """Pure-functional LM: ``init`` → params pytree, ``apply`` → logits.

    ``constrain`` is an optional activation-sharding hook (x -> x) applied at
    block boundaries; the engine passes a `with_sharding_constraint` closure so
    the model stays mesh-agnostic.
    """

    def __init__(self, config: TransformerConfig,
                 constrain: Optional[Callable] = None,
                 block_transform: Optional[Callable] = None):
        self.config = config
        self.constrain = constrain or (lambda x: x)
        # per-layer param hook applied INSIDE the scan body to each
        # layer's slice of params["blocks"] before use — the seam that
        # lets int8 serving dequantize one layer at a time (live set =
        # one full-precision layer, not the whole tree; the role of the
        # reference's per-gemm dequant, csrc/.../dequantize.cu). The
        # params tree may then hold any structure block_transform maps
        # to the standard block tree.
        self.block_transform = block_transform or (lambda sp: sp)
        self.mesh = None          # bound by the engine (ring attention)
        # Manual-collective axis names, set ONLY on the shallow copy
        # :meth:`tp_serving_view` returns for the tensor-parallel
        # serving step (inside its shard_map region).  None — the
        # default on every directly-constructed model — keeps all
        # non-serving paths (generate, training, pipeline) untouched.
        self._tp_axis: Optional[str] = None   # 'model': heads/KV/MLP
        self._dp_axis: Optional[str] = None   # 'data': decode slots
        # training TP (tp_train_view): swap the raw psum for the
        # copy_to/reduce_from custom-vjp pair so backward is exact
        self._tp_exact_bwd: bool = False
        if config.attention_layers:
            if len(config.attention_layers) != config.num_layers:
                raise ValueError(
                    f"attention_layers has {len(config.attention_layers)} "
                    f"entries for {config.num_layers} layers")
            bad = set(config.attention_layers) - {"global", "local"}
            if bad:
                raise ValueError(f"attention_layers entries must be "
                                 f"'global'/'local', got {sorted(bad)}")
            if config.moe_enabled:
                raise NotImplementedError(
                    "attention_layers (per-layer local windows) is not "
                    "plumbed through the MoE superblock scan")
            if config.attn_impl != "xla":
                raise NotImplementedError(
                    f"attention_layers needs attn_impl='xla' (the Pallas "
                    f"kernels take no per-layer window operand); got "
                    f"{config.attn_impl!r}")
        if config.attn_softmax_scale and config.attn_impl != "xla":
            raise NotImplementedError(
                "attn_softmax_scale != 1/sqrt(hd) needs attn_impl='xla' "
                "(the Pallas kernels bake in the standard scale)")
        if config.pos_embedding == "rotary":
            self._cos, self._sin = L.rotary_freqs(
                config.hdim, config.rotary_dim, config.max_seq_len,
                config.rotary_base)
        if config.moe_enabled:
            from ..moe.layer import MoEConfig, MoELayer
            self._moe = MoELayer(
                config.d_model,
                MoEConfig(num_experts=config.moe_num_experts,
                          k=config.moe_k,
                          capacity_factor=config.moe_capacity_factor,
                          eval_capacity_factor=config.moe_eval_capacity_factor,
                          min_capacity=config.moe_min_capacity,
                          use_residual=config.moe_use_residual,
                          noisy_gate_policy=config.moe_noisy_gate_policy,
                          use_rts=config.moe_use_rts,
                          aux_loss_coef=config.moe_aux_loss_coef),
                d_ff=config.moe_d_ff or config.ff_dim,
                depth_scale=config.num_layers)

    def tp_serving_view(self, model_shards: int, tp_axis: Optional[str],
                        dp_axis: Optional[str]) -> "TransformerLM":
        """Shallow copy of this model whose config carries PER-SHARD
        head counts — the seam tensor-parallel serving applies through
        inside its shard_map region (docs/serving.md "Tensor-parallel
        serving").

        With ``num_heads``/``num_kv_heads`` divided by ``model_shards``
        (and ``head_dim`` pinned to its resolved value so the division
        cannot silently change it), every head-count-derived quantity —
        the fused-qkv split, the rotary reshape, the paged kernels'
        ``(slot, kv_head, page_group)`` grid — becomes shard-local with
        NO kernel changes: the kernels are shape-polymorphic and simply
        see fewer kv heads.  ``tp_axis``/``dp_axis`` arm the manual
        collectives (`psum` on block outputs, vocab-sharded embed/head,
        the data-axis KV-row gather); the original model is untouched,
        so ``generate()`` on the same engine keeps its single-device
        program.  Rotary tables, ``block_transform`` and ``constrain``
        are shared by reference."""
        import copy
        c = self.config
        if model_shards > 1:
            if c.kv_heads % model_shards or c.num_heads % model_shards:
                raise ValueError(
                    f"model_shards {model_shards} must divide num_heads "
                    f"{c.num_heads} and kv_heads {c.kv_heads}")
            local = dataclasses.replace(
                c, num_heads=c.num_heads // model_shards,
                num_kv_heads=c.kv_heads // model_shards,
                head_dim=c.hdim)
        else:
            local = c
        view = copy.copy(self)
        view.config = local
        view._tp_axis = tp_axis if model_shards > 1 else None
        view._dp_axis = dp_axis
        return view

    def tp_train_view(self, model_shards: int,
                      tp_axis: Optional[str]) -> "TransformerLM":
        """Per-shard view for tensor-parallel TRAINING regions (the 3D
        pipeline engine): same per-shard head-count seam as
        :meth:`tp_serving_view`, but the per-layer collective is the
        conjugate ``copy_to``/``reduce_from`` pair
        (`parallel/collectives.py`) instead of a raw forward psum, so
        hand-driven vjp and in-region autodiff both see exact gradients.
        Row-parallel bias pre-division and the fused-qkv column gather
        happen inside the training region (where they must sit in the
        differentiated function), not at engine prep."""
        view = self.tp_serving_view(model_shards, tp_axis, None)
        view._tp_exact_bwd = view._tp_axis is not None
        return view

    # -- init --------------------------------------------------------------
    # Split into per-piece initializers so streamed-parameter paths
    # (ZeRO-Infinity, runtime/zero/infinity.py) can materialize one layer at
    # a time; init() composes them and is bit-identical to the monolithic
    # form (vmap of init_superblock over split keys == the old stacked init).
    def _attn_block_init(self, k):
        c, dt = self.config, self.config.param_dtype
        d, nh, hd = c.d_model, c.num_heads, c.hdim
        norm_init = (L.layernorm_init if c.norm_type == "layernorm"
                     else L.rmsnorm_init)
        k1, k2 = jax.random.split(k, 2)
        blk = {
            "ln1": norm_init(None, d, dt),
            "attn": {
                "qkv": L.dense_init(k1, d, c.qkv_dim, c.use_bias, 0.02, dt),
                "out": {"kernel": L.scaled_init(k2, (nh * hd, d), 0.02,
                                                c.num_layers, dt)},
            },
            "ln2": norm_init(None, d, dt),
        }
        if c.use_bias:
            blk["attn"]["out"]["bias"] = jnp.zeros((d,), dt)
        return blk

    def _block_init(self, k):
        c, dt = self.config, self.config.param_dtype
        d, f = c.d_model, c.ff_dim
        ka, k3, k4, k5 = jax.random.split(k, 4)
        blk = self._attn_block_init(ka)
        blk["mlp"] = {
            "fc_in": L.dense_init(k3, d, f, c.use_bias, 0.02, dt),
            "fc_out": {"kernel": L.scaled_init(k4, (f, d), 0.02,
                                               c.num_layers, dt)},
        }
        if c.gated_mlp:
            blk["mlp"]["fc_gate"] = L.dense_init(k5, d, f, c.use_bias,
                                                 0.02, dt)
        if c.use_bias:
            blk["mlp"]["fc_out"]["bias"] = jnp.zeros((d,), dt)
        return blk

    def _moe_block_init(self, k):
        dt = self.config.param_dtype
        ka, km = jax.random.split(k, 2)
        blk = self._attn_block_init(ka)
        blk["moe"] = self._moe.init(km, dt)
        return blk

    def init_superblock(self, k) -> Dict:
        """One scanned layer's params (no leading stack axis)."""
        c = self.config
        if not c.moe_enabled:
            return self._block_init(k)
        if c.moe_freq == 1:
            return {"moe_blk": self._moe_block_init(k)}
        kd, km = jax.random.split(k, 2)
        return {"dense": self._block_init(kd),
                "moe_blk": self._moe_block_init(km)}

    def superblock_keys(self, rng) -> jax.Array:
        """Per-layer init keys; layer i of init() == init_superblock(keys[i])."""
        return jax.random.split(jax.random.split(rng, 8)[1],
                                self.config.scan_length)

    def init_resident(self, rng) -> Dict:
        """Everything outside the scanned blocks (embeddings, final norm,
        untied head) — the params a streamed path keeps device-resident."""
        c, dt = self.config, self.config.param_dtype
        d = c.d_model
        norm_init = (L.layernorm_init if c.norm_type == "layernorm"
                     else L.rmsnorm_init)
        keys = jax.random.split(rng, 8)
        params = {
            "embed": L.embedding_init(keys[0], c.vocab_size, d, 0.02, dt),
        }
        if c.final_layernorm:
            params["ln_f"] = norm_init(None, d, dt)
        if c.pos_embedding == "learned":
            params["pos_embed"] = L.embedding_init(keys[2], c.max_seq_len, d,
                                                   0.01, dt)
        if not c.tie_embeddings:
            params["lm_head"] = {"kernel": L.normal_init(
                keys[3], (d, c.vocab_size), 0.02, dt)}
        if c.embed_layernorm:
            params["ln_embed"] = norm_init(None, d, dt)
        if c.token_type_vocab:
            params["type_embed"] = L.embedding_init(
                keys[4], c.token_type_vocab, d, 0.02, dt)
        if c.mlm_head:
            params["mlm_head"] = {
                "dense": L.dense_init(keys[5], d, d, True, 0.02, dt),
                "ln": norm_init(None, d, dt),
                "bias": jnp.zeros((c.vocab_size,), dt),
            }
        return params

    def init(self, rng) -> Dict:
        params = self.init_resident(rng)
        params["blocks"] = jax.vmap(self.init_superblock)(
            self.superblock_keys(rng))
        return params

    def bind_mesh(self, mesh) -> None:
        """Attach the device mesh (needed by manual-collective attention
        paths like ring attention). The engine calls this at init."""
        self.mesh = mesh

    _flash_fallback_warned = False

    def _sparse_decode_mask(self, idx, t: int, tk: int):
        """[1, H|1, t, tk] bool: the training layout's block rows gathered
        at the query positions — cached decode sees exactly the pattern
        the model trained with (the block-level mask equivalent of the
        blocksparse kernel's index walk)."""
        c = self.config
        if c.sparsity_config is None:
            raise ValueError(
                "attn_impl='blocksparse' needs sparsity_config for the "
                "sparse decode mask")
        blk = c.sparsity_config.block
        nbk = -(-tk // blk)
        # the layout is built at the TRAINING context length: stochastic
        # layouts (BigBird random blocks) depend on the block count, so
        # rebuilding at cache capacity would apply a pattern the model
        # never trained with
        nb_train = c.max_seq_len // blk
        if nbk > nb_train:
            raise NotImplementedError(
                f"blocksparse decode cache ({tk} tokens) exceeds the "
                f"training context ({c.max_seq_len}) — the layout beyond "
                f"it is undefined; cap max_out_tokens at max_seq_len")
        import numpy as _np
        layout = _np.asarray(c.sparsity_config.make_layout(
            nb_train * blk))
        if layout.ndim == 2:
            layout = layout[None]                     # [1|H, nb, nb]
        layout = layout[:, :, :nbk]
        layout_j = jnp.asarray(layout.astype(bool))
        qpos = idx + jnp.arange(t)
        rows = jnp.take(layout_j, qpos // blk, axis=1)    # [H?, t, nbk]
        kmask = jnp.repeat(rows, blk, axis=-1)[..., :tk]  # [H?, t, tk]
        return kmask[None]                                # [1, H|1, t, tk]

    def _warn_flash_fallback(self, tq: int, tk: int) -> None:
        """Loud (once) on the flash→XLA perf cliff — a silent fallback hides
        an O(T²)-HBM regression (VERDICT weak #6)."""
        if not TransformerLM._flash_fallback_warned:
            from ..utils.logging import logger
            logger.warning(
                f"flash attention unsupported for seq {tq}/{tk} (block-size "
                f"divisibility) — falling back to XLA attention, which "
                f"materializes the [B,H,T,T] score matrix. Pad the sequence "
                f"to a multiple of the flash block for the fast path.")
            TransformerLM._flash_fallback_warned = True

    def _norm_fn(self):
        """The configured norm apply with eps bound (single source for the
        six former copies of the layernorm/rmsnorm selector)."""
        c = self.config
        base = (L.layernorm_apply if c.norm_type == "layernorm"
                else L.rmsnorm_apply)
        return partial(base, eps=c.layernorm_eps)

    _ACT_SITES = ("attn_in", "mlp_in")

    def _maybe_qact(self, x, site: str = "attn_in"):
        """Activation-quantization seam (compression subsystem): STE
        fake-quant on dense-projection inputs when act_quant_bits is set.
        ``act_quant_ranges`` switches to STATIC calibrated absmax ranges
        (one per site, ordered as ``_ACT_SITES``); an ``_act_calib`` dict
        set on the instance makes this seam RECORD absmax instead
        (eager-mode calibration pass, compression subsystem)."""
        c = self.config
        calib = getattr(self, "_act_calib", None)
        if calib is not None:
            calib[site] = max(calib.get(site, 0.0),
                              float(jnp.max(jnp.abs(
                                  x.astype(jnp.float32)))))
            return x
        if not c.act_quant_bits:
            return x
        if c.act_quant_ranges:
            from ..ops.quantizer.quantizer import fake_quantize_static
            absmax = c.act_quant_ranges[self._ACT_SITES.index(site)]
            return fake_quantize_static(x, float(absmax),
                                        c.act_quant_bits)
        from ..ops.quantizer.quantizer import fake_quantize
        return fake_quantize(x, c.act_quant_bits, 1, c.act_quant_symmetric)

    # global layers ride the same per-layer-window scan operand as local
    # ones; qpos-kpos never exceeds max_seq_len, so this sentinel means
    # "no window" without risking i32 overflow in the mask arithmetic
    _GLOBAL_WINDOW = 1 << 30

    def _layer_windows(self) -> Optional[jnp.ndarray]:
        """[num_layers] i32 per-layer attention window, or None when the
        config has no per-layer pattern."""
        c = self.config
        if not c.attention_layers:
            return None
        return jnp.asarray(
            [c.local_attention_window if a == "local"
             else self._GLOBAL_WINDOW for a in c.attention_layers],
            jnp.int32)

    @property
    def _attn_scale(self) -> Optional[float]:
        return self.config.attn_softmax_scale or None

    # -- block -------------------------------------------------------------
    def _attention(self, p, x, cache_kv=None, positions=None, window=None):
        c = self.config
        nh, hd = c.num_heads, c.hdim
        nkv = c.kv_heads
        qkv = L.dense_apply(p["qkv"], self._maybe_qact(x, "attn_in"))
        b, t = qkv.shape[0], qkv.shape[1]
        if nkv == nh:
            qkv3 = qkv.reshape(b, t, 3, nh, hd)
            q, k, v = qkv3[:, :, 0], qkv3[:, :, 1], qkv3[:, :, 2]
        else:
            q = qkv[..., :nh * hd].reshape(b, t, nh, hd)
            k = qkv[..., nh * hd:(nh + nkv) * hd].reshape(b, t, nkv, hd)
            v = qkv[..., (nh + nkv) * hd:].reshape(b, t, nkv, hd)
        if c.pos_embedding == "rotary":
            cos = self._cos.astype(jnp.float32)
            sin = self._sin.astype(jnp.float32)
            q = L.apply_rotary(q, cos, sin, positions,
                               interleaved=c.rotary_interleaved)
            k = L.apply_rotary(k, cos, sin, positions,
                               interleaved=c.rotary_interleaved)
        def expand_kv(a):
            # GQA expansion for the Pallas/ring kernels (which assume one
            # kv head per query head); the XLA paths use L.gqa_attention
            # and never materialize this
            return a if nkv == nh else jnp.repeat(a, nh // nkv, axis=2)

        new_cache = None
        offset = 0
        if isinstance(cache_kv, PagedMixedState):
            # continuous batching, mixed step: decode slots + one prompt
            # chunk in a single program (chunked prefill)
            return self._paged_mixed_attention(p, q, k, v, cache_kv, t, nh,
                                               hd)
        if isinstance(cache_kv, PagedKVCache):
            # continuous-batching decode: per-slot write into the shared
            # block pool + batched paged-attention kernel
            return self._paged_attention(p, q, k, v, cache_kv, b, t, nh, hd)
        if cache_kv is None and c.attn_impl in ("ring", "ulysses",
                                                "blocksparse"):
            # the flash kernel folds GQA via its k/v index maps and is NOT
            # in this list — expanding would multiply its HBM traffic by
            # the group size for nothing
            k, v = expand_kv(k), expand_kv(v)
        if cache_kv is None and c.attn_impl in ("ring", "ulysses"):
            from ..parallel.topology import SEQUENCE_AXIS
            if self.mesh is None or self.mesh.shape.get(SEQUENCE_AXIS, 1) < 2:
                raise ValueError(
                    f"attn_impl={c.attn_impl!r} needs a bound mesh with "
                    f"sequence>=2 (engine binds it; or call "
                    f"model.bind_mesh(mesh))")
            use_alibi = c.pos_embedding == "alibi"
            if c.attn_impl == "ring":
                from ..ops.transformer.ring_attention import ring_attention
                o = ring_attention(q, k, v, self.mesh, alibi=use_alibi)
            else:
                from ..ops.transformer.ulysses_attention import (
                    ulysses_attention)
                o = ulysses_attention(q, k, v, self.mesh, causal=c.causal,
                                      alibi=use_alibi)
            o = o.reshape(b, t, nh * hd)
            return L.dense_apply(p["out"], o), None
        if cache_kv is None and c.attn_impl == "blocksparse":
            from ..ops.sparse_attention.blocksparse_flash import (
                blocksparse_attention_bthd)
            if c.sparsity_config is None:
                raise ValueError(
                    "attn_impl='blocksparse' needs sparsity_config (an "
                    "ops.sparse_attention.SparsityConfig instance) on the "
                    "TransformerConfig")
            if t % c.sparsity_config.block == 0:
                o = blocksparse_attention_bthd(q, k, v, c.sparsity_config)
            else:
                # non-block-divisible length (e.g. mid-generation full
                # forwards): masked dense with the SAME layout — identical
                # semantics, without the kernel's divisibility constraint
                mask = self._sparse_decode_mask(jnp.asarray(0, jnp.int32),
                                                t, t)
                o = L.causal_attention(q, k, v, mask=mask, causal=c.causal)
            o = o.reshape(b, t, nh * hd)
            return L.dense_apply(p["out"], o), None
        if cache_kv is None and c.attn_impl == "flash" and \
                c.pos_embedding != "alibi" and window is None:
            from ..ops.transformer.flash_attention import (
                flash_attention_bthd, supports)
            if supports(q.shape[1], k.shape[1]):
                # k/v go in at kv-head width; ragged lengths are masked
                # in-kernel (ceil grid), so mid-sized odd sequences no
                # longer fall back to the O(T²) XLA path
                o = flash_attention_bthd(q, k, v, causal=c.causal)
                o = o.reshape(b, t, nh * hd)
                return L.dense_apply(p["out"], o), None
            self._warn_flash_fallback(q.shape[1], k.shape[1])
        if cache_kv is not None:
            ck, cv, idx = cache_kv
            ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                              (0, idx, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                              (0, idx, 0, 0))
            offset = idx
            new_cache = (ck, cv)
            tk = ck.shape[1]
            if not c.causal:
                raise NotImplementedError(
                    "KV-cache decode on a non-causal (encoder) model is "
                    "meaningless — encoders have no autoregressive order")
            if t == 1 and c.attn_impl == "flash" and \
                    c.pos_embedding != "alibi":
                # token-at-a-time hot path → fused Pallas decode kernel
                # (reference softmax_context, csrc/.../softmax.cu)
                from ..ops.transformer import decode_attention as DA
                if DA.supports(hd, tk):
                    o = DA.decode_attention(
                        q[:, 0], expand_kv(ck).astype(q.dtype),
                        expand_kv(cv).astype(q.dtype), idx + 1)[:, None]
                    o = o.reshape(b, t, nh * hd)
                    return L.dense_apply(p["out"], o), new_cache
            bias = None
            if c.pos_embedding == "alibi":
                qpos = (positions[0] if positions is not None
                        else idx + jnp.arange(t))
                bias = L.alibi_bias(nh, tk, qpos)[None]
            sparse_mask = None
            if c.attn_impl == "blocksparse":
                # decode applies the SAME layout the model trained with
                # (block-row gathered at the query positions) — dense
                # fallback would let every token see full history
                sparse_mask = self._sparse_decode_mask(idx, t, tk)
            band = None
            if window is not None:
                # honor explicit positions (left-padded batched decode) the
                # same way the ALiBi bias above does
                qpos = (positions[0] if positions is not None
                        else idx + jnp.arange(t))
                band = (qpos[:, None] - jnp.arange(tk)[None, :]) < window
            if nkv != nh:
                valid = jnp.arange(tk)[None, None, None, None, :] < (idx + t)
                if band is not None:
                    valid = valid & band[None, None, None]
                if sparse_mask is not None:
                    sm = (sparse_mask[:, :, None]      # [1,1,1,t,tk]
                          if sparse_mask.shape[1] == 1
                          else sparse_mask.reshape(1, nkv, nh // nkv, t,
                                                   tk))
                    valid = valid & sm
                o = L.gqa_attention(q, ck.astype(q.dtype),
                                    cv.astype(q.dtype), mask=valid,
                                    kv_positions_offset=offset, bias=bias,
                                    scale=self._attn_scale)
            else:
                valid = jnp.arange(tk)[None, None, None, :] < (idx + t)
                if band is not None:
                    valid = valid & band[None, None]
                if sparse_mask is not None:
                    valid = valid & sparse_mask
                o = L.causal_attention(q, ck.astype(q.dtype),
                                       cv.astype(q.dtype), mask=valid,
                                       kv_positions_offset=offset,
                                       bias=bias, scale=self._attn_scale)
        else:
            bias = None
            if c.pos_embedding == "alibi":
                bias = L.alibi_bias(nh, t, jnp.arange(t))[None]
            band = None
            if window is not None:
                pos = jnp.arange(t)
                band = (pos[:, None] - pos[None, :]) < window
            if nkv != nh:
                o = L.gqa_attention(
                    q, k, v, causal=c.causal, bias=bias,
                    mask=None if band is None else band[None, None, None],
                    scale=self._attn_scale)
            else:
                o = L.causal_attention(
                    q, k, v, causal=c.causal, bias=bias,
                    mask=None if band is None else band[None, None],
                    scale=self._attn_scale)
        o = o.reshape(b, t, nh * hd)
        return L.dense_apply(p["out"], o), new_cache

    def _paged_attention(self, p, q, k, v, paged: PagedKVCache, b, t, nh,
                         hd):
        """Ragged-batch decode against a paged KV pool (one layer).

        q/k/v [B, 1, nh|kvh, hd] — the new token per slot, rotary
        already applied with per-slot positions.  The new k/v scatter
        into each slot's current block (slots own disjoint blocks, so
        the write indices never collide; inactive slots write into the
        reserved null block 0), then the batched Pallas kernel attends
        over the block tables with per-slot lengths — no per-step cache
        copy, no ``jnp.pad``.  With a quantized pool
        (``paged.k_scale is not None``) the new rows are encoded at the
        scatter (``ops/quantizer/kv_quantize`` — one scale per row per
        kv head, written alongside) and the kernel dequantizes in its
        inner loop, so the pool never holds a full-precision copy."""
        if t != 1:
            raise NotImplementedError(
                f"paged decode is token-at-a-time (t=1), got t={t} — "
                f"prompts prefill through the dense cache path")
        pool_k, pool_v = paged.k_pool, paged.v_pool
        tables, lens = paged.block_tables, paged.lens
        kscale, vscale = paged.k_scale, paged.v_scale
        kv_bits = self._paged_kv_bits(pool_k, kscale, hd)
        nb, blk = pool_k.shape[0], pool_k.shape[1]
        slot = jnp.arange(b)
        # write position of the new token: block_table[len // blk]
        # offset len % blk, flattened over [nb * blk] rows
        write = tables[slot, lens // blk] * blk + lens % blk
        flat = (nb * blk,) + pool_k.shape[2:]
        if kv_bits:
            from ..ops.quantizer.quantizer import kv_quantize
            kq, ks = kv_quantize(k[:, 0], kv_bits)    # [B,kvh,De],[B,kvh]
            vq, vs = kv_quantize(v[:, 0], kv_bits)
            sflat = (nb * blk,) + kscale.shape[2:]
            pool_k = pool_k.reshape(flat).at[write].set(
                kq).reshape(pool_k.shape)
            pool_v = pool_v.reshape(flat).at[write].set(
                vq).reshape(pool_v.shape)
            kscale = kscale.reshape(sflat).at[write].set(
                ks).reshape(paged.k_scale.shape)
            vscale = vscale.reshape(sflat).at[write].set(
                vs).reshape(paged.v_scale.shape)
            kern_k, kern_v = pool_k, pool_v
        else:
            pool_k = pool_k.reshape(flat).at[write].set(
                k[:, 0].astype(pool_k.dtype)).reshape(pool_k.shape)
            pool_v = pool_v.reshape(flat).at[write].set(
                v[:, 0].astype(pool_v.dtype)).reshape(pool_v.shape)
            kern_k, kern_v = pool_k.astype(q.dtype), pool_v.astype(q.dtype)
        from ..ops.transformer.paged_decode_attention import (
            paged_decode_attention)
        o = paged_decode_attention(
            q[:, 0], kern_k, kern_v,
            # inactive slots (lens 0) must stay 0 so the kernel's
            # null-block page is masked off, not attended
            jnp.where(lens > 0, lens + 1, 0), tables,
            sm_scale=self._attn_scale,
            k_scale=kscale, v_scale=vscale, kv_bits=kv_bits)
        o = o.reshape(b, t, nh * hd)
        pools = (pool_k, pool_v) if not kv_bits else \
            (pool_k, pool_v, kscale, vscale)
        return L.dense_apply(p["out"], o), pools

    def _paged_mixed_attention(self, p, q, k, v, st: PagedMixedState, t,
                               nh, hd):
        """One layer of the mixed decode+spec-verify+chunked-prefill step.

        q/k/v arrive as ``[1, B + B*S + C, nh|kvh, hd]`` — the first B
        rows are each decode slot's new token, the next B*S rows
        (slot-major; S = ``st.spec_width``, 0 when the spec lane is
        off) are each slot's speculative draft run, and the last C rows
        are one slot's prompt chunk; rotary was already applied with
        per-row positions.  All groups scatter their k/v into the pool
        in one combined write (decode rows at ``table[len // blk]``,
        spec row i of slot b at position ``lens[b] + i``, chunk rows at
        ``base + i`` of the chunk slot's table; inactive/padded rows
        re-route to the reserved null block), then the kernels attend —
        the batched decode kernel over all slots, one decode-kernel
        call per spec depth (row i sees the slot's prefix plus draft
        tokens 0..i: causality via the length vector), and the causal
        chunk kernel over the chunk slot's pages — and the outputs
        concatenate back into the shared projection.  A quantized pool
        (``st.k_scale is not None``) encodes every row at the combined
        scatter and all kernels dequantize in-loop (see
        :meth:`_paged_attention`).  ``S == 0`` and ``C == 0`` are
        STATIC widths: the corresponding lane compiles away entirely,
        so the plain decode program is byte-identical to pre-spec
        builds."""
        pool_k, pool_v, tables, lens = (st.k_pool, st.v_pool,
                                        st.block_tables, st.lens)
        kscale, vscale = st.k_scale, st.v_scale
        kv_bits = self._paged_kv_bits(pool_k, kscale, hd)
        bsl = lens.shape[0]                   # decode slots
        sw = st.spec_width                    # spec rows per slot
        c = t - bsl - bsl * sw                # chunk width
        nb, blk = pool_k.shape[0], pool_k.shape[1]
        npages = tables.shape[1]
        act = st.dec_active > 0
        slot = jnp.arange(bsl)
        # decode rows: write position of each slot's new token (null
        # block row 0 for slots not decoding this iteration)
        wd = jnp.where(act, tables[slot, lens // blk] * blk + lens % blk,
                       0)
        writes = [wd]
        if sw:
            # spec rows: slot b's draft token i lands at position
            # lens[b] + i — the same cells a sequential decode would
            # fill, so accepted tokens are already committed and the
            # rejected tail is rolled back host-side by simply not
            # advancing lens past it (stale cells are re-written by the
            # next run before they can be attended).  Inactive slots
            # re-route to the null block; the page clamp keeps padded
            # positions in-table.
            sact = st.spec_active > 0
            spos = lens[:, None] + jnp.arange(sw)[None, :]     # [B, S]
            spage = jnp.minimum(spos // blk, npages - 1)
            ws = jnp.where(sact[:, None],
                           jnp.take_along_axis(tables, spage, axis=1)
                           * blk + spos % blk, 0)
            writes.append(ws.reshape(-1))
        if c:
            # chunk rows: absolute rows base..base+C-1 of the chunk
            # slot's table (null block for padding past chunk_len).
            # chunk_slot is a GLOBAL slot id: with data-sharded slots
            # it indexes the gathered tables (st.tables_g), which every
            # shard holds in full — the chunk work itself is replicated
            # over data.
            ci = jnp.arange(c)
            cpos = st.chunk_start + ci
            ctable = (tables if st.tables_g is None
                      else st.tables_g)[st.chunk_slot]
            cpage = jnp.minimum(cpos // blk, npages - 1)
            wc = jnp.where(ci < st.chunk_len,
                           ctable[cpage] * blk + cpos % blk, 0)
            writes.append(wc)
        dp = self._dp_axis

        def gather_rows(a):
            # decode-slot sharding: every data shard's pool replica must
            # apply EVERY slot's new row, so the per-shard decode rows
            # (and their write indices / quant scales) tile back into
            # global slot order before the combined scatter — the only
            # data-axis collective, [B_local, kvh, hd]-sized per layer
            return a if dp is None else jax.lax.all_gather(
                a, dp, axis=0, tiled=True)

        def shard_cat(rows):
            # re-tile the slot-owned segments (decode, spec) to global
            # slot order and keep the chunk segment as-is.  Spec rows
            # are slot-major [B_local * S], so a tiled all_gather
            # yields the global slot-major layout directly.
            parts = [gather_rows(rows[0])]
            if sw:
                parts.append(gather_rows(rows[1]))
            if c:
                parts.append(rows[-1])
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

        def seg(a):
            # split a [B + B*S + C, ...] row array into lane segments
            out = [a[:bsl]]
            if sw:
                out.append(a[bsl:bsl + bsl * sw])
            if c:
                out.append(a[bsl + bsl * sw:])
            return out
        write = shard_cat(writes)
        flat = (nb * blk,) + pool_k.shape[2:]
        if kv_bits:
            from ..ops.quantizer.quantizer import kv_quantize
            kq, ks = kv_quantize(k[0], kv_bits)   # [T,kvh,De],[T,kvh]
            vq, vs = kv_quantize(v[0], kv_bits)
            kq, vq = shard_cat(seg(kq)), shard_cat(seg(vq))
            ks, vs = shard_cat(seg(ks)), shard_cat(seg(vs))
            sflat = (nb * blk,) + kscale.shape[2:]
            pool_k = pool_k.reshape(flat).at[write].set(
                kq).reshape(pool_k.shape)
            pool_v = pool_v.reshape(flat).at[write].set(
                vq).reshape(pool_v.shape)
            kscale = kscale.reshape(sflat).at[write].set(
                ks).reshape(st.k_scale.shape)
            vscale = vscale.reshape(sflat).at[write].set(
                vs).reshape(st.v_scale.shape)
            pk, pv = pool_k, pool_v
        else:
            kw = shard_cat(seg(k[0].astype(pool_k.dtype)))
            vw = shard_cat(seg(v[0].astype(pool_v.dtype)))
            pool_k = pool_k.reshape(flat).at[write].set(
                kw).reshape(pool_k.shape)
            pool_v = pool_v.reshape(flat).at[write].set(
                vw).reshape(pool_v.shape)
            pk = pool_k.astype(q.dtype)
            pv = pool_v.astype(q.dtype)
        from ..ops.transformer.paged_decode_attention import (
            paged_decode_attention, paged_prefill_attention)
        o_parts = [paged_decode_attention(
            q[0, :bsl], pk, pv,
            # only slots decoding THIS iteration attend (their length
            # includes the just-written token); prefilling and empty
            # slots are masked to zero rows
            jnp.where(act, lens + 1, 0), tables,
            sm_scale=self._attn_scale,
            k_scale=kscale, v_scale=vscale, kv_bits=kv_bits)]
        if sw:
            # spec depth i attends the prefix plus draft rows 0..i
            # (all already in the pool from the combined scatter);
            # per-depth lengths give exact causality between draft rows
            qs = q[0, bsl:bsl + bsl * sw].reshape(bsl, sw, nh, hd)
            o_spec = [paged_decode_attention(
                qs[:, i], pk, pv,
                jnp.where(sact, lens + i + 1, 0), tables,
                sm_scale=self._attn_scale,
                k_scale=kscale, v_scale=vscale, kv_bits=kv_bits)
                for i in range(sw)]
            o_parts.append(jnp.stack(o_spec, axis=1).reshape(
                bsl * sw, nh, hd))
        if c:
            o_parts.append(paged_prefill_attention(
                q[0, bsl + bsl * sw:], pk, pv, st.chunk_start,
                st.chunk_len, ctable,
                sm_scale=self._attn_scale,
                k_scale=kscale, v_scale=vscale, kv_bits=kv_bits))
        o = (o_parts[0] if len(o_parts) == 1
             else jnp.concatenate(o_parts, axis=0))[None]
        o = o.reshape(1, t, nh * hd)
        pools = (pool_k, pool_v) if not kv_bits else \
            (pool_k, pool_v, kscale, vscale)
        return L.dense_apply(p["out"], o), pools

    def _mlp(self, p, x):
        xq = self._maybe_qact(x, "mlp_in")
        if self.config.gated_mlp:
            g = L.ACT_FNS[self.config.activation](
                L.dense_apply(p["fc_gate"], xq))
            return L.dense_apply(p["fc_out"],
                                 g * L.dense_apply(p["fc_in"], xq))
        h = L.dense_apply(p["fc_in"], xq)
        h = L.ACT_FNS[self.config.activation](h)
        return L.dense_apply(p["fc_out"], h)

    def _block(self, bp, x, cache_kv=None, positions=None, window=None):
        c = self.config
        norm = self._norm_fn()
        x = self.constrain(x)
        # Tensor-parallel serving (tp_serving_view): attention heads and
        # MLP columns are shard-local, so each branch output is a
        # PARTIAL sum over the model axis — `red` is the one per-layer
        # collective (row-parallel out/fc_out biases are pre-divided by
        # the shard count, so the psum restores them exactly); identity
        # everywhere else.  Training TP (tp_train_view) swaps in the
        # conjugate pair: `red` becomes reduce_from (psum fwd, identity
        # bwd) and `fin` (copy_to: identity fwd, psum bwd) marks where
        # the replicated stream enters each shard-local branch, so the
        # branch input's cotangent is reassembled from per-shard
        # partials. `fin` is identity on the serving path — forward
        # behavior there is byte-identical.
        if self._tp_axis is not None:
            if self._tp_exact_bwd:
                from ..parallel.collectives import copy_to, reduce_from
                red = reduce_from(self._tp_axis)
                fin = copy_to(self._tp_axis)
            else:
                red = lambda u: jax.lax.psum(u, self._tp_axis)  # noqa: E731
                fin = lambda u: u                               # noqa: E731
        else:
            red = lambda u: u                                   # noqa: E731
            fin = lambda u: u                                   # noqa: E731
        if c.norm_position == "post":
            # BERT family: ln(x + f(x)); ln1 after attention, ln2 after FFN
            a, new_cache = self._attention(bp["attn"], fin(x), cache_kv,
                                           positions, window)
            x = norm(bp["ln1"], x + red(a))
            x = norm(bp["ln2"], x + red(self._mlp(bp["mlp"], fin(x))))
        elif c.parallel_residual:
            a, new_cache = self._attention(bp["attn"],
                                           fin(norm(bp["ln1"], x)),
                                           cache_kv, positions, window)
            m = self._mlp(bp["mlp"], fin(norm(bp["ln2"], x)))
            x = x + red(a + m)
        else:
            a, new_cache = self._attention(bp["attn"],
                                           fin(norm(bp["ln1"], x)),
                                           cache_kv, positions, window)
            x = x + red(a)
            x = x + red(self._mlp(bp["mlp"], fin(norm(bp["ln2"], x))))
        return self.constrain(x), new_cache

    def _moe_block(self, bp, x, cache_kv=None, positions=None, rng=None,
                   train=True):
        """Attention + MoE-FFN block. Returns (x, new_cache, l_aux)."""
        c = self.config
        norm = self._norm_fn()
        x = self.constrain(x)
        a, new_cache = self._attention(bp["attn"], norm(bp["ln1"], x),
                                       cache_kv, positions)
        if c.parallel_residual:
            m, laux, _ = self._moe.apply(bp["moe"], norm(bp["ln2"], x),
                                         rng=rng, train=train)
            x = x + a + m
        else:
            x = x + a
            m, laux, _ = self._moe.apply(bp["moe"], norm(bp["ln2"], x),
                                         rng=rng, train=train)
            x = x + m
        return self.constrain(x), new_cache, laux

    def _superblock(self, sp, x, caches=None, positions=None, rng=None,
                    train=True, window=None):
        """One scanned unit: a dense block (moe_freq=2 only) followed by a
        MoE block, or just a dense block when MoE is off.

        ``caches`` — tuple of per-attention-layer (ck, cv, idx) or None.
        Returns (x, new_caches tuple | None, l_aux)."""
        c = self.config
        if not c.moe_enabled:
            y, nc = self._block(sp, x, caches[0] if caches else None,
                                positions, window)
            return y, ((nc,) if caches else None), jnp.zeros((), jnp.float32)
        new_caches = []
        if c.moe_freq == 2:
            x, nc = self._block(sp["dense"], x,
                                caches[0] if caches else None, positions)
            new_caches.append(nc)
        x, nc, laux = self._moe_block(
            sp["moe_blk"], x, caches[-1] if caches else None, positions,
            rng, train)
        new_caches.append(nc)
        return x, (tuple(new_caches) if caches else None), laux

    # (no separate _remat_block: callers wrap their scan body with _remat)
    def _remat(self, fn):
        """Wrap fn with the configured rematerialization policy —
        replaces the reference's activation-checkpointing subsystem
        (`runtime/activation_checkpointing/checkpointing.py:498`).
        ``dots_no_batch`` is the transformer sweet spot: dense matmul outputs
        are saved, the O(T²) attention scores are recomputed in backward."""
        c = self.config
        if c.remat == "none":
            return fn
        if c.remat == "host_offload":
            # Host (CPU) activation checkpointing (reference
            # activation_checkpointing/checkpointing.py:485
            # cpu_checkpointing): the per-layer residual stream spills to
            # pinned host DRAM between forward and backward instead of
            # living in HBM — XLA memories do the async transfers the
            # reference hand-rolled with pinned buffers + streams.
            # Everything else recomputes (full-remat semantics).
            policy = jax.checkpoint_policies.save_and_offload_only_these_names(
                names_which_can_be_saved=[],
                names_which_can_be_offloaded=["block_in"],
                offload_src="device", offload_dst="pinned_host")
            return jax.checkpoint(fn, policy=policy)
        policy = {
            "full": None,
            "dots_saveable": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        }[c.remat]
        return jax.checkpoint(fn, policy=policy)

    # -- full forward ------------------------------------------------------
    def apply(self, params, input_ids, cache=None, positions=None,
              token_type_ids=None):
        """input_ids [B, T] → logits [B, T, V] (fp32).

        ``cache`` — KV cache dict from `init_cache` for incremental decoding;
        returns (logits, updated_cache) when provided.
        """
        c = self.config
        if cache is None:
            # inference semantics: eval capacity factor, no gate noise —
            # same gating mode as the cached decode branch below
            x, _ = self.hidden_states_and_aux(
                params, input_ids, train=False,
                token_type_ids=token_type_ids)
            return self._project(params, x)

        if "block_tables" in cache:
            return self._apply_paged_decode(params, input_ids, cache)

        idx = cache["index"]
        if positions is None:
            # incremental decode default: continue from the cache index
            positions = idx + jnp.arange(input_ids.shape[1])[None, :]
        x = self._embed_tokens(params, input_ids, positions=positions)

        if c.moe_enabled:
            # cache leaves: [scan, A, B, T, H, Dh], A = attns per superblock
            def scan_fn(carry, xs):
                sp, ck, cv = xs
                sp = self.block_transform(sp)
                caches = tuple((ck[i], cv[i], idx)
                               for i in range(c.attn_per_block))
                y, ncs, _ = self._superblock(sp, carry, caches, positions,
                                             rng=None, train=False)
                nk = jnp.stack([nc[0] for nc in ncs])
                nv = jnp.stack([nc[1] for nc in ncs])
                return y, (nk, nv)
        elif c.attention_layers:
            def scan_fn(carry, xs):
                bp, ck, cv, win = xs
                bp = self.block_transform(bp)
                y, kv = self._block(bp, carry, (ck, cv, idx), positions,
                                    window=win)
                return y, kv
        else:
            def scan_fn(carry, xs):
                bp, ck, cv = xs
                bp = self.block_transform(bp)
                y, kv = self._block(bp, carry, (ck, cv, idx), positions)
                return y, kv
        xs = (params["blocks"], cache["k"], cache["v"])
        if not c.moe_enabled and c.attention_layers:
            xs = xs + (self._layer_windows(),)
        x, (nk, nv) = jax.lax.scan(scan_fn, x, xs)
        new_cache = {"k": nk, "v": nv, "index": idx + input_ids.shape[1]}
        if c.final_layernorm:
            x = self._norm_fn()(params["ln_f"], x)
        return self._project(params, x), new_cache

    def _embed_tokens(self, params, input_ids, positions=None,
                      token_type_ids=None):
        """Shared embedding path: word (+ position, + token-type) embeds,
        then the optional embedding layernorm (BLOOM, BERT)."""
        c = self.config
        if self._tp_axis is not None:
            # vocab-sharded table [V/mp, D] (the Megatron layout
            # partition_specs declares): each shard looks up the ids it
            # owns, masks the rest to zero rows, and one psum rebuilds
            # the full word embedding; position/type tables and the
            # embedding layernorm are replicated and applied AFTER the
            # psum so they land exactly once
            vloc = params["embed"]["embedding"].shape[0]
            lo = jax.lax.axis_index(self._tp_axis) * vloc
            local = input_ids - lo
            mine = (local >= 0) & (local < vloc)
            x = L.embedding_apply(params["embed"],
                                  jnp.where(mine, local, 0), c.dtype)
            x = jax.lax.psum(jnp.where(mine[..., None], x, 0),
                             self._tp_axis)
        else:
            x = L.embedding_apply(params["embed"], input_ids, c.dtype)
        if c.pos_embedding == "learned":
            if positions is None:
                positions = jnp.arange(input_ids.shape[1])[None, :]
            x = x + L.embedding_apply(params["pos_embed"], positions,
                                      c.dtype)
        if c.token_type_vocab:
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros_like(input_ids))
            x = x + L.embedding_apply(params["type_embed"], tt, c.dtype)
        if c.embed_layernorm:
            x = self._norm_fn()(params["ln_embed"], x)
        return x

    def _project(self, params, x):
        c = self.config
        if c.mlm_head:
            # BERT prediction-head transform (HF BertLMPredictionHead):
            # dense → act → LN → tied decoder + vocab bias
            mh = params["mlm_head"]
            h = L.dense_apply(mh["dense"], x)
            h = L.ACT_FNS[c.activation](h)
            h = self._norm_fn()(mh["ln"], h)
            logits = L.embedding_attend(params["embed"], h)
            return logits + mh["bias"].astype(logits.dtype)
        if c.tie_embeddings:
            logits = L.embedding_attend(params["embed"], x)
        else:
            logits = jnp.einsum("...d,dv->...v", x,
                                params["lm_head"]["kernel"].astype(x.dtype),
                                preferred_element_type=jnp.float32)
            if "bias" in params["lm_head"]:  # GPT-J carries a head bias
                logits = logits + params["lm_head"]["bias"]
        if self._tp_axis is not None:
            # vocab-sharded head (tied table [V/mp, D] or lm_head kernel
            # (None, 'model')): local [.., V/mp] logits tile back into
            # the full vocab — shard order IS vocab order, so greedy
            # argmax over the gather matches the single-device program
            logits = jax.lax.all_gather(logits, self._tp_axis, axis=-1,
                                        tiled=True)
        return logits

    def hidden_states_and_aux(self, params, input_ids, rng=None, train=True,
                              token_type_ids=None):
        """Forward up to the final norm → ([B,T,D], moe_aux_loss scalar)."""
        c = self.config
        x = self._embed_tokens(params, input_ids,
                               token_type_ids=token_type_ids)

        def sb_fn(sp, x, key, window=None):
            if c.remat == "host_offload":
                # name the per-layer residual stream so the offload remat
                # policy can spill it to host DRAM between fwd and bwd
                from jax.ad_checkpoint import checkpoint_name
                x = checkpoint_name(x, "block_in")
            sp = self.block_transform(sp)
            y, _, la = self._superblock(sp, x, None, None, key, train,
                                        window)
            return y, la
        sb = self._remat(sb_fn)
        zero = jnp.zeros((), jnp.float32)

        if rng is not None and c.moe_enabled:
            keys = jax.random.split(rng, c.scan_length)

            def scan_fn(carry, xs):
                sp, key = xs
                y, la = sb(sp, carry[0], key)
                return (y, carry[1] + la), None
            (x, laux), _ = jax.lax.scan(scan_fn, (x, zero),
                                        (params["blocks"], keys))
        elif c.attention_layers:
            # per-layer window rides the scan so the block compiles once
            def scan_fn(carry, xs):
                sp, win = xs
                y, la = sb(sp, carry[0], None, win)
                return (y, carry[1] + la), None
            (x, laux), _ = jax.lax.scan(
                scan_fn, (x, zero),
                (params["blocks"], self._layer_windows()))
        else:
            def scan_fn(carry, sp):
                y, la = sb(sp, carry[0], None)
                return (y, carry[1] + la), None
            (x, laux), _ = jax.lax.scan(scan_fn, (x, zero), params["blocks"])
        if not c.final_layernorm:
            return x, laux
        return self._norm_fn()(params["ln_f"], x), laux

    def hidden_states(self, params, input_ids):
        """Forward up to the final norm, pre-projection ([B,T,D])."""
        return self.hidden_states_and_aux(params, input_ids)[0]

    def _paged_supported(self) -> Optional[str]:
        """None when the paged decode path serves this config, else the
        reason it cannot (the serving engine surfaces it at build)."""
        c = self.config
        if not c.causal:
            return "paged decode needs a causal (decoder) model"
        if c.moe_enabled:
            return "paged decode does not cover MoE block stacks yet"
        if c.attention_layers:
            return ("paged decode does not apply per-layer local windows "
                    "(GPT-Neo family)")
        if c.pos_embedding == "alibi":
            return "paged decode does not carry the ALiBi bias yet"
        from ..ops.transformer.paged_decode_attention import supports
        if not supports(c.hdim):
            return f"head_dim {c.hdim} is not lane-aligned (multiple of 8)"
        return None

    @staticmethod
    def _paged_kv_bits(pool_k, k_scale, hd: int) -> int:
        """Static kv-cache width from the pool's (trace-time) shape: 0
        when unquantized, else 8 (int8 at full head_dim) or 4 (packed
        nibbles at head_dim // 2)."""
        if k_scale is None:
            return 0
        return 8 if pool_k.shape[-1] == hd else 4

    def _apply_paged_decode(self, params, input_ids, cache):
        """Continuous-batching decode step: one new token per slot
        against the paged KV pool.

        ``cache``: {"k"/"v": [L, num_blocks, block, kv_heads, hd] pools
        (int8 at hd | hd//2 width plus "k_scale"/"v_scale"
        [L, num_blocks, block, kv_heads] f32 when quantized),
        "block_tables": [B, pages] int32, "lens": [B] int32 (tokens
        already cached per slot; 0 = inactive)}.  Returns
        ``(logits [B, 1, V], cache with updated pools and lens + 1)``.
        Slots advance independently — this is the program the serving
        scheduler re-dispatches every iteration without retracing."""
        reason = self._paged_supported()
        if reason is not None:
            raise NotImplementedError(reason)
        if input_ids.shape[1] != 1:
            raise NotImplementedError(
                "paged decode consumes one token per slot per step")
        tables, lens = cache["block_tables"], cache["lens"]
        quant = cache.get("k_scale") is not None
        positions = lens[:, None]          # each slot decodes at its own pos
        x = self._embed_tokens(params, input_ids, positions=positions)

        def scan_fn(carry, xs):
            bp, *pools = xs
            bp = self.block_transform(bp)
            y, new_pools = self._block(
                bp, carry, PagedKVCache(*pools[:2], tables, lens,
                                        *pools[2:]), positions)
            return y, new_pools

        xs = (params["blocks"], cache["k"], cache["v"])
        if quant:
            xs += (cache["k_scale"], cache["v_scale"])
        x, pools = jax.lax.scan(scan_fn, x, xs)
        if self.config.final_layernorm:
            x = self._norm_fn()(params["ln_f"], x)
        new_cache = {"k": pools[0], "v": pools[1], "block_tables": tables,
                     "lens": jnp.where(lens > 0, lens + 1, 0)}
        if quant:
            new_cache["k_scale"], new_cache["v_scale"] = pools[2], pools[3]
        return self._project(params, x), new_cache

    def _apply_paged_mixed(self, params, cache, dec_tokens, dec_active,
                           chunk_ids, chunk_slot, chunk_start, chunk_len,
                           spec_tokens=None, spec_active=None):
        """Mixed continuous-batching step: one decode token per active
        slot PLUS one ``chunk_ids``-sized chunk of a single slot's
        prompt PLUS (optionally) a speculative verify run per slot, in
        ONE program (Sarathi-Serve chunked prefill — the prefill never
        monopolizes an iteration and the program shape is independent
        of the prompt-length distribution; the spec lane is Leviathan
        et al.'s verify step batched over slots).

        ``cache``: {"k"/"v": [L, num_blocks, block, kv_heads, hd] pools,
        "block_tables": [B, pages] int32, "lens": [B] int32 (rows
        already in the pool per slot)}.  ``dec_tokens``/``dec_active``
        [B] int32; ``chunk_ids`` [C] int32 (padded with anything past
        ``chunk_len``; C may be STATICALLY 0 — the chunk lane then
        compiles away); ``chunk_slot``/``chunk_start``/``chunk_len``
        int32 scalars.  ``spec_tokens`` [B, S] int32 arms the spec
        lane: row b holds the slot's last emitted token followed by
        draft proposals d_1..d_{S-1}, fed at positions lens[b]..
        lens[b]+S-1; ``spec_active`` [B] selects the verifying slots
        (their ``dec_active`` must be 0).  Returns ``(dec_logits
        [B, V], chunk_logits [V] — the chunk's LAST VALID position, the
        first-token sample point when the chunk completes a prefix,
        new_cache)``, with ``spec_logits [B, S, V]`` inserted after
        ``dec_logits`` when the spec lane is armed."""
        reason = self._paged_supported()
        if reason is not None:
            raise NotImplementedError(reason)
        tables, lens = cache["block_tables"], cache["lens"]
        quant = cache.get("k_scale") is not None
        bsl = dec_tokens.shape[0]
        sw = 0 if spec_tokens is None else spec_tokens.shape[1]
        c = chunk_ids.shape[0]
        pos_parts, id_parts = [lens], [dec_tokens]
        if sw:
            # spec positions: lens[b] + i for verifying slots; parked
            # at 0 for the rest (null-block rows, position clamped away
            # from the table edge like padded chunk rows)
            spos = jnp.where((spec_active > 0)[:, None],
                             lens[:, None] + jnp.arange(sw)[None, :], 0)
            pos_parts.append(spos.reshape(-1))
            id_parts.append(spec_tokens.reshape(-1))
        if c:
            ci = jnp.arange(c)
            # clamp padded chunk positions to 0: base + i past chunk_len
            # can exceed the rotary/learned position tables near
            # max_seq_len
            cpos = jnp.where(ci < chunk_len, chunk_start + ci, 0)
            pos_parts.append(cpos)
            id_parts.append(chunk_ids)
        positions = jnp.concatenate(pos_parts)[None]   # [1, B+B*S+C]
        ids = jnp.concatenate(id_parts)[None]
        x = self._embed_tokens(params, ids, positions=positions)
        # data-sharded decode slots: the chunk indexes a GLOBAL slot, so
        # gather the full block tables ONCE here (they are loop
        # constants — the layer scan reuses the gathered copy, it is not
        # a per-layer collective)
        tables_g = (None if self._dp_axis is None else
                    jax.lax.all_gather(tables, self._dp_axis, axis=0,
                                       tiled=True))
        st_args = (tables, lens, dec_active, chunk_slot, chunk_start,
                   chunk_len, tables_g, spec_active, sw)

        def scan_fn(carry, xs):
            bp, *pools = xs
            bp = self.block_transform(bp)
            y, new_pools = self._block(
                bp, carry, PagedMixedState(*pools[:2], *st_args,
                                           *pools[2:]), positions)
            return y, new_pools

        xs = (params["blocks"], cache["k"], cache["v"])
        if quant:
            xs += (cache["k_scale"], cache["v_scale"])
        x, pools = jax.lax.scan(scan_fn, x, xs)
        if self.config.final_layernorm:
            x = self._norm_fn()(params["ln_f"], x)
        # project only the rows anything samples from: the B decode
        # rows, the B*S spec rows, and the chunk's last valid position
        # (a [B + B*S + 1, V] head instead of [B + B*S + C, V])
        nsample = bsl + bsl * sw
        if c:
            last = jax.lax.dynamic_slice_in_dim(
                x[0], nsample + jnp.maximum(chunk_len - 1, 0), 1, axis=0)
            logits = self._project(
                params, jnp.concatenate([x[0, :nsample], last])[None])
            chunk_logits = logits[0, nsample]
        else:
            logits = self._project(params, x[0, :nsample][None])
            chunk_logits = jnp.zeros((logits.shape[-1],), logits.dtype)
        new_lens = lens + (dec_active > 0).astype(lens.dtype)
        # with data-sharded slots `lens` is this shard's rows and
        # chunk_slot is global: translate to the local row, dropping the
        # update on shards that don't own the chunk slot (the serving
        # engine recomputes lens host-side every dispatch either way —
        # including the spec lane's accepted-token advance, which only
        # the host knows after the accept/reject compare)
        cs = (chunk_slot if self._dp_axis is None else
              chunk_slot - jax.lax.axis_index(self._dp_axis) * bsl)
        new_lens = new_lens.at[cs].add(chunk_len, mode="drop")
        new_cache = {"k": pools[0], "v": pools[1], "block_tables": tables,
                     "lens": new_lens}
        if quant:
            new_cache["k_scale"], new_cache["v_scale"] = pools[2], pools[3]
        if sw:
            spec_logits = logits[0, bsl:nsample].reshape(
                bsl, sw, logits.shape[-1])
            return (logits[0, :bsl], spec_logits, chunk_logits, new_cache)
        return logits[0, :bsl], chunk_logits, new_cache

    def init_paged_cache(self, num_blocks: int, block_size: int,
                         dtype=None, kv_bits: int = 0) -> Dict:
        """Preallocated paged KV pool for continuous-batching serving:
        ``num_blocks`` fixed-size blocks of ``block_size`` tokens shared
        by every sequence through per-slot block tables (block 0 is the
        allocator's reserved null block).  Pools are per layer; tables
        and lens start empty — the serving engine owns them.

        ``kv_bits`` 8 or 4 stores the pool COMPRESSED: int8 values at
        head_dim (8-bit) or packed-nibble head_dim // 2 (4-bit) width,
        with per-row per-head f32 scales in ``k_scale``/``v_scale`` —
        2x / ~3.8x more tokens per HBM byte, and the attention kernels
        dequantize in their inner loop (``serving.kv_cache_bits``)."""
        reason = self._paged_supported()
        if reason is not None:
            raise NotImplementedError(reason)
        c = self.config
        dtype = dtype or c.dtype
        if kv_bits not in (0, 4, 8):
            raise ValueError(f"kv_bits must be 0, 4 or 8, got {kv_bits}")
        if kv_bits == 4 and c.hdim % 2:
            raise ValueError(
                f"packed int4 KV needs an even head_dim, got {c.hdim}")
        if kv_bits:
            d_eff = c.hdim if kv_bits == 8 else c.hdim // 2
            shape = (c.num_layers, num_blocks, block_size, c.kv_heads,
                     d_eff)
            sshape = shape[:-1]
            return {"k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(sshape, jnp.float32),
                    "v_scale": jnp.zeros(sshape, jnp.float32)}
        shape = (c.num_layers, num_blocks, block_size, c.kv_heads, c.hdim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def init_cache(self, batch: int, max_len: int, dtype=None) -> Dict:
        c = self.config
        dtype = dtype or c.dtype
        if c.moe_enabled:
            shape = (c.scan_length, c.attn_per_block, batch, max_len,
                     c.kv_heads, c.hdim)
        else:
            shape = (c.num_layers, batch, max_len, c.kv_heads, c.hdim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
                "index": jnp.array(0, jnp.int32)}

    # -- loss --------------------------------------------------------------
    def loss(self, params, batch) -> jnp.ndarray:
        """Causal LM loss. batch: {'input_ids' [B,T]} (labels = shifted) or
        explicit {'input_ids', 'labels', optional 'loss_mask'}."""
        ids = batch["input_ids"]
        mask = batch.get("loss_mask")
        if "labels" in batch:
            labels, logits_in = batch["labels"], ids
        else:
            # Shift labels, keep the full T through the model (power-of-two
            # seq lengths keep the flash kernel's block divisibility); the
            # final position is masked out instead of sliced off.
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.zeros_like(ids[:, :1])], axis=1)
            logits_in = ids
            last_mask = jnp.ones_like(ids, dtype=jnp.float32).at[:, -1].set(0.0)
            mask = last_mask if mask is None else mask * last_mask

        # Optional per-step gate randomness (RTS / noisy gating): pass
        # batch["moe_rng"] = jax.random.PRNGKey(step) to engine.train_step —
        # the engine splits it into one key per microbatch (shard_batch) and
        # the GAS scan delivers a (2,)-shaped key here. Absent = deterministic
        # routing.
        moe_rng = batch.get("moe_rng")
        aux_coef = (self.config.moe_aux_loss_coef
                    if self.config.moe_enabled else 0.0)

        x, laux = self.hidden_states_and_aux(params, logits_in, rng=moe_rng)
        return self.nll_from_hidden(params, x, labels, mask) \
            + aux_coef * laux

    def nll_from_hidden(self, params, x, labels, mask=None) -> jnp.ndarray:
        """Mean masked NLL from final hidden states ([B,T,D]) — the loss
        HEAD alone, exposed so it can be timed/attributed separately from
        the trunk (bench.py phase breakdown)."""
        c = self.config
        chunk = c.loss_chunk
        t = labels.shape[1]
        if c.fused_loss_head and not c.mlm_head and self._tp_axis is None:
            # Analytic fused head: backward recomputes chunk logits and
            # forms (softmax − onehot)·mask·ḡ in-VJP — no [B,T,V] logit
            # cotangent in HBM (ops/transformer/fused_loss.py).
            from ..ops.transformer.fused_loss import fused_linear_xent
            if c.tie_embeddings:
                w, bias, tw = params["embed"]["embedding"], None, True
            else:
                w = params["lm_head"]["kernel"]
                bias = params["lm_head"].get("bias")
                tw = False
            b = labels.shape[0]
            rows = b * t
            # chunk in whole token columns so the row chunking matches the
            # checkpointed path's [B, chunk] tiles
            row_chunk = b * chunk if (chunk and t > chunk
                                      and t % chunk == 0) else 0
            tot, cnt = fused_linear_xent(
                x.reshape(rows, x.shape[-1]), w, labels.reshape(rows),
                None if mask is None else mask.reshape(rows),
                bias=bias, transpose_w=tw, chunk=row_chunk)
            return tot / jnp.maximum(cnt, 1.0)
        if chunk and t > chunk and t % chunk == 0:
            # Chunked CE: never materialize [B,T,V]; per chunk the projection
            # + logsumexp recompute in backward (jax.checkpoint).
            n_chunks = t // chunk

            def to_chunks(a):
                return a.reshape(a.shape[0], n_chunks, chunk,
                                 *a.shape[2:]).swapaxes(0, 1)

            @jax.checkpoint
            def chunk_nll(xc, yc, mc):
                logits = self._project(params, xc)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(logits, yc[..., None],
                                          axis=-1)[..., 0]
                nll = lse - tgt
                return jnp.sum(nll * mc), jnp.sum(mc)

            mc_all = (to_chunks(mask.astype(jnp.float32)) if mask is not None
                      else jnp.ones((n_chunks, labels.shape[0], chunk),
                                    jnp.float32))

            def body(carry, xs):
                tot, cnt = carry
                s, n = chunk_nll(*xs)
                return (tot + s, cnt + n), None
            (tot, cnt), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                (to_chunks(x), to_chunks(labels), mc_all))
            return tot / jnp.maximum(cnt, 1.0)

        logits = self._project(params, x)
        # logsumexp form avoids materializing the full [B,T,V] log-prob array
        # (matters at vocab 50k: that array is the single biggest HBM tensor).
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = lse - tgt
        if mask is None:
            return jnp.mean(nll)
        mask = mask.astype(nll.dtype)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    # -- partitioning ------------------------------------------------------
    # TP rules keyed on the TRAILING (module, weight) path pair — depth-
    # independent so dense blocks, MoE superblocks, and stacked expert trees
    # all resolve. Specs are for the weight's own dims; leading stack axes
    # (scan layer axis, expert axis) are prepended in spec_for.
    _SUFFIX_RULES = {
        ("embed", "embedding"): ("model", None),
        ("pos_embed", "embedding"): (None, None),
        ("qkv", "kernel"): (None, "model"),
        ("qkv", "bias"): ("model",),
        ("out", "kernel"): ("model", None),
        ("out", "bias"): (None,),
        ("fc_in", "kernel"): (None, "model"),
        ("fc_in", "bias"): ("model",),
        ("fc_gate", "kernel"): (None, "model"),
        ("fc_gate", "bias"): ("model",),
        ("fc_out", "kernel"): ("model", None),
        ("fc_out", "bias"): (None,),
        ("lm_head", "kernel"): (None, "model"),
        ("type_embed", "embedding"): (None, None),
        ("dense", "kernel"): (None, None),     # mlm_head transform
        ("dense", "bias"): (None,),
        ("mlm_head", "bias"): (None,),
    }

    def partition_specs(self, params=None) -> Dict:
        """Params-shaped PartitionSpec tree: tensor-parallel layout over the
        ``model`` mesh axis (Megatron-style column/row split — role of the
        reference's `module_inject/replace_module.py:23` ReplaceWithTensorSlicing,
        decided here declaratively); MoE expert stacks shard over ``expert``
        (reference expert groups, `utils/groups.py:109`). Leading axis of
        ``blocks`` leaves is the scan/layer axis (never sharded)."""
        if params is None:
            params = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        # MoE subtrees defer to MoELayer's own spec tree (single source of
        # truth — pluggable experts bring their own specs); only the leading
        # scan axis is prepended here.
        moe_specs = (self._moe.partition_specs()
                     if self.config.moe_enabled else None)

        def spec_for(path, leaf):
            keys = tuple(p.key for p in path)
            ndim = len(leaf.shape)
            if "moe" in keys:
                sp = moe_specs
                for k in keys[keys.index("moe") + 1:]:
                    sp = sp[k]
                return P(None, *sp)            # [scan, ...moe spec...]
            if any(k.startswith("ln") for k in keys):  # norms replicate
                inner = (None,) * (1 if keys[0] != "blocks" else ndim - 1)
            else:
                inner = self._SUFFIX_RULES.get(keys[-2:])
                if inner is None:
                    raise KeyError(f"No partition rule for param {keys}")
            lead = [None] * (ndim - len(inner))
            return P(*lead, *inner)

        return jax.tree_util.tree_map_with_path(spec_for, params)
