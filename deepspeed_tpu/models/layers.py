"""Functional NN layers (pure init/apply, pytree params).

This is the compute vocabulary of the model zoo. Where the reference fuses
these into CUDA kernels (`/root/reference/csrc/transformer/` — gelu, layernorm,
softmax, dropout, transform kernels), we express them as jnp ops and let XLA
fuse them into the surrounding matmuls; Pallas kernels replace only the ops
XLA can't schedule well (attention — see `deepspeed_tpu/ops/`).

Params are plain nested dicts so every parallelism layer (ZeRO, TP, PP) can
operate on them as pytrees with partition-spec trees.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# Initializers
# ---------------------------------------------------------------------------
def normal_init(rng, shape, stddev=0.02, dtype=jnp.float32):
    return (stddev * jax.random.normal(rng, shape)).astype(dtype)


def scaled_init(rng, shape, stddev, num_layers, dtype=jnp.float32):
    """GPT-2 style residual-branch init: stddev / sqrt(2 * num_layers)."""
    return normal_init(rng, shape, stddev / math.sqrt(2.0 * num_layers), dtype)


def zeros_init(_rng, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones_init(_rng, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------
def dense_init(rng, in_dim: int, out_dim: int, use_bias: bool = True,
               stddev: float = 0.02, dtype=jnp.float32):
    p = {"kernel": normal_init(rng, (in_dim, out_dim), stddev, dtype)}
    if use_bias:
        p["bias"] = jnp.zeros((out_dim,), dtype)
    return p


def dense_apply(params, x, *, precision=None):
    # Kernel is cast to the activation dtype so fp32 master params don't
    # silently promote the whole stream to fp32 (bf16 in → bf16 out).
    y = jnp.einsum("...i,io->...o", x, params["kernel"].astype(x.dtype),
                   precision=precision)
    if "bias" in params:
        y = y + params["bias"].astype(y.dtype)
    return y


# ---------------------------------------------------------------------------
# LayerNorm / RMSNorm — computed in fp32 regardless of activation dtype,
# matching the reference's normalize_kernels.cu accumulation behavior.
# ---------------------------------------------------------------------------
def layernorm_init(_rng, dim: int, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layernorm_apply(params, x, eps: float = 1e-5):
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"].astype(jnp.float32)
    if "bias" in params:
        y = y + params["bias"].astype(jnp.float32)
    return y.astype(orig_dtype)


def rmsnorm_init(_rng, dim: int, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype)}


def rmsnorm_apply(params, x, eps: float = 1e-6):
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (y * params["scale"].astype(jnp.float32)).astype(orig_dtype)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------
def gelu(x):
    # tanh approximation — same variant as the reference's gelu_kernels.cu.
    return jax.nn.gelu(x, approximate=True)


ACT_FNS = {
    "gelu": gelu,
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


# ---------------------------------------------------------------------------
# Rotary position embeddings (GPT-NeoX style)
# ---------------------------------------------------------------------------
def rotary_freqs(head_dim: int, rotary_dim: int, max_seq: int,
                 base: float = 10000.0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    inv = 1.0 / (base ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32)
                          / rotary_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                      # [T, rotary_dim/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary(x, cos, sin, positions=None, interleaved=True):
    """x: [B, T, H, Dh]; rotate first rotary_dim dims.

    ``interleaved=True`` — GPT-J/RoFormer "rotate_every_two" pairing
    (dims 2i, 2i+1), the reference's rotate_every_two path in
    `csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu`.
    ``interleaved=False`` — GPT-NeoX "rotate_half" pairing (dims i, i+d/2),
    the convention of the NeoX family and HF GPTNeoX.
    """
    rotary_dim = cos.shape[-1] * 2
    x_rot, x_pass = x[..., :rotary_dim], x[..., rotary_dim:]
    if positions is None:
        c = cos[None, :x.shape[1], None, :]
        s = sin[None, :x.shape[1], None, :]
    else:
        c = cos[positions][:, :, None, :]
        s = sin[positions][:, :, None, :]
    if interleaved:
        x1, x2 = x_rot[..., 0::2], x_rot[..., 1::2]
        y1 = x1 * c - x2 * s
        y2 = x2 * c + x1 * s
        y = jnp.stack([y1, y2], axis=-1).reshape(x_rot.shape)
    else:
        half = rotary_dim // 2
        x1, x2 = x_rot[..., :half], x_rot[..., half:]
        y1 = x1 * c - x2 * s
        y2 = x2 * c + x1 * s
        y = jnp.concatenate([y1, y2], axis=-1)
    return jnp.concatenate([y.astype(x.dtype), x_pass], axis=-1)


# ---------------------------------------------------------------------------
# Attention core (XLA path; Pallas flash kernel replaces this on TPU hot path)
# ---------------------------------------------------------------------------
def causal_attention(q, k, v, *, mask: Optional[jnp.ndarray] = None,
                     scale: Optional[float] = None,
                     kv_positions_offset: int = 0,
                     causal: bool = True,
                     bias: Optional[jnp.ndarray] = None):
    """q,k,v: [B, Tq, H, Dh] / [B, Tk, H, Dh]. Softmax in fp32 (the reference's
    softmax_kernels.cu accumulates fp32 too). Returns [B, Tq, H, Dh].

    ``causal=False`` — encoder (bidirectional) attention. ``bias`` —
    additive fp32 logit bias broadcastable to [B, H, Tq, Tk] (ALiBi)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # bf16 operands, fp32 accumulation — MXU-native mixed precision.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    tq, tk = q.shape[1], k.shape[1]
    if causal:
        q_pos = jnp.arange(tq) + kv_positions_offset
        k_pos = jnp.arange(tk)
        cmask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(cmask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def gqa_attention(q, k, v, *, mask: Optional[jnp.ndarray] = None,
                  scale: Optional[float] = None,
                  kv_positions_offset: int = 0, causal: bool = True,
                  bias: Optional[jnp.ndarray] = None):
    """Grouped-query attention WITHOUT materializing expanded k/v:
    q [B,Tq,H,Dh] with H = G·Hkv groups attends k/v [B,Tk,Hkv,Dh] via a
    group einsum — peak working set stays at the kv-width cache (the
    memory moment GQA exists for). ``mask`` broadcastable to
    [B,1,1,Tq,Tk]; ``bias`` to [B,H,Tq,Tk] (regrouped internally)."""
    b, tq, nh, hd = q.shape
    nkv = k.shape[2]
    g = nh // nkv
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    qg = q.reshape(b, tq, nkv, g, hd)
    logits = jnp.einsum("btkgd,bskd->bkgts", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        bias = jnp.broadcast_to(
            bias.astype(jnp.float32),
            bias.shape[:-3] + (nh,) + bias.shape[-2:])
        logits = logits + bias.reshape(bias.shape[:-3] + (nkv, g)
                                       + bias.shape[-2:])
    tk = k.shape[1]
    if causal:
        q_pos = jnp.arange(tq) + kv_positions_offset
        cmask = q_pos[:, None] >= jnp.arange(tk)[None, :]
        logits = jnp.where(cmask[None, None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", probs, v)
    return out.reshape(b, tq, nh, hd)


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """ALiBi head slopes (Press et al.; BLOOM's build_alibi_tensor,
    HF modeling_bloom.py): powers of 2^(-8/n) with the non-power-of-two
    extension interleaving from 2^(-4/n)."""
    import math as _m
    n = 2 ** _m.floor(_m.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(_m.log2(n) - 3)))
    slopes = [base ** (i + 1) for i in range(n)]
    if n < num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(_m.log2(2 * n) - 3)))
        extra = [extra_base ** (i + 1) for i in range(0, 2 * (num_heads - n),
                                                      2)]
        slopes += extra
    return jnp.asarray(slopes, jnp.float32)


def alibi_bias(num_heads: int, tk: int, q_positions) -> jnp.ndarray:
    """[H, Tq, Tk] additive bias: -slope_h * |q_pos - k_pos| — equals the
    BLOOM causal convention on the visible (k <= q) region and stays a
    distance PENALTY (never a boost) for future keys when used
    bidirectionally."""
    slopes = alibi_slopes(num_heads)                     # [H]
    k_pos = jnp.arange(tk)
    rel = -jnp.abs(k_pos[None, :] - q_positions[:, None])   # [Tq, Tk] <= 0
    return slopes[:, None, None] * rel[None].astype(jnp.float32)


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------
def embedding_init(rng, vocab: int, dim: int, stddev=0.02, dtype=jnp.float32):
    return {"embedding": normal_init(rng, (vocab, dim), stddev, dtype)}


def embedding_apply(params, ids, dtype=None):
    emb = params["embedding"]
    if dtype is not None:
        emb = emb.astype(dtype)
    return jnp.take(emb, ids, axis=0)


def embedding_apply_onehot(params, ids, dtype=None):
    """Embedding lookup as one_hot @ table — the gather-free form that
    GSPMD can partition when the vocab dim is sharded (TP embeddings under
    manual collectives; the reference shards embeddings the same way via
    VocabParallelEmbedding-style masking)."""
    emb = params["embedding"]
    if dtype is not None:
        emb = emb.astype(dtype)
    oh = jax.nn.one_hot(ids, emb.shape[0], dtype=emb.dtype)
    return jnp.einsum("...v,vd->...d", oh, emb)


def embedding_attend(params, x):
    """Tied-softmax projection: x @ embedding.T — bf16 operands, fp32
    accumulation (logits come out fp32 without a fp32 matmul)."""
    return jnp.einsum("...d,vd->...v", x,
                      params["embedding"].astype(x.dtype),
                      preferred_element_type=jnp.float32)
