"""TPU-native Stable-Diffusion-class model family: UNet / VAE / CLIP text.

Role-equivalent of the reference's diffusers integration
(`/root/reference/deepspeed/model_implementations/diffusers/unet.py`,
`vae.py`, `transformers/clip_encoder.py` and the fused kernels in
`ops/transformer/inference/diffusers_attention.py` +
`csrc/spatial/csrc/opt_bias_add.cu`): there the HF torch modules are
wrapped in CUDA graphs and their attention/bias-add swapped for fused
kernels. Here the models are implemented natively in JAX with NHWC
layouts (TPU conv units want channels-last — the reference itself moves
to ``torch.channels_last``), jit replaces CUDA-graph capture, and XLA
fuses the bias-add/GroupNorm/SiLU chains the reference hand-wrote
kernels for.

Architecture follows the published Stable-Diffusion v1.x component specs
(UNet2DConditionModel / AutoencoderKL / CLIPTextModel as documented by
their HF configs); weight import from HF checkpoints is handled by
`module_inject.diffusion_policies`.

All modules are pure-function: ``init(rng) -> params`` pytree,
``apply(params, ...)`` jittable.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import layers as L

# ---------------------------------------------------------------------------
# primitives (NHWC)
# ---------------------------------------------------------------------------
_DN = ("NHWC", "HWIO", "NHWC")


def conv_init(rng, kh, kw, cin, cout, dtype=jnp.float32):
    fan_in = kh * kw * cin
    k = jax.random.normal(rng, (kh, kw, cin, cout), dtype) / math.sqrt(
        fan_in)
    return {"kernel": k, "bias": jnp.zeros((cout,), dtype)}


def conv_apply(p, x, stride=1, padding="SAME"):
    if padding == "SAME":
        # torch Conv2d(padding=(k-1)//2) semantics: SYMMETRIC pads (XLA
        # "SAME" pads asymmetrically under stride>1, which would shift
        # every strided conv half a pixel vs the HF checkpoints)
        k = p["kernel"].shape[0]
        padding = [((k - 1) // 2, (k - 1) // 2)] * 2
    y = jax.lax.conv_general_dilated(
        x, p["kernel"].astype(x.dtype), (stride, stride), padding,
        dimension_numbers=_DN)
    return y + p["bias"].astype(x.dtype)


def groupnorm_init(_rng, c, dtype=jnp.float32):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def groupnorm_apply(p, x, groups=32, eps=1e-5):
    """NHWC GroupNorm (diffusers default eps 1e-5, VAE uses 1e-6)."""
    n, h, w, c = x.shape
    g = min(groups, c)
    xg = x.reshape(n, h, w, g, c // g).astype(jnp.float32)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    out = xg.reshape(n, h, w, c)
    return (out * p["scale"].astype(jnp.float32)
            + p["bias"].astype(jnp.float32)).astype(x.dtype)


def silu(x):
    return x * jax.nn.sigmoid(x)


def timestep_embedding(t, dim, max_period=10000.0, flip_sin_to_cos=True,
                       shift=0.0):
    """Sinusoidal timestep embedding (diffusers get_timestep_embedding:
    half sin / half cos, SD flips to cos-first)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :] + shift
    sin, cos = jnp.sin(args), jnp.cos(args)
    emb = jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos],
                          axis=-1)
    if dim % 2:
        emb = jnp.pad(emb, ((0, 0), (0, 1)))
    return emb


def _attn(q, k, v, heads):
    """Multi-head attention over flattened token axes ([B, T, C])."""
    b, tq, c = q.shape
    tk = k.shape[1]
    dh = c // heads
    q = q.reshape(b, tq, heads, dh).transpose(0, 2, 1, 3)
    k = k.reshape(b, tk, heads, dh).transpose(0, 2, 1, 3)
    v = v.reshape(b, tk, heads, dh).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    s = s / math.sqrt(dh)
    a = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
    return o.transpose(0, 2, 1, 3).reshape(b, tq, c)


# ---------------------------------------------------------------------------
# UNet building blocks
# ---------------------------------------------------------------------------
def _resnet_init(rng, cin, cout, temb_dim, dtype):
    ks = jax.random.split(rng, 4)
    p = {"norm1": groupnorm_init(None, cin, dtype),
         "conv1": conv_init(ks[0], 3, 3, cin, cout, dtype),
         "norm2": groupnorm_init(None, cout, dtype),
         "conv2": conv_init(ks[1], 3, 3, cout, cout, dtype)}
    if temb_dim:
        p["time_emb_proj"] = L.dense_init(ks[2], temb_dim, cout)
    if cin != cout:
        p["conv_shortcut"] = conv_init(ks[3], 1, 1, cin, cout, dtype)
    return p


def _resnet_apply(p, x, temb, groups=32, eps=1e-5):
    h = conv_apply(p["conv1"], silu(groupnorm_apply(p["norm1"], x,
                                                    groups, eps)))
    if temb is not None and "time_emb_proj" in p:
        h = h + L.dense_apply(p["time_emb_proj"],
                              silu(temb))[:, None, None, :].astype(h.dtype)
    h = conv_apply(p["conv2"], silu(groupnorm_apply(p["norm2"], h,
                                                    groups, eps)))
    if "conv_shortcut" in p:
        x = conv_apply(p["conv_shortcut"], x)
    return x + h


def _basic_tblock_init(rng, dim, ctx_dim, dtype):
    """BasicTransformerBlock: self-attn, cross-attn, GEGLU ff."""
    ks = jax.random.split(rng, 10)
    d = dim

    def attn(k1, k2, k3, k4, kv_dim):
        return {"to_q": L.dense_init(k1, d, d, use_bias=False),
                "to_k": L.dense_init(k2, kv_dim, d, use_bias=False),
                "to_v": L.dense_init(k3, kv_dim, d, use_bias=False),
                "to_out": L.dense_init(k4, d, d)}
    return {
        "norm1": L.layernorm_init(None, d),
        "attn1": attn(ks[0], ks[1], ks[2], ks[3], d),
        "norm2": L.layernorm_init(None, d),
        "attn2": attn(ks[4], ks[5], ks[6], ks[7], ctx_dim),
        "norm3": L.layernorm_init(None, d),
        "ff": {"proj_in": L.dense_init(ks[8], d, 8 * d),   # GEGLU: 2 x 4d
               "proj_out": L.dense_init(ks[9], 4 * d, d)},
    }


def _basic_tblock_apply(p, x, ctx, heads):
    def run_attn(ap, h, kv):
        q = L.dense_apply(ap["to_q"], h)
        k = L.dense_apply(ap["to_k"], kv)
        v = L.dense_apply(ap["to_v"], kv)
        return L.dense_apply(ap["to_out"], _attn(q, k, v, heads))

    x = x + run_attn(p["attn1"], L.layernorm_apply(p["norm1"], x),
                     L.layernorm_apply(p["norm1"], x))
    x = x + run_attn(p["attn2"], L.layernorm_apply(p["norm2"], x), ctx)
    h = L.dense_apply(p["ff"]["proj_in"], L.layernorm_apply(p["norm3"], x))
    a, g = jnp.split(h, 2, axis=-1)
    # GEGLU with EXACT gelu (diffusers uses F.gelu, not the tanh approx)
    x = x + L.dense_apply(p["ff"]["proj_out"],
                          a * jax.nn.gelu(g, approximate=False))
    return x


def _transformer2d_init(rng, c, ctx_dim, depth, dtype):
    ks = jax.random.split(rng, depth + 2)
    return {
        "norm": groupnorm_init(None, c, dtype),
        "proj_in": conv_init(ks[0], 1, 1, c, c, dtype),
        "blocks": [_basic_tblock_init(ks[1 + i], c, ctx_dim, dtype)
                   for i in range(depth)],
        "proj_out": conv_init(ks[depth + 1], 1, 1, c, c, dtype),
    }


def _transformer2d_apply(p, x, ctx, heads, groups=32):
    n, h, w, c = x.shape
    res = x
    x = groupnorm_apply(p["norm"], x, groups, 1e-6)
    x = conv_apply(p["proj_in"], x)
    x = x.reshape(n, h * w, c)
    for bp in p["blocks"]:
        x = _basic_tblock_apply(bp, x, ctx, heads)
    x = x.reshape(n, h, w, c)
    return conv_apply(p["proj_out"], x) + res


# ---------------------------------------------------------------------------
# UNet2DCondition
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class UNetConfig:
    """SD v1.x UNet2DConditionModel surface (HF config names)."""
    sample_size: int = 64
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8          # head COUNT in SD1 configs
    transformer_depth: int = 1
    norm_num_groups: int = 32
    # which down blocks carry cross-attention (SD1: all but the last)
    down_block_types: Tuple[str, ...] = (
        "CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
        "CrossAttnDownBlock2D", "DownBlock2D")
    up_block_types: Tuple[str, ...] = (
        "UpBlock2D", "CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
        "CrossAttnUpBlock2D")
    dtype: Any = jnp.float32


class UNet2DCondition:
    """Denoising UNet with text cross-attention (NHWC, jit-ready)."""

    def __init__(self, config: UNetConfig):
        self.config = config

    # -- init --------------------------------------------------------------
    def init(self, rng) -> Dict:
        c = self.config
        dt = c.dtype
        bo = c.block_out_channels
        temb = bo[0] * 4
        keys = iter(jax.random.split(rng, 1024))
        p: Dict[str, Any] = {
            "conv_in": conv_init(next(keys), 3, 3, c.in_channels, bo[0],
                                 dt),
            "time_embedding": {
                "linear_1": L.dense_init(next(keys), bo[0], temb),
                "linear_2": L.dense_init(next(keys), temb, temb)},
        }
        # down blocks
        downs = []
        ch = bo[0]
        for bi, btype in enumerate(c.down_block_types):
            cout = bo[bi]
            blk = {"resnets": [], "attentions": []}
            for li in range(c.layers_per_block):
                blk["resnets"].append(_resnet_init(
                    next(keys), ch if li == 0 else cout, cout, temb, dt))
                if btype == "CrossAttnDownBlock2D":
                    blk["attentions"].append(_transformer2d_init(
                        next(keys), cout, c.cross_attention_dim,
                        c.transformer_depth, dt))
            if bi != len(bo) - 1:
                blk["downsample"] = conv_init(next(keys), 3, 3, cout, cout,
                                              dt)
            downs.append(blk)
            ch = cout
        p["down_blocks"] = downs
        # mid
        p["mid_block"] = {
            "resnets": [_resnet_init(next(keys), ch, ch, temb, dt),
                        _resnet_init(next(keys), ch, ch, temb, dt)],
            "attentions": [_transformer2d_init(
                next(keys), ch, c.cross_attention_dim,
                c.transformer_depth, dt)],
        }
        # up blocks (mirror: consume layers_per_block+1 skips each)
        ups = []
        rev = list(reversed(bo))
        for bi, btype in enumerate(c.up_block_types):
            cout = rev[bi]
            prev = rev[max(bi - 1, 0)]
            skip_base = rev[min(bi + 1, len(rev) - 1)]
            blk = {"resnets": [], "attentions": []}
            for li in range(c.layers_per_block + 1):
                res_skip = (skip_base if li == c.layers_per_block
                            else cout)
                res_in = prev if li == 0 else cout
                blk["resnets"].append(_resnet_init(
                    next(keys), res_in + res_skip, cout, temb, dt))
                if btype == "CrossAttnUpBlock2D":
                    blk["attentions"].append(_transformer2d_init(
                        next(keys), cout, c.cross_attention_dim,
                        c.transformer_depth, dt))
            if bi != len(bo) - 1:
                blk["upsample"] = conv_init(next(keys), 3, 3, cout, cout,
                                            dt)
            ups.append(blk)
        p["up_blocks"] = ups
        p["conv_norm_out"] = groupnorm_init(None, bo[0], dt)
        p["conv_out"] = conv_init(next(keys), 3, 3, bo[0], c.out_channels,
                                  dt)
        return p

    # -- forward -----------------------------------------------------------
    def apply(self, p, sample, timesteps, encoder_hidden_states):
        """sample [B,H,W,C_in] (NHWC latents), timesteps [B] int/float,
        encoder_hidden_states [B, T_text, ctx_dim] -> eps [B,H,W,C_out]."""
        c = self.config
        g = c.norm_num_groups
        heads = c.attention_head_dim
        ctx = encoder_hidden_states
        temb = timestep_embedding(jnp.asarray(timesteps),
                                  c.block_out_channels[0])
        te = p["time_embedding"]
        temb = L.dense_apply(te["linear_2"],
                             silu(L.dense_apply(te["linear_1"], temb)))

        x = conv_apply(p["conv_in"], sample)
        skips = [x]
        for bi, blk in enumerate(p["down_blocks"]):
            has_attn = len(blk["attentions"]) > 0
            for li, rp in enumerate(blk["resnets"]):
                x = _resnet_apply(rp, x, temb, g)
                if has_attn:
                    x = _transformer2d_apply(blk["attentions"][li], x, ctx,
                                             heads, g)
                skips.append(x)
            if "downsample" in blk:
                x = conv_apply(blk["downsample"], x, stride=2)
                skips.append(x)

        mid = p["mid_block"]
        x = _resnet_apply(mid["resnets"][0], x, temb, g)
        x = _transformer2d_apply(mid["attentions"][0], x, ctx, heads, g)
        x = _resnet_apply(mid["resnets"][1], x, temb, g)

        for bi, blk in enumerate(p["up_blocks"]):
            has_attn = len(blk["attentions"]) > 0
            for li, rp in enumerate(blk["resnets"]):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = _resnet_apply(rp, x, temb, g)
                if has_attn:
                    x = _transformer2d_apply(blk["attentions"][li], x, ctx,
                                             heads, g)
            if "upsample" in blk:
                n, h, w, cc = x.shape
                x = jax.image.resize(x, (n, h * 2, w * 2, cc), "nearest")
                x = conv_apply(blk["upsample"], x)

        x = silu(groupnorm_apply(p["conv_norm_out"], x, g))
        return conv_apply(p["conv_out"], x)


# ---------------------------------------------------------------------------
# VAE (AutoencoderKL)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215
    dtype: Any = jnp.float32


class AutoencoderKL:
    """VAE encoder/decoder (SD latent space). Mid blocks carry one
    single-head self-attention (diffusers AttnBlock)."""

    def __init__(self, config: VAEConfig):
        self.config = config

    def _attnblock_init(self, rng, ch):
        ks = jax.random.split(rng, 4)
        return {"group_norm": groupnorm_init(None, ch),
                "to_q": L.dense_init(ks[0], ch, ch),
                "to_k": L.dense_init(ks[1], ch, ch),
                "to_v": L.dense_init(ks[2], ch, ch),
                "to_out": L.dense_init(ks[3], ch, ch)}

    def _attnblock_apply(self, p, x, groups):
        n, h, w, ch = x.shape
        hh = groupnorm_apply(p["group_norm"], x, groups, 1e-6)
        hh = hh.reshape(n, h * w, ch)
        q = L.dense_apply(p["to_q"], hh)
        k = L.dense_apply(p["to_k"], hh)
        v = L.dense_apply(p["to_v"], hh)
        o = L.dense_apply(p["to_out"], _attn(q, k, v, heads=1))
        return x + o.reshape(n, h, w, ch)

    def _mid_init(self, rng, ch, dt):
        ks = jax.random.split(rng, 3)
        return {"resnets": [_resnet_init(ks[0], ch, ch, 0, dt),
                            _resnet_init(ks[1], ch, ch, 0, dt)],
                "attentions": [self._attnblock_init(ks[2], ch)]}

    def init(self, rng) -> Dict:
        c = self.config
        dt = c.dtype
        bo = c.block_out_channels
        keys = iter(jax.random.split(rng, 512))
        # encoder
        enc: Dict[str, Any] = {
            "conv_in": conv_init(next(keys), 3, 3, c.in_channels, bo[0],
                                 dt),
            "down_blocks": [], "mid_block": None}
        ch = bo[0]
        for bi, cout in enumerate(bo):
            blk = {"resnets": [_resnet_init(
                next(keys), ch if li == 0 else cout, cout, 0, dt)
                for li in range(c.layers_per_block)]}
            if bi != len(bo) - 1:
                blk["downsample"] = conv_init(next(keys), 3, 3, cout, cout,
                                              dt)
            enc["down_blocks"].append(blk)
            ch = cout
        enc["mid_block"] = self._mid_init(next(keys), ch, dt)
        enc["conv_norm_out"] = groupnorm_init(None, ch, dt)
        enc["conv_out"] = conv_init(next(keys), 3, 3, ch,
                                    2 * c.latent_channels, dt)
        # decoder
        dec: Dict[str, Any] = {
            "conv_in": conv_init(next(keys), 3, 3, c.latent_channels, ch,
                                 dt),
            "mid_block": self._mid_init(next(keys), ch, dt),
            "up_blocks": []}
        rev = list(reversed(bo))
        for bi, cout in enumerate(rev):
            cin = rev[max(bi - 1, 0)]
            blk = {"resnets": [_resnet_init(
                next(keys), cin if li == 0 else cout, cout, 0, dt)
                for li in range(c.layers_per_block + 1)]}
            if bi != len(bo) - 1:
                blk["upsample"] = conv_init(next(keys), 3, 3, cout, cout,
                                            dt)
            dec["up_blocks"].append(blk)
        dec["conv_norm_out"] = groupnorm_init(None, bo[0], dt)
        dec["conv_out"] = conv_init(next(keys), 3, 3, bo[0],
                                    c.in_channels, dt)
        return {"encoder": enc, "decoder": dec,
                "quant_conv": conv_init(next(keys), 1, 1,
                                        2 * c.latent_channels,
                                        2 * c.latent_channels, dt),
                "post_quant_conv": conv_init(next(keys), 1, 1,
                                             c.latent_channels,
                                             c.latent_channels, dt)}

    def encode(self, p, images):
        """images [B,H,W,3] -> (mean, logvar) of the latent posterior."""
        c = self.config
        g = c.norm_num_groups
        e = p["encoder"]
        x = conv_apply(e["conv_in"], images)
        for blk in e["down_blocks"]:
            for rp in blk["resnets"]:
                x = _resnet_apply(rp, x, None, g, 1e-6)
            if "downsample" in blk:
                x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
                x = conv_apply(blk["downsample"], x, stride=2,
                               padding="VALID")
        m = e["mid_block"]
        x = _resnet_apply(m["resnets"][0], x, None, g, 1e-6)
        x = self._attnblock_apply(m["attentions"][0], x, g)
        x = _resnet_apply(m["resnets"][1], x, None, g, 1e-6)
        x = silu(groupnorm_apply(e["conv_norm_out"], x, g, 1e-6))
        x = conv_apply(e["conv_out"], x)
        x = conv_apply(p["quant_conv"], x)
        mean, logvar = jnp.split(x, 2, axis=-1)
        return mean, logvar

    def decode(self, p, latents):
        """latents [B,h,w,4] (already / scaling_factor) -> [B,H,W,3]."""
        c = self.config
        g = c.norm_num_groups
        d = p["decoder"]
        x = conv_apply(p["post_quant_conv"], latents)
        x = conv_apply(d["conv_in"], x)
        m = d["mid_block"]
        x = _resnet_apply(m["resnets"][0], x, None, g, 1e-6)
        x = self._attnblock_apply(m["attentions"][0], x, g)
        x = _resnet_apply(m["resnets"][1], x, None, g, 1e-6)
        for blk in d["up_blocks"]:
            for rp in blk["resnets"]:
                x = _resnet_apply(rp, x, None, g, 1e-6)
            if "upsample" in blk:
                n, h, w, cc = x.shape
                x = jax.image.resize(x, (n, h * 2, w * 2, cc), "nearest")
                x = conv_apply(blk["upsample"], x)
        x = silu(groupnorm_apply(d["conv_norm_out"], x, g, 1e-6))
        return conv_apply(d["conv_out"], x)


# ---------------------------------------------------------------------------
# CLIP text encoder
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class CLIPTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32


class CLIPTextEncoder:
    """CLIP text tower (HF CLIPTextModel semantics: causal mask,
    quick_gelu, final LN; returns last_hidden_state)."""

    def __init__(self, config: CLIPTextConfig):
        self.config = config

    def init(self, rng) -> Dict:
        c = self.config
        d = c.hidden_size
        keys = iter(jax.random.split(rng, 8 * c.num_hidden_layers + 4))
        p = {"token_embedding": L.embedding_init(next(keys), c.vocab_size,
                                                 d),
             "position_embedding": L.embedding_init(
                 next(keys), c.max_position_embeddings, d),
             "final_layer_norm": L.layernorm_init(None, d),
             "layers": []}
        for _ in range(c.num_hidden_layers):
            p["layers"].append({
                "layer_norm1": L.layernorm_init(None, d),
                "q_proj": L.dense_init(next(keys), d, d),
                "k_proj": L.dense_init(next(keys), d, d),
                "v_proj": L.dense_init(next(keys), d, d),
                "out_proj": L.dense_init(next(keys), d, d),
                "layer_norm2": L.layernorm_init(None, d),
                "fc1": L.dense_init(next(keys), d, c.intermediate_size),
                "fc2": L.dense_init(next(keys), c.intermediate_size, d),
            })
        return p

    def apply(self, p, input_ids):
        c = self.config
        t = input_ids.shape[1]
        x = (L.embedding_apply(p["token_embedding"], input_ids)
             + L.embedding_apply(p["position_embedding"],
                                 jnp.arange(t)[None, :]))
        mask = jnp.where(
            jnp.arange(t)[None, :] <= jnp.arange(t)[:, None], 0.0,
            -jnp.inf).astype(jnp.float32)
        h = c.num_attention_heads
        dh = c.hidden_size // h
        for lp in p["layers"]:
            r = x
            y = L.layernorm_apply(lp["layer_norm1"], x, c.layer_norm_eps)
            q = L.dense_apply(lp["q_proj"], y)
            k = L.dense_apply(lp["k_proj"], y)
            v = L.dense_apply(lp["v_proj"], y)
            b = y.shape[0]
            q = q.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
            k = k.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
            v = v.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
            s = (jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(dh)
                 + mask[None, None])
            a = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(
                v.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", a, v)
            o = o.transpose(0, 2, 1, 3).reshape(b, t, c.hidden_size)
            x = r + L.dense_apply(lp["out_proj"], o)
            r = x
            y = L.layernorm_apply(lp["layer_norm2"], x, c.layer_norm_eps)
            y = L.dense_apply(lp["fc1"], y)
            y = y * jax.nn.sigmoid(1.702 * y)          # quick_gelu
            x = r + L.dense_apply(lp["fc2"], y)
        return L.layernorm_apply(p["final_layer_norm"], x,
                                 c.layer_norm_eps)


# ---------------------------------------------------------------------------
# DDIM scheduler + pipeline
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class DDIMConfig:
    num_train_timesteps: int = 1000
    beta_start: float = 0.00085
    beta_end: float = 0.012
    beta_schedule: str = "scaled_linear"    # SD default
    # SD's shipped scheduler config: timesteps shift up by one and the
    # final step targets alphas_cumprod[0], not alpha=1
    steps_offset: int = 1
    set_alpha_to_one: bool = False


class DDIMScheduler:
    """Deterministic DDIM sampling (eta=0)."""

    def __init__(self, config: DDIMConfig = DDIMConfig()):
        self.config = config
        c = config
        if c.beta_schedule == "scaled_linear":
            betas = np.linspace(c.beta_start ** 0.5, c.beta_end ** 0.5,
                                c.num_train_timesteps) ** 2
        else:
            betas = np.linspace(c.beta_start, c.beta_end,
                                c.num_train_timesteps)
        self.alphas_cumprod = jnp.asarray(
            np.cumprod(1.0 - betas), jnp.float32)
        self.final_alpha_cumprod = (
            jnp.float32(1.0) if c.set_alpha_to_one
            else self.alphas_cumprod[0])

    def timesteps(self, num_steps: int) -> np.ndarray:
        c = self.config
        step = c.num_train_timesteps // num_steps
        ts = (np.arange(num_steps) * step).round()[::-1].astype(np.int32)
        return np.minimum(ts + c.steps_offset, c.num_train_timesteps - 1)

    def step(self, eps, t, t_prev, sample):
        ac = self.alphas_cumprod
        a_t = ac[t]
        a_prev = jnp.where(t_prev >= 0, ac[jnp.maximum(t_prev, 0)],
                           self.final_alpha_cumprod)
        x0 = (sample - jnp.sqrt(1 - a_t) * eps) / jnp.sqrt(a_t)
        return jnp.sqrt(a_prev) * x0 + jnp.sqrt(1 - a_prev) * eps

    # pipeline seam (shared with EulerDiscreteScheduler): DDIM's latent
    # state is already in the UNet's variance-preserving space
    def scale_model_input(self, sample, t):
        return sample

    def init_noise_sigma(self, t0) -> float:
        return 1.0


class EulerDiscreteScheduler:
    """Deterministic Euler sampling (SD 2.x's shipped scheduler family):
    the latent state lives in sigma space (x = x0 + sigma * eps,
    sigma = sqrt((1-acp)/acp)), the UNet input is rescaled by
    1/sqrt(sigma^2+1), and each step is a first-order ODE update
    x <- x + (sigma_prev - sigma) * eps."""

    def __init__(self, config: DDIMConfig = DDIMConfig()):
        self.config = config
        c = config
        if c.beta_schedule == "scaled_linear":
            betas = np.linspace(c.beta_start ** 0.5, c.beta_end ** 0.5,
                                c.num_train_timesteps) ** 2
        else:
            betas = np.linspace(c.beta_start, c.beta_end,
                                c.num_train_timesteps)
        ac = np.cumprod(1.0 - betas)
        self.alphas_cumprod = jnp.asarray(ac, jnp.float32)
        self.sigmas = jnp.asarray(np.sqrt((1.0 - ac) / ac), jnp.float32)

    def timesteps(self, num_steps: int) -> np.ndarray:
        c = self.config
        step = c.num_train_timesteps // num_steps
        ts = (np.arange(num_steps) * step).round()[::-1].astype(np.int32)
        return np.minimum(ts + c.steps_offset, c.num_train_timesteps - 1)

    def init_noise_sigma(self, t0) -> float:
        return float(self.sigmas[int(t0)])

    def scale_model_input(self, sample, t):
        s = self.sigmas[t]
        return sample / jnp.sqrt(s * s + 1.0)

    def step(self, eps, t, t_prev, sample):
        s = self.sigmas[t]
        s_prev = jnp.where(t_prev >= 0,
                           self.sigmas[jnp.maximum(t_prev, 0)], 0.0)
        # epsilon prediction: dx/dsigma = eps
        return sample + (s_prev - s) * eps


class StableDiffusionPipeline:
    """Text -> image: CLIP encode, DDIM loop over the jitted UNet with
    classifier-free guidance, VAE decode. The jit on (unet step, decode)
    is the TPU equivalent of the reference's CUDA-graph capture
    (`model_implementations/diffusers/unet.py` DSUNet)."""

    def __init__(self, unet: UNet2DCondition, vae: AutoencoderKL,
                 text_encoder: CLIPTextEncoder,
                 scheduler: Optional[DDIMScheduler] = None):
        self.unet, self.vae, self.text = unet, vae, text_encoder
        self.scheduler = scheduler or DDIMScheduler()
        self._unet_step = jax.jit(self._raw_unet_step)
        self._decode = jax.jit(
            lambda vp, z: self.vae.decode(
                vp, z / self.vae.config.scaling_factor))
        self._encode_text = jax.jit(self.text.apply)

    def _raw_unet_step(self, up, latents, t, t_prev, ctx, guidance):
        model_in = self.scheduler.scale_model_input(latents, t)
        both = jnp.concatenate([model_in, model_in], axis=0)
        tt = jnp.full((both.shape[0],), t, jnp.int32)
        eps = self.unet.apply(up, both, tt, ctx)
        e_uncond, e_text = jnp.split(eps, 2, axis=0)
        eps = e_uncond + guidance * (e_text - e_uncond)
        return self.scheduler.step(eps, t, t_prev, latents)

    def __call__(self, params: Dict, prompt_ids, uncond_ids,
                 num_steps: int = 50, guidance: float = 7.5,
                 latents=None, rng=None, height=None, width=None):
        """params: {"unet":…, "vae":…, "text_encoder":…};
        prompt_ids/uncond_ids [B, 77] CLIP token ids."""
        uc = self.unet.config
        b = prompt_ids.shape[0]
        hh = (height or uc.sample_size * 8) // 8
        ww = (width or uc.sample_size * 8) // 8
        ctx = jnp.concatenate([
            self._encode_text(params["text_encoder"], uncond_ids),
            self._encode_text(params["text_encoder"], prompt_ids)], axis=0)
        ts = self.scheduler.timesteps(num_steps)
        if latents is None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            latents = jax.random.normal(
                rng, (b, hh, ww, uc.in_channels), jnp.float32)
            latents = latents * self.scheduler.init_noise_sigma(ts[0])
        for i, t in enumerate(ts):
            t_prev = ts[i + 1] if i + 1 < len(ts) else -1
            latents = self._unet_step(params["unet"], latents,
                                      jnp.int32(t), jnp.int32(t_prev),
                                      ctx, jnp.float32(guidance))
        images = self._decode(params["vae"], latents)
        return jnp.clip(images * 0.5 + 0.5, 0.0, 1.0)
