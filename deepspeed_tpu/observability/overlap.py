"""Host/device overlap profiler: where does an iteration's wall time go?

ROADMAP item 4 (async multi-step scheduling) needs an instrument before
it needs a scheduler: you cannot pipeline a bubble you cannot measure.
This module splits every engine iteration's wall time into

  - **host-plan** — scheduler/allocator/promote planning and bookkeeping
    between dispatches (wall minus everything below);
  - **dispatch-enqueue** — from calling the jitted step function to its
    return (tracing/dispatch of the async computation);
  - **device-wait** — from dispatch return to the host-side
    materialization the engine already performs (``np.asarray`` on the
    sampled ids), i.e. the host blocked on the device.

and derives ``overlap_frac = 1 - device_wait / wall`` — the fraction of
the iteration the host spent doing useful work rather than blocked on
the device. Today's synchronous engines sit near their floor; the async
scheduler's acceptance test is this number going UP.

Contract (same as every observability hook in this repo):
  - the timestamps reuse instants the engines already capture for their
    latency histograms — **no new device syncs** in any path;
  - disabled (default), every engine call site is ONE attribute check
    (``if ovl.enabled:``) — no allocation, no clock read;
  - enabled, the serving iteration adds two ``perf_counter`` reads
    (iteration bracket) and one per dispatch (enqueue/wait split);
  - export rides the existing flush boundary: gauges + histograms into
    the metrics registry, a per-iteration track into the Chrome trace
    via the tracer's event-source hook.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

#: overlap iteration tracks render as their own Perfetto process group
OVERLAP_TRACK_PID_OFFSET = 2000

#: buckets for the dimensionless overlap fraction
FRAC_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8,
                0.9, 0.95, 1.0)


class _Rec:
    __slots__ = ("kind", "t0_ns", "total_ns", "plan_ns", "enq_ns",
                 "wait_ns", "frac", "dispatches")

    def __init__(self):
        self.kind = ""
        self.t0_ns = 0
        self.total_ns = 0
        self.plan_ns = 0
        self.enq_ns = 0
        self.wait_ns = 0
        self.frac = 0.0
        self.dispatches = 0


class OverlapProfiler:
    """Per-iteration host/device overlap accounting (module singleton).

    Serving protocol (``ServingEngine._step_impl``)::

        if ovl.enabled: ovl.begin()
        ...                                # per dispatch:
        if ovl.enabled: ovl.note_dispatch(enqueue_s, wait_s)
        ...
        if ovl.enabled: ovl.end("serving")

    Training records one-shot (``ovl.observe("train", ...)``) from the
    timestamps the step path already takes.
    """

    def __init__(self, capacity: int = 2048):
        self.enabled = False
        self._capacity = int(capacity)
        self._ring: List[_Rec] = []
        self._n = 0
        self._lock = threading.Lock()
        self.rank = 0
        self._metrics: Dict[str, tuple] = {}
        # open-iteration accumulators (engine step loop is single-threaded)
        self._it_t0_ns = 0
        self._it_enq_s = 0.0
        self._it_wait_s = 0.0
        self._it_dispatches = 0

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool, capacity: Optional[int] = None,
                  rank: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and int(capacity) > 0:
                if int(capacity) != self._capacity or not self._ring:
                    self._capacity = int(capacity)
                    self._ring = []
                    self._n = 0
            if rank is not None:
                self.rank = int(rank)
            if enabled and not self._ring:
                self._ring = [_Rec() for _ in range(self._capacity)]
            self.enabled = bool(enabled)

    def _metrics_for(self, kind: str) -> tuple:
        m = self._metrics.get(kind)
        if m is not None:
            return m
        from . import get_registry
        reg = get_registry()
        # literal registration per engine kind — dstpu-lint's DRIFT001
        # resolver reads these names, keeping code and the docs metric
        # table verifiably in sync
        if kind == "serving":
            m = (reg.gauge("dstpu_serving_host_plan_ms",
                           "host planning time in the last serving "
                           "iteration"),
                 reg.gauge("dstpu_serving_device_wait_ms",
                           "host blocked on device in the last serving "
                           "iteration"),
                 reg.gauge("dstpu_serving_overlap_frac",
                           "1 - device_wait/wall for the last serving "
                           "iteration"),
                 reg.histogram("dstpu_serving_host_plan_seconds",
                               "serving per-iteration host planning time"),
                 reg.histogram("dstpu_serving_device_wait_seconds",
                               "serving per-iteration device wait"),
                 reg.histogram("dstpu_serving_overlap_frac_dist",
                               "serving per-iteration overlap fraction",
                               buckets=FRAC_BUCKETS))
        else:
            m = (reg.gauge("dstpu_train_host_plan_ms",
                           "host planning time in the last training step"),
                 reg.gauge("dstpu_train_device_wait_ms",
                           "host blocked on device in the last training "
                           "step"),
                 reg.gauge("dstpu_train_overlap_frac",
                           "1 - device_wait/wall for the last training "
                           "step"),
                 reg.histogram("dstpu_train_host_plan_seconds",
                               "training per-step host planning time"),
                 reg.histogram("dstpu_train_device_wait_seconds",
                               "training per-step device wait"),
                 reg.histogram("dstpu_train_overlap_frac_dist",
                               "training per-step overlap fraction",
                               buckets=FRAC_BUCKETS))
        self._metrics[kind] = m
        return m

    # -- serving iteration protocol ----------------------------------------
    def begin(self) -> None:
        self._it_t0_ns = time.perf_counter_ns()
        self._it_enq_s = 0.0
        self._it_wait_s = 0.0
        self._it_dispatches = 0

    def note_dispatch(self, enqueue_s: float, wait_s: float) -> None:
        self._it_enq_s += max(0.0, enqueue_s)
        self._it_wait_s += max(0.0, wait_s)
        self._it_dispatches += 1

    def end(self, kind: str = "serving") -> None:
        t0 = self._it_t0_ns
        if not t0:
            return
        self._it_t0_ns = 0
        total_s = (time.perf_counter_ns() - t0) / 1e9
        self.observe(kind, total_s=total_s, enqueue_s=self._it_enq_s,
                     wait_s=self._it_wait_s, t0_ns=t0,
                     dispatches=self._it_dispatches)

    # -- one-shot (training) ----------------------------------------------
    def observe(self, kind: str, total_s: float, enqueue_s: float,
                wait_s: float, t0_ns: Optional[int] = None,
                dispatches: int = 1) -> None:
        total_s = max(0.0, total_s)
        enqueue_s = max(0.0, min(enqueue_s, total_s))
        wait_s = max(0.0, min(wait_s, total_s - enqueue_s))
        plan_s = max(0.0, total_s - enqueue_s - wait_s)
        frac = 1.0 - (wait_s / total_s) if total_s > 0 else 1.0
        g_plan, g_wait, g_frac, h_plan, h_wait, h_frac = \
            self._metrics_for(kind)
        g_plan.set(plan_s * 1e3)
        g_wait.set(wait_s * 1e3)
        g_frac.set(frac)
        h_plan.observe(plan_s)
        h_wait.observe(wait_s)
        h_frac.observe(frac)
        with self._lock:
            if not self._ring:
                return
            rec = self._ring[self._n % self._capacity]
            rec.kind = kind
            rec.t0_ns = t0_ns if t0_ns is not None else \
                time.perf_counter_ns()
            rec.total_ns = int(total_s * 1e9)
            rec.plan_ns = int(plan_s * 1e9)
            rec.enq_ns = int(enqueue_s * 1e9)
            rec.wait_ns = int(wait_s * 1e9)
            rec.frac = frac
            rec.dispatches = dispatches
            self._n += 1

    # -- one-shot (pipeline bubble probe) ----------------------------------
    def record_bubble(self, frac: float) -> None:
        """Measured pipeline-bubble fraction (the pipeline engine's
        ``measure_bubble_fraction`` probe, `runtime/pipe/engine.py`).
        A gauge, not a histogram: the probe is an explicit profiling
        call, and the interesting value is the latest fit."""
        g = self._metrics.get("bubble")
        if g is None:
            from . import get_registry
            g = get_registry().gauge(
                "dstpu_train_bubble_frac",
                "measured pipeline bubble fraction (two-point slope fit "
                "over the compiled schedule)")
            self._metrics["bubble"] = g
        g.set(max(0.0, min(1.0, float(frac))))

    # -- introspection -----------------------------------------------------
    @property
    def recorded(self) -> int:
        return min(self._n, self._capacity)

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            if not self._n or not self._ring:
                return None
            rec = self._ring[(self._n - 1) % self._capacity]
            return {"kind": rec.kind, "total_s": rec.total_ns / 1e9,
                    "host_plan_s": rec.plan_ns / 1e9,
                    "enqueue_s": rec.enq_ns / 1e9,
                    "device_wait_s": rec.wait_ns / 1e9,
                    "overlap_frac": rec.frac,
                    "dispatches": rec.dispatches}

    def reset(self) -> None:
        with self._lock:
            self._n = 0

    # -- export (tracer event source) --------------------------------------
    def chrome_events(self, epoch_ns: int, rank: int
                      ) -> List[Dict[str, Any]]:
        """Per-iteration overlap track: one X slice per iteration plus a
        'C' counter series Perfetto renders as a graph."""
        pid = OVERLAP_TRACK_PID_OFFSET + rank
        with self._lock:
            n = min(self._n, self._capacity)
            start = self._n - n
            recs = [self._ring[i % self._capacity]
                    for i in range(start, self._n)]
        if not recs:
            return []
        kinds = sorted({r.kind for r in recs})
        tids = {k: i + 1 for i, k in enumerate(kinds)}
        out: List[Dict[str, Any]] = [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": f"overlap profiler rank {rank}"}},
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_sort_index",
             "args": {"sort_index": pid}},
        ]
        for k, t in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": t,
                        "name": "thread_name",
                        "args": {"name": f"{k} iterations"}})
        for rec in recs:
            ts = (rec.t0_ns - epoch_ns) / 1000.0
            out.append({"ph": "X", "pid": pid, "tid": tids[rec.kind],
                        "name": f"{rec.kind}_iteration", "cat": "overlap",
                        "ts": ts, "dur": rec.total_ns / 1000.0,
                        "args": {"host_plan_ms": rec.plan_ns / 1e6,
                                 "enqueue_ms": rec.enq_ns / 1e6,
                                 "device_wait_ms": rec.wait_ns / 1e6,
                                 "overlap_frac": round(rec.frac, 4),
                                 "dispatches": rec.dispatches}})
            out.append({"ph": "C", "pid": pid, "tid": tids[rec.kind],
                        "name": f"{rec.kind}_overlap", "ts": ts,
                        "args": {"host_plan_ms": rec.plan_ns / 1e6,
                                 "device_wait_ms": rec.wait_ns / 1e6}})
        return out


_profiler = OverlapProfiler()


def get_overlap_profiler() -> OverlapProfiler:
    return _profiler
