"""Metrics registry: counters / gauges / histograms with JSON and
Prometheus-textfile exporters.

The scalar half of the telemetry layer (spans live in ``tracer.py``).
Sites across the stack feed signals that previously died in local state:
resilience retry/give-up counts, skipped optimizer steps, jit program
builds, step-time distribution, comms volume, swap queue depth, device
memory watermark. Export formats:

  - Prometheus textfile (node_exporter textfile-collector convention:
    write ``<dir>/dstpu_rank<r>.prom`` atomically, let the collector
    scrape it) — fleet dashboards;
  - JSON snapshot — ad-hoc tooling and tests;
  - ``to_events(step)`` — the existing ``MonitorMaster`` fan-out, so
    TensorBoard/CSV/W&B see every scalar for free.

Everything here is stdlib-only and never touches the device: collectors
that read device-adjacent state (memory_stats, comms logs) are plain
host calls registered by their owners via ``set_collector``.
"""
from __future__ import annotations

import bisect
import json
import math
import os
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: default histogram boundaries — seconds, spanning 100 µs .. 60 s (step
#: times, I/O latencies); override per-histogram for other units
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0)


def interpolate_quantile(bounds: Sequence[float],
                         counts: Sequence[int], q: float) -> float:
    """The one histogram-quantile estimator (`histogram_quantile()`
    semantics): linear interpolation inside the bucket that crosses the
    target rank, with the +inf tail clamped to the highest finite bound.

    ``bounds`` are the finite upper bounds (sorted); ``counts`` are the
    **per-bucket** (non-cumulative) counts with the +inf tail appended,
    so ``len(counts) == len(bounds) + 1``.  Shared by ``Histogram``,
    the textfile ``_p50/_p95/_p99`` companion lines, and the fleet
    aggregator's bucket-wise merge — one estimator means a merged
    histogram and its sources can disagree by at most interpolation
    inside a single bucket, never by estimator drift.
    """
    if not (0.0 <= q <= 1.0):
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = sum(counts)
    if total == 0:
        return 0.0
    target = q * total
    acc = 0
    for i, c in enumerate(counts):
        if acc + c >= target and c > 0:
            if i >= len(bounds):                    # +inf bucket
                return float(bounds[-1])
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * (target - acc) / c
        acc += c
    return float(bounds[-1])


def decumulate(buckets: Sequence[Sequence[Any]]
               ) -> Tuple[Tuple[float, ...], List[int]]:
    """Split a ``to_json()``-shaped cumulative bucket list
    (``[[le | "+Inf", cumulative], ...]``) back into finite bounds and
    per-bucket counts (with +inf tail) — the inverse of
    ``Histogram.cumulative()``, used when merging registry *snapshots*
    rather than live ``Histogram`` objects."""
    bounds: List[float] = []
    counts: List[int] = []
    prev = 0
    for le, cum in buckets:
        if not (le == "+Inf" or le == math.inf):
            bounds.append(float(le))
        counts.append(int(cum) - prev)
        prev = int(cum)
    return tuple(bounds), counts


def sanitize_name(name: str) -> str:
    """Map an arbitrary span/op name onto the Prometheus charset.

    ASCII-strict: ``str.isalnum()`` is true for plenty of characters
    Prometheus rejects (``é``, ``Ⅻ``, CJK), so anything outside
    ``[a-zA-Z0-9_]`` becomes ``_``.
    """
    out = []
    for ch in name:
        out.append(ch if (ch.isascii() and ch.isalnum()) or ch == "_"
                   else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def tenant_metric_name(prefix: str, tenant: str, *parts: str) -> str:
    """Build a dynamic per-tenant series name that is always a valid
    Prometheus identifier AND collision-free.

    Escaping alone is not enough: two hostile tenant ids (``"a b"`` and
    ``"a.b"``) would both sanitize to ``a_b`` and silently merge their
    series — so whenever sanitization had to change the name (or it was
    empty), a short stable checksum of the *original* id is appended.
    """
    s = sanitize_name(tenant)
    if s != tenant or not s:
        s = f"{s}_{zlib.crc32(tenant.encode('utf-8', 'surrogatepass')) & 0xffff:04x}"
    return "_".join((prefix, s) + parts)


class Counter:
    """Monotonically increasing count."""
    kind = "counter"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-written value."""
    kind = "gauge"
    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics).

    Observations may carry an **exemplar** — a trace id linking the
    bucket back to the concrete request that landed in it (OpenMetrics
    exemplar semantics: the newest exemplar per bucket wins). Exemplar
    storage is lazily allocated on the first exemplar-carrying
    observation, so histograms without request tracing pay nothing.
    """
    kind = "histogram"
    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum",
                 "_count", "_exemplars")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # +inf tail
        self._sum = 0.0
        self._count = 0
        self._exemplars: Optional[List[Optional[Tuple[str, float]]]] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = [None] * len(self._counts)
                self._exemplars[i] = (exemplar, v)

    def exemplars(self) -> Dict[int, Tuple[str, float]]:
        """{bucket_index: (trace_id, value)} for buckets holding one."""
        with self._lock:
            if self._exemplars is None:
                return {}
            return {i: ex for i, ex in enumerate(self._exemplars)
                    if ex is not None}

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile by linear interpolation inside the
        bucket bounds (the `histogram_quantile()` estimator): the +inf
        bucket clamps to the highest finite bound, matching Prometheus."""
        with self._lock:
            counts = list(self._counts)
        return interpolate_quantile(self.buckets, counts, q)

    def merge(self, *others: "Histogram") -> "Histogram":
        """Bucket-wise merge: a NEW histogram whose per-bucket counts
        are the element-wise sums of ``self`` and ``others``.

        This is the fleet-aggregation primitive: merging the replicas'
        bucket counts and interpolating once is exact up to bucket
        resolution, whereas averaging per-replica quantiles is simply
        wrong (a p99 is not a mean).  All inputs must share identical
        bucket bounds — silently resampling mismatched layouts would
        hide exactly the kind of drift the lint exists to catch."""
        for o in others:
            if o.buckets != self.buckets:
                raise ValueError(
                    f"cannot merge histogram {o.name!r}: bucket bounds "
                    f"differ from {self.name!r} ({o.buckets} vs "
                    f"{self.buckets})")
        out = Histogram(self.name, help=self.help, buckets=self.buckets)
        for h in (self,) + others:
            with h._lock:
                counts = list(h._counts)
                s, c = h._sum, h._count
            for i, n in enumerate(counts):
                out._counts[i] += n
            out._sum += s
            out._count += c
            ex = h.exemplars()
            if ex:
                if out._exemplars is None:
                    out._exemplars = [None] * len(out._counts)
                for i, pair in ex.items():
                    out._exemplars[i] = pair
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def value(self) -> float:
        """Mean observation (the scalar fed to MonitorMaster)."""
        return self._sum / self._count if self._count else 0.0

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count), ...] ending with (+inf, count)."""
        with self._lock:
            counts = list(self._counts)
        out, acc = [], 0
        for le, c in zip(self.buckets, counts):
            acc += c
            out.append((le, acc))
        out.append((math.inf, acc + counts[-1]))
        return out


class MetricsRegistry:
    """Name-keyed get-or-create registry.

    ``enabled`` gates only the per-step feeds in the engine and the
    exporters; rare-event sites (retry loops, rendezvous) increment
    unconditionally — the cost is nanoseconds and the history is there
    the moment an operator turns export on.
    """

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}
        self._collectors: Dict[str, Callable[[], None]] = {}

    # -- creation ----------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()

    # -- collectors --------------------------------------------------------
    def set_collector(self, name: str, fn: Callable[[], None]) -> None:
        """Register (or replace) a pre-export hook that refreshes derived
        gauges — keyed by name so re-built engines don't stack stale
        closures."""
        with self._lock:
            self._collectors[name] = fn

    def collect(self) -> None:
        with self._lock:
            fns = list(self._collectors.values())
        for fn in fns:
            try:
                fn()
            except Exception:   # a broken collector must not kill export
                pass

    # -- export ------------------------------------------------------------
    def to_events(self, step: int, prefix: str = "Metrics/"
                  ) -> List[Tuple[str, float, int]]:
        """MonitorMaster-shaped [(name, value, step), ...]."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return [(prefix + name, float(m.value), step)
                for name, m in metrics]

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            metrics = sorted(self._metrics.items())
        out: Dict[str, Any] = {}
        for name, m in metrics:
            if m.kind == "histogram":
                out[name] = {"kind": m.kind, "sum": m.sum,
                             "count": m.count, "mean": m.value,
                             "p50": m.quantile(0.50),
                             "p95": m.quantile(0.95),
                             "p99": m.quantile(0.99),
                             "buckets": [[le if le != math.inf else "+Inf",
                                          c] for le, c in m.cumulative()]}
                ex = m.exemplars()
                if ex:
                    bounds = m.buckets
                    out[name]["exemplars"] = {
                        ("+Inf" if i >= len(bounds)
                         else repr(float(bounds[i]))): {
                            "trace_id": tid, "value": v}
                        for i, (tid, v) in sorted(ex.items())}
            else:
                out[name] = {"kind": m.kind, "value": m.value}
        return out

    def export_json(self, path: str) -> str:
        self.collect()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=2)
        os.replace(tmp, path)
        return path

    def to_prometheus(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if m.kind == "histogram":
                ex = m.exemplars()
                for i, (le, c) in enumerate(m.cumulative()):
                    le_s = "+Inf" if le == math.inf else repr(float(le))
                    line = f'{name}_bucket{{le="{le_s}"}} {c}'
                    if i in ex:
                        # OpenMetrics exemplar: link the bucket to the
                        # request trace that landed in it most recently
                        tid, v = ex[i]
                        line += f' # {{trace_id="{tid}"}} {v!r}'
                    lines.append(line)
                lines.append(f"{name}_sum {m.sum!r}")
                lines.append(f"{name}_count {m.count}")
                # estimated quantiles (interpolated inside the bucket
                # bounds) as companion gauges — dashboards get p50/p95/
                # p99 without a histogram_quantile() recording rule
                for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                    lines.append(f"{name}_{tag} {m.quantile(q)!r}")
            else:
                lines.append(f"{name} {m.value!r}")
        return "\n".join(lines) + "\n"

    def export_prometheus(self, path: str) -> str:
        """Atomic write — the node_exporter textfile collector must never
        read a torn file."""
        self.collect()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)
        return path
