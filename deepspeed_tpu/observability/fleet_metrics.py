"""Fleet metrics aggregation: one merged view over per-replica registries.

The fleet autoscaler (PR 16) used to steer by poking each replica handle
for its queue depth; dashboards saw whichever replica wrote a shared
gauge last. This module gives the fleet ONE metrics surface:

  - replicas contribute **snapshots** in the ``MetricsRegistry.to_json``
    shape (``add_snapshot``), or the aggregator refreshes itself from a
    live router (``observe_router``, which asks each ``ReplicaHandle``
    for ``metrics_snapshot()``);
  - **counters** are summed across replicas;
  - **gauges** keep a per-replica labeled series AND per-class ("role")
    rollups, plus the fleet sum;
  - **histograms** are merged **bucket-wise** — per-bucket counts are
    summed and the fleet p50/p95/p99 interpolated from the MERGED
    buckets (``interpolate_quantile``), never by averaging per-replica
    quantiles (a p99 is not a mean; averaging quantiles is statistically
    meaningless the moment replicas see different load);
  - exports mirror the per-process registry: an atomic Prometheus
    textfile (``{replica=...}`` / ``{fleet_class=...}`` labels) and a
    JSON snapshot.

The autoscaler reads ``class_queue_depth`` / ``class_replicas`` /
``burn_rate`` from here instead of touching replicas ad hoc, so policy
and dashboards see the same numbers. Stdlib-only; export-time code.
"""
from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from .metrics import decumulate, interpolate_quantile

#: gauge consulted for per-class queue depth rollups
QUEUE_DEPTH_GAUGE = "dstpu_serving_queue_depth"

#: gauge marking a replica routable (1) — the ``healthy_only`` filters
#: below skip replicas whose snapshot carries it at 0
UP_GAUGE = "dstpu_fleet_replica_up"

_SLO_BURN_PREFIX = "dstpu_slo_tenant_"


def _replica_up(r: Any) -> bool:
    """Routable-ness of a duck-typed replica handle: a real
    ``ReplicaHandle`` exposes ``state`` (HEALTHY = routable), policy-test
    stubs may expose only ``healthy``; absent both, assume up."""
    state = getattr(r, "state", None)
    if state is not None:
        return str(getattr(state, "value", state)) == "healthy"
    return bool(getattr(r, "healthy", True))


def _is_hist(entry: Dict[str, Any]) -> bool:
    return entry.get("kind") == "histogram"


def hist_snapshot(h: Any) -> Dict[str, Any]:
    """One live ``Histogram`` as a ``to_json()``-shaped entry — what a
    replica contributes to the aggregator for bucket-wise merging."""
    return {"kind": "histogram", "sum": h.sum, "count": h.count,
            "buckets": [[le if le != math.inf else "+Inf", c]
                        for le, c in h.cumulative()]}


class FleetMetricsAggregator:
    """Merge per-replica registry snapshots into a fleet-level view."""

    def __init__(self, fleet_id: str = "fleet"):
        self.fleet_id = fleet_id
        self._lock = threading.Lock()
        self._snapshots: Dict[str, Dict[str, Any]] = {}
        self._roles: Dict[str, str] = {}

    # -- intake ------------------------------------------------------------
    def add_snapshot(self, replica_id: str, snapshot: Dict[str, Any],
                     role: str = "mixed") -> None:
        """Register/replace one replica's ``to_json()``-shaped snapshot."""
        with self._lock:
            self._snapshots[str(replica_id)] = dict(snapshot)
            self._roles[str(replica_id)] = str(role)

    def observe_router(self, router: Any) -> int:
        """Refresh snapshots from a live fleet router's replica handles.

        Defensive by design: policy tests drive the autoscaler with stub
        routers, so any handle lacking ``metrics_snapshot`` contributes
        a minimal gauge-only snapshot built from the attributes every
        stub already has (``queue_depth``, ``state``/``healthy``).
        Replaces the previous observation wholesale — a replica the
        router no longer lists vanishes from the fleet view instead of
        contributing a stale snapshot forever. Returns the number of
        replicas observed.
        """
        fresh: Dict[str, Dict[str, Any]] = {}
        fresh_roles: Dict[str, str] = {}
        seen = 0
        for r in list(getattr(router, "replicas", []) or []):
            rid = str(getattr(r, "replica_id", f"replica{seen}"))
            role = str(getattr(r, "role", "mixed"))
            snap_fn = getattr(r, "metrics_snapshot", None)
            if callable(snap_fn):
                try:
                    snap = snap_fn()
                except Exception:
                    continue
            else:
                snap = {
                    QUEUE_DEPTH_GAUGE: {
                        "kind": "gauge",
                        "value": float(getattr(r, "queue_depth", 0) or 0)},
                    UP_GAUGE: {
                        "kind": "gauge",
                        "value": 1.0 if _replica_up(r) else 0.0},
                }
            fresh[rid] = dict(snap)
            fresh_roles[rid] = role
            seen += 1
        with self._lock:
            self._snapshots = fresh
            self._roles = fresh_roles
        return seen

    def clear(self) -> None:
        with self._lock:
            self._snapshots.clear()
            self._roles.clear()

    @property
    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._snapshots)

    # -- merge core --------------------------------------------------------
    def _cut(self) -> Tuple[Dict[str, Dict[str, Any]], Dict[str, str]]:
        with self._lock:
            return ({rid: snap for rid, snap in self._snapshots.items()},
                    dict(self._roles))

    @staticmethod
    def _merge_hist(name: str, entries: List[Dict[str, Any]]
                    ) -> Dict[str, Any]:
        """Bucket-wise merge of ``to_json()`` histogram entries."""
        bounds: Optional[Tuple[float, ...]] = None
        counts: List[int] = []
        total_sum, total_count = 0.0, 0
        for entry in entries:
            b, c = decumulate(entry.get("buckets", []))
            if bounds is None:
                bounds, counts = b, list(c)
            elif b != bounds:
                raise ValueError(
                    f"cannot merge histogram {name!r}: replica bucket "
                    f"bounds differ ({b} vs {bounds})")
            else:
                for i, n in enumerate(c):
                    counts[i] += n
            total_sum += float(entry.get("sum", 0.0))
            total_count += int(entry.get("count", 0))
        bounds = bounds or ()
        out: Dict[str, Any] = {
            "kind": "histogram", "sum": total_sum, "count": total_count,
            "mean": total_sum / total_count if total_count else 0.0}
        if bounds:
            out["p50"] = interpolate_quantile(bounds, counts, 0.50)
            out["p95"] = interpolate_quantile(bounds, counts, 0.95)
            out["p99"] = interpolate_quantile(bounds, counts, 0.99)
            cum, acc = [], 0
            for le, c in zip(bounds, counts[:-1]):
                acc += c
                cum.append([le, acc])
            cum.append(["+Inf", acc + counts[-1]])
            out["buckets"] = cum
        else:
            out["p50"] = out["p95"] = out["p99"] = 0.0
            out["buckets"] = []
        return out

    def merged(self) -> Dict[str, Any]:
        """The fleet snapshot: same shape as ``MetricsRegistry.to_json``
        plus per-replica / per-class breakdowns on scalar entries."""
        snaps, roles = self._cut()
        names: Dict[str, str] = {}
        for snap in snaps.values():
            for name, entry in snap.items():
                names.setdefault(name, entry.get("kind", "gauge"))
        out: Dict[str, Any] = {}
        for name in sorted(names):
            kind = names[name]
            entries = [(rid, snap[name]) for rid, snap in sorted(
                snaps.items()) if name in snap]
            if kind == "histogram":
                out[name] = self._merge_hist(
                    name, [e for _rid, e in entries if _is_hist(e)])
                continue
            per_replica = {rid: float(e.get("value", 0.0))
                           for rid, e in entries}
            per_class: Dict[str, float] = {}
            for rid, v in per_replica.items():
                role = roles.get(rid, "mixed")
                per_class[role] = per_class.get(role, 0.0) + v
            out[name] = {"kind": kind,
                         "value": sum(per_replica.values()),
                         "replicas": per_replica,
                         "classes": per_class}
        return out

    # -- autoscaler feeds --------------------------------------------------
    @staticmethod
    def _snap_up(snap: Dict[str, Any]) -> bool:
        entry = snap.get(UP_GAUGE)
        if entry is None:
            return True
        return float(entry.get("value", 1.0)) > 0.0

    def class_queue_depth(self, role: Optional[str] = None,
                          healthy_only: bool = False) -> float:
        """Total queued requests for one replica class (or the fleet);
        ``healthy_only`` counts routable replicas only — the
        autoscaler's view, matching its healthy-replica policy."""
        snaps, roles = self._cut()
        total = 0.0
        for rid, snap in snaps.items():
            if role is not None and roles.get(rid, "mixed") != role:
                continue
            if healthy_only and not self._snap_up(snap):
                continue
            entry = snap.get(QUEUE_DEPTH_GAUGE)
            if entry is not None:
                total += float(entry.get("value", 0.0))
        return total

    def class_replicas(self, role: Optional[str] = None,
                       healthy_only: bool = False) -> int:
        """Replicas currently contributing snapshots for a class."""
        snaps, roles = self._cut()
        return sum(
            1 for rid in snaps
            if (role is None or roles.get(rid, "mixed") == role)
            and (not healthy_only or self._snap_up(snaps[rid])))

    def burn_rate(self, kind: str = "ttft", which: str = "fast") -> float:
        """Worst per-tenant SLO burn rate across the fleet for ``kind``
        (max over tenants and replicas of the ``…_burn_fast`` /
        ``…_burn_slow`` gauges the SLO monitor exports)."""
        suffix = f"_{kind}_burn_{which}"
        snaps, _roles = self._cut()
        worst = 0.0
        for snap in snaps.values():
            for name, entry in snap.items():
                if name.startswith(_SLO_BURN_PREFIX) and \
                        name.endswith(suffix):
                    worst = max(worst, float(entry.get("value", 0.0)))
        return worst

    # -- export ------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {"fleet_id": self.fleet_id,
                "replicas": {rid: self._roles.get(rid, "mixed")
                             for rid in self.replica_ids},
                "metrics": self.merged()}

    def to_prometheus(self) -> str:
        """Fleet textfile: labeled per-replica and per-class series plus
        the fleet rollup; histogram lines come from the MERGED buckets."""
        merged = self.merged()
        lines: List[str] = []
        for name, entry in merged.items():
            kind = entry.get("kind", "gauge")
            lines.append(f"# TYPE {name} {kind}")
            if kind == "histogram":
                for le, cum in entry.get("buckets", []):
                    le_s = "+Inf" if le in ("+Inf", math.inf) \
                        else repr(float(le))
                    lines.append(f'{name}_bucket{{le="{le_s}"}} {cum}')
                lines.append(f"{name}_sum {entry['sum']!r}")
                lines.append(f"{name}_count {entry['count']}")
                for tag in ("p50", "p95", "p99"):
                    lines.append(f"{name}_{tag} {entry[tag]!r}")
                continue
            for rid, v in sorted(entry.get("replicas", {}).items()):
                lines.append(f'{name}{{replica="{rid}"}} {v!r}')
            for role, v in sorted(entry.get("classes", {}).items()):
                lines.append(f'{name}{{fleet_class="{role}"}} {v!r}')
            lines.append(f"{name} {entry['value']!r}")
        return "\n".join(lines) + "\n"

    def export_json(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=2)
        os.replace(tmp, path)
        return path

    def export_prometheus(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_prometheus())
        os.replace(tmp, path)
        return path
