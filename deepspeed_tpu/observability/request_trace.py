"""Request-scoped tracing: a per-request timeline from submit to terminal.

The span tracer (``tracer.py``) answers "where did this *iteration* go";
this module answers "why was THIS request slow". Every serving request is
assigned a trace id at ``submit()`` and the lifecycle sites that already
exist — admit, prefill chunk, decode batches, spec accept/reject,
preemption, quarantine, ``_terminalize`` — stamp segments into a bounded
per-request timeline. At flush the timelines are exported as extra
Chrome-trace tracks (one Perfetto thread per request, grouped under a
"serving requests" process per rank) merged into the same
``trace_rank<r>.json`` the span tracer writes, so the step spans and the
request waterfalls line up on one clock.

Overhead contract (the same one the span tracer pins):
  - disabled (default): every call site is ONE attribute check
    (``if rt.enabled:``) — no allocation, no clock read, no device sync;
  - enabled: list/dict mutation plus at most one ``perf_counter_ns``
    read per stamp; dispatch segments reuse the timestamps the engine
    already took for its latency histograms, so the hot path gains no
    extra clock reads;
  - export rides the existing flush boundary (``SpanTracer.flush``)
    via the tracer's event-source hook — never a new host sync.

Trace ids double as histogram exemplars: the engine/front-end pass
``req.trace_id`` into ``Histogram.observe(..., exemplar=...)`` so a bad
TTFT/ITL p99 bucket links back to concrete request timelines.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

#: request tracks render as their own Perfetto process group, offset from
#: the per-rank span process so the waterfall sorts below the step spans
REQUEST_TRACK_PID_OFFSET = 1000


class _Timeline:
    """One request's recorded lifetime. Mutated in place; bounded."""
    __slots__ = ("trace_id", "req_id", "tenant", "tid", "events", "phase",
                 "phase_t0_ns", "done", "dropped_segments")

    def __init__(self, trace_id: str, req_id: str, tenant: str, tid: int):
        self.trace_id = trace_id
        self.req_id = req_id
        self.tenant = tenant
        self.tid = tid
        # (ph, name, ts_ns, dur_ns, args) — ph "X" duration / "i" instant
        self.events: List[tuple] = []
        self.phase: Optional[str] = None
        self.phase_t0_ns = 0
        self.done = False
        self.dropped_segments = 0


class RequestTraceRecorder:
    """Process-global per-request timeline recorder.

    Bounded two ways: at most ``capacity`` request timelines are retained
    (oldest *completed* evicted first) and each timeline holds at most
    ``max_segments`` stamped events (later dispatch segments are counted
    as dropped; phase transitions and the terminal stamp always land).
    """

    def __init__(self, capacity: int = 512, max_segments: int = 256):
        self.enabled = False
        self._capacity = int(capacity)
        self._max_segments = int(max_segments)
        self._traces: "OrderedDict[str, _Timeline]" = OrderedDict()
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._tid_seq = itertools.count(1)
        self._dropped = 0
        self.rank = 0

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool, capacity: Optional[int] = None,
                  max_segments: Optional[int] = None,
                  rank: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and int(capacity) > 0:
                self._capacity = int(capacity)
            if max_segments is not None and int(max_segments) > 0:
                self._max_segments = int(max_segments)
            if rank is not None:
                self.rank = int(rank)
            self.enabled = bool(enabled)

    # -- introspection -----------------------------------------------------
    @property
    def recorded(self) -> int:
        return len(self._traces)

    @property
    def dropped(self) -> int:
        """Timelines evicted by the retention cap."""
        return self._dropped

    def get(self, trace_id: Optional[str]) -> Optional[_Timeline]:
        return self._traces.get(trace_id) if trace_id else None

    def lookup(self, req: Any) -> Optional[_Timeline]:
        """Timeline for a live request. Prefers the per-leg storage key
        (``req._trace_key``) over ``req.trace_id`` — under fleet trace
        propagation several legs (prefill, decode, failover replay)
        share ONE trace_id but each owns its own timeline."""
        key = getattr(req, "_trace_key", None) or \
            getattr(req, "trace_id", None)
        return self._traces.get(key) if key else None

    def reset(self) -> None:
        with self._lock:
            self._traces.clear()
            self._dropped = 0

    # -- internal ----------------------------------------------------------
    def _evict_locked(self) -> None:
        while len(self._traces) > self._capacity:
            victim = None
            for tl in self._traces.values():      # oldest completed first
                if tl.done:
                    victim = tl.trace_id
                    break
            if victim is None:                    # all live: drop oldest
                victim = next(iter(self._traces))
            del self._traces[victim]
            self._dropped += 1

    def _append(self, tl: _Timeline, ph: str, name: str, ts_ns: int,
                dur_ns: int, args: Optional[Dict[str, Any]],
                force: bool = False) -> None:
        if len(tl.events) >= self._max_segments and not force:
            tl.dropped_segments += 1
            return
        tl.events.append((ph, name, ts_ns, dur_ns, args))

    def _close_phase(self, tl: _Timeline, now_ns: int) -> None:
        if tl.phase is not None:
            self._append(tl, "X", tl.phase, tl.phase_t0_ns,
                         max(0, now_ns - tl.phase_t0_ns), None, force=True)
            tl.phase = None

    def _open_phase(self, tl: _Timeline, name: str, now_ns: int) -> None:
        tl.phase = name
        tl.phase_t0_ns = now_ns

    # -- lifecycle stamps (call sites guard on ``.enabled``) ---------------
    def on_submit(self, req: Any) -> str:
        """Assign ``req.trace_id`` and open the ``queued`` phase.

        A trace id already present on the request is HONOURED, not
        replaced — that is the distributed-trace contract: the fleet
        router mints one id per fleet request and every leg (prefill
        worker, decode replica, failover replay) stamps its segments
        under it. Each leg still gets its own timeline: on a storage-key
        collision the new leg is filed under ``trace_id#<seq>`` and the
        request remembers its key in ``req._trace_key``."""
        now = time.perf_counter_ns()
        preset = getattr(req, "trace_id", None)
        trace_id = preset or f"r{self.rank:x}-{next(self._seq):06x}"
        with self._lock:
            key = trace_id
            if key in self._traces:
                key = f"{trace_id}#{next(self._seq):x}"
            tl = _Timeline(trace_id, req.req_id, req.tenant,
                           next(self._tid_seq))
            self._open_phase(tl, "queued", now)
            self._traces[key] = tl
            self._evict_locked()
        req.trace_id = trace_id
        try:
            req._trace_key = key
        except Exception:       # slotted/frozen request objects opt out
            pass
        return trace_id

    def on_admit(self, req: Any, slot: int, cache_hit_tokens: int) -> None:
        tl = self.lookup(req)
        if tl is None:
            return
        now = time.perf_counter_ns()
        with self._lock:
            self._close_phase(tl, now)
            self._append(tl, "i", "admit", now, 0,
                         {"slot": slot, "cache_hit_tokens": cache_hit_tokens,
                          "trace_id": tl.trace_id})
            # a full prefix-cache hit skips straight to decode
            self._open_phase(tl, "prefill" if req.prefilling else "decode",
                             now)

    def on_preempt(self, req: Any) -> None:
        tl = self.lookup(req)
        if tl is None:
            return
        now = time.perf_counter_ns()
        with self._lock:
            self._close_phase(tl, now)
            self._append(tl, "i", "preempt", now, 0,
                         {"preemptions": req.preemptions}, force=True)
            self._open_phase(tl, "queued", now)

    def on_prefill_chunk(self, req: Any, t0_s: float, dur_s: float,
                         start: int, tokens: int, done: bool) -> None:
        tl = self.lookup(req)
        if tl is None:
            return
        t0_ns = int(t0_s * 1e9)
        with self._lock:
            self._append(tl, "X", "prefill_chunk", t0_ns, int(dur_s * 1e9),
                         {"start": start, "tokens": tokens})
            if done and tl.phase == "prefill":
                now = t0_ns + int(dur_s * 1e9)
                self._close_phase(tl, now)
                self._open_phase(tl, "decode", now)

    def on_decode(self, reqs: List[Any], t0_s: float, dur_s: float,
                  batch: int) -> None:
        t0_ns = int(t0_s * 1e9)
        dur_ns = int(dur_s * 1e9)
        with self._lock:
            for req in reqs:
                tl = self.lookup(req)
                if tl is not None:
                    self._append(tl, "X", "decode", t0_ns, dur_ns,
                                 {"batch": batch})

    def on_promote(self, reqs: List[Any], t0_s: float, dur_s: float,
                   blocks: int) -> None:
        """Host-tier promotion window: KV blocks restored from host
        DRAM/NVMe into the device pool while the request is held in the
        PROMOTING phase (docs/serving.md "Tiered prefix cache")."""
        t0_ns = int(t0_s * 1e9)
        dur_ns = int(dur_s * 1e9)
        with self._lock:
            for req in reqs:
                tl = self.lookup(req)
                if tl is not None:
                    self._append(tl, "X", "promote", t0_ns, dur_ns,
                                 {"blocks": blocks})

    def on_spec(self, reqs: List[Any], t0_s: float, dur_s: float,
                proposed: int, accepted: int) -> None:
        t0_ns = int(t0_s * 1e9)
        dur_ns = int(dur_s * 1e9)
        with self._lock:
            for req in reqs:
                tl = self.lookup(req)
                if tl is not None:
                    self._append(tl, "X", "spec_decode", t0_ns, dur_ns,
                                 {"proposed": proposed, "accepted": accepted})

    def on_segment(self, req: Any, name: str, t0_s: float, dur_s: float,
                   **args: Any) -> None:
        """Explicit duration segment stamped from timestamps the caller
        already took (fabric publish window, failover replay window) —
        these are fleet-trace flow anchors, so they always land even in
        a segment-capped timeline."""
        tl = self.lookup(req)
        if tl is None:
            return
        with self._lock:
            self._append(tl, "X", name, int(t0_s * 1e9), int(dur_s * 1e9),
                         args or None, force=True)

    def mark(self, req: Any, name: str, **args: Any) -> None:
        """Instantaneous event (quarantine, growth-hold, ...)."""
        tl = self.lookup(req)
        if tl is None:
            return
        with self._lock:
            self._append(tl, "i", name, time.perf_counter_ns(), 0,
                         args or None)

    def on_terminal(self, req: Any) -> None:
        tl = self.lookup(req)
        if tl is None:
            return
        now = time.perf_counter_ns()
        with self._lock:
            self._close_phase(tl, now)
            args = {"status": getattr(req.status, "name", str(req.status)),
                    "tokens": len(req.output),
                    "preemptions": req.preemptions,
                    "trace_id": tl.trace_id}
            if req.error:
                args["error"] = str(req.error)[:200]
            if tl.dropped_segments:
                args["dropped_segments"] = tl.dropped_segments
            self._append(tl, "i", "terminal", now, 0, args, force=True)
            tl.done = True

    # -- export (tracer event source; runs at the flush boundary) ----------
    def chrome_events(self, epoch_ns: int, rank: int) -> List[Dict[str, Any]]:
        """Chrome-trace events for every retained timeline: one thread
        track per request under a 'serving requests' process group."""
        pid = REQUEST_TRACK_PID_OFFSET + rank
        with self._lock:
            timelines = list(self._traces.values())
        if not timelines:
            return []
        out: List[Dict[str, Any]] = [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": f"serving requests rank {rank}"}},
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_sort_index",
             "args": {"sort_index": pid}},
        ]
        for tl in timelines:
            out.append({"ph": "M", "pid": pid, "tid": tl.tid,
                        "name": "thread_name",
                        "args": {"name": f"{tl.req_id} [{tl.tenant}]"}})
            out.append({"ph": "M", "pid": pid, "tid": tl.tid,
                        "name": "thread_sort_index",
                        "args": {"sort_index": tl.tid}})
            events = list(tl.events)
            if tl.phase is not None:     # still-open phase: emit to "now"
                now = time.perf_counter_ns()
                events.append(("X", tl.phase, tl.phase_t0_ns,
                               max(0, now - tl.phase_t0_ns),
                               {"open": True}))
            for ph, name, ts_ns, dur_ns, args in events:
                ev: Dict[str, Any] = {
                    "ph": ph, "pid": pid, "tid": tl.tid, "name": name,
                    "cat": "request", "ts": (ts_ns - epoch_ns) / 1000.0}
                if ph == "X":
                    ev["dur"] = dur_ns / 1000.0
                else:
                    ev["s"] = "t"
                ev["args"] = dict(args) if args else {}
                ev["args"].setdefault("trace_id", tl.trace_id)
                out.append(ev)
        return out


_recorder = RequestTraceRecorder()


def get_request_tracer() -> RequestTraceRecorder:
    return _recorder
