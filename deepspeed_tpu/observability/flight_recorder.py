"""Black-box flight recorder: bounded engine-state ring + post-mortem dump.

When a serving engine dies with a ``ServingError`` (watchdog trip, fatal
fault, failure to drain) or the training engine skips a burst of steps,
the histogram means and the last log line are not enough to reconstruct
*how it got there*. The flight recorder keeps a fixed-size ring of
per-iteration engine snapshots — queue depth, pool occupancy,
preemption/pinned counts, lifecycle/spec counters — plus the last N
terminal events, all plain host-side ints gathered at the existing
iteration boundary. On failure it dumps a **post-mortem bundle**:

    <output_dir>/postmortem-r<rank>-<seq>/
        reason.json       what tripped, free-form detail, engine diagnose
        snapshots.json    the ring, oldest first
        terminals.json    last N terminal request events
        metrics.prom      Prometheus textfile at the moment of death
        trace.json        Chrome trace (spans + request waterfalls),
                          when tracing is enabled
        fleet_events.json fleet lifecycle ring (handoffs, failovers,
                          drains/joins, replica deaths + the trace ids
                          of in-flight requests), when a fleet recorded
        manifest.json     content checksums (runtime/resilience integrity)

Every file is written with the atomic-write machinery from
``runtime/resilience/integrity.py`` and the bundle is sealed with
``write_manifest`` so tooling can verify it was not torn by the dying
process. Dumping must never make a bad day worse: ``dump()`` swallows
its own errors and rate-limits repeated triggers.

Overhead contract: disabled (default) every site is one attribute
check; enabled, ``record()`` is one in-place ring write of an
already-built dict — no I/O, no device interaction until a failure
actually dumps.
"""
from __future__ import annotations

import os
import shutil
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class FlightRecorder:
    """Process-global snapshot ring with atomic post-mortem bundles."""

    def __init__(self, capacity: int = 256):
        self.enabled = False
        self._capacity = int(capacity)
        self._ring: List[Optional[Dict[str, Any]]] = []
        self._n = 0                          # total snapshots ever recorded
        self._terminals: deque = deque(maxlen=64)
        #: fleet lifecycle events (handoff / failover / drain / join /
        #: replica_dead) — sealed into every bundle as fleet_events.json
        self._fleet_events: deque = deque(maxlen=64)
        self._lock = threading.Lock()
        self.output_dir = "flight_recorder"
        self.skip_burst_steps = 8
        self.max_bundles = 4
        self.min_dump_interval_s = 1.0
        self.rank = 0
        self._dump_seq = 0
        self._last_dump_t: Optional[float] = None
        self.last_bundle: Optional[str] = None

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool, capacity: Optional[int] = None,
                  output_dir: Optional[str] = None,
                  max_terminal_events: Optional[int] = None,
                  skip_burst_steps: Optional[int] = None,
                  max_bundles: Optional[int] = None,
                  rank: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and int(capacity) > 0:
                if int(capacity) != self._capacity or not self._ring:
                    self._capacity = int(capacity)
                    self._ring = []
                    self._n = 0
            if output_dir is not None:
                self.output_dir = output_dir
            if max_terminal_events is not None and int(max_terminal_events) > 0:
                self._terminals = deque(self._terminals,
                                        maxlen=int(max_terminal_events))
            if skip_burst_steps is not None:
                self.skip_burst_steps = int(skip_burst_steps)
            if max_bundles is not None and int(max_bundles) > 0:
                self.max_bundles = int(max_bundles)
            if rank is not None:
                self.rank = int(rank)
            if enabled and not self._ring:
                # preallocated like the span ring: record() never grows it
                self._ring = [None] * self._capacity
            self.enabled = bool(enabled)

    # -- recording (call sites guard on ``.enabled``) ----------------------
    def record(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            if not self._ring:
                return
            self._ring[self._n % self._capacity] = snap
            self._n += 1

    def note_terminal(self, info: Dict[str, Any]) -> None:
        with self._lock:
            self._terminals.append(info)

    def note_fleet_event(self, info: Dict[str, Any]) -> None:
        """Record one fleet lifecycle event (router/replica sites guard
        on ``.enabled``); stamped with a timestamp if the caller did not
        provide one."""
        if "t" not in info:
            info = dict(info, t=time.perf_counter())
        with self._lock:
            self._fleet_events.append(info)

    # -- introspection -----------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def recorded(self) -> int:
        return min(self._n, self._capacity)

    @property
    def dropped(self) -> int:
        return max(0, self._n - self._capacity)

    def snapshots(self) -> List[Dict[str, Any]]:
        """Retained snapshots, oldest first."""
        with self._lock:
            n = min(self._n, self._capacity)
            start = self._n - n
            return [self._ring[i % self._capacity]
                    for i in range(start, self._n)]

    def terminals(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._terminals)

    def fleet_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._fleet_events)

    def reset(self) -> None:
        with self._lock:
            self._ring = [None] * self._capacity if self._ring else []
            self._n = 0
            self._terminals.clear()
            self._fleet_events.clear()

    # -- post-mortem -------------------------------------------------------
    def dump(self, reason: str, detail: str = "",
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write a post-mortem bundle; returns its path, or None when
        disabled, rate-limited, or the dump itself failed (a recorder
        failure must never mask the original error)."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            if (self._last_dump_t is not None
                    and now - self._last_dump_t < self.min_dump_interval_s):
                return None
            self._last_dump_t = now
            self._dump_seq += 1
            seq = self._dump_seq
        try:
            return self._write_bundle(seq, reason, detail, extra)
        except Exception:
            return None

    def _write_bundle(self, seq: int, reason: str, detail: str,
                      extra: Optional[Dict[str, Any]]) -> str:
        from ..runtime.resilience.integrity import (atomic_write_json,
                                                    atomic_write_text,
                                                    write_manifest)
        bundle = os.path.join(self.output_dir,
                              f"postmortem-r{self.rank}-{seq:04d}")
        if os.path.exists(bundle):           # restarted process, stale seq
            bundle = f"{bundle}-{os.getpid()}"
        os.makedirs(bundle, exist_ok=True)
        atomic_write_json(os.path.join(bundle, "reason.json"), {
            "reason": reason, "detail": detail, "extra": extra or {},
            "rank": self.rank, "pid": os.getpid(),
            "unix_time": time.time(),
        }, indent=2)
        atomic_write_json(os.path.join(bundle, "snapshots.json"), {
            "count": self.recorded, "dropped": self.dropped,
            "snapshots": self.snapshots(),
        }, indent=2)
        atomic_write_json(os.path.join(bundle, "terminals.json"),
                          self.terminals(), indent=2)
        fleet_events = self.fleet_events()
        if fleet_events:
            # fleet context (when this process hosts a fleet): the event
            # ring plus the trace ids a post-mortem can chase into the
            # merged fleet trace
            atomic_write_json(os.path.join(bundle, "fleet_events.json"),
                              fleet_events, indent=2)
        from . import get_registry, get_tracer
        reg = get_registry()
        reg.collect()
        atomic_write_text(os.path.join(bundle, "metrics.prom"),
                          reg.to_prometheus())
        tracer = get_tracer()
        if tracer.enabled:
            # request-track event sources ride the same flush
            tracer.flush(path=os.path.join(bundle, "trace.json"))
        write_manifest(bundle)
        self._prune_bundles()
        self.last_bundle = bundle
        return bundle

    def _prune_bundles(self) -> None:
        try:
            mine = sorted(
                d for d in os.listdir(self.output_dir)
                if d.startswith(f"postmortem-r{self.rank}-"))
        except OSError:
            return
        for stale in mine[:-self.max_bundles]:
            shutil.rmtree(os.path.join(self.output_dir, stale),
                          ignore_errors=True)


_flight = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _flight
