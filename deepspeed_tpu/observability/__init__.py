"""Unified telemetry: span tracer + metrics registry (docs/observability.md).

One process-global :class:`~.tracer.SpanTracer` and one
:class:`~.metrics.MetricsRegistry`, configured from the master config's
``observability`` block (``runtime/config.py`` ``ObservabilityConfig``)
by whichever engine comes up first. Instrumentation sites across the
stack use the module helpers:

    from ..observability import trace_span, get_registry

    with trace_span("checkpoint/save", tag=tag):
        ...
    get_registry().counter("dstpu_io_retries_total").inc()

Span naming convention: ``subsystem/event`` with subsystem one of
``engine | pipe | offload | infinity | swap | checkpoint | comm |
elastic`` — the subsystem becomes the natural Perfetto search prefix.
Metric naming: Prometheus style, ``dstpu_<noun>_<unit>[_total]``.

With the block disabled (the default), ``trace_span`` is a single
attribute check returning a shared no-op and nothing here touches the
device — the acceptance contract the integration test pins.
"""
from __future__ import annotations

import atexit
import os
from typing import Any, List, Optional, Tuple

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      sanitize_name)
from .tracer import NULL_SPAN, SpanTracer  # noqa: F401

_tracer = SpanTracer()
_registry = MetricsRegistry()
_export = {"prometheus_dir": None, "json_path": None,
           "interval_steps": 0}
_atexit_armed = False


def get_tracer() -> SpanTracer:
    return _tracer


def get_registry() -> MetricsRegistry:
    return _registry


def trace_span(name: str, cat: str = "", **args):
    """Span context manager; the disabled path is one attribute check."""
    t = _tracer
    if not t.enabled:
        return NULL_SPAN
    return t.span(name, cat, **args)


#: metrics pre-registered at configure time so the very first Prometheus
#: textfile already carries every core series (a counter that appears
#: only after its first increment breaks rate() on restart)
_CORE_METRICS = (
    ("counter", "dstpu_train_steps_total",
     "optimizer steps taken (engine train_step)"),
    ("counter", "dstpu_train_skipped_steps_total",
     "steps skipped on overflow / non-finite grad norm (resilience)"),
    ("counter", "dstpu_io_retries_total",
     "transient I/O failures retried (runtime/resilience retry_call)"),
    ("counter", "dstpu_io_retry_giveups_total",
     "I/O operations that exhausted the retry budget"),
    ("counter", "dstpu_jit_programs_built_total",
     "jit programs traced+compiled by the engine (recompile watermark)"),
    ("counter", "dstpu_checkpoint_saves_total", "checkpoint save calls"),
    ("counter", "dstpu_checkpoint_loads_total", "checkpoint load calls"),
    ("counter", "dstpu_rendezvous_total",
     "elastic rendezvous generations joined"),
    ("histogram", "dstpu_step_time_seconds",
     "synchronized train-step wall time"),
    ("gauge", "dstpu_swap_queue_depth",
     "in-flight NVMe slot-store aio operations"),
    ("gauge", "dstpu_device_peak_memory_bytes",
     "device memory high-water mark (memory_stats)"),
    # training-phase roofline gauges, fed whenever a phase breakdown
    # runs (profiling/phase_bench.py feed_registry; bench.py, autotuner
    # trials with profiling on) — docs/training_perf.md
    ("gauge", "dstpu_train_backward_ms",
     "measured backward phase time per train step"),
    ("gauge", "dstpu_train_backward_efficiency",
     "backward roofline efficiency (ideal/measured, binding resource)"),
)


def _register_core_metrics() -> None:
    for kind, name, help in _CORE_METRICS:
        getattr(_registry, kind)(name, help=help)


def configure(obs_config: Any = None, rank: int = 0
              ) -> Tuple[SpanTracer, MetricsRegistry]:
    """Apply an ``ObservabilityConfig`` (or None → all off) to the
    process-global tracer/registry. Idempotent; the newest engine wins —
    telemetry is per-process, not per-engine."""
    global _atexit_armed
    if obs_config is None:
        _tracer.configure(enabled=False)
        _registry.enabled = False
        return _tracer, _registry
    tr = obs_config.tracing
    mt = obs_config.metrics
    _tracer.configure(enabled=tr.enabled, capacity=tr.buffer_size,
                      output_dir=tr.output_dir, rank=rank)
    _registry.enabled = bool(mt.enabled)
    _export["prometheus_dir"] = mt.prometheus_dir
    _export["json_path"] = mt.json_path
    _export["interval_steps"] = int(mt.export_interval_steps or 0)
    if mt.enabled:
        _register_core_metrics()
    if (tr.enabled or mt.enabled) and not _atexit_armed:
        atexit.register(flush_all)
        _atexit_armed = True
    return _tracer, _registry


def export_metrics() -> List[str]:
    """Write the configured metric exports (Prometheus textfile + JSON)."""
    if not _registry.enabled:
        return []
    paths: List[str] = []
    if _export["prometheus_dir"]:
        paths.append(_registry.export_prometheus(os.path.join(
            _export["prometheus_dir"], f"dstpu_rank{_tracer.rank}.prom")))
    if _export["json_path"]:
        paths.append(_registry.export_json(_export["json_path"]))
    return paths


def export_interval_steps() -> int:
    return _export["interval_steps"]


def flush_all(sync: Any = None) -> List[str]:
    """Flush trace + metric exports. ``sync`` — optional device value to
    join first (the explicit flush-boundary sync, via host_transfer)."""
    paths: List[str] = []
    if _tracer.enabled:
        paths.append(_tracer.flush(sync=sync))
    paths.extend(export_metrics())
    return paths
