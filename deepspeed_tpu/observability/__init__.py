"""Unified telemetry: span tracer + metrics registry (docs/observability.md).

One process-global :class:`~.tracer.SpanTracer` and one
:class:`~.metrics.MetricsRegistry`, configured from the master config's
``observability`` block (``runtime/config.py`` ``ObservabilityConfig``)
by whichever engine comes up first. Instrumentation sites across the
stack use the module helpers:

    from ..observability import trace_span, get_registry

    with trace_span("checkpoint/save", tag=tag):
        ...
    get_registry().counter("dstpu_io_retries_total").inc()

Span naming convention: ``subsystem/event`` with subsystem one of
``engine | pipe | offload | infinity | swap | checkpoint | comm |
elastic`` — the subsystem becomes the natural Perfetto search prefix.
Metric naming: Prometheus style, ``dstpu_<noun>_<unit>[_total]``.

With the block disabled (the default), ``trace_span`` is a single
attribute check returning a shared no-op and nothing here touches the
device — the acceptance contract the integration test pins.
"""
from __future__ import annotations

import atexit
import os
from typing import Any, List, Optional, Tuple

from .fleet_metrics import FleetMetricsAggregator  # noqa: F401
from .fleet_trace import (FleetTraceAssembler,  # noqa: F401
                          FleetTraceContext, validate_fleet_trace)
from .flight_recorder import FlightRecorder, get_flight_recorder  # noqa: F401
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      interpolate_quantile, sanitize_name,
                      tenant_metric_name)
from .overlap import OverlapProfiler, get_overlap_profiler  # noqa: F401
from .request_trace import (RequestTraceRecorder,  # noqa: F401
                            get_request_tracer)
from .slo import SloAlert, SloMonitor  # noqa: F401
from .slo import from_defaults as slo_from_defaults  # noqa: F401
from .tracer import NULL_SPAN, SpanTracer  # noqa: F401

_tracer = SpanTracer()
_registry = MetricsRegistry()
_export = {"prometheus_dir": None, "json_path": None,
           "interval_steps": 0}
_atexit_armed = False


def get_tracer() -> SpanTracer:
    return _tracer


def get_registry() -> MetricsRegistry:
    return _registry


def trace_span(name: str, cat: str = "", **args):
    """Span context manager; the disabled path is one attribute check."""
    t = _tracer
    if not t.enabled:
        return NULL_SPAN
    return t.span(name, cat, **args)


#: metrics pre-registered at configure time so the very first Prometheus
#: textfile already carries every core series (a counter that appears
#: only after its first increment breaks rate() on restart)
_CORE_METRICS = (
    ("counter", "dstpu_train_steps_total",
     "optimizer steps taken (engine train_step)"),
    ("counter", "dstpu_train_skipped_steps_total",
     "steps skipped on overflow / non-finite grad norm (resilience)"),
    ("counter", "dstpu_io_retries_total",
     "transient I/O failures retried (runtime/resilience retry_call)"),
    ("counter", "dstpu_io_retry_giveups_total",
     "I/O operations that exhausted the retry budget"),
    ("counter", "dstpu_jit_programs_built_total",
     "jit programs traced+compiled by the engine (recompile watermark)"),
    ("counter", "dstpu_checkpoint_saves_total", "checkpoint save calls"),
    ("counter", "dstpu_checkpoint_loads_total", "checkpoint load calls"),
    ("counter", "dstpu_rendezvous_total",
     "elastic rendezvous generations joined"),
    ("histogram", "dstpu_step_time_seconds",
     "synchronized train-step wall time"),
    ("gauge", "dstpu_swap_queue_depth",
     "in-flight NVMe slot-store aio operations"),
    ("gauge", "dstpu_device_peak_memory_bytes",
     "device memory high-water mark (memory_stats)"),
    # training-phase roofline gauges, fed whenever a phase breakdown
    # runs (profiling/phase_bench.py feed_registry; bench.py, autotuner
    # trials with profiling on) — docs/training_perf.md
    ("gauge", "dstpu_train_backward_ms",
     "measured backward phase time per train step"),
    ("gauge", "dstpu_train_backward_efficiency",
     "backward roofline efficiency (ideal/measured, binding resource)"),
)


def _register_core_metrics() -> None:
    for kind, name, help in _CORE_METRICS:
        getattr(_registry, kind)(name, help=help)


def configure(obs_config: Any = None, rank: int = 0
              ) -> Tuple[SpanTracer, MetricsRegistry]:
    """Apply an ``ObservabilityConfig`` (or None → all off) to the
    process-global tracer/registry. Idempotent; the newest engine wins —
    telemetry is per-process, not per-engine."""
    global _atexit_armed
    from . import slo as _slo_mod
    _rt = get_request_tracer()
    _fr = get_flight_recorder()
    _ovl = get_overlap_profiler()
    if obs_config is None:
        _tracer.configure(enabled=False)
        _registry.enabled = False
        _rt.configure(enabled=False)
        _fr.configure(enabled=False)
        _ovl.configure(enabled=False)
        _slo_mod.set_defaults(enabled=False)
        return _tracer, _registry
    tr = obs_config.tracing
    mt = obs_config.metrics
    _tracer.configure(enabled=tr.enabled, capacity=tr.buffer_size,
                      output_dir=tr.output_dir, rank=rank)
    _registry.enabled = bool(mt.enabled)
    _export["prometheus_dir"] = mt.prometheus_dir
    _export["json_path"] = mt.json_path
    _export["interval_steps"] = int(mt.export_interval_steps or 0)
    if mt.enabled:
        _register_core_metrics()
    # request-scoped tracing: rides the span tracer's flush as an extra
    # per-request track source (config validation already requires
    # tracing.enabled when request_tracing.enabled)
    rt_cfg = getattr(obs_config, "request_tracing", None)
    rt_enabled = bool(rt_cfg is not None and rt_cfg.enabled)
    _rt.configure(enabled=rt_enabled,
                  capacity=rt_cfg.capacity if rt_cfg else None,
                  max_segments=rt_cfg.max_segments if rt_cfg else None,
                  rank=rank)
    _tracer.set_event_source(
        "request_trace", _rt.chrome_events if rt_enabled else None)
    # SLO burn-rate alerting defaults (the serving front-end builds its
    # monitor from these via slo.from_defaults())
    slo_cfg = getattr(obs_config, "slo", None)
    if slo_cfg is not None and slo_cfg.enabled:
        _slo_mod.set_defaults(
            enabled=True, objective=slo_cfg.objective,
            fast_window_s=slo_cfg.fast_window_s,
            slow_window_s=slo_cfg.slow_window_s,
            burn_threshold=slo_cfg.burn_threshold,
            resolve_fraction=slo_cfg.resolve_fraction,
            min_samples=slo_cfg.min_samples)
    else:
        _slo_mod.set_defaults(enabled=False)
    # host/device overlap profiler: per-iteration host-plan / enqueue /
    # device-wait split; its iteration track rides the tracer flush
    ov_cfg = getattr(obs_config, "overlap", None)
    ov_enabled = bool(ov_cfg is not None and ov_cfg.enabled)
    _ovl.configure(enabled=ov_enabled,
                   capacity=ov_cfg.capacity if ov_cfg else None,
                   rank=rank)
    _tracer.set_event_source(
        "overlap", _ovl.chrome_events if ov_enabled else None)
    # flight recorder: bounded snapshot ring + post-mortem bundles
    fl_cfg = getattr(obs_config, "flight", None)
    fl_enabled = bool(fl_cfg is not None and fl_cfg.enabled)
    _fr.configure(enabled=fl_enabled,
                  capacity=fl_cfg.capacity if fl_cfg else None,
                  output_dir=fl_cfg.output_dir if fl_cfg else None,
                  max_terminal_events=(fl_cfg.max_terminal_events
                                       if fl_cfg else None),
                  skip_burst_steps=(fl_cfg.skip_burst_steps
                                    if fl_cfg else None),
                  max_bundles=fl_cfg.max_bundles if fl_cfg else None,
                  rank=rank)
    if (tr.enabled or mt.enabled) and not _atexit_armed:
        atexit.register(flush_all)
        _atexit_armed = True
    return _tracer, _registry


def export_metrics() -> List[str]:
    """Write the configured metric exports (Prometheus textfile + JSON)."""
    if not _registry.enabled:
        return []
    paths: List[str] = []
    if _export["prometheus_dir"]:
        paths.append(_registry.export_prometheus(os.path.join(
            _export["prometheus_dir"], f"dstpu_rank{_tracer.rank}.prom")))
    if _export["json_path"]:
        paths.append(_registry.export_json(_export["json_path"]))
    return paths


def export_interval_steps() -> int:
    return _export["interval_steps"]


def flush_all(sync: Any = None) -> List[str]:
    """Flush trace + metric exports. ``sync`` — optional device value to
    join first (the explicit flush-boundary sync, via host_transfer)."""
    paths: List[str] = []
    if _tracer.enabled:
        paths.append(_tracer.flush(sync=sync))
    paths.extend(export_metrics())
    return paths
