"""Fleet-wide distributed tracing: one trace id per fleet request, ONE
merged Perfetto file per fleet.

PR 13 gave every serving request a ``trace_id`` and a per-engine
waterfall; the fleet (disaggregated prefill/decode + failover) broke the
story into pieces — a request now crosses a prefill worker, the shared
KV fabric, a decode replica, and possibly a failover sibling, and each
leg recorded its own unrelated timeline. This module restores the single
narrative:

  - ``FleetTraceContext`` mints fleet-scoped trace ids. The router
    stamps one onto every ``FleetRequest`` at submit; ``SubmitSpec``
    carries it into each replica's ``ServingEngine.submit``, where the
    request-trace recorder HONOURS the preset id instead of minting its
    own (``RequestTraceRecorder.on_submit``). Every leg — prefill,
    decode, failover replay — therefore stamps its segments under the
    SAME trace id, each on its own timeline track.
  - ``FleetTraceAssembler`` merges per-replica/-process trace exports
    into one Chrome trace-event document and synthesizes **flow arrows**
    (ph ``s``/``t``/``f`` sharing an id) chaining the legs of each
    trace chronologically: prefill leg → ``fabric_publish`` segment →
    ``promote`` (fabric claim) → decode leg → failover replay. Loaded in
    Perfetto the fleet request renders as one waterfall with arrows
    hopping across replica tracks.
  - ``validate_fleet_trace`` is the acceptance check (used by tests and
    the ``run_tests.sh`` fleet-obs stage, from a separate process):
    trace-id continuity, flow-arrow endpoints resolving to real slices,
    and no orphan legs.

Everything here is stdlib-only, export-time code — nothing on the hot
path. The hot-path cost of fleet tracing is the request tracer's
existing contract (one attribute check when disabled).
"""
from __future__ import annotations

import itertools
import json
import os
import zlib
from typing import Any, Dict, List, Optional, Tuple

#: category + name for synthesized flow events — namespaced so the
#: validator (and Perfetto queries) can find the fleet chains
FLOW_CAT = "fleet"
FLOW_NAME = "fleet_handoff"

#: pid stride between merged sources so per-process tracks never collide
SOURCE_PID_STRIDE = 1_000_000

#: X-segment names that anchor a flow hop *inside* a leg (in addition to
#: the leg's first/last slices): the fabric publish window on the
#: prefill leg and the claim/promote window on the decode leg
_INNER_ANCHORS = ("fabric_publish", "promote")


class FleetTraceContext:
    """Mints fleet-scoped trace ids (``fleet-<origin>-<seq>``).

    One per router. The id format is deliberately distinct from the
    per-rank ``r<rank>-<seq>`` ids the request tracer mints for
    non-fleet requests, so a trace file self-describes which requests
    crossed the fleet.
    """

    def __init__(self, origin: str = "0"):
        self.origin = str(origin)
        self._seq = itertools.count()

    def mint(self) -> str:
        return f"fleet-{self.origin}-{next(self._seq):06x}"


def _x_events_by_track(events: List[Dict[str, Any]]
                       ) -> Dict[Tuple[Any, Any], List[Dict[str, Any]]]:
    by_track: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("cat") == "request":
            by_track.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    for xs in by_track.values():
        xs.sort(key=lambda e: (e.get("ts", 0.0), -e.get("dur", 0.0)))
    return by_track


class FleetTraceAssembler:
    """Merge per-replica trace exports and draw the fleet flow arrows.

    Sources are whole Chrome trace documents (``add_file``/``add_doc``)
    or bare event lists (``add_events``). With more than one source,
    pids are remapped onto disjoint ranges (``SOURCE_PID_STRIDE`` apart)
    so two rank-0 exports don't merge their tracks; the in-process fleet
    (one tracer, one file) passes through unchanged.
    """

    def __init__(self):
        self._sources: List[Tuple[str, List[Dict[str, Any]],
                                  Dict[str, Any]]] = []

    # -- intake ------------------------------------------------------------
    def add_events(self, events: List[Dict[str, Any]],
                   label: Optional[str] = None) -> "FleetTraceAssembler":
        self._sources.append((label or f"source{len(self._sources)}",
                              list(events), {}))
        return self

    def add_doc(self, doc: Dict[str, Any],
                label: Optional[str] = None) -> "FleetTraceAssembler":
        self._sources.append((label or f"source{len(self._sources)}",
                              list(doc.get("traceEvents", [])),
                              dict(doc.get("otherData", {}))))
        return self

    def add_file(self, path: str,
                 label: Optional[str] = None) -> "FleetTraceAssembler":
        with open(path) as f:
            return self.add_doc(json.load(f), label=label or path)

    # -- assembly ----------------------------------------------------------
    def _merged_events(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        remap = len(self._sources) > 1
        for idx, (_label, events, _meta) in enumerate(self._sources):
            base = idx * SOURCE_PID_STRIDE if remap else 0
            for e in events:
                if base and "pid" in e:
                    e = dict(e)
                    e["pid"] = base + e["pid"]
                out.append(e)
        return out

    def _flow_events(self, events: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
        """Synthesize one flow chain per multi-leg trace id."""
        by_track = _x_events_by_track(events)
        # trace_id -> (pid, tid) -> ordered X events of that leg
        legs: Dict[str, Dict[Tuple[Any, Any], List[Dict[str, Any]]]] = {}
        for track, xs in by_track.items():
            for e in xs:
                tid = (e.get("args") or {}).get("trace_id")
                if tid:
                    legs.setdefault(tid, {}).setdefault(track, []).append(e)
        flows: List[Dict[str, Any]] = []
        for trace_id in sorted(legs):
            tracks = legs[trace_id]
            if len(tracks) < 2:
                continue            # single-leg request: nothing to chain
            anchors: List[Dict[str, Any]] = []
            for track_xs in tracks.values():
                chosen = {id(track_xs[0]): track_xs[0],
                          id(track_xs[-1]): track_xs[-1]}
                for e in track_xs:
                    if e.get("name") in _INNER_ANCHORS:
                        chosen[id(e)] = e
                anchors.extend(chosen.values())
            anchors.sort(key=lambda e: (e.get("ts", 0.0),
                                        -e.get("dur", 0.0)))
            fid = zlib.crc32(trace_id.encode("utf-8")) & 0xFFFFFFFF
            last = len(anchors) - 1
            for i, a in enumerate(anchors):
                ev = {"ph": "s" if i == 0 else ("f" if i == last else "t"),
                      "cat": FLOW_CAT, "name": FLOW_NAME, "id": fid,
                      "pid": a.get("pid"), "tid": a.get("tid"),
                      "ts": a.get("ts", 0.0),
                      "args": {"trace_id": trace_id, "hop": i}}
                if ev["ph"] == "f":
                    ev["bp"] = "e"
                flows.append(ev)
        return flows

    def assemble(self) -> Dict[str, Any]:
        events = self._merged_events()
        events.extend(self._flow_events(events))
        dropped = 0
        for _label, _events, meta in self._sources:
            dropped += int(meta.get("dropped", meta.get("dropped_spans", 0))
                           or 0)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "producer": "deepspeed_tpu.observability.fleet_trace",
                "sources": [label for label, _e, _m in self._sources],
                "dropped": dropped,
            },
        }

    def write(self, path: str) -> str:
        doc = self.assemble()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def validate_fleet_trace(doc: Any) -> Dict[str, Dict[str, int]]:
    """Validate a merged fleet trace document (or event list).

    Checks, raising ``ValueError`` on the first violation:
      - **continuity**: every multi-leg trace id has a flow chain;
      - **endpoints resolve**: each flow event's ``(pid, tid, ts)``
        lands inside an ``X`` slice of the same track carrying the same
        trace id;
      - **no orphan segments**: every leg of a multi-leg trace hosts at
        least one flow-chain node.

    Returns ``{trace_id: {"legs": n, "flow_events": n}}`` for reporting.
    Designed to be runnable from a separate process against the JSON
    artifact alone (the run_tests.sh fleet-obs stage does exactly that).
    """
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    by_track = _x_events_by_track(events)
    legs_by_trace: Dict[str, set] = {}
    for track, xs in by_track.items():
        for e in xs:
            tid = (e.get("args") or {}).get("trace_id")
            if tid:
                legs_by_trace.setdefault(tid, set()).add(track)
    flows_by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("cat") == FLOW_CAT and e.get("ph") in ("s", "t", "f"):
            tid = (e.get("args") or {}).get("trace_id")
            if not tid:
                raise ValueError(f"flow event without trace_id: {e}")
            flows_by_trace.setdefault(tid, []).append(e)
    report: Dict[str, Dict[str, int]] = {}
    for trace_id, tracks in sorted(legs_by_trace.items()):
        flows = flows_by_trace.get(trace_id, [])
        if len(tracks) > 1 and not flows:
            raise ValueError(
                f"trace {trace_id!r} spans {len(tracks)} legs but has no "
                f"flow chain (continuity broken)")
        covered = set()
        for f in flows:
            track = (f.get("pid"), f.get("tid"))
            ts = f.get("ts", 0.0)
            slices = by_track.get(track, [])
            if not any(e.get("ts", 0.0) <= ts
                       <= e.get("ts", 0.0) + e.get("dur", 0.0)
                       and (e.get("args") or {}).get("trace_id") == trace_id
                       for e in slices):
                raise ValueError(
                    f"flow endpoint for trace {trace_id!r} at "
                    f"pid={f.get('pid')} tid={f.get('tid')} ts={ts} does "
                    f"not resolve to any slice of that leg")
            covered.add(track)
        if len(tracks) > 1 and covered != tracks:
            raise ValueError(
                f"orphan segments in trace {trace_id!r}: legs "
                f"{sorted(tracks - covered)} are not on the flow chain")
        report[trace_id] = {"legs": len(tracks), "flow_events": len(flows)}
    return report
