"""Span tracer: Chrome trace-event JSON over a preallocated ring buffer.

The host-side complement of the JAX/XLA profiler: device ops show up in
the XLA trace, but the subsystems this framework adds around the device —
swap workers, host optimizer sweeps, checkpoint commits, retry loops,
rendezvous — are invisible to it. ``trace_span("zero/nvme_write", ...)``
context managers record wall-clock spans into a fixed-capacity ring
(oldest spans overwritten, nothing ever grows on the hot path) and
``flush()`` serializes them as Chrome trace-event JSON — one file per
process, with process/rank metadata and one track per thread — loadable
directly in Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Overhead contract (docs/observability.md):
  - disabled: ``span()`` is one attribute check returning a shared
    no-op singleton — no allocation, no clock read;
  - enabled: two ``perf_counter_ns`` reads and one in-place ring-record
    mutation per span; no I/O, no device interaction;
  - flush: the ONLY place a device sync may happen, and only when the
    caller passes ``sync=`` — routed through the whitelisted
    ``host_transfer()`` so ``dstpu-lint``'s SYNC rules stay clean.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class _Rec:
    """One preallocated ring slot, mutated in place at span exit."""
    __slots__ = ("name", "cat", "ts_ns", "dur_ns", "tid", "args")

    def __init__(self):
        self.name = ""
        self.cat = ""
        self.ts_ns = 0
        self.dur_ns = 0
        self.tid = 0
        self.args: Optional[Dict[str, Any]] = None


class _NullSpan:
    """Shared do-nothing span — the entire disabled code path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class _LiveSpan:
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0

    def set(self, **attrs):
        """Attach attributes discovered mid-span."""
        if self._args is None:
            self._args = attrs
        else:
            self._args.update(attrs)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._tracer._commit(self._name, self._cat, self._t0,
                             time.perf_counter_ns(), self._args)
        return False


class SpanTracer:
    """Fixed-capacity span recorder with Chrome trace-event export.

    One per process (module singleton via ``observability.get_tracer()``);
    thread-safe — worker threads (swap ring, infinity optimizer pool,
    offload sweep) record onto their own Perfetto tracks keyed by thread
    id, named from ``threading.current_thread().name``.
    """

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self._capacity = int(capacity)
        self._ring: List[_Rec] = []          # preallocated on first enable
        self._n = 0                          # total spans ever committed
        self._lock = threading.Lock()
        self._thread_names: Dict[int, str] = {}
        self._epoch_ns = time.perf_counter_ns()
        self.rank = 0
        self.output_dir = "traces"
        self._m_dropped = None     # lazily-bound overflow counter
        # extra flush-time event providers (the request-trace recorder
        # merges its per-request waterfall tracks here) — keyed so
        # re-configuration doesn't stack duplicates. Each provider is
        # called as fn(epoch_ns, rank) -> [chrome events].
        self._event_sources: Dict[str, Any] = {}

    # -- configuration -----------------------------------------------------
    def configure(self, enabled: bool, capacity: Optional[int] = None,
                  output_dir: Optional[str] = None,
                  rank: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and int(capacity) > 0:
                if int(capacity) != self._capacity or not self._ring:
                    self._capacity = int(capacity)
                    self._ring = []
                    self._n = 0
            if output_dir is not None:
                self.output_dir = output_dir
            if rank is not None:
                self.rank = int(rank)
            if enabled and not self._ring:
                # THE preallocation: every span the process will ever
                # record lands in one of these slots
                self._ring = [_Rec() for _ in range(self._capacity)]
                self._epoch_ns = time.perf_counter_ns()
            self.enabled = bool(enabled)

    # -- recording ---------------------------------------------------------
    def span(self, name: str, cat: str = "", **args):
        """Context manager timing one host-side span. Disabled → the
        shared no-op singleton (no allocation)."""
        if not self.enabled:
            return NULL_SPAN
        return _LiveSpan(self, name, cat, args or None)

    def _commit(self, name: str, cat: str, t0_ns: int, t1_ns: int,
                args: Optional[Dict[str, Any]]) -> None:
        tid = threading.get_ident()
        with self._lock:
            if not self._ring:      # disabled mid-span; drop silently
                return
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            rec = self._ring[self._n % self._capacity]
            rec.name = name
            rec.cat = cat
            rec.ts_ns = t0_ns
            rec.dur_ns = t1_ns - t0_ns
            rec.tid = tid
            rec.args = args
            self._n += 1
            overflowed = self._n > self._capacity
        if overflowed:
            # ring wraparound just overwrote the oldest span — truncation
            # must be loud (a trace missing its head is easy to misread
            # as "nothing happened early")
            if self._m_dropped is None:
                from . import get_registry
                reg = get_registry()
                self._m_dropped = reg.counter(
                    "dstpu_trace_dropped_spans_total",
                    "spans overwritten by trace ring wraparound")
            self._m_dropped.inc()

    # -- introspection -----------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def recorded(self) -> int:
        """Spans currently held (≤ capacity)."""
        return min(self._n, self._capacity)

    @property
    def dropped(self) -> int:
        """Spans overwritten by ring wraparound."""
        return max(0, self._n - self._capacity)

    def reset(self) -> None:
        with self._lock:
            self._n = 0

    # -- event sources -----------------------------------------------------
    def set_event_source(self, key: str, fn: Any) -> None:
        """Register (or replace) a flush-time event provider; pass
        ``None`` to remove it."""
        with self._lock:
            if fn is None:
                self._event_sources.pop(key, None)
            else:
                self._event_sources[key] = fn

    @property
    def epoch_ns(self) -> int:
        return self._epoch_ns

    # -- export ------------------------------------------------------------
    def _events(self) -> List[Dict[str, Any]]:
        """Trace events, oldest first, under the lock (consistent cut even
        while workers keep recording)."""
        with self._lock:
            n = min(self._n, self._capacity)
            start = self._n - n
            out = []
            for i in range(start, self._n):
                rec = self._ring[i % self._capacity]
                ev = {"ph": "X", "pid": self.rank, "tid": rec.tid,
                      "name": rec.name,
                      "ts": (rec.ts_ns - self._epoch_ns) / 1000.0,
                      "dur": rec.dur_ns / 1000.0}
                if rec.cat:
                    ev["cat"] = rec.cat
                if rec.args:
                    ev["args"] = dict(rec.args)
                out.append(ev)
            threads = dict(self._thread_names)
        meta: List[Dict[str, Any]] = [
            {"ph": "M", "pid": self.rank, "tid": 0, "name": "process_name",
             "args": {"name": f"deepspeed_tpu rank {self.rank} "
                              f"(pid {os.getpid()})"}},
            {"ph": "M", "pid": self.rank, "tid": 0,
             "name": "process_sort_index", "args": {"sort_index": self.rank}},
        ]
        for tid, tname in sorted(threads.items()):
            meta.append({"ph": "M", "pid": self.rank, "tid": tid,
                         "name": "thread_name", "args": {"name": tname}})
        with self._lock:
            sources = list(self._event_sources.values())
        extra: List[Dict[str, Any]] = []
        for fn in sources:
            try:
                extra.extend(fn(self._epoch_ns, self.rank))
            except Exception:    # a broken source must not kill the flush
                pass
        return meta + out + extra

    def flush(self, path: Optional[str] = None, sync: Any = None) -> str:
        """Serialize the ring to Chrome trace-event JSON.

        ``sync`` — optional device value to join before the cut (the ONE
        deliberate flush-boundary device sync, routed through
        ``host_transfer(block=True)``). Returns the written path. The
        ring is NOT cleared: re-flushing overwrites the file with the
        newest window of spans.
        """
        if sync is not None:
            from ..runtime.utils import host_transfer
            host_transfer(sync, block=True)
        if path is None:
            os.makedirs(self.output_dir, exist_ok=True)
            path = os.path.join(self.output_dir,
                                f"trace_rank{self.rank}.json")
        doc = {
            "traceEvents": self._events(),
            "displayTimeUnit": "ms",
            "otherData": {"producer": "deepspeed_tpu.observability",
                          "rank": self.rank, "pid": os.getpid(),
                          "dropped": self.dropped,
                          "dropped_spans": self.dropped},
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
