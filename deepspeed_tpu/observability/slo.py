"""Per-tenant SLO burn-rate alerting over the serving latency streams.

Classic SRE multi-window burn-rate alerting (Beyer et al., *The Site
Reliability Workbook* ch. 5) applied to the per-tenant TTFT and
inter-token SLOs that ``TenantSpec`` already declares: an observation is
*bad* when its latency exceeds the tenant's target, the **burn rate** is
the bad fraction divided by the error budget (``1 - objective``), and an
alert needs BOTH a fast window (seconds — catches the breach while
requests are still in flight, long before enough terminals accumulate
for a p99 histogram to show it) and a slow window (minutes — immunity to
single-request blips) burning above threshold.

Alert state machine per ``(tenant, kind)`` with hysteresis::

    inactive -> pending   both windows burn >= threshold
    pending  -> firing    condition held for ``pending_s`` (0 = same eval)
    pending  -> inactive  condition dropped before firing (silent)
    firing   -> resolved  fast burn fell below threshold*resolve_fraction
    resolved -> inactive  (resolved is the notification edge)

Transitions to ``firing``/``resolved`` increment ``dstpu_slo_*``
counters/gauges and fan out to ``on_alert`` subscribers; the serving
front-end additionally biases its admission/shed policies while an
alert is firing (docs/serving.md, docs/observability.md).

Stdlib-only, never touches the device; the front-end feeds it from the
same iteration-boundary token events that feed the histograms, so
enabling it adds no host syncs to the hot path.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

#: alert kinds — one latency stream per TenantSpec SLO field
KIND_TTFT = "ttft"
KIND_ITL = "itl"


@dataclass
class SloAlert:
    """One alert transition, handed to ``on_alert`` subscribers."""
    tenant: str
    kind: str                 # "ttft" | "itl"
    state: str                # "pending" | "firing" | "resolved"
    burn_fast: float
    burn_slow: float
    target_s: float
    at: float                 # monitor clock at the transition


@dataclass
class _KeyState:
    events: Deque[Tuple[float, bool]] = field(default_factory=deque)
    state: str = "inactive"
    since: float = 0.0
    target_s: float = 0.0
    burn_fast: float = 0.0
    burn_slow: float = 0.0


class SloMonitor:
    """Multi-window burn-rate evaluator + alert state machine.

    ``objective`` is the fraction of observations that must meet the
    tenant's target (0.9 → a 10% error budget); ``burn_threshold`` is
    how many times faster than budget the error rate must run, in both
    windows, before an alert fires. ``time_fn`` is injectable so the
    window math is unit-testable with synthetic clocks.
    """

    def __init__(self, objective: float = 0.9,
                 fast_window_s: float = 30.0,
                 slow_window_s: float = 300.0,
                 burn_threshold: float = 2.0,
                 pending_s: float = 0.0,
                 resolve_fraction: float = 0.5,
                 min_samples: int = 5,
                 eval_interval_s: float = 0.0,
                 on_alert: Optional[Callable[[SloAlert], None]] = None,
                 registry: Any = None,
                 time_fn: Callable[[], float] = time.perf_counter):
        if not (0.0 < objective < 1.0):
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        self.objective = float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.pending_s = float(pending_s)
        self.resolve_fraction = float(resolve_fraction)
        self.min_samples = int(min_samples)
        self.eval_interval_s = float(eval_interval_s)
        self.time_fn = time_fn
        self._keys: Dict[Tuple[str, str], _KeyState] = {}
        self._lock = threading.Lock()
        self._callbacks: List[Callable[[SloAlert], None]] = []
        if on_alert is not None:
            self._callbacks.append(on_alert)
        self._last_eval = -float("inf")
        if registry is None:
            from . import get_registry
            registry = get_registry()
        self._registry = registry
        self._m_alerts = registry.counter(
            "dstpu_slo_alerts_total",
            help="SLO burn-rate alerts that reached firing")
        self._m_resolved = registry.counter(
            "dstpu_slo_alerts_resolved_total",
            help="SLO burn-rate alerts that resolved after firing")
        self._m_firing = registry.gauge(
            "dstpu_slo_alerts_firing",
            help="SLO burn-rate alerts currently firing")
        self._m_evals = registry.counter(
            "dstpu_slo_evaluations_total",
            help="burn-rate evaluation passes")

    # -- subscriptions -----------------------------------------------------
    def subscribe(self, fn: Callable[[SloAlert], None]) -> None:
        self._callbacks.append(fn)

    # -- feeds -------------------------------------------------------------
    def observe(self, tenant: str, kind: str, latency_s: float,
                target_s: float, now: Optional[float] = None) -> None:
        """Record one latency observation against ``target_s``.

        ``target_s <= 0`` means the tenant declared no SLO for this kind
        — the observation is ignored entirely.
        """
        if target_s <= 0.0:
            return
        if now is None:
            now = self.time_fn()
        key = (tenant, kind)
        with self._lock:
            ks = self._keys.get(key)
            if ks is None:
                ks = self._keys[key] = _KeyState()
            ks.target_s = float(target_s)
            ks.events.append((now, latency_s > target_s))
        if now - self._last_eval >= self.eval_interval_s:
            self.evaluate(now)

    # -- evaluation --------------------------------------------------------
    def _window_burn(self, ks: _KeyState, now: float) -> Tuple[float, float,
                                                               int]:
        """(burn_fast, burn_slow, n_fast) over the pruned event deque."""
        horizon = now - self.slow_window_s
        ev = ks.events
        while ev and ev[0][0] < horizon:
            ev.popleft()
        fast_t0 = now - self.fast_window_s
        n_slow = len(ev)
        bad_slow = n_fast = bad_fast = 0
        for t, bad in ev:
            if bad:
                bad_slow += 1
            if t >= fast_t0:
                n_fast += 1
                if bad:
                    bad_fast += 1
        budget = 1.0 - self.objective
        burn_fast = (bad_fast / n_fast / budget) if n_fast else 0.0
        burn_slow = (bad_slow / n_slow / budget) if n_slow else 0.0
        return burn_fast, burn_slow, n_fast

    def evaluate(self, now: Optional[float] = None) -> List[SloAlert]:
        """Run the state machine; returns the transitions it emitted."""
        if now is None:
            now = self.time_fn()
        self._last_eval = now
        self._m_evals.inc()
        transitions: List[SloAlert] = []
        with self._lock:
            keys = list(self._keys.items())
        for (tenant, kind), ks in keys:
            with self._lock:
                burn_fast, burn_slow, n_fast = self._window_burn(ks, now)
                ks.burn_fast, ks.burn_slow = burn_fast, burn_slow
                cond = (n_fast >= self.min_samples
                        and burn_fast >= self.burn_threshold
                        and burn_slow >= self.burn_threshold)
                alert = None
                if ks.state == "inactive" and cond:
                    ks.state, ks.since = "pending", now
                if ks.state == "pending":
                    if not cond:
                        ks.state = "inactive"
                    elif now - ks.since >= self.pending_s:
                        ks.state = "firing"
                        alert = "firing"
                elif ks.state == "firing":
                    if burn_fast <= (self.burn_threshold
                                     * self.resolve_fraction):
                        ks.state = "inactive"
                        alert = "resolved"
                self._tenant_gauges(tenant, kind)[0].set(burn_fast)
                self._tenant_gauges(tenant, kind)[1].set(burn_slow)
            if alert is not None:
                transitions.append(SloAlert(
                    tenant=tenant, kind=kind, state=alert,
                    burn_fast=burn_fast, burn_slow=burn_slow,
                    target_s=ks.target_s, at=now))
        for tr in transitions:
            if tr.state == "firing":
                self._m_alerts.inc()
                self._tenant_counter(tr.tenant, tr.kind).inc()
            elif tr.state == "resolved":
                self._m_resolved.inc()
            for fn in list(self._callbacks):
                try:
                    fn(tr)
                except Exception:   # observers must never kill serving
                    pass
        self._m_firing.set(sum(
            1 for ks in self._keys.values() if ks.state == "firing"))
        return transitions

    # -- queries -----------------------------------------------------------
    def firing(self, tenant: str, kind: str) -> bool:
        ks = self._keys.get((tenant, kind))
        return ks is not None and ks.state == "firing"

    def firing_any(self, tenant: str) -> bool:
        return (self.firing(tenant, KIND_TTFT)
                or self.firing(tenant, KIND_ITL))

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Host-side state dump (flight-recorder / bench friendly)."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for (tenant, kind), ks in self._keys.items():
                out[f"{tenant}/{kind}"] = {
                    "state": ks.state, "burn_fast": round(ks.burn_fast, 4),
                    "burn_slow": round(ks.burn_slow, 4),
                    "target_s": ks.target_s, "samples": len(ks.events)}
        return out

    # -- per-tenant series -------------------------------------------------
    def _series(self, tenant: str, kind: str) -> str:
        from .metrics import tenant_metric_name
        return tenant_metric_name("dstpu_slo_tenant", tenant, kind)

    def _tenant_gauges(self, tenant: str, kind: str):
        base = self._series(tenant, kind)
        return (self._registry.gauge(f"{base}_burn_fast"),
                self._registry.gauge(f"{base}_burn_slow"))

    def _tenant_counter(self, tenant: str, kind: str):
        return self._registry.counter(f"{self._series(tenant, kind)}"
                                      f"_alerts_total")


#: defaults applied by ``observability.configure`` (SloConfig block);
#: ``SloMonitor.from_defaults()`` returns None while disabled so callers
#: holding the result pay one ``is None`` check and nothing else
_defaults: Dict[str, Any] = {"enabled": False}


def set_defaults(**kw: Any) -> None:
    _defaults.clear()
    _defaults.update(kw)


def from_defaults(**overrides: Any) -> Optional[SloMonitor]:
    """Build an ``SloMonitor`` from the configured ``observability.slo``
    block, or None when the block is disabled."""
    if not _defaults.get("enabled"):
        return None
    kw = {k: v for k, v in _defaults.items() if k != "enabled"}
    kw.update(overrides)
    return SloMonitor(**kw)
