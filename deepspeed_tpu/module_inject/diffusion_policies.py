"""HF diffusers/transformers checkpoint import for the diffusion family.

Role-equivalent of the reference's ``generic_injection``
(`/root/reference/deepspeed/module_inject/replace_module.py:211`) and the
diffusers policy classes (`replace_policy.py` UNetPolicy/VAEPolicy/
CLIPPolicy): there, torch modules are walked and their weights moved into
fused kernel modules; here, a flat HF ``state_dict`` (torch tensors or
numpy arrays, named by the published diffusers/transformers conventions)
is re-laid-out into the pure pytrees of `models/diffusion.py` — torch
OIHW convs become NHWC-friendly HWIO, ``Linear`` [out,in] transposes to
[in,out].

Entry points:
  load_unet(config, state_dict)         -> UNet2DCondition params
  load_vae(config, state_dict)          -> AutoencoderKL params
  load_clip_text(config, state_dict)    -> CLIPTextEncoder params
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np


from ..utils.interop import to_numpy


def _np(v) -> np.ndarray:
    # dtype=None: diffusion checkpoints keep their source dtype (the
    # pipeline casts at device_put)
    return to_numpy(v, dtype=None)


class _SD:
    """State-dict view with presence tracking so unconsumed keys are a
    loud error (a misspelled mapping silently dropping weights is the
    classic injection bug)."""

    def __init__(self, sd: Dict[str, Any], prefix: str = ""):
        self.sd = {k: v for k, v in sd.items()}
        self.used = set()
        self.prefix = prefix

    def has(self, name: str) -> bool:
        return self.prefix + name in self.sd

    def take(self, name: str) -> np.ndarray:
        key = self.prefix + name
        if key not in self.sd:
            raise KeyError(
                f"checkpoint missing '{key}' — state dict does not match "
                f"the configured architecture")
        self.used.add(key)
        return _np(self.sd[key])

    def check_consumed(self, ignore=()) -> None:
        left = [k for k in self.sd
                if k not in self.used
                and not any(k.startswith(i) for i in ignore)]
        if left:
            raise ValueError(
                f"{len(left)} checkpoint tensors were not consumed by the "
                f"policy (first: {left[:5]}) — refusing a silent partial "
                f"load")


def _conv(sd: _SD, name: str) -> Dict:
    w = sd.take(f"{name}.weight")           # OIHW
    b = sd.take(f"{name}.bias")
    return {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)),
            "bias": jnp.asarray(b)}


def _linear(sd: _SD, name: str, bias: bool = True) -> Dict:
    w = sd.take(f"{name}.weight")           # [out, in]
    p = {"kernel": jnp.asarray(w.T)}
    if bias:
        p["bias"] = jnp.asarray(sd.take(f"{name}.bias"))
    return p


def _linear_or_conv1x1(sd: _SD, name: str) -> Dict:
    """SD1 proj_in/proj_out and old VAE attention store 1x1 convs where
    newer checkpoints store Linear — accept both, emit conv params."""
    w = sd.take(f"{name}.weight")
    b = jnp.asarray(sd.take(f"{name}.bias"))
    if w.ndim == 4:
        return {"kernel": jnp.asarray(w.transpose(2, 3, 1, 0)), "bias": b}
    return {"kernel": jnp.asarray(w.T[None, None]), "bias": b}


def _norm(sd: _SD, name: str) -> Dict:
    return {"scale": jnp.asarray(sd.take(f"{name}.weight")),
            "bias": jnp.asarray(sd.take(f"{name}.bias"))}


def _resnet(sd: _SD, name: str, temb: bool) -> Dict:
    p = {"norm1": _norm(sd, f"{name}.norm1"),
         "conv1": _conv(sd, f"{name}.conv1"),
         "norm2": _norm(sd, f"{name}.norm2"),
         "conv2": _conv(sd, f"{name}.conv2")}
    if temb and sd.has(f"{name}.time_emb_proj.weight"):
        p["time_emb_proj"] = _linear(sd, f"{name}.time_emb_proj")
    if sd.has(f"{name}.conv_shortcut.weight"):
        p["conv_shortcut"] = _conv(sd, f"{name}.conv_shortcut")
    elif sd.has(f"{name}.nin_shortcut.weight"):       # old VAE naming
        p["conv_shortcut"] = _conv(sd, f"{name}.nin_shortcut")
    return p


def _cross_attn(sd: _SD, name: str) -> Dict:
    return {"to_q": _linear(sd, f"{name}.to_q", bias=False),
            "to_k": _linear(sd, f"{name}.to_k", bias=False),
            "to_v": _linear(sd, f"{name}.to_v", bias=False),
            "to_out": _linear(sd, f"{name}.to_out.0")}


def _tblock(sd: _SD, name: str) -> Dict:
    return {"norm1": _norm(sd, f"{name}.norm1"),
            "attn1": _cross_attn(sd, f"{name}.attn1"),
            "norm2": _norm(sd, f"{name}.norm2"),
            "attn2": _cross_attn(sd, f"{name}.attn2"),
            "norm3": _norm(sd, f"{name}.norm3"),
            "ff": {"proj_in": _linear(sd, f"{name}.ff.net.0.proj"),
                   "proj_out": _linear(sd, f"{name}.ff.net.2")}}


def _transformer2d(sd: _SD, name: str, depth: int) -> Dict:
    return {"norm": _norm(sd, f"{name}.norm"),
            "proj_in": _linear_or_conv1x1(sd, f"{name}.proj_in"),
            "blocks": [_tblock(sd, f"{name}.transformer_blocks.{k}")
                       for k in range(depth)],
            "proj_out": _linear_or_conv1x1(sd, f"{name}.proj_out")}


# ---------------------------------------------------------------------------
# UNet
# ---------------------------------------------------------------------------
def load_unet(config, state_dict: Dict[str, Any]) -> Dict:
    """diffusers UNet2DConditionModel state_dict -> UNet2DCondition
    params (models/diffusion.py layout)."""
    c = config
    sd = _SD(state_dict)
    p: Dict[str, Any] = {
        "conv_in": _conv(sd, "conv_in"),
        "time_embedding": {
            "linear_1": _linear(sd, "time_embedding.linear_1"),
            "linear_2": _linear(sd, "time_embedding.linear_2")},
    }
    downs = []
    for bi, btype in enumerate(c.down_block_types):
        blk = {"resnets": [], "attentions": []}
        for li in range(c.layers_per_block):
            blk["resnets"].append(
                _resnet(sd, f"down_blocks.{bi}.resnets.{li}", True))
            if btype == "CrossAttnDownBlock2D":
                blk["attentions"].append(_transformer2d(
                    sd, f"down_blocks.{bi}.attentions.{li}",
                    c.transformer_depth))
        if sd.has(f"down_blocks.{bi}.downsamplers.0.conv.weight"):
            blk["downsample"] = _conv(
                sd, f"down_blocks.{bi}.downsamplers.0.conv")
        downs.append(blk)
    p["down_blocks"] = downs
    p["mid_block"] = {
        "resnets": [_resnet(sd, "mid_block.resnets.0", True),
                    _resnet(sd, "mid_block.resnets.1", True)],
        "attentions": [_transformer2d(sd, "mid_block.attentions.0",
                                      c.transformer_depth)],
    }
    ups = []
    for bi, btype in enumerate(c.up_block_types):
        blk = {"resnets": [], "attentions": []}
        for li in range(c.layers_per_block + 1):
            blk["resnets"].append(
                _resnet(sd, f"up_blocks.{bi}.resnets.{li}", True))
            if btype == "CrossAttnUpBlock2D":
                blk["attentions"].append(_transformer2d(
                    sd, f"up_blocks.{bi}.attentions.{li}",
                    c.transformer_depth))
        if sd.has(f"up_blocks.{bi}.upsamplers.0.conv.weight"):
            blk["upsample"] = _conv(sd, f"up_blocks.{bi}.upsamplers.0.conv")
        ups.append(blk)
    p["up_blocks"] = ups
    p["conv_norm_out"] = _norm(sd, "conv_norm_out")
    p["conv_out"] = _conv(sd, "conv_out")
    sd.check_consumed()
    return p


# ---------------------------------------------------------------------------
# VAE
# ---------------------------------------------------------------------------
def _vae_attn(sd: _SD, name: str) -> Dict:
    if sd.has(f"{name}.to_q.weight"):      # modern diffusers Attention
        names = ("group_norm", "to_q", "to_k", "to_v", "to_out.0")
    else:                                  # pre-refactor diffusers
        names = ("group_norm", "query", "key", "value", "proj_attn")

    def lin(n):
        w = sd.take(f"{name}.{n}.weight")
        if w.ndim == 4:                    # 1x1 conv form
            w = w[:, :, 0, 0]
        return {"kernel": jnp.asarray(w.T),
                "bias": jnp.asarray(sd.take(f"{name}.{n}.bias"))}
    return {"group_norm": _norm(sd, f"{name}.{names[0]}"),
            "to_q": lin(names[1]), "to_k": lin(names[2]),
            "to_v": lin(names[3]), "to_out": lin(names[4])}


def _vae_mid(sd: _SD, name: str) -> Dict:
    return {"resnets": [_resnet(sd, f"{name}.resnets.0", False),
                        _resnet(sd, f"{name}.resnets.1", False)],
            "attentions": [_vae_attn(sd, f"{name}.attentions.0")]}


def load_vae(config, state_dict: Dict[str, Any]) -> Dict:
    c = config
    sd = _SD(state_dict)
    n_blocks = len(c.block_out_channels)
    enc: Dict[str, Any] = {"conv_in": _conv(sd, "encoder.conv_in"),
                           "down_blocks": []}
    for bi in range(n_blocks):
        blk = {"resnets": [
            _resnet(sd, f"encoder.down_blocks.{bi}.resnets.{li}", False)
            for li in range(c.layers_per_block)]}
        if sd.has(f"encoder.down_blocks.{bi}.downsamplers.0.conv.weight"):
            blk["downsample"] = _conv(
                sd, f"encoder.down_blocks.{bi}.downsamplers.0.conv")
        enc["down_blocks"].append(blk)
    enc["mid_block"] = _vae_mid(sd, "encoder.mid_block")
    enc["conv_norm_out"] = _norm(sd, "encoder.conv_norm_out")
    enc["conv_out"] = _conv(sd, "encoder.conv_out")

    dec: Dict[str, Any] = {"conv_in": _conv(sd, "decoder.conv_in"),
                           "mid_block": _vae_mid(sd, "decoder.mid_block"),
                           "up_blocks": []}
    for bi in range(n_blocks):
        blk = {"resnets": [
            _resnet(sd, f"decoder.up_blocks.{bi}.resnets.{li}", False)
            for li in range(c.layers_per_block + 1)]}
        if sd.has(f"decoder.up_blocks.{bi}.upsamplers.0.conv.weight"):
            blk["upsample"] = _conv(
                sd, f"decoder.up_blocks.{bi}.upsamplers.0.conv")
        dec["up_blocks"].append(blk)
    dec["conv_norm_out"] = _norm(sd, "decoder.conv_norm_out")
    dec["conv_out"] = _conv(sd, "decoder.conv_out")
    out = {"encoder": enc, "decoder": dec,
           "quant_conv": _conv(sd, "quant_conv"),
           "post_quant_conv": _conv(sd, "post_quant_conv")}
    sd.check_consumed()
    return out


# ---------------------------------------------------------------------------
# CLIP text
# ---------------------------------------------------------------------------
def load_clip_text(config, state_dict: Dict[str, Any]) -> Dict:
    """transformers CLIPTextModel state_dict (with or without the
    ``text_model.`` prefix) -> CLIPTextEncoder params."""
    pre = ("text_model."
           if any(k.startswith("text_model.") for k in state_dict) else "")
    sd = _SD(state_dict, prefix=pre)
    p = {"token_embedding": {"embedding": jnp.asarray(_np(
            sd.take("embeddings.token_embedding.weight")))},
         "position_embedding": {"embedding": jnp.asarray(_np(
             sd.take("embeddings.position_embedding.weight")))},
         "final_layer_norm": _norm(sd, "final_layer_norm"),
         "layers": []}
    for i in range(config.num_hidden_layers):
        base = f"encoder.layers.{i}"
        p["layers"].append({
            "layer_norm1": _norm(sd, f"{base}.layer_norm1"),
            "q_proj": _linear(sd, f"{base}.self_attn.q_proj"),
            "k_proj": _linear(sd, f"{base}.self_attn.k_proj"),
            "v_proj": _linear(sd, f"{base}.self_attn.v_proj"),
            "out_proj": _linear(sd, f"{base}.self_attn.out_proj"),
            "layer_norm2": _norm(sd, f"{base}.layer_norm2"),
            "fc1": _linear(sd, f"{base}.mlp.fc1"),
            "fc2": _linear(sd, f"{base}.mlp.fc2"),
        })
    sd.check_consumed(ignore=(pre + "embeddings.position_ids",))
    return p
