"""Auto tensor-parallelism for models without partition specs.

Role-equivalent of the reference's AutoTP heuristic
(`/root/reference/deepspeed/module_inject/auto_tp.py`, 92 LoC), which walks
the module tree looking for linear layers to slice and all-reduce points.
Declarative redesign: given only the params pytree (shapes), derive a
PartitionSpec tree that shards each weight's largest divisible dim over the
``model`` axis; GSPMD then places the all-reduces the reference has to
discover by graph analysis. Biases/scalars replicate (sharded-bias handling
is exactly the class of bug the reference's heuristic has to special-case).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.topology import MODEL_AXIS


def auto_tp_specs(param_shapes, mesh: Mesh, min_size: int = 1024):
    """Shapes pytree → PartitionSpec pytree (TP over ``model``).

    Leaves with fewer than 2 dims, smaller than ``min_size`` elements, or
    with no dim divisible by the axis size stay replicated."""
    tp = mesh.shape.get(MODEL_AXIS, 1)

    def spec(leaf) -> P:
        shape = tuple(getattr(leaf, "shape", ()))
        entries = [None] * len(shape)
        if tp > 1 and len(shape) >= 2 and int(np.prod(shape)) >= min_size:
            divisible = [d for d, s in enumerate(shape) if s % tp == 0]
            if divisible:
                best = max(divisible, key=lambda d: shape[d])
                entries[best] = MODEL_AXIS
        return P(*entries)

    return jax.tree_util.tree_map(spec, param_shapes)
