"""Weight-injection policies: HuggingFace checkpoints → TransformerLM params.

Role-equivalent of the reference's per-architecture policies + containers
(`/root/reference/deepspeed/module_inject/policy.py`,
`module_inject/containers/gpt2.py`, `containers/gptneox.py`, registry at
`replace_policy.py:17`): each policy knows the source model's weight-name map
and emits our stacked-scan params pytree. Where the reference swaps nn.Modules
for fused-kernel modules holding sliced tensors, here conversion is pure data
movement — the TP slicing happens afterwards when the tree is device_put into
the mesh shardings (`inference/engine.py`), so policies stay layout-free.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..models.transformer import TransformerConfig
from ..utils.interop import to_numpy as _np


def _stack(sd: Dict[str, Any], fmt: str, n: int, **kw) -> np.ndarray:
    return np.stack([_np(sd[fmt.format(i=i, **kw)]) for i in range(n)])


def _map_act(hf_act: str) -> str:
    """HF activation name → ACT_FNS key. HF 'gelu' is the EXACT erf GeLU;
    'gelu_new'/'gelu_fast'/'gelu_pytorch_tanh' are tanh approximations."""
    table = {"gelu": "gelu_exact", "gelu_new": "gelu", "gelu_fast": "gelu",
             "gelu_pytorch_tanh": "gelu", "relu": "relu", "silu": "silu"}
    if hf_act not in table:
        raise ValueError(f"Unsupported HF activation {hf_act!r}")
    return table[hf_act]


def hf_gpt2_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPT2Config → TransformerConfig."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.n_positions,
        num_layers=hf_cfg.n_layer,
        num_heads=hf_cfg.n_head,
        d_model=hf_cfg.n_embd,
        pos_embedding="learned",
        parallel_residual=False,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation_function),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=hf_cfg.layer_norm_epsilon,
        **overrides)


def load_hf_gpt2(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    """HF GPT-2 state dict (transformer.* naming; Conv1D weights already
    [in, out]) → params pytree. QKV layout matches: c_attn concatenates
    [q|k|v] on the output dim, exactly our qkv reshape order."""
    sd = {k.replace("transformer.", ""): v for k, v in state_dict.items()}
    n = config.num_layers

    def blk(name):
        return _stack(sd, "h.{i}." + name, n)

    params = {
        "embed": {"embedding": _np(sd["wte.weight"])},
        "pos_embed": {"embedding": _np(sd["wpe.weight"])},
        "blocks": {
            "ln1": {"scale": blk("ln_1.weight"), "bias": blk("ln_1.bias")},
            "attn": {
                "qkv": {"kernel": blk("attn.c_attn.weight"),
                        "bias": blk("attn.c_attn.bias")},
                "out": {"kernel": blk("attn.c_proj.weight"),
                        "bias": blk("attn.c_proj.bias")},
            },
            "ln2": {"scale": blk("ln_2.weight"), "bias": blk("ln_2.bias")},
            "mlp": {
                "fc_in": {"kernel": blk("mlp.c_fc.weight"),
                          "bias": blk("mlp.c_fc.bias")},
                "fc_out": {"kernel": blk("mlp.c_proj.weight"),
                           "bias": blk("mlp.c_proj.bias")},
            },
        },
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    return params


def hf_neox_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPTNeoXConfig → TransformerConfig."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.intermediate_size,
        pos_embedding="rotary",
        rotary_pct=hf_cfg.rotary_pct,
        rotary_base=getattr(hf_cfg, "rotary_emb_base", 10000.0),
        rotary_interleaved=False,     # HF GPTNeoX uses rotate_half
        parallel_residual=hf_cfg.use_parallel_residual,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.hidden_act),
        use_bias=True,
        tie_embeddings=False,
        layernorm_eps=hf_cfg.layer_norm_eps,
        **overrides)


def load_hf_neox(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    """HF GPT-NeoX state dict → params pytree.

    Two layout conversions (reference container: `containers/gptneox.py`):
    torch Linear weights are [out, in] → transposed; NeoX fuses QKV
    per-head as [h, 3, d] on the output dim → regrouped to our [3, h, d]."""
    sd = {k.replace("gpt_neox.", ""): v for k, v in state_dict.items()}
    n, nh = config.num_layers, config.num_heads
    d, hd = config.d_model, config.hdim

    def blk_t(name):   # linear kernels: [out, in] -> [in, out], stacked
        return np.stack([
            _np(sd[f"layers.{i}.{name}.weight"]).T for i in range(n)])

    def blk_b(name):
        return _stack(sd, "layers.{i}." + name + ".bias", n)

    def blk_ln(name, leaf):
        return _stack(sd, "layers.{i}." + name + "." + leaf, n)

    qkv_w = np.stack([_np(sd[f"layers.{i}.attention.query_key_value.weight"])
                      for i in range(n)])            # [L, 3*D, D] torch [out,in]
    qkv_w = (qkv_w.reshape(n, nh, 3, hd, d)          # out dim = [h, 3, hd]
             .transpose(0, 4, 2, 1, 3)               # [L, D, 3, h, hd]
             .reshape(n, d, 3 * nh * hd))
    qkv_b = np.stack([_np(sd[f"layers.{i}.attention.query_key_value.bias"])
                      for i in range(n)])
    qkv_b = (qkv_b.reshape(n, nh, 3, hd).transpose(0, 2, 1, 3)
             .reshape(n, 3 * nh * hd))

    params = {
        "embed": {"embedding": _np(sd["embed_in.weight"])},
        "blocks": {
            "ln1": {"scale": blk_ln("input_layernorm", "weight"),
                    "bias": blk_ln("input_layernorm", "bias")},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": blk_t("attention.dense"),
                        "bias": blk_b("attention.dense")},
            },
            "ln2": {"scale": blk_ln("post_attention_layernorm", "weight"),
                    "bias": blk_ln("post_attention_layernorm", "bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("mlp.dense_h_to_4h"),
                          "bias": blk_b("mlp.dense_h_to_4h")},
                "fc_out": {"kernel": blk_t("mlp.dense_4h_to_h"),
                           "bias": blk_b("mlp.dense_4h_to_h")},
            },
        },
        "ln_f": {"scale": _np(sd["final_layer_norm.weight"]),
                 "bias": _np(sd["final_layer_norm.bias"])},
        "lm_head": {"kernel": _np(state_dict["embed_out.weight"]).T},
    }
    return params


def hf_opt_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.OPTConfig → TransformerConfig (reference
    `containers/opt.py` / HFOPTLayerPolicy)."""
    if not getattr(hf_cfg, "do_layer_norm_before", True):
        raise ValueError("OPT with do_layer_norm_before=False (350m "
                         "post-norm variant) is not supported")
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.ffn_dim,
        pos_embedding="learned",
        parallel_residual=False,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation_function),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=1e-5,
        **overrides)


def load_hf_opt(state_dict: Dict[str, Any],
                config: TransformerConfig) -> Dict:
    """HF OPT state dict → params. Torch Linear kernels transpose; the
    separate q/k/v projections concatenate into our fused [d, 3·nh·hd]
    layout (q|k|v blocks, head-major — the order our qkv reshape reads);
    OPT's positional table carries a +2 offset (HF
    OPTLearnedPositionalEmbedding) — rows 2: are the real positions."""
    sd = {k.replace("model.decoder.", ""): v
          for k, v in state_dict.items()}
    n = config.num_layers

    def t(name, i):
        return _np(sd[f"layers.{i}.{name}.weight"]).T

    def b(name, i):
        return _np(sd[f"layers.{i}.{name}.bias"])

    qkv_w = np.stack([np.concatenate(
        [t("self_attn.q_proj", i), t("self_attn.k_proj", i),
         t("self_attn.v_proj", i)], axis=-1) for i in range(n)])
    qkv_b = np.stack([np.concatenate(
        [b("self_attn.q_proj", i), b("self_attn.k_proj", i),
         b("self_attn.v_proj", i)]) for i in range(n)])

    def blk_t(name):
        return np.stack([t(name, i) for i in range(n)])

    def blk_b(name):
        return np.stack([b(name, i) for i in range(n)])

    def blk_ln(name, leaf):
        return _stack(sd, "layers.{i}." + name + "." + leaf, n)

    params = {
        "embed": {"embedding": _np(sd["embed_tokens.weight"])},
        "pos_embed": {"embedding": _np(sd["embed_positions.weight"])[2:]},
        "blocks": {
            "ln1": {"scale": blk_ln("self_attn_layer_norm", "weight"),
                    "bias": blk_ln("self_attn_layer_norm", "bias")},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": blk_t("self_attn.out_proj"),
                        "bias": blk_b("self_attn.out_proj")},
            },
            "ln2": {"scale": blk_ln("final_layer_norm", "weight"),
                    "bias": blk_ln("final_layer_norm", "bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("fc1"), "bias": blk_b("fc1")},
                "fc_out": {"kernel": blk_t("fc2"), "bias": blk_b("fc2")},
            },
        },
        "ln_f": {"scale": _np(sd["final_layer_norm.weight"]),
                 "bias": _np(sd["final_layer_norm.bias"])},
    }
    return params


def hf_bloom_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.BloomConfig → TransformerConfig (reference
    `containers/bloom.py` / BLOOMLayerPolicy): ALiBi positions + embedding
    layernorm."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=2048,
        num_layers=hf_cfg.n_layer,
        num_heads=hf_cfg.n_head,
        d_model=hf_cfg.hidden_size,
        pos_embedding="alibi",
        embed_layernorm=True,
        parallel_residual=False,
        norm_type="layernorm",
        activation="gelu",            # BloomGelu is the tanh approximation
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=hf_cfg.layer_norm_epsilon,
        **overrides)


def load_hf_bloom(state_dict: Dict[str, Any],
                  config: TransformerConfig) -> Dict:
    """HF BLOOM state dict → params. Fused QKV is per-head [h, 3, hd] on
    the output dim (HF modeling_bloom _split_heads) → regrouped to our
    [3, h, hd]; torch Linear kernels transpose."""
    sd = {k.replace("transformer.", ""): v for k, v in state_dict.items()}
    n, nh = config.num_layers, config.num_heads
    d, hd = config.d_model, config.hdim

    qkv_w = np.stack([_np(sd[f"h.{i}.self_attention.query_key_value.weight"])
                      for i in range(n)])              # [L, 3D, D] torch
    qkv_w = (qkv_w.reshape(n, nh, 3, hd, d)
             .transpose(0, 4, 2, 1, 3)                 # [L, D, 3, h, hd]
             .reshape(n, d, 3 * nh * hd))
    qkv_b = np.stack([_np(sd[f"h.{i}.self_attention.query_key_value.bias"])
                      for i in range(n)])
    qkv_b = (qkv_b.reshape(n, nh, 3, hd).transpose(0, 2, 1, 3)
             .reshape(n, 3 * nh * hd))

    def blk_t(name):
        return np.stack([
            _np(sd[f"h.{i}.{name}.weight"]).T for i in range(n)])

    def blk_b(name):
        return _stack(sd, "h.{i}." + name + ".bias", n)

    def blk_ln(name, leaf):
        return _stack(sd, "h.{i}." + name + "." + leaf, n)

    params = {
        "embed": {"embedding": _np(sd["word_embeddings.weight"])},
        "ln_embed": {"scale": _np(sd["word_embeddings_layernorm.weight"]),
                     "bias": _np(sd["word_embeddings_layernorm.bias"])},
        "blocks": {
            "ln1": {"scale": blk_ln("input_layernorm", "weight"),
                    "bias": blk_ln("input_layernorm", "bias")},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": blk_t("self_attention.dense"),
                        "bias": blk_b("self_attention.dense")},
            },
            "ln2": {"scale": blk_ln("post_attention_layernorm", "weight"),
                    "bias": blk_ln("post_attention_layernorm", "bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("mlp.dense_h_to_4h"),
                          "bias": blk_b("mlp.dense_h_to_4h")},
                "fc_out": {"kernel": blk_t("mlp.dense_4h_to_h"),
                           "bias": blk_b("mlp.dense_4h_to_h")},
            },
        },
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    return params


def hf_bert_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.BertConfig → TransformerConfig (reference
    `containers/bert.py` / HFBertLayerPolicy): bidirectional post-norm
    encoder with token types and the MLM prediction head."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.intermediate_size,
        pos_embedding="learned",
        causal=False,
        norm_position="post",
        final_layernorm=False,
        embed_layernorm=True,
        token_type_vocab=hf_cfg.type_vocab_size,
        mlm_head=True,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.hidden_act),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=hf_cfg.layer_norm_eps,
        **overrides)


def load_hf_bert(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    """HF BertForMaskedLM state dict → params. Separate q/k/v transpose +
    concat to the fused layout; post-norm LNs map attention.output.
    LayerNorm → ln1 and output.LayerNorm → ln2."""
    sd = {k.replace("bert.", ""): v for k, v in state_dict.items()}
    n = config.num_layers
    pre = "encoder.layer.{i}."

    def t(name, i):
        return _np(sd[f"encoder.layer.{i}.{name}.weight"]).T

    def b(name, i):
        return _np(sd[f"encoder.layer.{i}.{name}.bias"])

    qkv_w = np.stack([np.concatenate(
        [t("attention.self.query", i), t("attention.self.key", i),
         t("attention.self.value", i)], axis=-1) for i in range(n)])
    qkv_b = np.stack([np.concatenate(
        [b("attention.self.query", i), b("attention.self.key", i),
         b("attention.self.value", i)]) for i in range(n)])

    def blk_t(name):
        return np.stack([t(name, i) for i in range(n)])

    def blk_b(name):
        return np.stack([b(name, i) for i in range(n)])

    def blk_ln(name, leaf):
        return _stack(sd, pre + name + "." + leaf, n)

    params = {
        "embed": {"embedding": _np(sd["embeddings.word_embeddings.weight"])},
        "pos_embed": {"embedding": _np(
            sd["embeddings.position_embeddings.weight"])},
        "type_embed": {"embedding": _np(
            sd["embeddings.token_type_embeddings.weight"])},
        "ln_embed": {"scale": _np(sd["embeddings.LayerNorm.weight"]),
                     "bias": _np(sd["embeddings.LayerNorm.bias"])},
        "blocks": {
            "ln1": {"scale": blk_ln("attention.output.LayerNorm", "weight"),
                    "bias": blk_ln("attention.output.LayerNorm", "bias")},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": blk_t("attention.output.dense"),
                        "bias": blk_b("attention.output.dense")},
            },
            "ln2": {"scale": blk_ln("output.LayerNorm", "weight"),
                    "bias": blk_ln("output.LayerNorm", "bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("intermediate.dense"),
                          "bias": blk_b("intermediate.dense")},
                "fc_out": {"kernel": blk_t("output.dense"),
                           "bias": blk_b("output.dense")},
            },
        },
        "mlm_head": {
            "dense": {
                "kernel": _np(state_dict[
                    "cls.predictions.transform.dense.weight"]).T,
                "bias": _np(state_dict[
                    "cls.predictions.transform.dense.bias"])},
            "ln": {"scale": _np(state_dict[
                       "cls.predictions.transform.LayerNorm.weight"]),
                   "bias": _np(state_dict[
                       "cls.predictions.transform.LayerNorm.bias"])},
            "bias": _np(state_dict["cls.predictions.bias"]),
        },
    }
    return params


def hf_llama_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.LlamaConfig → TransformerConfig: RMSNorm + SwiGLU
    gated MLP + full-dim rotate-half rotary, no biases, untied head.
    Grouped-query attention (num_key_value_heads < heads) maps to
    num_kv_heads; rope_scaling / bias-carrying checkpoints reject loudly
    (converting them would yield silently wrong logits)."""
    nkv = getattr(hf_cfg, "num_key_value_heads",
                  hf_cfg.num_attention_heads)
    if getattr(hf_cfg, "rope_scaling", None):
        raise NotImplementedError(
            f"rope_scaling={hf_cfg.rope_scaling!r} (Llama-3 / long-context "
            f"RoPE rescaling) is not implemented — converting without it "
            f"would yield silently wrong logits")
    if getattr(hf_cfg, "attention_bias", False):
        raise NotImplementedError(
            "attention_bias=True checkpoints carry q/k/v biases this "
            "no-bias conversion would drop")
    if getattr(hf_cfg, "mlp_bias", False):
        raise NotImplementedError(
            "mlp_bias=True checkpoints carry gate/up/down biases this "
            "no-bias conversion would drop")
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        num_kv_heads=(0 if nkv == hf_cfg.num_attention_heads else nkv),
        head_dim=getattr(hf_cfg, "head_dim", None) or 0,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.intermediate_size,
        pos_embedding="rotary",
        rotary_pct=1.0,
        rotary_base=getattr(hf_cfg, "rope_theta", 10000.0),
        rotary_interleaved=False,     # HF llama rotate_half
        parallel_residual=False,
        norm_type="rmsnorm",
        activation=_map_act(hf_cfg.hidden_act),
        gated_mlp=True,
        use_bias=False,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        layernorm_eps=hf_cfg.rms_norm_eps,
        **overrides)


def load_hf_llama(state_dict: Dict[str, Any],
                  config: TransformerConfig) -> Dict:
    """HF LLaMA state dict → params (torch kernels transpose; q|k|v
    concat to the fused layout; gate/up/down → fc_gate/fc_in/fc_out)."""
    sd = {k.replace("model.", "", 1): v for k, v in state_dict.items()}
    n = config.num_layers

    def t(name, i):
        return _np(sd[f"layers.{i}.{name}.weight"]).T

    qkv_w = np.stack([np.concatenate(
        [t("self_attn.q_proj", i), t("self_attn.k_proj", i),
         t("self_attn.v_proj", i)], axis=-1) for i in range(n)])

    def blk_t(name):
        return np.stack([t(name, i) for i in range(n)])

    def blk_ln(name):
        return _stack(sd, "layers.{i}." + name + ".weight", n)

    params = {
        "embed": {"embedding": _np(sd["embed_tokens.weight"])},
        "blocks": {
            "ln1": {"scale": blk_ln("input_layernorm")},
            "attn": {
                "qkv": {"kernel": qkv_w},
                "out": {"kernel": blk_t("self_attn.o_proj")},
            },
            "ln2": {"scale": blk_ln("post_attention_layernorm")},
            "mlp": {
                "fc_gate": {"kernel": blk_t("mlp.gate_proj")},
                "fc_in": {"kernel": blk_t("mlp.up_proj")},
                "fc_out": {"kernel": blk_t("mlp.down_proj")},
            },
        },
        "ln_f": {"scale": _np(sd["norm.weight"])},
    }
    if not config.tie_embeddings:
        params["lm_head"] = {"kernel": _np(state_dict["lm_head.weight"]).T}
    return params


def hf_gptj_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPTJConfig → TransformerConfig (reference
    `containers/gptj.py` HFGPTJLayerPolicy): partial interleaved rotary,
    SINGLE-layernorm parallel residual (ln_1 feeds both attn and mlp —
    expressed by loading identical ln1/ln2, mathematically exact), no
    attention biases (loaded as zeros), untied lm_head WITH bias."""
    hdim = hf_cfg.n_embd // hf_cfg.n_head
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.n_positions,
        num_layers=hf_cfg.n_layer,
        num_heads=hf_cfg.n_head,
        d_model=hf_cfg.n_embd,
        d_ff=(hf_cfg.n_inner or 4 * hf_cfg.n_embd),
        pos_embedding="rotary",
        rotary_pct=hf_cfg.rotary_dim / hdim,
        rotary_interleaved=True,      # GPT-J rotates every two
        parallel_residual=True,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation_function),
        use_bias=True,
        tie_embeddings=False,
        layernorm_eps=hf_cfg.layer_norm_epsilon,
        **overrides)


def load_hf_gptj(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    sd = {k.replace("transformer.", ""): v for k, v in state_dict.items()}
    n, d = config.num_layers, config.d_model

    def t(name, i):
        return _np(sd[f"h.{i}.{name}.weight"]).T

    qkv_w = np.stack([np.concatenate(
        [t("attn.q_proj", i), t("attn.k_proj", i), t("attn.v_proj", i)],
        axis=-1) for i in range(n)])
    zeros_b = np.zeros((n, 3 * d), np.float32)
    ln1_s = _stack(sd, "h.{i}.ln_1.weight", n)
    ln1_b = _stack(sd, "h.{i}.ln_1.bias", n)
    params = {
        "embed": {"embedding": _np(sd["wte.weight"])},
        "blocks": {
            "ln1": {"scale": ln1_s, "bias": ln1_b},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": zeros_b},
                "out": {"kernel": np.stack(
                    [t("attn.out_proj", i) for i in range(n)]),
                    "bias": np.zeros((n, d), np.float32)},
            },
            # single-LN parallel residual: ln2 := ln_1 (same input x)
            "ln2": {"scale": ln1_s.copy(), "bias": ln1_b.copy()},
            "mlp": {
                "fc_in": {"kernel": np.stack(
                    [t("mlp.fc_in", i) for i in range(n)]),
                    "bias": _stack(sd, "h.{i}.mlp.fc_in.bias", n)},
                "fc_out": {"kernel": np.stack(
                    [t("mlp.fc_out", i) for i in range(n)]),
                    "bias": _stack(sd, "h.{i}.mlp.fc_out.bias", n)},
            },
        },
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
        "lm_head": {"kernel": _np(state_dict["lm_head.weight"]).T,
                    "bias": _np(state_dict["lm_head.bias"])},
    }
    return params


def hf_distilbert_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.DistilBertConfig → TransformerConfig (reference
    `containers/distil_bert.py`): BERT-style post-norm encoder without
    token types; MLM head tied to the word embeddings."""
    if getattr(hf_cfg, "sinusoidal_pos_embds", False):
        raise NotImplementedError(
            "DistilBERT with sinusoidal_pos_embds: only the learned-"
            "position variant (the published checkpoints) is mapped")
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.n_layers,
        num_heads=hf_cfg.n_heads,
        d_model=hf_cfg.dim,
        d_ff=hf_cfg.hidden_dim,
        pos_embedding="learned",
        causal=False,
        norm_position="post",
        final_layernorm=False,
        embed_layernorm=True,
        mlm_head=True,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=1e-12,
        **overrides)


def load_hf_distilbert(state_dict: Dict[str, Any],
                       config: TransformerConfig) -> Dict:
    sd = {k.replace("distilbert.", ""): v for k, v in state_dict.items()}
    n = config.num_layers
    pre = "transformer.layer.{i}."

    def t(name, i):
        return _np(sd[f"transformer.layer.{i}.{name}.weight"]).T

    def b(name, i):
        return _np(sd[f"transformer.layer.{i}.{name}.bias"])

    qkv_w = np.stack([np.concatenate(
        [t("attention.q_lin", i), t("attention.k_lin", i),
         t("attention.v_lin", i)], axis=-1) for i in range(n)])
    qkv_b = np.stack([np.concatenate(
        [b("attention.q_lin", i), b("attention.k_lin", i),
         b("attention.v_lin", i)]) for i in range(n)])
    params = {
        "embed": {"embedding": _np(
            sd["embeddings.word_embeddings.weight"])},
        "pos_embed": {"embedding": _np(
            sd["embeddings.position_embeddings.weight"])},
        "ln_embed": {"scale": _np(sd["embeddings.LayerNorm.weight"]),
                     "bias": _np(sd["embeddings.LayerNorm.bias"])},
        "blocks": {
            "ln1": {"scale": _stack(sd, pre + "sa_layer_norm.weight", n),
                    "bias": _stack(sd, pre + "sa_layer_norm.bias", n)},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": np.stack(
                    [t("attention.out_lin", i) for i in range(n)]),
                    "bias": np.stack(
                        [b("attention.out_lin", i) for i in range(n)])},
            },
            "ln2": {"scale": _stack(sd, pre + "output_layer_norm.weight",
                                    n),
                    "bias": _stack(sd, pre + "output_layer_norm.bias",
                                   n)},
            "mlp": {
                "fc_in": {"kernel": np.stack(
                    [t("ffn.lin1", i) for i in range(n)]),
                    "bias": np.stack([b("ffn.lin1", i)
                                      for i in range(n)])},
                "fc_out": {"kernel": np.stack(
                    [t("ffn.lin2", i) for i in range(n)]),
                    "bias": np.stack([b("ffn.lin2", i)
                                      for i in range(n)])},
            },
        },
        "mlm_head": {
            "dense": {"kernel": _np(state_dict["vocab_transform.weight"]).T,
                      "bias": _np(state_dict["vocab_transform.bias"])},
            "ln": {"scale": _np(state_dict["vocab_layer_norm.weight"]),
                   "bias": _np(state_dict["vocab_layer_norm.bias"])},
            "bias": _np(state_dict["vocab_projector.bias"]),
        },
    }
    return params


def hf_gptneo_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPTNeoConfig → TransformerConfig (reference container
    `module_inject/containers/gptneo.py:73`).

    Two architecture oddities the config carries: UNSCALED softmax logits
    (the reference policy passes scale_attention=False, `gptneo.py:75`) and
    the alternating global/local attention pattern — per-layer windows ride
    the layer scan (TransformerConfig.attention_layers), closing the r2-r4
    documented reject."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_layers,
        num_heads=hf_cfg.num_heads,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.intermediate_size or 4 * hf_cfg.hidden_size,
        pos_embedding="learned",
        parallel_residual=False,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation_function),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=hf_cfg.layer_norm_epsilon,
        attn_softmax_scale=1.0,
        attention_layers=tuple(hf_cfg.attention_layers),
        local_attention_window=hf_cfg.window_size,
        attn_impl="xla",
        **overrides)


def load_hf_gptneo(state_dict: Dict[str, Any],
                   config: TransformerConfig) -> Dict:
    """HF GPT-Neo state dict → params pytree.

    Unlike GPT-2's Conv1D, every projection is nn.Linear ([out, in] →
    transpose); q/k/v are separate and BIAS-FREE (out_proj keeps its bias),
    so the fused qkv bias is zero-filled — same concat order the reference's
    maybe_copy_qkv uses (`containers/gptneo.py:40`)."""
    sd = {k.replace("transformer.", ""): v for k, v in state_dict.items()}
    n = config.num_layers
    d = config.d_model

    def blk_t(name):
        return _stack(sd, "h.{i}." + name, n).transpose(0, 2, 1)

    def blk_b(name):
        return _stack(sd, "h.{i}." + name, n)

    qkv_kernel = np.concatenate(
        [blk_t("attn.attention.q_proj.weight"),
         blk_t("attn.attention.k_proj.weight"),
         blk_t("attn.attention.v_proj.weight")], axis=2)
    params = {
        "embed": {"embedding": _np(sd["wte.weight"])},
        "pos_embed": {"embedding": _np(sd["wpe.weight"])},
        "blocks": {
            "ln1": {"scale": blk_b("ln_1.weight"),
                    "bias": blk_b("ln_1.bias")},
            "attn": {
                "qkv": {"kernel": qkv_kernel,
                        "bias": np.zeros((n, 3 * d), np.float32)},
                "out": {"kernel": blk_t("attn.attention.out_proj.weight"),
                        "bias": blk_b("attn.attention.out_proj.bias")},
            },
            "ln2": {"scale": blk_b("ln_2.weight"),
                    "bias": blk_b("ln_2.bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("mlp.c_fc.weight"),
                          "bias": blk_b("mlp.c_fc.bias")},
                "fc_out": {"kernel": blk_t("mlp.c_proj.weight"),
                           "bias": blk_b("mlp.c_proj.bias")},
            },
        },
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    return params


# registry (reference replace_policy.py:17)
POLICIES = {
    "gpt2": (hf_gpt2_config, load_hf_gpt2),
    "gpt_neox": (hf_neox_config, load_hf_neox),
    "opt": (hf_opt_config, load_hf_opt),
    "bloom": (hf_bloom_config, load_hf_bloom),
    "bert": (hf_bert_config, load_hf_bert),
    "llama": (hf_llama_config, load_hf_llama),
    "gptj": (hf_gptj_config, load_hf_gptj),
    "distilbert": (hf_distilbert_config, load_hf_distilbert),
    "gpt_neo": (hf_gptneo_config, load_hf_gptneo),
}


def convert_hf_model(hf_model, **config_overrides):
    """(transformers PreTrainedModel) → (TransformerConfig, params).

    Policy selected from ``model_type`` like the reference's registry walk
    (`replace_module.py:306`)."""
    mtype = hf_model.config.model_type
    if mtype not in POLICIES:
        raise ValueError(
            f"No policy for model_type={mtype!r}; have {list(POLICIES)}")
    cfg_fn, load_fn = POLICIES[mtype]
    cfg = cfg_fn(hf_model.config, **config_overrides)
    return cfg, load_fn(hf_model.state_dict(), cfg)
