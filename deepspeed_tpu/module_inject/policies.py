"""Weight-injection policies: HuggingFace checkpoints → TransformerLM params.

Role-equivalent of the reference's per-architecture policies + containers
(`/root/reference/deepspeed/module_inject/policy.py`,
`module_inject/containers/gpt2.py`, `containers/gptneox.py`, registry at
`replace_policy.py:17`): each policy knows the source model's weight-name map
and emits our stacked-scan params pytree. Where the reference swaps nn.Modules
for fused-kernel modules holding sliced tensors, here conversion is pure data
movement — the TP slicing happens afterwards when the tree is device_put into
the mesh shardings (`inference/engine.py`), so policies stay layout-free.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..models.transformer import TransformerConfig


def _np(t) -> np.ndarray:
    """torch tensor / array-like → numpy (host)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _stack(sd: Dict[str, Any], fmt: str, n: int, **kw) -> np.ndarray:
    return np.stack([_np(sd[fmt.format(i=i, **kw)]) for i in range(n)])


def _map_act(hf_act: str) -> str:
    """HF activation name → ACT_FNS key. HF 'gelu' is the EXACT erf GeLU;
    'gelu_new'/'gelu_fast'/'gelu_pytorch_tanh' are tanh approximations."""
    table = {"gelu": "gelu_exact", "gelu_new": "gelu", "gelu_fast": "gelu",
             "gelu_pytorch_tanh": "gelu", "relu": "relu", "silu": "silu"}
    if hf_act not in table:
        raise ValueError(f"Unsupported HF activation {hf_act!r}")
    return table[hf_act]


def hf_gpt2_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPT2Config → TransformerConfig."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.n_positions,
        num_layers=hf_cfg.n_layer,
        num_heads=hf_cfg.n_head,
        d_model=hf_cfg.n_embd,
        pos_embedding="learned",
        parallel_residual=False,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.activation_function),
        use_bias=True,
        tie_embeddings=True,
        layernorm_eps=hf_cfg.layer_norm_epsilon,
        **overrides)


def load_hf_gpt2(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    """HF GPT-2 state dict (transformer.* naming; Conv1D weights already
    [in, out]) → params pytree. QKV layout matches: c_attn concatenates
    [q|k|v] on the output dim, exactly our qkv reshape order."""
    sd = {k.replace("transformer.", ""): v for k, v in state_dict.items()}
    n = config.num_layers

    def blk(name):
        return _stack(sd, "h.{i}." + name, n)

    params = {
        "embed": {"embedding": _np(sd["wte.weight"])},
        "pos_embed": {"embedding": _np(sd["wpe.weight"])},
        "blocks": {
            "ln1": {"scale": blk("ln_1.weight"), "bias": blk("ln_1.bias")},
            "attn": {
                "qkv": {"kernel": blk("attn.c_attn.weight"),
                        "bias": blk("attn.c_attn.bias")},
                "out": {"kernel": blk("attn.c_proj.weight"),
                        "bias": blk("attn.c_proj.bias")},
            },
            "ln2": {"scale": blk("ln_2.weight"), "bias": blk("ln_2.bias")},
            "mlp": {
                "fc_in": {"kernel": blk("mlp.c_fc.weight"),
                          "bias": blk("mlp.c_fc.bias")},
                "fc_out": {"kernel": blk("mlp.c_proj.weight"),
                           "bias": blk("mlp.c_proj.bias")},
            },
        },
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    return params


def hf_neox_config(hf_cfg, **overrides) -> TransformerConfig:
    """transformers.GPTNeoXConfig → TransformerConfig."""
    return TransformerConfig(
        vocab_size=hf_cfg.vocab_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        num_layers=hf_cfg.num_hidden_layers,
        num_heads=hf_cfg.num_attention_heads,
        d_model=hf_cfg.hidden_size,
        d_ff=hf_cfg.intermediate_size,
        pos_embedding="rotary",
        rotary_pct=hf_cfg.rotary_pct,
        rotary_base=getattr(hf_cfg, "rotary_emb_base", 10000.0),
        rotary_interleaved=False,     # HF GPTNeoX uses rotate_half
        parallel_residual=hf_cfg.use_parallel_residual,
        norm_type="layernorm",
        activation=_map_act(hf_cfg.hidden_act),
        use_bias=True,
        tie_embeddings=False,
        layernorm_eps=hf_cfg.layer_norm_eps,
        **overrides)


def load_hf_neox(state_dict: Dict[str, Any],
                 config: TransformerConfig) -> Dict:
    """HF GPT-NeoX state dict → params pytree.

    Two layout conversions (reference container: `containers/gptneox.py`):
    torch Linear weights are [out, in] → transposed; NeoX fuses QKV
    per-head as [h, 3, d] on the output dim → regrouped to our [3, h, d]."""
    sd = {k.replace("gpt_neox.", ""): v for k, v in state_dict.items()}
    n, nh = config.num_layers, config.num_heads
    d, hd = config.d_model, config.hdim

    def blk_t(name):   # linear kernels: [out, in] -> [in, out], stacked
        return np.stack([
            _np(sd[f"layers.{i}.{name}.weight"]).T for i in range(n)])

    def blk_b(name):
        return _stack(sd, "layers.{i}." + name + ".bias", n)

    def blk_ln(name, leaf):
        return _stack(sd, "layers.{i}." + name + "." + leaf, n)

    qkv_w = np.stack([_np(sd[f"layers.{i}.attention.query_key_value.weight"])
                      for i in range(n)])            # [L, 3*D, D] torch [out,in]
    qkv_w = (qkv_w.reshape(n, nh, 3, hd, d)          # out dim = [h, 3, hd]
             .transpose(0, 4, 2, 1, 3)               # [L, D, 3, h, hd]
             .reshape(n, d, 3 * nh * hd))
    qkv_b = np.stack([_np(sd[f"layers.{i}.attention.query_key_value.bias"])
                      for i in range(n)])
    qkv_b = (qkv_b.reshape(n, nh, 3, hd).transpose(0, 2, 1, 3)
             .reshape(n, 3 * nh * hd))

    params = {
        "embed": {"embedding": _np(sd["embed_in.weight"])},
        "blocks": {
            "ln1": {"scale": blk_ln("input_layernorm", "weight"),
                    "bias": blk_ln("input_layernorm", "bias")},
            "attn": {
                "qkv": {"kernel": qkv_w, "bias": qkv_b},
                "out": {"kernel": blk_t("attention.dense"),
                        "bias": blk_b("attention.dense")},
            },
            "ln2": {"scale": blk_ln("post_attention_layernorm", "weight"),
                    "bias": blk_ln("post_attention_layernorm", "bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("mlp.dense_h_to_4h"),
                          "bias": blk_b("mlp.dense_h_to_4h")},
                "fc_out": {"kernel": blk_t("mlp.dense_4h_to_h"),
                           "bias": blk_b("mlp.dense_4h_to_h")},
            },
        },
        "ln_f": {"scale": _np(sd["final_layer_norm.weight"]),
                 "bias": _np(sd["final_layer_norm.bias"])},
        "lm_head": {"kernel": _np(state_dict["embed_out.weight"]).T},
    }
    return params


# registry (reference replace_policy.py:17)
POLICIES = {
    "gpt2": (hf_gpt2_config, load_hf_gpt2),
    "gpt_neox": (hf_neox_config, load_hf_neox),
}


def convert_hf_model(hf_model, **config_overrides):
    """(transformers PreTrainedModel) → (TransformerConfig, params).

    Policy selected from ``model_type`` like the reference's registry walk
    (`replace_module.py:306`)."""
    mtype = hf_model.config.model_type
    if mtype not in POLICIES:
        raise ValueError(
            f"No policy for model_type={mtype!r}; have {list(POLICIES)}")
    cfg_fn, load_fn = POLICIES[mtype]
    cfg = cfg_fn(hf_model.config, **config_overrides)
    return cfg, load_fn(hf_model.state_dict(), cfg)
