"""Module injection: HF-checkpoint policies + auto-TP.

Counterpart of `/root/reference/deepspeed/module_inject/` — the reference
swaps nn.Modules for kernel-injected replicas with sliced weights; here
policies convert foreign checkpoints into the native params pytree and TP
slicing is a sharding declaration (`auto_tp_specs`) applied at device_put.
"""
from .auto_tp import auto_tp_specs
from .policies import (POLICIES, convert_hf_model, hf_gpt2_config,
                       hf_neox_config, load_hf_gpt2, load_hf_neox)

__all__ = ["auto_tp_specs", "POLICIES", "convert_hf_model",
           "hf_gpt2_config", "hf_neox_config", "load_hf_gpt2",
           "load_hf_neox"]
