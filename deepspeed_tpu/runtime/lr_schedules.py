"""LR schedules.

Same schedule vocabulary as the reference (`/root/reference/deepspeed/runtime/
lr_schedules.py:17-21`: LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR) plus
WarmupCosineLR. Schedules here are pure functions ``step -> lr`` built from
config, so they trace cleanly into the jitted train step (the reference calls
``lr_scheduler.step()`` eagerly each step instead).
"""
from __future__ import annotations

import math
from typing import Callable, Dict

import jax.numpy as jnp

LRSchedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant_lr(lr: float) -> LRSchedule:
    return lambda step: jnp.asarray(lr, jnp.float32)


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000,
              warmup_type: str = "log") -> LRSchedule:
    """Reference ``WarmupLR`` (`lr_schedules.py:689`): warm up then hold."""
    wmin, wmax, wsteps = float(warmup_min_lr), float(warmup_max_lr), max(
        1, int(warmup_num_steps))

    def sched(step):
        s = jnp.minimum(step.astype(jnp.float32), wsteps)
        if warmup_type == "log":
            # log-warmup: lr grows with log(step)/log(warmup_steps)
            frac = jnp.log1p(s) / math.log(wsteps + 1)
        else:
            frac = s / wsteps
        return wmin + (wmax - wmin) * jnp.clip(frac, 0.0, 1.0)

    return sched


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001,
                    warmup_num_steps: int = 1000,
                    warmup_type: str = "log") -> LRSchedule:
    """Reference ``WarmupDecayLR`` (`lr_schedules.py:743`): warmup then linear
    decay to 0 at total_num_steps."""
    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps,
                     warmup_type)
    total = float(total_num_steps)
    wsteps = float(max(1, warmup_num_steps))

    def sched(step):
        s = step.astype(jnp.float32)
        decay = jnp.clip((total - s) / jnp.maximum(total - wsteps, 1.0), 0.0, 1.0)
        return jnp.where(s < wsteps, warm(step), warmup_max_lr * decay)

    return sched


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0,
                     warmup_max_lr: float = 0.001) -> LRSchedule:
    total = float(total_num_steps)
    wsteps = float(max(1, warmup_num_steps))

    def sched(step):
        s = step.astype(jnp.float32)
        warm_frac = warmup_min_ratio + (1 - warmup_min_ratio) * jnp.clip(
            s / wsteps, 0.0, 1.0)
        prog = jnp.clip((s - wsteps) / jnp.maximum(total - wsteps, 1.0), 0.0, 1.0)
        cos = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (
            1 + jnp.cos(math.pi * prog))
        return warmup_max_lr * jnp.where(s < wsteps, warm_frac, cos)

    return sched


def one_cycle(cycle_min_lr: float, cycle_max_lr: float,
              cycle_first_step_size: int = 2000,
              cycle_second_step_size: int = None,
              decay_step_size: int = 0,
              decay_lr_rate: float = 0.0) -> LRSchedule:
    """Reference ``OneCycle`` (`lr_schedules.py:441`): triangular cycle then
    optional decay phase."""
    up = float(cycle_first_step_size)
    down = float(cycle_second_step_size
                 if cycle_second_step_size is not None else up)

    def sched(step):
        s = step.astype(jnp.float32)
        in_up = s < up
        in_down = (s >= up) & (s < up + down)
        frac_up = jnp.clip(s / up, 0.0, 1.0)
        frac_down = jnp.clip((s - up) / down, 0.0, 1.0)
        lr_cycle = jnp.where(
            in_up, cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac_up,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac_down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(s - (up + down), 0.0) / decay_step_size
            lr_decayed = cycle_min_lr / (1.0 + decay_steps * decay_lr_rate)
            return jnp.where(in_up | in_down, lr_cycle, lr_decayed)
        return jnp.where(in_up | in_down, lr_cycle, cycle_min_lr)

    return sched


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False) -> LRSchedule:
    """Reference ``LRRangeTest`` (`lr_schedules.py:335`): linearly/staircase
    increasing LR probe for finding stable LR ranges."""
    def sched(step):
        s = step.astype(jnp.float32) / lr_range_test_step_size
        if lr_range_test_staircase:
            s = jnp.floor(s)
        return lr_range_test_min_lr * (1.0 + s * lr_range_test_step_rate)

    return sched


REGISTRY: Dict[str, Callable[..., LRSchedule]] = {
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
    "OneCycle": one_cycle,
    "LRRangeTest": lr_range_test,
    "Constant": lambda lr=1e-3: constant_lr(lr),
}


def get_lr_schedule(type_name: str, params: dict) -> LRSchedule:
    if type_name not in REGISTRY:
        raise ValueError(
            f"Unknown scheduler {type_name}; have {sorted(REGISTRY)}")
    return REGISTRY[type_name](**params)
