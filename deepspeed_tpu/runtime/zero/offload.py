"""ZeRO-Offload tier 1: optimizer state + fp32 masters in host DRAM.

Role-equivalent of the reference's CPU offload path — ZeRO
``offload_optimizer: {device: cpu}`` wiring in
`/root/reference/deepspeed/runtime/zero/stage_1_and_2.py` (cpu_offload flag)
and `stage3.py:480` (_configure_tensor_swapping), with the host update done
by ``DeepSpeedCPUAdam`` (`csrc/adam/cpu_adam.cpp`). TPU redesign:

  - Device HBM holds ONLY the compute-dtype (bf16) parameters; fp32 masters
    + Adam moments are host numpy, stepped by the native library
    (`ops/csrc/cpu_adam.cpp`). That is 12 host bytes vs 2 device bytes per
    parameter — the "params/chip" lever of BASELINE.md.
  - One jitted program computes summed grads + their norm; the host folds
    loss-scale x microbatch-count x clip-factor into the C++ sweep's single
    grad multiply; the updated bf16 copies (produced in the same sweep)
    are uploaded back into the parameter shardings.
  - fp16 dynamic loss scaling runs its state machine host-side (the jitted
    version lives in `runtime/fp16/loss_scaler.py`; semantics identical).

The transfer pattern is device→host grads, host→device params each step —
the same wire traffic as the reference's cpu_offload, scheduled by
dispatch/donation instead of CUDA streams.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ...observability import trace_span
from ...utils.logging import logger
from ..utils import host_transfer


class HostLossScaler:
    """Host-side mirror of DynamicLossScaler's state machine."""

    def __init__(self, scaler):
        self.scale = float(scaler.initial_scale)
        self.window = scaler.scale_window
        self.min_scale = scaler.min_scale
        self.factor = scaler.scale_factor
        self.hysteresis0 = scaler.hysteresis
        self.hysteresis = scaler.hysteresis
        self.good_steps = 0
        self.detect_overflow = scaler.detect_overflow

    def update(self, overflow: bool) -> None:
        if overflow:
            self.hysteresis = max(self.hysteresis - 1, 0)
            if self.hysteresis <= 0:
                self.scale = max(self.scale / self.factor, self.min_scale)
                self.hysteresis = self.hysteresis0
            self.good_steps = 0
        else:
            self.good_steps += 1
            if self.good_steps >= self.window:
                self.scale *= self.factor
                self.good_steps = 0
                self.hysteresis = self.hysteresis0


class ZeroOffloadHostOptimizer:
    """Host half of the offload engine: masters + moments + step."""

    def __init__(self, engine, init_params_f32):
        cfg = engine._config
        oc = cfg.optimizer
        name = (oc.type if oc is not None else "adamw").lower()
        params = dict(oc.params) if oc is not None else {}
        lr = params.pop("lr", 1e-3)
        self.leaves, self.treedef = jax.tree_util.tree_flatten(
            init_params_f32)
        host = [np.asarray(l, dtype=np.float32) for l in self.leaves]

        from ...ops.adam.cpu_adam import (DeepSpeedCPUAdam,
                                          DeepSpeedCPUAdagrad)
        if name in ("adam", "adamw", "fusedadam", "cpuadam",
                    "deepspeedcpuadam"):
            betas = params.pop("betas", (0.9, 0.999))
            self.opt = DeepSpeedCPUAdam(
                host, lr=lr, betas=tuple(betas),
                eps=params.pop("eps", 1e-8),
                weight_decay=params.pop("weight_decay", 0.0),
                adamw_mode=params.pop("adam_w_mode", name != "adam"))
        elif name in ("adagrad", "cpuadagrad"):
            self.opt = DeepSpeedCPUAdagrad(
                host, lr=lr, eps=params.pop("eps", 1e-10),
                weight_decay=params.pop("weight_decay", 0.0))
        else:
            raise NotImplementedError(
                f"offload_optimizer supports Adam/AdamW/Adagrad, got {name} "
                f"(reference cpu_offload has the same restriction)")
        self.lr_default = lr
        self._bf16 = None   # upload buffers, allocated on first bf16 emit
        self.host_bytes = sum(
            sum(a.nbytes for a in arrs)
            for arrs in self.opt.state_arrays().values())

    def step(self, grad_leaves: List[np.ndarray], lr: float,
             grad_scale: float, emit_bf16: bool) -> List[np.ndarray]:
        """Update masters in place; return the new device-upload arrays
        (bf16 views when emit_bf16, else the fp32 masters)."""
        if emit_bf16 and self._bf16 is None:
            self._bf16 = [np.empty(m.shape, np.uint16)
                          for m in self.opt.master]
        self.opt.step(grad_leaves, lr=lr, grad_scale=grad_scale,
                      out_bf16=self._bf16 if emit_bf16 else None)
        if emit_bf16:
            return [b.view(ml_dtypes.bfloat16) for b in self._bf16]
        return self.opt.master

    def step_pipelined(self, grad_dev_leaves: List, shardings: List,
                       lr: float, grad_scale: float, emit_bf16: bool,
                       upload_dtype=None,
                       bucket_bytes: int = 32 << 20,
                       fetch_fn=None) -> List:
        """Overlapped offload step (reference
        ``PipelinedOptimizerSwapper``, `pipelined_optimizer_swapper.py:55`):
        leaves are walked in buckets of ~``bucket_bytes`` so that bucket
        i+1's device→host gradient fetch, bucket i's native optimizer
        sweep (worker thread — ctypes releases the GIL), and bucket i-1's
        host→device parameter upload all run concurrently.

        ``grad_dev_leaves`` — device arrays (fetch started with
        copy_to_host_async by the caller); returns the new device param
        leaves in order. ``fetch_fn(k) -> np.ndarray`` overrides the
        plain D2H fetch — the wire-codec path decodes the compressed
        payload here instead (runtime/zero/wire_codec.py)."""
        from concurrent.futures import ThreadPoolExecutor
        if emit_bf16 and self._bf16 is None:
            self._bf16 = [np.empty(m.shape, np.uint16)
                          for m in self.opt.master]
        # bucket boundaries over the leaf list
        buckets: List[List[int]] = [[]]
        acc = 0
        for idx, m in enumerate(self.opt.master):
            buckets[-1].append(idx)
            acc += m.nbytes
            if acc >= bucket_bytes:
                buckets.append([])
                acc = 0
        if not buckets[-1]:
            buckets.pop()

        self.opt.step_count += 1

        def sweep(idxs, ghosts):
            # runs on the offload-opt worker thread — its own trace track
            with trace_span("offload/sweep_bucket", leaves=len(idxs)):
                for k, gi in zip(idxs, ghosts):
                    self.opt.step_one(k, gi, lr=lr, grad_scale=grad_scale,
                                      out_bf16=(self._bf16[k] if emit_bf16
                                                else None))
            if emit_bf16:
                return [self._bf16[k].view(ml_dtypes.bfloat16)
                        for k in idxs]
            return [self.opt.master[k] for k in idxs]

        new_leaves: List = [None] * len(self.opt.master)

        def upload(idxs, outs):
            with trace_span("offload/upload_bucket", leaves=len(idxs)):
                for k, o in zip(idxs, outs):
                    if upload_dtype is not None:
                        o = o.astype(upload_dtype)
                    new_leaves[k] = jax.device_put(o, shardings[k])

        if not hasattr(self, "_pool"):
            self._pool = ThreadPoolExecutor(max_workers=1,
                                            thread_name_prefix="offload-opt")
        if fetch_fn is None:
            def fetch_fn(k):
                # deliberate D2H — the grad leg of the offload wire
                return host_transfer(grad_dev_leaves[k])
        prev: Optional[tuple] = None
        for idxs in buckets:
            with trace_span("offload/fetch_bucket", leaves=len(idxs)):
                ghosts = [fetch_fn(k) for k in idxs]
            fut = self._pool.submit(sweep, idxs, ghosts)
            if prev is not None:
                # upload bucket i-1 on the main thread WHILE the worker
                # sweeps bucket i — result() only joins the already-queued
                # i-1 sweep, keeping all three lanes busy
                p_idxs, p_fut = prev
                upload(p_idxs, p_fut.result())
            prev = (idxs, fut)
        p_idxs, p_fut = prev
        upload(p_idxs, p_fut.result())
        return new_leaves

    def reset_from_params(self, params_tree) -> None:
        """Re-derive masters from a (restored) device param tree and zero
        the moments — the module-only / no-optimizer-states load path."""
        leaves = jax.tree_util.tree_leaves(jax.device_get(params_tree))
        sd = self.opt.state_arrays()
        fresh = {name: ([np.asarray(l, dtype=np.float32) for l in leaves]
                        if name == "master"
                        else [np.zeros_like(a) for a in arrs])
                 for name, arrs in sd.items()}
        self.opt.load_state_arrays(fresh, step_count=0)

    # -- checkpoint --------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"arrays": self.opt.state_arrays(),
                "step_count": self.opt.step_count}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.opt.load_state_arrays(sd["arrays"], int(sd["step_count"]))


def validate_offload_config(cfg) -> str:
    """Classify the offload config → ``"none" | "optimizer" | "infinity"``;
    raises on configs the framework cannot honor (silent no-ops are bugs).

    ``optimizer`` — host-DRAM optimizer state, params stay in HBM (this
    module). ``infinity`` — parameter streaming + host/NVMe optimizer
    state (`runtime/zero/infinity.py`)."""
    z = cfg.zero_config
    oo, op = z.offload_optimizer, z.offload_param
    from ...runtime.config import OffloadDeviceEnum as E
    bits = int(getattr(z, "offload_wire_bits", 0) or 0)
    if bits not in (0, 1, 4, 8):
        # one copy of the range check, ahead of BOTH classifications
        raise ValueError(
            f"zero_optimization.offload_wire_bits must be 0, 1, 4 or 8; "
            f"got {bits}")
    if bits and (oo is None or oo.device == E.none) and \
            (op is None or op.device == E.none):
        raise ValueError(
            "zero_optimization.offload_wire_bits compresses the OFFLOAD "
            "grad wire, but no offload is configured — set "
            "offload_optimizer: {device: cpu} (tier 1) or offload_param "
            "(Infinity), or drop offload_wire_bits (a silently ignored "
            "knob is a bug)")
    if op is not None and op.device != E.none:
        # param offload → the ZeRO-Infinity streamed path; its own
        # validator enforces the rest (bf16, dense, adam, 1-chip)
        if jax.process_count() > 1:
            raise NotImplementedError(
                "ZeRO-Infinity is single-host; multi-host param offload "
                "is not built")
        return "infinity"
    if oo is None or oo.device == E.none:
        return "none"
    if oo.device == E.nvme:
        raise NotImplementedError(
            "offload_optimizer device=nvme without offload_param is not a "
            "built configuration — the NVMe optimizer tier rides the "
            "ZeRO-Infinity path (add offload_param: {device: cpu}) or use "
            "device=cpu")
    if jax.process_count() > 1:
        raise NotImplementedError(
            "optimizer offload is single-controller-per-host only for now: "
            "on a multi-host mesh every process would gather full masters "
            "(device_get of non-addressable shards fails) — disable offload "
            "or run single-host")
    return "optimizer"
