"""Gradient-wire codec for the ZeRO-Infinity device->host stream.

Role-equivalent of the reference's 1-bit error-feedback compression
(`/root/reference/deepspeed/runtime/comm/nccl.py:52-204`
``compressed_allreduce``) applied to a different wire: the reference
compresses the *network* collective for 1-bit Adam; here the scarce link
is the *D2H offload wire* that carries every streamed layer gradient to
the host Adam sweep (`runtime/zero/infinity.py`).

Design departure, stated for the record: the reference's scheme keeps a
persistent per-tensor error-feedback buffer on the worker. On the
beyond-HBM engine that buffer would live in device HBM and cost
2-4 bytes/param across ALL layers — i.e. as much memory as holding the
entire sharded model resident, which is exactly what ZeRO-Infinity exists
to avoid. Instead this codec uses **grouped stochastic rounding**:
per-chunk max-abs scales plus randomized rounding make the quantizer
unbiased (E[decode(encode(g))] = g) with NO persistent state, so the bias
that error feedback exists to repair never arises; the variance averages
out across gradient accumulation and Adam's moment EMAs. (The network-
collective 1-bit path with true error feedback remains available in
`runtime/comm/compressed.py` where the error buffer is dp-sharded and
cheap.)

Wire formats (per layer vector of n elements, chunk = ``CHUNK``):
  8-bit: int8 values + f32 scale per chunk          -> n bytes   (2x vs bf16)
  4-bit: two values per byte + f32 scale per chunk  -> n/2 bytes (4x)
  1-bit: sign bits packed 8/byte + f32 scale        -> n/8 bytes (16x)

Encode runs jitted on device (output sharded like the flat grad vector so
each chip packs only its shard); decode is vectorized numpy on the host,
accumulating straight into the fp32 sweep buffer.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: quantization group size — one f32 scale per CHUNK elements; 2048 keeps
#: the scale overhead at 0.2% of the 8-bit wire and aligns with the 8/dp
#: divisibility the packed formats need
CHUNK = 2048


def wire_bytes(n: int, bits: int) -> int:
    """Wire volume of one encoded vector (payload + scales)."""
    n_chunks = (n + CHUNK - 1) // CHUNK
    payload = {8: n, 4: n // 2, 1: n // 8}[bits]
    return payload + 4 * n_chunks


# ---------------------------------------------------------------------------
# device-side encode (jit-compiled by the caller)
# ---------------------------------------------------------------------------
def _chunk_scales(flat: jnp.ndarray, levels: float) -> jnp.ndarray:
    """Per-chunk max-abs / levels; 0-chunks get scale 1 (payload is 0).
    A chunk containing NaN/Inf gets a NaN scale ON PURPOSE: the decode
    then poisons the masters exactly like the uncompressed path would —
    quantizing a diverged gradient into finite garbage would hide the
    divergence (advisor r5)."""
    chunks = flat.reshape(-1, CHUNK)
    amax = jnp.max(jnp.abs(chunks), axis=1)
    s = jnp.where(amax > 0, amax / levels, 1.0)
    return jnp.where(jnp.isfinite(amax), s, jnp.nan)


def encode(flat: jnp.ndarray, bits: int, key: jnp.ndarray
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """bf16/f32 [n] (n % CHUNK == 0) -> (payload uint8, scales f32).

    Stochastic rounding: q = floor(g/s + u), u ~ U[0,1), so E[q·s] = g.
    """
    n = flat.shape[0]
    if n % CHUNK:
        raise ValueError(f"wire codec needs n % {CHUNK} == 0, got {n}")
    x = flat.astype(jnp.float32)
    if bits == 1:
        # unbiased sign: q in {-s, +s} with P(+s) = (g + s) / (2s),
        # s = per-chunk max|g| — E[q] = g exactly, |g| <= s by construction.
        # All-zero chunks return s = 0 (the sign payload is never zero, so
        # the scale must carry the zero).
        amax = jnp.max(jnp.abs(x.reshape(-1, CHUNK)), axis=1)
        s = amax
        xs = x.reshape(-1, CHUNK) / jnp.where(amax > 0, amax, 1.0)[:, None]
        p_up = (xs + 1.0) * 0.5
        u = jax.random.uniform(key, xs.shape)
        bit = (u < p_up).astype(jnp.uint8)                # 1 -> +s, 0 -> -s
        weights = (2 ** jnp.arange(8, dtype=jnp.uint8))[None, :]
        packed = jnp.sum(bit.reshape(-1, 8) * weights, axis=1,
                         dtype=jnp.uint8)
        return packed, s
    levels = {8: 127.0, 4: 7.0}[bits]
    s = _chunk_scales(x, levels)
    xs = x.reshape(-1, CHUNK) / s[:, None]                # in [-levels, levels]
    u = jax.random.uniform(key, xs.shape)
    q = jnp.clip(jnp.floor(xs + u), -levels, levels).astype(jnp.int8)
    if bits == 8:
        return jax.lax.bitcast_convert_type(q.reshape(-1), jnp.uint8), s
    # 4-bit: offset to [0, 14], two nibbles per byte
    q4 = (q + 7).astype(jnp.uint8).reshape(-1, 2)
    return (q4[:, 0] | (q4[:, 1] << 4)), s


# ---------------------------------------------------------------------------
# H2D parameter wire (the opposite direction): host encodes, device decodes.
#
# Parameters are VALUES, not averaged quantities — stochastic rounding's
# unbiasedness buys nothing (no accumulation to wash the variance out) and
# would make consecutive forwards of unchanged weights disagree. So the
# param wire uses deterministic round-to-nearest; the f32 masters on the
# host remain exact and the quantization error is re-derived fresh from the
# masters every upload (it never compounds step over step).
# ---------------------------------------------------------------------------
def encode_params_host(flat: np.ndarray, bits: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """bf16/f32 host vector [n] (n % CHUNK == 0) -> (payload uint8,
    scales f32). 8-bit: n bytes; 4-bit: n/2 bytes (two nibbles/byte,
    offset-7 code like the grad wire so decode is shared shape-wise)."""
    n = flat.shape[0]
    if n % CHUNK:
        raise ValueError(f"param wire needs n % {CHUNK} == 0, got {n}")
    levels = {8: 127.0, 4: 7.0}[bits]
    # host-side cast of an already-host slot view (never a device array —
    # device transfers route through runtime/utils.py host_transfer);
    # copy=False keeps an f32 input zero-copy like np.asarray did
    x = flat.astype(np.float32, copy=False).reshape(-1, CHUNK)
    amax = np.max(np.abs(x), axis=1)
    s = np.where(amax > 0, amax / levels, 1.0).astype(np.float32)
    # NaN/Inf chunks keep a NaN scale so a poisoned master poisons the
    # device copy too instead of quantizing divergence into finite noise
    s = np.where(np.isfinite(amax), s, np.nan).astype(np.float32)
    with np.errstate(invalid="ignore"):   # NaN chunks: payload is garbage,
        q = np.clip(np.rint(x / s[:, None]),  # the NaN scale carries the poison
                    -levels, levels).astype(np.int8)
    if bits == 8:
        return q.reshape(-1).view(np.uint8), s
    q4 = (q.reshape(-1, 2) + 7).astype(np.uint8)
    return (q4[:, 0] | (q4[:, 1] << 4)), s


def decode_params(payload: jnp.ndarray, scales: jnp.ndarray, bits: int,
                  out_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Device-side (jit-traceable) decode: (payload uint8, scales f32) ->
    flat [n] in ``out_dtype``. Called INSIDE each layer's compiled
    program so XLA fuses the dequant into the first consumers — the
    bf16 flat never round-trips HBM as a separate pass."""
    if bits == 8:
        vals = jax.lax.bitcast_convert_type(
            payload, jnp.int8).astype(jnp.float32)
    elif bits == 4:
        lo = (payload & 0x0F).astype(jnp.int32) - 7
        hi = (payload >> 4).astype(jnp.int32) - 7
        vals = jnp.stack([lo, hi], axis=-1).reshape(-1).astype(jnp.float32)
    else:
        raise ValueError(f"param wire bits={bits}")
    vals = vals.reshape(-1, CHUNK) * scales[:, None]
    return vals.reshape(-1).astype(out_dtype)


# ---------------------------------------------------------------------------
# host-side decode (numpy; the worker thread's side of the wire)
# ---------------------------------------------------------------------------
def decode_into(out: np.ndarray, payload: np.ndarray, scales: np.ndarray,
                bits: int, accumulate: bool = False) -> None:
    """payload/scales (host numpy) -> fp32 [n]; ``accumulate`` adds into
    ``out`` (the collect-mode fp32 grad row) instead of overwriting."""
    n = out.shape[0]
    if bits == 1:
        bit = np.unpackbits(payload, bitorder="little")[:n]
        vals = (bit.astype(np.float32) * 2.0 - 1.0)
    elif bits == 8:
        vals = payload.view(np.int8).astype(np.float32)
    elif bits == 4:
        lo = (payload & 0x0F).astype(np.int16) - 7
        hi = (payload >> 4).astype(np.int16) - 7
        vals = np.empty(n, np.float32)
        vals[0::2] = lo
        vals[1::2] = hi
    else:
        raise ValueError(f"bits={bits}")
    vals = vals.reshape(-1, CHUNK) * scales[:, None].astype(np.float32)
    if accumulate:
        out += vals.reshape(-1)
    else:
        out[:] = vals.reshape(-1)
