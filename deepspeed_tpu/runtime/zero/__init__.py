from .sharding import ZeroShardingPolicy, shard_over_axis, constrain, to_named  # noqa: F401
