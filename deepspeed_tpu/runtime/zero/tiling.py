"""TiledLinear: split one big linear into tiles.

Role-equivalent of the reference ``TiledLinear`` (`/root/reference/
deepspeed/runtime/zero/tiling.py:27`), which splits a huge nn.Linear into
in/out tile grids so ZeRO-3 gathers one tile at a time. Functional form:
params are a [rows, cols] grid of kernel tiles; applying scans over column
tiles (a natural remat/gather boundary), accumulating partial products —
the peak live weight memory is one tile row instead of the full matrix.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ...models import layers as L


class TiledLinear:
    def __init__(self, in_features: int, out_features: int,
                 in_splits: int = 1, out_splits: int = 1,
                 use_bias: bool = True):
        if in_features % in_splits or out_features % out_splits:
            raise ValueError(
                f"splits must divide features: {in_features}/{in_splits}, "
                f"{out_features}/{out_splits}")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.use_bias = use_bias
        self.tile_in = in_features // in_splits
        self.tile_out = out_features // out_splits

    def init(self, rng, dtype=jnp.float32) -> Dict:
        keys = jax.random.split(rng, self.in_splits)
        # [in_splits, out_splits, tile_in, tile_out] stacked tile grid
        kernel = jnp.stack([
            jnp.stack([L.normal_init(k2, (self.tile_in, self.tile_out),
                                     0.02, dtype)
                       for k2 in jax.random.split(k, self.out_splits)])
            for k in keys])
        p = {"kernel": kernel}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.out_features,), dtype)
        return p

    def apply(self, params, x):
        """x [..., in] → [..., out]; scan over input tiles so only one tile
        row of weights is live per step (the ZeRO-3 gather unit)."""
        xt = x.reshape(*x.shape[:-1], self.in_splits, self.tile_in)
        xt = jnp.moveaxis(xt, -2, 0)          # [in_splits, ..., tile_in]

        def step(acc, inp):
            xs, kt = inp                      # kt [out_splits, ti, to]
            part = jnp.einsum("...i,oit->...ot", xs,
                              kt.astype(xs.dtype))
            return acc + part.reshape(*xs.shape[:-1], self.out_features), None

        zero = jnp.zeros((*x.shape[:-1], self.out_features), x.dtype)
        out, _ = jax.lax.scan(step, zero, (xt, params["kernel"]))
        if self.use_bias:
            out = out + params["bias"].astype(out.dtype)
        return out
