"""ZeRO as sharding policy.

The reference implements ZeRO with ~5k LoC of imperative partitioning,
bucketing, and hook machinery (`/root/reference/deepspeed/runtime/zero/
stage_1_and_2.py:102` DeepSpeedZeroOptimizer, `stage3.py:65`
DeepSpeedZeroOptimizer_Stage3, `partition_parameters.py:539` zero.Init,
`partitioned_param_coordinator.py:44` prefetcher). On TPU under GSPMD the
same dataflow is a *declaration*: we transform the model's tensor-parallel
PartitionSpecs into specs for gradients, optimizer state, and (stage 3)
parameters over the ``data`` mesh axis, and XLA emits the reduce-scatters,
all-gathers, and their overlap schedule that the reference hand-codes:

  stage 0 — grads psum over data (classic DP; engine.py:1890 allreduce_gradients)
  stage 1 — optimizer state + fp32 master params sharded over data;
            XLA: grads all-reduced, update computed on the local shard,
            updated params all-gathered (reference stage_1_and_2.py step :1750)
  stage 2 — + gradient specs sharded over data → XLA reduce-scatters grads
            instead of all-reducing (reference average_tensor :942 IPG path)
  stage 3 — + parameter specs sharded over data → just-in-time all-gather
            per scan block, scheduled by the XLA latency-hiding scheduler
            (reference fetch_sub_module / prefetch machinery)

The "partitioning" itself: for each leaf we shard the largest dimension not
already claimed by another mesh axis and divisible by the data-axis size;
leaves with no such dimension stay replicated (the analogue of the reference's
``param_persistence_threshold`` keeping small params resident).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.topology import DATA_AXIS, DCN_DATA_AXIS


def _spec_entries(spec: Optional[P], ndim: int) -> list:
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    return entries


def _used_axes(entries) -> set:
    used = set()
    for e in entries:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            used.update(e)
        else:
            used.add(e)
    return used


def shard_over_axis(spec: Optional[P], shape: Tuple[int, ...], mesh: Mesh,
                    axis: Union[str, Sequence[str]] = DATA_AXIS,
                    exclude_dims: Sequence[int] = (),
                    min_size: int = 0) -> P:
    """Add `axis` (one mesh axis name, or a sequence sharded jointly —
    the multi-axis data-parallel product, e.g. ``(dcn_data, data)``) to
    the largest free dim of `shape` divisible by the combined axis size;
    no-op if every requested axis is already used or size 1, or no dim
    qualifies (→ replicated, the small-param persistence case)."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    entries = _spec_entries(spec, len(shape))
    # an axis already claimed by `spec` (or trivial in this mesh) drops
    # out of the joint product rather than vetoing the whole shard
    axes = tuple(a for a in axes
                 if mesh.shape.get(a, 1) > 1 and a not in _used_axes(entries))
    axis_size = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
    if axis_size <= 1:
        return P(*entries)
    if int(np.prod(shape)) < min_size:
        return P(*entries)
    best, best_size = None, 0
    for d, (e, s) in enumerate(zip(entries, shape)):
        if d in exclude_dims:
            continue
        # dim may already carry other axes; require divisibility by the
        # combined factor so GSPMD tiles evenly.
        existing = 1
        if e is not None:
            names = e if isinstance(e, (tuple, list)) else (e,)
            for n in names:
                existing *= mesh.shape.get(n, 1)
        if s % (existing * axis_size) != 0:
            continue
        if s >= best_size:
            best, best_size = d, s
    if best is None:
        return P(*entries)
    e = entries[best]
    if e is None:
        entries[best] = axes if len(axes) > 1 else axes[0]
    else:
        names = tuple(e) if isinstance(e, (tuple, list)) else (e,)
        entries[best] = names + axes
    return P(*entries)


def grad_reduce_plan(region_specs, grad_specs, data_axes: Sequence[str]):
    """Per-leaf plan for reducing gradients over the data-parallel axis
    product INSIDE a manual shard_map region (the 3D pipeline engine).

    ``region_specs`` — the region's param entry specs (pipe/model view);
    ``grad_specs`` — the ZeRO policy's grad spec tree (data axes added
    for stage >= 2); ``data_axes`` — the size>1 data-parallel axes the
    region is manual over, in mesh order.

    Returns ``(plan_tree, out_spec_tree)``: plan leaves are ints
    (``collectives.REDUCE_PSUM`` = all-reduce over the product, ``d >=
    0`` = reduce-scatter along dim ``d`` — the dim the policy sharded
    over the data product, so the gradient leaves the region already in
    its ZeRO-2 layout); out specs are the region specs with the data
    axes inserted at the scatter dim.  Int leaves (not tuples) so the
    plan tree zips leaf-for-leaf against the grads tree."""
    from ...parallel.collectives import REDUCE_PSUM
    dset = set(data_axes)

    def one(rsp, gsp):
        ndim = max(len(list(gsp)) if gsp is not None else 0,
                   len(list(rsp)) if rsp is not None else 0)
        gentries = _spec_entries(gsp, ndim)
        out = _spec_entries(rsp, ndim)
        for d, e in enumerate(gentries):
            names = (tuple(e) if isinstance(e, (tuple, list))
                     else ((e,) if e is not None else ()))
            if dset & set(names):
                base = out[d]
                if base is None:
                    out[d] = (tuple(data_axes) if len(data_axes) > 1
                              else data_axes[0])
                else:
                    bnames = (tuple(base) if isinstance(base, (tuple, list))
                              else (base,))
                    out[d] = bnames + tuple(data_axes)
                return d, P(*out)
        return REDUCE_PSUM, P(*out)

    pairs = jax.tree_util.tree_map(
        one, region_specs, grad_specs,
        is_leaf=lambda x: isinstance(x, P) or x is None)
    plan = jax.tree_util.tree_map(
        lambda pr: pr[0], pairs,
        is_leaf=lambda x: isinstance(x, tuple) and len(x) == 2
        and isinstance(x[1], P))
    out_specs = jax.tree_util.tree_map(
        lambda pr: pr[1], pairs,
        is_leaf=lambda x: isinstance(x, tuple) and len(x) == 2
        and isinstance(x[1], P))
    return plan, out_specs


class ZeroShardingPolicy:
    """Derives all spec trees for a ZeRO stage.

    ``scan_dims`` maps a params-subtree prefix to the dim index that is a
    lax.scan layer axis (excluded from stage-3 param sharding so each scan
    step gathers only its own layer block, not the whole stack).
    """

    def __init__(self, stage: int, mesh: Mesh,
                 param_specs: Any, param_shapes: Any,
                 scan_axis_paths: Sequence[str] = ("blocks",),
                 min_partition_size: int = 0,
                 param_persistence_threshold: int = 0):
        if not 0 <= stage <= 3:
            raise ValueError(f"ZeRO stage must be 0..3, got {stage}")
        self.stage = stage
        self.mesh = mesh
        self.param_specs = param_specs
        self.param_shapes = param_shapes
        self.scan_axis_paths = tuple(scan_axis_paths)
        # stage-3: params below param_persistence_threshold elements stay
        # resident (replicated) instead of sharded+gathered per use — the
        # reference's persisted-param set (zero/config.py). Folding it into
        # min_partition_size applies it to every stage-3 spec tree (live
        # params, masters, grads, moments), which is the whole point of
        # persistence: small tensors aren't worth the collective.
        if stage >= 3:
            min_partition_size = max(min_partition_size,
                                     param_persistence_threshold)
        self.min_partition_size = min_partition_size
        self.param_persistence_threshold = param_persistence_threshold

    # -- helpers -----------------------------------------------------------
    def _is_scan_path(self, path) -> bool:
        return bool(path) and getattr(path[0], "key", None) in self.scan_axis_paths

    def _sharded_tree(self, exclude_scan_dim: bool):
        def f(path, spec, shp):
            shape = tuple(getattr(shp, "shape", shp))
            excl = (0,) if (exclude_scan_dim and self._is_scan_path(path)) else ()
            # partition over the FULL data-parallel product — on a
            # multi-slice mesh `data` alone is only the intra-slice
            # replicas, and stopping there leaves a dcn_data-factor of
            # the memory saving on the table (specs come from the mesh,
            # never from jax.device_count())
            return shard_over_axis(spec, shape, self.mesh,
                                   (DCN_DATA_AXIS, DATA_AXIS),
                                   exclude_dims=excl,
                                   min_size=self.min_partition_size)
        return jax.tree_util.tree_map_with_path(
            f, self.param_specs, self.param_shapes,
            is_leaf=lambda x: isinstance(x, P) or x is None)

    # -- public spec trees -------------------------------------------------
    def model_param_specs(self):
        """Specs for the live (compute-dtype) parameters."""
        if self.stage >= 3:
            return self._sharded_tree(exclude_scan_dim=True)
        return self.param_specs

    def master_param_specs(self):
        """fp32 master copies live with the optimizer state."""
        if self.stage >= 1:
            return self._sharded_tree(exclude_scan_dim=True)
        return self.param_specs

    def grad_specs(self):
        if self.stage >= 2:
            return self._sharded_tree(exclude_scan_dim=True)
        return self.param_specs

    def opt_state_specs(self, opt_state_shapes):
        """Map every params-shaped subtree inside the optimizer state to
        sharded specs; scalar leaves (step counters) replicate.

        Recurses to ANY depth so wrapped optax states match too — e.g.
        ScaleByAdamState.mu/nu nested inside a chain tuple (the reference
        shards whatever tensors the optimizer holds, stage_1_and_2.py:638
        initialize_optimizer_states)."""
        moment_specs = (self._sharded_tree(exclude_scan_dim=True)
                        if self.stage >= 1 else self.param_specs)
        params_treedef = jax.tree_util.tree_structure(self.param_shapes)
        param_leaf_shapes = [
            tuple(getattr(x, "shape", ())) for x in
            jax.tree_util.tree_leaves(self.param_shapes)]
        found = [False]

        def matches(subtree) -> bool:
            try:
                if jax.tree_util.tree_structure(subtree) != params_treedef:
                    return False
                return [tuple(getattr(x, "shape", ())) for x in
                        jax.tree_util.tree_leaves(subtree)] == \
                    param_leaf_shapes
            except Exception:
                return False

        def replicate(leaf):
            return P(*([None] * len(getattr(leaf, "shape", ()))))

        # is_leaf=matches stops descent exactly at params-shaped subtrees;
        # everything else (including registered pytree nodes — dataclass
        # optimizer states etc.) is traversed by tree_map itself.
        def map_node(node):
            if matches(node):
                found[0] = True
                return moment_specs
            return replicate(node)

        specs = jax.tree_util.tree_map(map_node, opt_state_shapes,
                                       is_leaf=matches)
        has_tensor_state = any(
            len(getattr(l, "shape", ())) > 0
            for l in jax.tree_util.tree_leaves(opt_state_shapes))
        if self.stage >= 1 and not found[0] and has_tensor_state:
            from ...utils.logging import logger
            logger.warning(
                "ZeRO stage %d: no params-shaped subtree found in the "
                "optimizer state — optimizer state will be fully replicated "
                "(no memory saving). Check the optimizer's state layout.",
                self.stage)
        return specs


def to_named(mesh: Mesh, spec_tree):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        spec_tree, is_leaf=lambda x: isinstance(x, P))


def _bound_axis_names():
    """Mesh axes currently bound as MANUAL by an enclosing shard_map
    trace (empty outside one, or when the internal API is absent)."""
    try:
        from jax._src.core import get_axis_env
        return set(getattr(get_axis_env(), "axis_sizes", {}) or {})
    except Exception:
        return set()


def constrain(tree, mesh: Mesh, spec_tree):
    """with_sharding_constraint over a tree (inside jit).

    Inside a fully-manual ``shard_map`` region (the legacy-jax
    degradation of ``parallel/shard_map_compat.py``) a constraint
    naming a manual axis is rejected at lowering; the constraint is a
    layout HINT, so specs touching a manual axis are dropped there
    rather than failing the compile.
    """
    manual = _bound_axis_names()

    def one(x, s):
        if manual:
            named = {a for part in s if part is not None
                     for a in ((part,) if isinstance(part, str) else part)}
            if named & manual:
                return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s))
    return jax.tree_util.tree_map(one, tree, spec_tree)
