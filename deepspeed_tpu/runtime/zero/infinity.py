"""ZeRO-Infinity: train past HBM by streaming layer parameters.

Role-equivalent of the reference's ZeRO-Infinity data path —
`/root/reference/deepspeed/runtime/zero/stage3.py:480`
(_configure_tensor_swapping), `runtime/swap_tensor/partitioned_param_swapper
.py:35` (async param swap with inflight tracking) and
`pipelined_optimizer_swapper.py:55` (double-buffered optimizer state) —
redesigned for the XLA compilation model:

  The reference hooks every ``nn.Module`` pre/post-forward to fetch and
  release partitioned parameters. Here the model's OWN structure is the
  swap schedule: the transformer is a stack of identical scanned layers, so
  the training step becomes a Python-driven pipeline over THREE compiled
  programs (embed, block, head-loss) plus their VJPs. The layer loop
  streams each layer's flattened bf16 parameter vector host→device one step
  ahead of compute (double buffering via JAX async dispatch), and the
  backward walk streams bf16 gradients device→host where the native
  CPU-Adam sweep (`ops/csrc/cpu_adam.cpp` ds_adam_step_g16) folds them into
  fp32 masters held in a DRAM or NVMe ``SlotStore`` — overlapped with the
  next layer's backward on device.

Device HBM therefore holds: the resident params (embeddings, final norm,
head — fp32 masters, optimizer-stepped on host), TWO layer-parameter
buffers and one layer's VJP residuals (both independent of depth), plus
the activation stash — one [B,T,D] tensor PER layer, i.e. linear in depth
(it is the parameter/optimizer memory that goes beyond-HBM, not
activations; shrink the stash with remat or micro-batching). Host tiers:

  offload_param.device:      cpu (DRAM byte store) | nvme (file + aio)
  offload_optimizer.device:  cpu | nvme  (master|m|v slots, SlotOptimizer)

Step modes (all overlap the host work with device compute via a pool of
per-layer-ordered workers, one per host core up to 8):
  pure stream   — gas==1, no clipping: each layer's Adam update runs inside
                  the backward (no host grad accumulator at all).
  streamed gas  — gas>1, no clipping: microbatches 0..gas-2 accumulate into
                  a host fp32 store; during the LAST microbatch each
                  layer's update fires as soon as its accumulation
                  completes — the sweep still hides inside the backward.
  clip-gated    — clipping on (any gas): accumulate + record each layer's
                  exact accumulated ||g||² as it completes; the global norm
                  is ready the moment the last layer's grad lands, then the
                  sweep runs parallel across the worker pool (the update
                  must see the true norm — reference runtime/utils.py:325
                  clip_grad_norm_ — so it cannot fire earlier without
                  changing the math).

Multi-chip composition (ZeRO-3 x Infinity): on a data-parallel mesh the
flat layer vector is padded to a multiple of the dp width and sharded
``P(data)`` — each chip's HBM holds 1/D of the two layer buffers, XLA
all-gathers the vector at use inside ``block_fwd`` and reduce-scatters
``dflat`` back to shards (the GSPMD re-expression of the reference's
rank-partitioned swap, `runtime/zero/stage3.py:480`
_configure_tensor_swapping + `partitioned_param_swapper.py:35` per-rank
partition IO). Host slot stores are sized to the PROCESS-LOCAL span of the
shard axis, so on a multi-host pod each host streams only its ranks'
partitions over PCIe/NVMe while the gather rides ICI. Batches shard over
the same axis; the host Adam sweep is untouched (it just sees a shorter
vector per process).

Restrictions (all raised loudly): data-parallel-only meshes (model/pipe/
sequence/expert axes must be 1 under offload), bf16 compute (no fp16
loss scaling), dense blocks (no MoE), Adam/AdamW.
"""
from __future__ import annotations

import math
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from ...observability import trace_span
from ...utils.logging import logger
from ..resilience import get_fault_injector, policy_from_config, retry_call
from ..utils import host_transfer
from . import wire_codec


def _savez_retry(path: str, policy=None, **arrays) -> None:
    """One slot .npz write through the shared retry policy + the
    ``infinity.slot_write`` fault-injection site. A partial write that
    failed is simply overwritten by the retry (np.savez truncates)."""
    def _write():
        get_fault_injector().check("infinity.slot_write", path=path)
        np.savez(path, **arrays)
    retry_call(_write, policy=policy,
               what=f"infinity slot write {os.path.basename(path)}")


def _load_npz_retry(path: str, policy=None):
    """Open a slot .npz through the retry policy + the
    ``infinity.slot_read`` site. Retries cover the open; a truncated
    archive surfaces at member read and is the integrity layer's job
    (checkpoint manifest), not the retry layer's."""
    def _open():
        get_fault_injector().check("infinity.slot_read", path=path)
        return np.load(path)
    return retry_call(_open, policy=policy,
                      what=f"infinity slot read {os.path.basename(path)}")


def _flatten_info(tpl):
    """Leaves (by tree order), their shapes/sizes, offsets and total n."""
    leaves, treedef = jax.tree_util.tree_flatten(tpl)
    shapes = [tuple(l.shape) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    offsets = np.cumsum([0] + sizes).tolist()
    return treedef, shapes, sizes, offsets, int(offsets[-1])


class InfinityStepper:
    """Layer-streamed train step with host/NVMe parameter + optimizer
    state. Owned by ``DeepSpeedEngine`` when ``offload_param`` is active."""

    def __init__(self, engine, rng):
        self.engine = engine
        model = engine.model
        cfg = engine._config
        self._validate(engine, model, cfg)
        self.model = model
        c = model.config
        self.L = c.scan_length
        self.gas = engine.gradient_accumulation_steps
        self.clip = float(cfg.gradient_clipping or 0.0)
        zc = cfg.zero_config
        op, oo = zc.offload_param, zc.offload_optimizer

        # -- optimizer hyperparams from config -----------------------------
        oc = cfg.optimizer
        name = (oc.type if oc is not None else "adamw").lower()
        params = dict(oc.params) if oc is not None else {}
        self.lr_default = params.pop("lr", 1e-3)
        betas = tuple(params.pop("betas", (0.9, 0.999)))
        eps = params.pop("eps", 1e-8)
        wd = params.pop("weight_decay", 0.0)
        adamw = params.pop("adam_w_mode", name != "adam")

        # -- layout --------------------------------------------------------
        layer_tpl = jax.eval_shape(model.init_superblock,
                                   jax.random.PRNGKey(0))
        (self._treedef, self._shapes, self._sizes, self._offsets,
         self.n_elems) = _flatten_info(layer_tpl)
        self.resident_tpl = jax.eval_shape(model.init_resident,
                                           jax.random.PRNGKey(0))
        self.total_params = (self.L * self.n_elems + sum(
            int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(self.resident_tpl)))

        # -- shardings (multi-chip: dp-sharded layer vector) ---------------
        from ...parallel import topology as topo
        mesh = engine.mesh
        self.dp = topo.dp_world_size(mesh)
        # flat layer vector padded so it splits evenly into dp shards;
        # both the vector and the batch ride the data-like axes. With wire
        # compression each dp shard must also be a whole number of
        # quantization chunks so every chip encodes its shard locally.
        self.wire_bits = int(getattr(zc, "offload_wire_bits", 0) or 0)
        if self.wire_bits not in (0, 1, 4, 8):
            raise ValueError(
                f"zero_optimization.offload_wire_bits must be 0, 1, 4 or 8; "
                f"got {self.wire_bits}")
        # H2D param wire (offload_param_bits): quantized uploads + a
        # quantized device cache; see runtime/config.py for the contract
        self.param_bits = int(getattr(zc, "offload_param_bits", 0) or 0)
        if self.param_bits not in (0, 4, 8):
            raise ValueError(
                f"zero_optimization.offload_param_bits must be 0, 4 or 8; "
                f"got {self.param_bits}")
        quantum = self.dp * (wire_codec.CHUNK
                             if (self.wire_bits or self.param_bits) else 1)
        self.n_pad = -(-self.n_elems // quantum) * quantum
        # device layer-cache budget: how many streamed layers may stay
        # resident at once (2 = the minimal double-buffer; more turns the
        # backward's re-uploads into cache hits when HBM allows). The
        # config knob is in params-at-bf16; a quantized cache holds more
        # layers in the same bytes, so account in bytes.
        cache_bytes_pp = {0: 2.0, 8: 1.0, 4: 0.5}[self.param_bits]
        budget_bytes = int(zc.max_live_parameters) * 2
        per_layer_bytes = max(self.n_elems, 1) * cache_bytes_pp
        self.max_live_layers = int(np.clip(
            int(budget_bytes / per_layer_bytes), 2, self.L))
        self._flat_shard = topo.batch_sharding(mesh)
        self._batch_shard = topo.batch_sharding(mesh)
        self._repl = topo.replicated(mesh)
        # process-local span of the shard axis (multi-host: each host's
        # stores cover only its ranks' partitions)
        imap = self._flat_shard.devices_indices_map((self.n_pad,))
        spans = sorted(
            (0 if idx[0].start is None else int(idx[0].start),
             self.n_pad if idx[0].stop is None else int(idx[0].stop))
            for dev, idx in imap.items()
            if dev.process_index == jax.process_index())
        self._lo, self._hi = spans[0][0], spans[-1][1]
        uniq = sorted(set(spans))
        if any(a[1] != b[0] for a, b in zip(uniq, uniq[1:])):
            raise NotImplementedError(
                "ZeRO-Infinity needs this process's dp shards contiguous in "
                f"the flat vector; got spans {spans}")
        self.n_local = self._hi - self._lo

        # -- host stores ---------------------------------------------------
        from ..swap_tensor.slot_store import make_slot_store
        from ..swap_tensor.partitioned_optimizer_swapper import SlotOptimizer
        aio_cfg = cfg.aio
        shared_aio = None
        if "nvme" in (op.device.value, oo.device.value):
            from ...ops.aio import AsyncIOHandle
            shared_aio = AsyncIOHandle(
                block_size=aio_cfg.block_size,
                num_threads=aio_cfg.thread_count)
        self.param_store = make_slot_store(
            op.device.value, self.L, self.n_local * 2,
            nvme_path=op.nvme_path, aio=shared_aio,
            buffer_count=max(4, op.buffer_count), name="params")
        # upload pins are held by the STREAMING thread until each async H2D
        # transfer completes — give the store a way to reclaim them when
        # its ring runs dry (otherwise that thread would block waiting on
        # its own release path). Gated to the streaming thread: the
        # optimizer worker must NOT run the sweep (it would race
        # _pending_uploads and invert the store-lock/upload order) — it
        # falls through to the store's cond.wait until the streaming
        # thread sweeps.
        self._stream_thread = threading.current_thread()

        def _reclaim():
            if threading.current_thread() is self._stream_thread:
                self._sweep_uploads(block=True)
        self.param_store.reclaim = _reclaim
        # shared transient-I/O retry policy for the slot streams
        # (runtime/resilience; the host/NVMe tiers are the I/O surface a
        # multi-day run actually hits)
        self._io_policy = policy_from_config(
            getattr(cfg, "resilience", None))
        self._skip_nonfinite = bool(
            getattr(cfg, "resilience", None) is not None
            and cfg.resilience.skip_nonfinite_grad_steps)
        self.param_store.io_policy = self._io_policy
        self.opt = SlotOptimizer(
            self.L, self.n_local, device=oo.device.value,
            nvme_path=oo.nvme_path, aio=shared_aio,
            buffer_count=max(3, oo.buffer_count), lr=self.lr_default,
            betas=betas, eps=eps, weight_decay=wd, adamw_mode=adamw,
            name="optimizer")
        self.opt.store.io_policy = self._io_policy
        self._aio = shared_aio

        # collect-mode gradient accumulator, allocated lazily (fp32 [L, n])
        self._grad_accum: Optional[np.ndarray] = None

        # H2D quantized-upload encode offload: the numpy quantize pass
        # (encode_params_host) used to run inline in _ensure_layer ON the
        # streaming thread, stalling the H2D lane (and every program
        # dispatch behind it) for the duration of each layer's encode.
        # Now: (a) encoded payloads are CACHED while a layer's masters
        # are unchanged (the whole backward walk and any eval re-upload
        # re-use the forward's encode — the sweep invalidates per
        # layer), and (b) upcoming layers are encoded AHEAD on the
        # layer-pinned worker pool so the stream thread uploads a ready
        # payload. Both are gated to DRAM param stores: an NVMe store's
        # pinned ring must not be acquired from a worker while the
        # stream thread blocks on that worker's result (ring reclaim is
        # stream-thread-gated — classic lock-order deadlock), and a
        # full-model encode cache in DRAM would defeat NVMe offload.
        self._enc_lock = threading.Lock()
        self._enc_cache: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        self._enc_version = [0] * self.L
        self._enc_futures: Dict[int, Future] = {}
        self._enc_async = bool(self.param_bits) and \
            op.device.value == "cpu"

        # -- init ----------------------------------------------------------
        self._init_state(rng)

        # Resident tier (embeddings + norms + head) keeps masters AND Adam
        # moments on DEVICE: the resident tree is small relative to blocks
        # but its gradients are model-width x vocab — streaming them
        # device→host every step would put megabytes-per-step on the slow
        # D2H wire for no memory win. ~16 bytes/param of HBM buys zero
        # per-step resident transfers. The update is the engine's own
        # configured Optimizer (runtime/optimizers.py adam — one source of
        # the Adam math alongside the native host sweep).
        self._res_treedef = jax.tree_util.tree_structure(self.resident)
        self._res_optim = engine.optimizer
        with self.engine.mesh:
            # dstpu: ignore[TRACE003] -- one compile at init, not per step
            self.res_state = jax.jit(self._res_optim.init)(self.resident)

        # -- compiled programs (built lazily per batch-key signature) ------
        self._programs: Dict = {}
        # wire-compression RNG: one base key, folded with a monotone
        # sequence number per encoded layer-grad (deterministic, no
        # device-side RNG state to checkpoint)
        self._wire_base = jax.random.PRNGKey(0x1bad)
        self._wire_seq = 0
        # slot -> tuple of device arrays: (bf16 flat,) uncompressed, or
        # (payload, scales) under the quantized param wire
        self._dev: Dict[int, Tuple[jax.Array, ...]] = {}
        # (slot|None, device arrays, host refs kept alive for the DMA)
        self._pending_uploads: List[Tuple] = []
        # Host optimizer parallelism: one single-thread executor per worker,
        # layer i dispatched to worker i % N — per-layer ordering (accum of
        # microbatch j before j+1) is preserved while distinct layers sweep
        # on distinct cores (the native Adam + numpy accum release the GIL).
        nw = int(getattr(oo, "worker_count", 0) or 0)
        if nw <= 0:
            nw = min(os.cpu_count() or 1, 8)
        if "nvme" in (op.device.value, oo.device.value):
            # each concurrent sweep task pins one param-ring AND one
            # opt-ring buffer; bound concurrency below the smaller ring so
            # two tasks can never exhaust both rings against each other
            nw = min(nw, 2)
        self._workers = [ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"infinity-opt{k}")
            for k in range(nw)]
        try:
            from ...ops.adam.cpu_adam import _lib as adam_lib
            self._native = adam_lib()    # probed once; None → numpy paths
        except Exception:
            self._native = None
        host_gb = (self.param_store.host_bytes + self.opt.host_bytes) / 2**30
        disk_gb = (self.param_store.disk_bytes + self.opt.disk_bytes) / 2**30
        logger.info(
            f"ZeRO-Infinity: {self.total_params / 1e9:.2f}B params, "
            f"{self.L} layers x {self.n_elems / 1e6:.1f}M elems, dp="
            f"{self.dp} (local span {self.n_local / 1e6:.1f}M); host "
            f"{host_gb:.1f} GiB, nvme {disk_gb:.1f} GiB "
            f"(params={op.device.value}, optimizer={oo.device.value}); "
            f"device layer cache {self.max_live_layers}/{self.L} layers "
            f"(~{self.max_live_layers * self.n_pad * cache_bytes_pp / self.dp / 2**30:.2f}"
            f" GiB/chip — zero_optimization.max_live_parameters bounds it)"
            + (f"; D2H wire {self.wire_bits}-bit stochastic-rounded"
               if self.wire_bits else "")
            + (f"; H2D param wire {self.param_bits}-bit RTN"
               if self.param_bits else ""))

    # ------------------------------------------------------------------
    @staticmethod
    def _validate(engine, model, cfg) -> None:
        if getattr(getattr(model, "config", None), "attention_layers", ()):
            raise NotImplementedError(
                "ZeRO-Infinity streams layers through a layer-index-free "
                "block_fwd, which cannot carry the per-layer attention "
                "windows of attention_layers (GPT-Neo family); train this "
                "model with the in-HBM engine, or drop attention_layers")
        for attr in ("init_superblock", "init_resident", "_superblock"):
            if not hasattr(model, attr):
                raise NotImplementedError(
                    "ZeRO-Infinity needs a scan-layer model exposing "
                    "init_superblock/init_resident (TransformerLM does); "
                    f"got {type(model).__name__}")
        from ...parallel import topology as topo
        mesh = engine.mesh
        for axis in (topo.MODEL_AXIS, topo.PIPE_AXIS, topo.SEQUENCE_AXIS):
            if mesh.shape.get(axis, 1) > 1:
                raise NotImplementedError(
                    f"ZeRO-Infinity composes with data-like sharding "
                    f"only; mesh axis '{axis}' has size "
                    f"{mesh.shape[axis]} — use a data/expert mesh under "
                    f"offload_param, or drop offload for tp/pp/sp")
        if mesh.shape.get(topo.EXPERT_AXIS, 1) > 1 and \
                not getattr(model.config, "moe_enabled", False):
            raise NotImplementedError(
                "expert mesh axis under offload needs an MoE model (the "
                "expert axis is data-like only for MoE's all_to_all)")
        if engine.fp16_enabled:
            raise NotImplementedError(
                "ZeRO-Infinity requires bf16 (fp16 loss scaling is not "
                "wired into the streamed step); set bf16.enabled")
        # MoE composes: expert params stream inside the superblock's flat
        # vector like dense params (the reference trains MoE under
        # ZeRO-Offload the same way); only the expert-parallel MESH axis
        # is rejected above (dp-only composition).
        oc = cfg.optimizer
        name = (oc.type if oc is not None else "adamw").lower()
        if name not in ("adam", "adamw", "fusedadam", "cpuadam",
                        "deepspeedcpuadam"):
            raise NotImplementedError(
                f"ZeRO-Infinity host sweep supports Adam/AdamW, got {name}")
        zc = cfg.zero_config
        if zc.offload_optimizer is None or \
                zc.offload_optimizer.device.value == "none":
            raise ValueError(
                "offload_param without offload_optimizer would keep full "
                "optimizer state in HBM, defeating the point — set "
                "offload_optimizer: {device: cpu|nvme}")

    # ------------------------------------------------------------------
    # init
    # ------------------------------------------------------------------
    def _init_state(self, rng) -> None:
        """Materialize one layer at a time on device, spill to the stores.
        Layer i here is bit-identical to row i of ``model.init`` (the vmap
        over ``superblock_keys`` — parity tested).

        With ``infinity_host_init`` the layer slots are drawn host-side
        instead (same shapes/scales, different RNG) — skips the per-layer
        device→host fetch, which dominates startup on slow D2H links."""
        model = self.model
        with self.engine.mesh:
            # dstpu: ignore[TRACE003] -- one compile at init, not per step
            self.resident = jax.jit(model.init_resident,
                                    out_shardings=self._repl)(rng)
        if self.engine._config.zero_config.infinity_host_init:
            nrng = np.random.default_rng(
                int(jax.random.randint(rng, (), 0, 2**31 - 1)))
            flat = np.empty(self.n_elems, np.float32)
            stds = self._host_init_stds()
            for i in range(self.L):
                for off, size, std in zip(self._offsets, self._sizes, stds):
                    span = flat[off:off + size]
                    if std > 0.0:
                        span[:] = nrng.standard_normal(
                            size, dtype=np.float32) * std
                    else:          # biases 0 (norm scales fixed up below)
                        span[:] = 0.0
                self._set_norm_scales_one(self._unflatten_host(flat))
                self._init_slot_from_full(i, flat)
        else:
            with self.engine.mesh:
                def one_layer(k):
                    leaves = jax.tree_util.tree_leaves(
                        model.init_superblock(k))
                    flat = jnp.concatenate(
                        [l.reshape(-1).astype(jnp.float32) for l in leaves])
                    return flat

                init_fn = jax.jit(one_layer)
                keys = model.superblock_keys(rng)
                for i in range(self.L):
                    # every process computes the (identical) full vector,
                    # stores only its local span
                    self._init_slot_from_full(i, np.asarray(init_fn(keys[i])))
        self.param_store.flush()
        self.opt.flush()

    def _local_f32(self, flat_full: np.ndarray) -> np.ndarray:
        """This process's span of the padded flat vector (pad tail zeros)."""
        out = np.zeros(self.n_local, np.float32)
        hi = min(self._hi, self.n_elems)
        if hi > self._lo:
            out[:hi - self._lo] = flat_full[self._lo:hi]
        return out

    def _init_slot_from_full(self, i: int, flat_full: np.ndarray) -> None:
        loc = self._local_f32(flat_full)
        self.opt.init_slot(i, loc)
        buf = self.param_store.acquire(i)
        buf[:self.n_local * 2].view(np.uint16)[:] = (
            loc.astype(ml_dtypes.bfloat16).view(np.uint16))
        self.param_store.release(i, dirty=True)
        self._invalidate_encoded(i)

    def _host_init_stds(self) -> List[float]:
        """Per-leaf init stddev matching model init (models/transformer.py
        _block_init): 0.02 for kernels, 0.02/sqrt(2*num_layers) for the
        residual-branch projections (scaled_init), 0 for 1-d leaves."""
        layer_tpl = jax.eval_shape(self.model.init_superblock,
                                   jax.random.PRNGKey(0))
        nl = self.model.config.num_layers

        def std_for(path, leaf):
            keys = tuple(str(getattr(p, "key", "")) for p in path)
            if len(leaf.shape) < 2:
                return 0.0
            if keys[-2:] in (("out", "kernel"), ("fc_out", "kernel")):
                return 0.02 / math.sqrt(2.0 * nl)
            return 0.02
        tree = jax.tree_util.tree_map_with_path(std_for, layer_tpl)
        return jax.tree_util.tree_leaves(tree)

    def _unflatten_host(self, flat: np.ndarray):
        """Host-side views of a flat slot, shaped as the layer tree."""
        leaves = [flat[o:o + s].reshape(sh)
                  for o, s, sh in zip(self._offsets, self._sizes,
                                      self._shapes)]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def _set_norm_scales_one(self, layer_tree) -> None:
        """Host init: norm 'scale' leaves → 1.0 (views mutate the slot)."""
        def visit(path, leaf):
            keys = [getattr(p, "key", "") for p in path]
            if any(str(k).startswith("ln") for k in keys) and \
                    "scale" in [str(k) for k in keys]:
                leaf[...] = 1.0
            return leaf
        jax.tree_util.tree_map_with_path(visit, layer_tree)

    # ------------------------------------------------------------------
    # device layer cache
    # ------------------------------------------------------------------
    def _sweep_uploads(self, block: bool = False) -> None:
        """Release param-store pins whose H2D transfer has completed. The
        pin must outlive the transfer: ``device_put`` is async and reads the
        pinned host buffer when the DMA runs — releasing immediately would
        let the NVMe ring recycle the buffer under the transfer."""
        still = []
        for slot, arrs, refs in self._pending_uploads:
            if block:
                for a in arrs:
                    host_transfer(a, block=True)  # join the H2D DMA
            if all(a.is_ready() for a in arrs):
                if slot is not None:
                    self.param_store.release(slot, dirty=False)
            else:
                still.append((slot, arrs, refs))
        self._pending_uploads = still

    def _put_vec(self, host_local: np.ndarray, total: int) -> jax.Array:
        """Upload the process-local span of a P(data)-sharded 1-D vector of
        ``total`` elements (every wire vector's length divides evenly over
        the dp axis by n_pad construction). Single-process: one sharded
        device_put (JAX slices per device). Multi-host: each process
        contributes only its addressable shards."""
        if jax.process_count() == 1:
            return jax.device_put(host_local, self._flat_shard)
        lo0 = self._lo * total // self.n_pad   # local span start, scaled
        shards = []
        imap = self._flat_shard.addressable_devices_indices_map((total,))
        for dev, idx in imap.items():
            sl = idx[0]
            lo = 0 if sl.start is None else int(sl.start)
            hi = total if sl.stop is None else int(sl.stop)
            shards.append(jax.device_put(
                host_local[lo - lo0:hi - lo0], dev))
        return jax.make_array_from_single_device_arrays(
            (total,), self._flat_shard, shards)

    def _put_flat(self, host_bf16_local: np.ndarray) -> jax.Array:
        return self._put_vec(host_bf16_local, self.n_pad)

    def _fetch_flat(self, arr: jax.Array) -> np.ndarray:
        """bf16 device vector → host, process-local span only (the D2H wire
        carries each host's partition, reference partitioned_param_swapper
        per-rank IO). Deliberate sync — this IS the offload wire."""
        if jax.process_count() == 1:
            return host_transfer(arr)
        out = np.empty(self.n_local, ml_dtypes.bfloat16)
        for sh in arr.addressable_shards:
            sl = sh.index[0]
            lo = 0 if sl.start is None else int(sl.start)
            out[lo - self._lo:lo - self._lo + sh.data.shape[0]] = (
                host_transfer(sh.data))
        return out

    def _fetch_span(self, arr: jax.Array) -> np.ndarray:
        """Process-local span of any P(data)-sharded 1-D vector (wire
        payload / scales — lengths proportional to n_pad). Deliberate
        sync — the compressed-wire half of the offload stream."""
        if jax.process_count() == 1:
            return host_transfer(arr)
        shards = sorted(((0 if sh.index[0].start is None
                          else int(sh.index[0].start), sh.data)
                         for sh in arr.addressable_shards))
        return np.concatenate([host_transfer(d) for _, d in shards])

    def _decode_wire(self, wire, out: np.ndarray,
                     accumulate: bool) -> None:
        """Host side of the compressed grad wire: fetch payload + scales
        (process-local spans) and decode into the fp32 vector."""
        payload = self._fetch_span(wire[0])
        scales = self._fetch_span(wire[1])
        wire_codec.decode_into(out, payload, scales, self.wire_bits,
                               accumulate=accumulate)

    def _ensure_layer(self, i: int, keep) -> Tuple[jax.Array, ...]:
        """Device copy of layer i's sharded param vector — (bf16 flat,) or
        (payload, scales) under the quantized param wire — uploading from
        the host store on miss. Eviction honours
        ``zero_optimization.max_live_parameters`` (reference stage3
        max_live_parameters budget): layers stay resident up to the budget
        so the backward walk re-uses the forward's uploads instead of
        re-crossing the H2D wire — oldest-uploaded evicted first (on a
        forward sweep that keeps exactly the layers the backward needs
        first)."""
        if i in self._dev:
            return self._dev[i]
        while len(self._dev) >= self.max_live_layers:
            victim = next((k for k in self._dev if k not in keep), None)
            if victim is None:
                break
            del self._dev[victim]
        self._sweep_uploads()
        if self.param_bits:
            # quantized upload: the encoded payload comes from the cache,
            # an ahead-of-need worker encode, or (NVMe store / cold
            # start) an inline pass; the async DMA reads the ENCODED
            # arrays — no slot pin outlives this call (refs keep the
            # payload alive instead)
            payload, scales = self._encoded_params(i)
            pay_total = {8: self.n_pad, 4: self.n_pad // 2}[self.param_bits]
            arrs = (self._put_vec(payload, pay_total),
                    self._put_vec(scales, self.n_pad // wire_codec.CHUNK))
            self._pending_uploads.append((None, arrs, (payload, scales)))
        else:
            buf = self.param_store.acquire(i)
            host = buf[:self.n_local * 2].view(ml_dtypes.bfloat16)
            arrs = (self._put_flat(host),)
            # pin held until transfer done
            self._pending_uploads.append((i, arrs, ()))
        self._dev[i] = arrs
        return arrs

    # -- H2D encode cache / worker offload (param_bits only) ------------
    def _invalidate_encoded(self, i: int) -> None:
        """Layer i's masters changed (host Adam sweep, checkpoint load,
        init): any cached or in-flight encoded payload is stale."""
        if not self.param_bits:
            return
        with self._enc_lock:
            self._enc_version[i] += 1
            self._enc_cache.pop(i, None)
            self._enc_futures.pop(i, None)

    def _encode_slot(self, i: int, version: int):
        """Worker-pool task: pinned slot -> (payload, scales) encode.
        Runs on layer i's OWN pinned worker, so it serializes after any
        queued sweep of the same layer (whose slot write would have
        bumped ``version`` and made this result dead on arrival)."""
        buf = self.param_store.acquire(i)
        try:
            host = buf[:self.n_local * 2].view(ml_dtypes.bfloat16)
            enc = wire_codec.encode_params_host(host, self.param_bits)
        finally:
            self.param_store.release(i, dirty=False)
        with self._enc_lock:
            if self._enc_version[i] == version:
                self._enc_cache[i] = enc
        return version, enc

    def _prefetch_encode(self, i: int) -> None:
        """Queue layer i's quantize pass ahead of need so the streaming
        thread uploads a ready payload instead of stalling the H2D lane
        on the numpy encode (the forward walk prefetches i+2 while
        uploading i+1 and computing i; the backward mirrors it)."""
        if not self._enc_async or not 0 <= i < self.L or i in self._dev:
            return
        with self._enc_lock:
            if i in self._enc_cache or i in self._enc_futures:
                return
            fut = self._submit(i, self._encode_slot, self._enc_version[i])
            self._enc_futures[i] = fut

    def _encoded_params(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        """Encoded (payload, scales) for layer i: unchanged-master cache
        hit -> in-flight worker prefetch -> inline encode."""
        if self._enc_async:
            with self._enc_lock:
                enc = self._enc_cache.get(i)
                fut = self._enc_futures.pop(i, None)
            if enc is not None:
                return enc
            if fut is not None:
                version, enc = fut.result()
                with self._enc_lock:
                    if self._enc_version[i] == version:
                        return enc
        with self._enc_lock:
            v0 = self._enc_version[i]
        buf = self.param_store.acquire(i)
        try:
            host = buf[:self.n_local * 2].view(ml_dtypes.bfloat16)
            enc = wire_codec.encode_params_host(host, self.param_bits)
        finally:
            self.param_store.release(i, dirty=False)
        if self._enc_async:
            with self._enc_lock:
                if self._enc_version[i] == v0:
                    self._enc_cache[i] = enc
        return enc

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _unflatten(self, flat: jax.Array):
        leaves = [jax.lax.slice(flat, (o,), (o + s,)).reshape(sh)
                  for o, s, sh in zip(self._offsets, self._sizes,
                                      self._shapes)]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def _build_programs(self, has_labels: bool, has_mask: bool):
        key = (has_labels, has_mask)
        if key in self._programs:
            return self._programs[key]
        model, c = self.model, self.model.config
        from ...models import layers as Lx
        norm = (Lx.layernorm_apply if c.norm_type == "layernorm"
                else Lx.rmsnorm_apply)
        eps = c.layernorm_eps

        def cast_res(res):
            return jax.tree_util.tree_map(
                lambda p: p.astype(c.dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, res)

        def embed_fwd(res, ids, tt):
            # Delegate to the model's shared embedding path so the offload
            # forward math matches the in-HBM path exactly — including the
            # token-type add (BERT) and embedding layernorm (BLOOM) that
            # init_resident stores (models/transformer.py _embed_tokens).
            return model._embed_tokens(
                cast_res(res), ids,
                token_type_ids=(tt if c.token_type_vocab else None))

        # MoE: the load-balance aux loss contributes aux_coef * Σ l_aux
        # to the training loss; its gradient rides the SAME per-layer vjp
        # (cotangent aux_coef on the l_aux output) so gating weights
        # train correctly under streaming
        aux_coef = (float(getattr(c, "moe_aux_loss_coef", 0.0))
                    if getattr(c, "moe_enabled", False) else 0.0)

        def flat_fwd(flat, x):
            lp = self._unflatten(flat)
            y, _, laux = model._superblock(lp, x, None, None, None, True)
            return y, jnp.asarray(laux, jnp.float32)

        pb = self.param_bits

        if pb:
            # quantized layer cache: each program takes (payload, scales)
            # and fuses the dequant into the layer compute. block_vjp
            # differentiates w.r.t. the DEQUANTIZED flat — that gradient
            # is what the host sweep applies to the exact f32 masters
            # (straight-through: d(dequant)/d(master) treated as identity,
            # the standard QAT estimator; the quantization error is
            # re-derived from the masters at every upload, never carried).
            def block_fwd(payload, scales, x):
                flat = wire_codec.decode_params(payload, scales, pb)
                return flat_fwd(flat, x)

            def block_vjp(payload, scales, x, dy):
                flat = wire_codec.decode_params(payload, scales, pb)
                (y, laux), vjp = jax.vjp(flat_fwd, flat, x)
                del y, laux
                dflat, dx = vjp((dy, jnp.asarray(aux_coef, jnp.float32)))
                sq = jnp.sum(jnp.square(dflat.astype(jnp.float32)))
                return dflat, dx, sq
        else:
            block_fwd = flat_fwd

        def head_loss(res, xL, ids, labels, mask):
            # mirrors model.loss's label/mask/chunk semantics
            # (models/transformer.py loss) with the resident subtree as
            # the param source
            if not has_labels:
                labels = jnp.concatenate(
                    [ids[:, 1:], jnp.zeros_like(ids[:, :1])], axis=1)
                last = jnp.ones_like(ids, jnp.float32).at[:, -1].set(0.0)
                mask = last if not has_mask else mask * last
            elif not has_mask:
                mask = jnp.ones_like(labels, jnp.float32)
            res = cast_res(res)
            x = norm(res["ln_f"], xL, eps=eps)
            t = labels.shape[1]
            chunk = c.loss_chunk
            if chunk and t > chunk and t % chunk == 0:
                n_chunks = t // chunk

                def to_chunks(a):
                    return a.reshape(a.shape[0], n_chunks, chunk,
                                     *a.shape[2:]).swapaxes(0, 1)

                @jax.checkpoint
                def chunk_nll(xc, yc, mc):
                    logits = model._project(res, xc)
                    lse = jax.scipy.special.logsumexp(logits, axis=-1)
                    tgt = jnp.take_along_axis(logits, yc[..., None],
                                              axis=-1)[..., 0]
                    return jnp.sum((lse - tgt) * mc), jnp.sum(mc)

                def body(carry, xs):
                    s, n = chunk_nll(*xs)
                    return (carry[0] + s, carry[1] + n), None
                (tot, cnt), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32),
                           jnp.zeros((), jnp.float32)),
                    (to_chunks(x), to_chunks(labels),
                     to_chunks(mask.astype(jnp.float32))))
                return tot / jnp.maximum(cnt, 1.0)
            logits = model._project(res, x)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(logits, labels[..., None],
                                      axis=-1)[..., 0]
            nll = (lse - tgt) * mask.astype(jnp.float32)
            return jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0)

        def head_vjp(res, xL, ids, labels, mask):
            loss, grads = jax.value_and_grad(head_loss, argnums=(0, 1))(
                res, xL, ids, labels, mask)
            return loss, grads[0], grads[1]

        if not pb:
            def block_vjp(flat, x, dy):
                (y, laux), vjp = jax.vjp(flat_fwd, flat, x)
                del y, laux
                dflat, dx = vjp((dy, jnp.asarray(aux_coef, jnp.float32)))
                sq = jnp.sum(jnp.square(dflat.astype(jnp.float32)))
                return dflat, dx, sq

        def embed_vjp(res, ids, tt, dx):
            _, vjp = jax.vjp(lambda r: embed_fwd(r, ids, tt), res)
            return vjp(dx)[0]

        def res_combine(a, b):
            summed = jax.tree_util.tree_map(
                lambda x, y: x.astype(jnp.float32) + y.astype(jnp.float32),
                a, b)
            sq = sum(jnp.sum(jnp.square(l))
                     for l in jax.tree_util.tree_leaves(summed))
            return summed, sq

        # out_shardings pin the ZeRO contract: activations ride the batch
        # axis, dflat is reduce-scattered back to dp shards (XLA emits the
        # psum-fused scatter), resident grads and scalars replicate
        with self.engine.mesh:
            progs = dict(
                embed_fwd=jax.jit(embed_fwd,
                                  out_shardings=self._batch_shard),
                block_fwd=jax.jit(block_fwd, out_shardings=(
                    self._batch_shard, self._repl)),
                head_vjp=jax.jit(head_vjp, out_shardings=(
                    self._repl, self._repl, self._batch_shard)),
                block_vjp=jax.jit(block_vjp, out_shardings=(
                    self._flat_shard, self._batch_shard, self._repl)),
                embed_vjp=jax.jit(embed_vjp, out_shardings=self._repl),
                res_combine=jax.jit(res_combine, out_shardings=(
                    self._repl, self._repl)),
                encode_grad=(jax.jit(
                    lambda dflat, k: wire_codec.encode(
                        dflat, self.wire_bits, k),
                    out_shardings=(self._flat_shard, self._flat_shard))
                    if self.wire_bits else None),
                eval_loss=jax.jit(
                    lambda res, xL, ids, labels, mask:
                    head_loss(res, xL, ids, labels, mask),
                    out_shardings=self._repl),
            )
        self._programs[key] = progs
        return progs

    # ------------------------------------------------------------------
    # micro fwd/bwd
    # ------------------------------------------------------------------
    def _prep_batch(self, batch):
        ids = np.asarray(batch["input_ids"])  # dstpu: ignore[SYNC003] -- host batch data
        gas = self.gas
        if ids.ndim == 2:
            b = ids.shape[0]
            if b % gas:
                raise ValueError(f"batch {b} not divisible by gas {gas}")
            ids = ids.reshape(gas, b // gas, *ids.shape[1:])
        if ids.shape[1] % self.dp:
            raise ValueError(
                f"micro-batch {ids.shape[1]} not divisible by the "
                f"data-parallel width {self.dp} (Infinity shards the batch "
                f"over the dp axis)")
        labels = batch.get("labels")
        mask = batch.get("loss_mask")
        tt = batch.get("token_type_ids")

        def reshape_like(a):
            a = np.asarray(a)  # dstpu: ignore[SYNC003] -- host batch data
            return (a.reshape(gas, a.shape[0] // gas, *a.shape[1:])
                    if a.ndim == 2 else a)
        return (ids,
                reshape_like(labels) if labels is not None else None,
                reshape_like(mask) if mask is not None else None,
                reshape_like(tt) if tt is not None else None)

    def _forward_stream(self, progs, ids_dev, tt_dev, stash: bool = True):
        """Streamed forward → (activation stash | None, final hidden,
        Σ moe aux loss)."""
        L = self.L
        x = progs["embed_fwd"](self.resident, ids_dev, tt_dev)
        acts: List[Any] = [None] * L if stash else None
        aux = jnp.zeros((), jnp.float32)
        self._ensure_layer(0, {0})
        self._prefetch_encode(1)
        for i in range(L):
            if i + 1 < L:
                self._ensure_layer(i + 1, {i, i + 1})
            self._prefetch_encode(i + 2)
            if stash:
                acts[i] = x
            x, la = progs["block_fwd"](*self._dev[i], x)
            aux = aux + la
        return acts, x, aux

    def _tt_dev(self, tt, ids):
        """Token-type ids on device. Models without a type vocab get a
        (1,1) dummy (the jitted program drops the unused arg); models with
        one default to all-zero types, matching ``_embed_tokens``."""
        if not self.model.config.token_type_vocab:
            return jnp.zeros((1, 1), jnp.int32)
        if tt is None:
            tt = np.zeros_like(np.asarray(ids))  # dstpu: ignore[SYNC003] -- host batch data
        # dstpu: ignore[SYNC003] -- host batch data, upload is async
        return jax.device_put(np.asarray(tt), self._batch_shard)

    def _micro_fwd_bwd(self, progs, ids, labels, mask, tt,
                       on_layer_grad: Callable[[int, Any], None]):
        """One microbatch forward+backward, streaming layer grads into
        ``on_layer_grad``. Returns (loss, resident_grad_tree_dev,
        res_sq_dev, total_sq_dev); total_sq's block-grad terms are
        PRE-quantization when the wire codec is active (the decoded norm
        is recomputed host-side in that case)."""
        zero_i = jnp.zeros((1, 1), jnp.int32)
        # dstpu: ignore[SYNC003] -- host batch data, uploads are async
        ids_dev = jax.device_put(np.asarray(ids), self._batch_shard)
        # dstpu: ignore[SYNC003] -- host batch data
        labels_dev = (jax.device_put(np.asarray(labels), self._batch_shard)
                      if labels is not None else zero_i)
        # dstpu: ignore[SYNC003] -- host batch data
        mask_dev = (jax.device_put(np.asarray(mask, np.float32),
                                   self._batch_shard)
                    if mask is not None
                    else jnp.zeros((1, 1), jnp.float32))
        tt_dev = self._tt_dev(tt, ids)
        acts, xL, aux = self._forward_stream(progs, ids_dev, tt_dev)
        loss, d_res_head, dy = progs["head_vjp"](
            self.resident, xL, ids_dev, labels_dev, mask_dev)
        if getattr(self.model.config, "moe_enabled", False):
            loss = loss + self.model.config.moe_aux_loss_coef * aux
        sqs = []
        for i in reversed(range(self.L)):
            if i - 1 >= 0:
                self._ensure_layer(i - 1, {i, i - 1})
            self._prefetch_encode(i - 2)
            dflat, dy, sq = progs["block_vjp"](*self._dev[i], acts[i], dy)
            acts[i] = None
            if self.wire_bits:
                # quantize on device; only the packed payload + per-chunk
                # scales cross the D2H wire (wire_codec: unbiased
                # stochastic rounding, no persistent error state)
                self._wire_seq += 1
                wire = progs["encode_grad"](
                    dflat, jax.random.fold_in(self._wire_base,
                                              self._wire_seq))
            else:
                wire = dflat
            for part in (wire if isinstance(wire, tuple) else (wire,)):
                try:
                    part.copy_to_host_async()
                except Exception:
                    pass
            sqs.append(sq)
            on_layer_grad(i, wire)
        d_res_embed = progs["embed_vjp"](self.resident, ids_dev, tt_dev, dy)
        d_res, res_sq = progs["res_combine"](d_res_head, d_res_embed)
        total_sq = res_sq + sum(sqs)
        return loss, d_res, res_sq, total_sq

    # ------------------------------------------------------------------
    # optimizer application
    # ------------------------------------------------------------------
    def _step_layer(self, i: int, wire, lr: float,
                    grad_scale: float) -> None:
        """Worker-thread task: D2H-complete grad → native Adam sweep →
        bf16 emit into the param store slot (stream mode)."""
        with trace_span("infinity/opt_layer", layer=i, mode="stream"):
            if self.wire_bits:
                g32 = np.empty(self.n_local, np.float32)
                self._decode_wire(wire, g32, accumulate=False)
                # the reported grad_norm must describe the grads actually
                # APPLIED — the stochastically-rounded decode, not the
                # pre-quantization device values (advisor r4, low)
                self._layer_sq[i] = float(np.dot(g32, g32))
                g = g32
            else:
                g = self._fetch_flat(wire).view(np.uint16)  # bf16 wire
            self.opt.prefetch(i)
            pbuf = self.param_store.acquire(i)
            out16 = pbuf[:self.n_local * 2].view(np.uint16)
            self.opt.step_slot(i, g, lr=lr,
                               grad_scale=grad_scale, out_bf16=out16)
            self.param_store.release(i, dirty=True)
            self._invalidate_encoded(i)

    def _submit(self, i: int, fn, *args):
        """Dispatch a layer task to its pinned worker (i % N) — preserves
        per-layer ordering, parallelizes across layers."""
        return self._workers[i % len(self._workers)].submit(fn, i, *args)

    def _accum_layer(self, i: int, wire) -> None:
        """Worker-thread task: accumulate the wire grad into the fp32 host
        store (collect mode). ``_grad_accum`` is allocated by the main
        thread before any submission (lazy alloc here would race across
        workers)."""
        if self.wire_bits:
            self._decode_wire(wire, self._grad_accum[i], accumulate=True)
            return
        g = self._fetch_flat(wire).view(np.uint16)
        if self._native is not None:
            from ...ops.adam.cpu_adam import _C_F32, _C_U16, _ptr
            self._native.ds_accum_g16(self.n_local,
                                      _ptr(self._grad_accum[i], _C_F32),
                                      _ptr(np.ascontiguousarray(g), _C_U16))
        else:
            self._grad_accum[i] += g.view(ml_dtypes.bfloat16).astype(
                np.float32)

    def _apply_layer_from_accum(self, i: int, lr: float,
                                grad_scale: float) -> None:
        """Worker-thread task: Adam over the accumulated fp32 grad row →
        bf16 emit into the param store slot; zero the row for next step."""
        with trace_span("infinity/opt_layer", layer=i, mode="accum"):
            self.opt.prefetch(i)
            pbuf = self.param_store.acquire(i)
            out16 = pbuf[:self.n_local * 2].view(np.uint16)
            self.opt.step_slot(i, self._grad_accum[i], lr=lr,
                               grad_scale=grad_scale, out_bf16=out16)
            self.param_store.release(i, dirty=True)
            self._invalidate_encoded(i)
            self._grad_accum[i] = 0.0

    def _finish_layer(self, i: int, dflat, lr: float,
                      apply_scale: Optional[float]) -> None:
        """Worker-thread task for the LAST microbatch of a layer:
        accumulate, record the layer's exact accumulated ||g||², and — when
        no clipping gates the update (``apply_scale`` set) — run the Adam
        sweep for this layer immediately, overlapped with the backward of
        the layers below it (streamed update under gradient accumulation)."""
        self._accum_layer(i, dflat)
        row = self._grad_accum[i]
        self._layer_sq[i] = float(np.dot(row, row))
        if apply_scale is not None:
            self._apply_layer_from_accum(i, lr, apply_scale)

    def _step_resident(self, grads_dev, lr: float,
                       grad_scale: float) -> None:
        """Device-resident optimizer step over the summed resident grad
        tree (the engine's configured Optimizer; grad_scale folds
        microbatch count x clip factor, like the native sweep)."""
        if getattr(self, "_res_apply", None) is None:
            opt = self._res_optim

            def apply(res, st, g, lr_, scale):
                g = jax.tree_util.tree_map(lambda x: x / scale, g)
                return opt.apply(g, st, res, lr_)
            with self.engine.mesh:
                self._res_apply = jax.jit(apply, out_shardings=self._repl)
        self.resident, self.res_state = self._res_apply(
            self.resident, self.res_state, grads_dev,
            jnp.asarray(lr, jnp.float32),
            jnp.asarray(grad_scale, jnp.float32))

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def train_step(self, batch) -> Dict:
        t0 = time.perf_counter()
        self._stream_thread = threading.current_thread()
        engine = self.engine
        ids, labels, mask, tt = self._prep_batch(batch)
        progs = self._build_programs(labels is not None, mask is not None)
        step_i = int(engine.state["step"])
        # one deliberate sync: lr feeds the host Adam sweep's arguments
        lr = float(host_transfer(engine.lr_schedule(jnp.asarray(step_i))))
        gas = self.gas
        # pure stream: grads are final on arrival, no norm gate — the Adam
        # sweep rides inside the backward with no accumulator at all
        pure_stream = (gas == 1 and self.clip == 0.0)
        self.opt.begin_step()

        futures = []
        micro_stats: List[Tuple] = []   # (loss, res_sq, sq) device scalars
        res_acc = None
        self._dev.clear()
        if not pure_stream and self._grad_accum is None:
            self._grad_accum = np.zeros((self.L, self.n_local), np.float32)
        self._layer_sq = np.zeros(self.L, np.float64)
        if getattr(self, "_res_add", None) is None:
            with self.engine.mesh:
                self._res_add = jax.jit(lambda a, b: jax.tree_util.tree_map(
                    jnp.add, a, b), out_shardings=self._repl)
                self._res_sq = jax.jit(lambda t: sum(
                    jnp.sum(jnp.square(l))
                    for l in jax.tree_util.tree_leaves(t)),
                    out_shardings=self._repl)
        for j in range(gas):
            last = (j == gas - 1)
            if pure_stream:
                def on_grad(i, dflat):
                    futures.append(self._submit(
                        i, self._step_layer, dflat, lr, 1.0))
            elif last:
                # streamed finish: clip==0 applies Adam per layer as its
                # accumulated grad completes, overlapped with the ongoing
                # backward; clip>0 only records the exact per-layer ||g||²
                # (the update must wait for the global norm)
                apply_scale = float(gas) if self.clip == 0.0 else None

                def on_grad(i, dflat, s=apply_scale):
                    futures.append(self._submit(
                        i, self._finish_layer, dflat, lr, s))
            else:
                def on_grad(i, dflat):
                    futures.append(self._submit(i, self._accum_layer, dflat))
            loss, d_res, res_sq, sq = self._micro_fwd_bwd(
                progs, ids[j],
                labels[j] if labels is not None else None,
                mask[j] if mask is not None else None,
                tt[j] if tt is not None else None, on_grad)
            # keep the per-microbatch scalars LAZY: float() here would
            # block the stream thread on microbatch j's full backward
            # before it may dispatch j+1 — gas-1 needless pipeline stalls
            # per step (dstpu-lint SYNC002 caught it). Converted after
            # the worker join below, when they are ready for free.
            micro_stats.append((loss, res_sq, sq))
            res_acc = d_res if res_acc is None else self._res_add(res_acc,
                                                                 d_res)
        # Release every upload pin BEFORE blocking on the workers: once
        # this thread parks in result(), nobody else may reclaim them
        # (slot_store.reclaim is gated to the stream thread), and a worker
        # needing a param-ring buffer would starve against our own pins.
        self._sweep_uploads(block=True)
        with trace_span("infinity/worker_join", tasks=len(futures)):
            for f in futures:
                f.result()   # surface worker exceptions, join the sweep
        loss_total = sum(float(host_transfer(ls)) for ls, _, _ in
                         micro_stats)
        res_sq_total = sum(float(host_transfer(rs)) for _, rs, _ in
                           micro_stats)
        sq_total = sum(float(host_transfer(s)) for _, _, s in micro_stats)

        grad_scale = float(gas)
        if pure_stream:
            if self.wire_bits:
                # the applied grads are the stochastically-rounded wire
                # decode: report THEIR norm (recorded per layer by
                # _step_layer), not the pre-quantization device values
                block_sq = float(np.sum(self._layer_sq))
                if jax.process_count() > 1:
                    from jax.experimental import multihost_utils
                    block_sq = float(np.sum(
                        multihost_utils.process_allgather(
                            np.float32(block_sq))))
                gnorm = math.sqrt(res_sq_total + block_sq)
            else:
                # gas==1: Σ per-layer ||g||² IS the exact squared norm
                gnorm = math.sqrt(sq_total)
        else:
            # exact norm of the ACCUMULATED grads (clipping must see the
            # true norm — reference runtime/utils.py:325 clip_grad_norm_);
            # per-layer terms were recorded by _finish_layer as each
            # layer's accumulation completed
            sq = float(host_transfer(self._res_sq(res_acc)))
            block_sq = float(np.sum(self._layer_sq))
            if jax.process_count() > 1:
                # each host holds a disjoint span of the block grads —
                # sum the partial squared norms across processes
                from jax.experimental import multihost_utils
                block_sq = float(np.sum(multihost_utils.process_allgather(
                    np.float32(block_sq))))
            sq += block_sq
            gnorm = math.sqrt(sq) / gas
            if self.clip > 0.0:
                if not np.isfinite(gnorm) and self._skip_nonfinite:
                    # clip-gated mode is the one Infinity mode where the
                    # sweep has NOT run yet when the norm is known — a
                    # poisoned step can still be skipped outright
                    # (resilience.skip_nonfinite_grad_steps)
                    logger.warning(
                        f"non-finite global grad norm ({gnorm}) — skipping "
                        f"the optimizer sweep for this step")
                    self.opt.step_count -= 1   # undo begin_step
                    self._grad_accum[:] = 0.0
                    engine.state["skipped"] = engine.state["skipped"] + 1
                    self._dev.clear()
                    self._sweep_uploads(block=True)
                    self.param_store.flush()
                    self.opt.flush()
                    metrics = {"loss": loss_total / gas, "grad_norm": gnorm,
                               "lr": lr, "overflow": 1, "loss_scale": 1.0,
                               "step_time": time.perf_counter() - t0}
                    self._last_metrics = metrics
                    return metrics
                if np.isfinite(gnorm) and gnorm > self.clip:
                    grad_scale *= gnorm / self.clip
                # clip-gated sweep, parallel across layers/cores
                with trace_span("infinity/clip_sweep", layers=self.L):
                    sweep = [self._submit(i, self._apply_layer_from_accum,
                                          lr, grad_scale)
                             for i in range(self.L)]
                    for f in sweep:
                        f.result()
        self._step_resident(res_acc, lr, grad_scale)
        self._dev.clear()   # device copies are stale after the sweep
        self._sweep_uploads(block=True)
        self.param_store.flush()
        self.opt.flush()

        engine.state["step"] = engine.state["step"] + 1
        metrics = {"loss": loss_total / gas, "grad_norm": gnorm, "lr": lr,
                   "overflow": 0, "loss_scale": 1.0,
                   "step_time": time.perf_counter() - t0}
        self._last_metrics = metrics
        return metrics

    def eval_loss(self, batch) -> float:
        """Eval takes the batch whole (no gas split — eval batches need not
        match the training batch triple), streamed forward without an
        activation stash."""
        ids = np.asarray(batch["input_ids"])  # dstpu: ignore[SYNC003] -- host batch data
        labels = batch.get("labels")
        mask = batch.get("loss_mask")
        progs = self._build_programs(labels is not None, mask is not None)
        self._stream_thread = threading.current_thread()
        self._dev.clear()
        if ids.shape[0] % self.dp:
            raise ValueError(
                f"eval batch {ids.shape[0]} not divisible by dp {self.dp}")
        ids_dev = jax.device_put(ids, self._batch_shard)
        zero_i = jnp.zeros((1, 1), jnp.int32)
        tt_dev = self._tt_dev(batch.get("token_type_ids"), ids)
        _, xL, aux = self._forward_stream(progs, ids_dev, tt_dev,
                                          stash=False)
        out = float(host_transfer(progs["eval_loss"](
            self.resident, xL, ids_dev,
            # dstpu: ignore[SYNC003] -- host batch data
            jax.device_put(np.asarray(labels), self._batch_shard)
            if labels is not None else zero_i,
            # dstpu: ignore[SYNC003] -- host batch data
            jax.device_put(np.asarray(mask, np.float32), self._batch_shard)
            if mask is not None
            else jnp.zeros((1, 1), jnp.float32))))
        if getattr(self.model.config, "moe_enabled", False):
            out += float(self.model.config.moe_aux_loss_coef * aux)
        self._sweep_uploads(block=True)
        return out

    def _require_single_process(self, what: str) -> None:
        if jax.process_count() > 1:
            raise NotImplementedError(
                f"{what} on a multi-host pod needs a cross-process gather "
                f"of the partitioned host slots — run it from a "
                f"single-process restore, or use per-host save dirs")

    def gather_params(self):
        """Full (unstacked→stacked) param tree as host numpy — the
        zero_to_fp32 equivalent for tests/export. Masters (fp32)."""
        self._require_single_process("gather_params")
        blocks_flat = np.stack([self.opt.master(i)[:self.n_elems]
                                for i in range(self.L)])
        leaves = []
        for o, s, sh in zip(self._offsets, self._sizes, self._shapes):
            leaves.append(blocks_flat[:, o:o + s].reshape((self.L,) + sh))
        blocks = jax.tree_util.tree_unflatten(self._treedef, leaves)
        res = jax.device_get(self.resident)
        res = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32), res)
        res["blocks"] = blocks
        return res

    # -- checkpoint --------------------------------------------------------
    def save_to_dir(self, path: str) -> None:
        """Stream the full host state (fp32 masters + moments + resident
        optimizer) to ``path``, one slot at a time — constant memory, any
        model size. Called by the checkpoint engine
        (runtime/checkpoint_engine/engine.py) for infinity-mode saves."""
        import json
        import os
        self._require_single_process("Infinity checkpoint save")
        os.makedirs(path, exist_ok=True)
        for i in range(self.L):
            p, m, v = self.opt.state(i)
            # logical (unpadded) vectors — checkpoints are mesh-independent,
            # a D=1 save restores onto a D=8 mesh and vice versa
            n = self.n_elems
            _savez_retry(os.path.join(path, f"slot_{i:05d}.npz"),
                         self._io_policy, p=p[:n], m=m[:n], v=v[:n])
        res = self._resident_state_host()
        _savez_retry(os.path.join(path, "resident.npz"), self._io_policy,
                     **{f"{k}_{j}": a for k, arrs in res.items()
                        for j, a in enumerate(arrs)})

        def path_str(p):
            return "/".join(str(getattr(x, "key", x)) for x in p)
        # shape-only templates from __init__ — no device transfers here
        layer_tpl = jax.eval_shape(self.model.init_superblock,
                                   jax.random.PRNGKey(0))
        layer_leaves = [
            {"path": path_str(p), "shape": list(l.shape)}
            for p, l in jax.tree_util.tree_flatten_with_path(layer_tpl)[0]]
        res_leaves = [
            {"path": path_str(p), "shape": list(l.shape)}
            for p, l in jax.tree_util.tree_flatten_with_path(
                self.resident_tpl)[0]]
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"L": self.L, "n_elems": self.n_elems,
                       "step_count": self.opt.step_count,
                       "res_step_count": self.res_step_count,
                       "n_res_leaves": len(res["master"]),
                       # leaf layout: lets offline tools (universal
                       # checkpoint export) rebuild the full fp32 tree
                       # from the flat slots without a live engine
                       "layer_leaves": layer_leaves,
                       "res_leaves": res_leaves}, f)

    @property
    def res_step_count(self) -> int:
        return int(self.res_state["step"])

    def _resident_state_host(self) -> Dict[str, List[np.ndarray]]:
        """Device-resident optimizer state → host leaf lists."""
        return {
            "master": [np.asarray(x, np.float32) for x in
                       jax.tree_util.tree_leaves(
                           jax.device_get(self.resident))],
            "m": [np.asarray(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(self.res_state["m"]))],
            "v": [np.asarray(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(self.res_state["v"]))],
        }

    def _load_resident_state(self, res: Dict[str, List[np.ndarray]],
                             step_count: int) -> None:
        def put(leaves):
            return jax.device_put(jax.tree_util.tree_unflatten(
                self._res_treedef,
                [np.asarray(a, np.float32) for a in leaves]), self._repl)
        self.resident = put(res["master"])
        self.res_state = {"step": jnp.asarray(int(step_count), jnp.int32),
                          "m": put(res["m"]), "v": put(res["v"])}

    def load_from_dir(self, path: str, load_optimizer_states: bool = True
                      ) -> None:
        import json
        import os
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta["L"] != self.L or meta["n_elems"] != self.n_elems:
            raise ValueError(
                f"checkpoint layout (L={meta['L']}, n={meta['n_elems']}) "
                f"does not match this model (L={self.L}, n={self.n_elems})")
        zl = np.zeros(self.n_local, np.float32)
        for i in range(self.L):
            with _load_npz_retry(os.path.join(path, f"slot_{i:05d}.npz"),
                                 self._io_policy) as z:
                p = self._local_f32(z["p"])
                m = self._local_f32(z["m"]) if load_optimizer_states else zl
                v = self._local_f32(z["v"]) if load_optimizer_states else zl
                self.opt.load_state(i, p, m, v)
                buf = self.param_store.acquire(i)
                buf[:self.n_local * 2].view(np.uint16)[:] = (
                    p.astype(ml_dtypes.bfloat16).view(np.uint16))
                self.param_store.release(i, dirty=True)
                self._invalidate_encoded(i)
        with _load_npz_retry(os.path.join(path, "resident.npz"),
                             self._io_policy) as z:
            n = meta["n_res_leaves"]
            res = {k: [z[f"{k}_{j}"] for j in range(n)]
                   for k in ("master", "m", "v")}
        if not load_optimizer_states:
            res = {k: (arrs if k == "master"
                       else [np.zeros_like(a) for a in arrs])
                   for k, arrs in res.items()}
        self._load_resident_state(
            res, meta["res_step_count"] if load_optimizer_states else 0)
        self.opt.step_count = (int(meta["step_count"])
                               if load_optimizer_states else 0)
        self.param_store.flush()
        self.opt.flush()

    def state_dict(self) -> Dict:
        self._require_single_process("Infinity state_dict")
        n = self.n_elems
        return {
            "step_count": self.opt.step_count,
            "slots": [tuple(a[:n] for a in self.opt.state(i))
                      for i in range(self.L)],
            "resident": self._resident_state_host(),
            "res_step_count": self.res_step_count,
        }

    def load_state_dict(self, sd: Dict) -> None:
        self.opt.step_count = int(sd["step_count"])
        for i, (p, m, v) in enumerate(sd["slots"]):
            p, m, v = (self._local_f32(np.asarray(a)) for a in (p, m, v))
            self.opt.load_state(i, p, m, v)
            buf = self.param_store.acquire(i)
            buf[:self.n_local * 2].view(np.uint16)[:] = (
                p.astype(ml_dtypes.bfloat16).view(np.uint16))
            self.param_store.release(i, dirty=True)
            self._invalidate_encoded(i)
        self._load_resident_state(sd["resident"], sd["res_step_count"])
        self.param_store.flush()
        self.opt.flush()

    def close(self) -> None:
        for w in self._workers:
            w.shutdown(wait=True)
        self.param_store.close()
        self.opt.close()
        if self._aio is not None:
            self._aio.close()
