"""Progressive layer drop schedule.

Role-equivalent of the reference ``ProgressiveLayerDrop``
(`/root/reference/deepspeed/runtime/progressive_layer_drop.py`): keep-prob
theta(t) = (1 - gamma)·exp(-gamma·t) ... actually the reference uses
theta(t) = theta_min + (1 - theta_min)·exp(-gamma·t) decayed per step; the
model consumes theta as the per-layer survival probability (stochastic
depth). Traceable in the step counter.
"""
from __future__ import annotations

import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta_min = theta
        self.gamma = gamma

    def theta(self, global_step) -> jnp.ndarray:
        """Keep probability at this step (→ theta_min as t→∞)."""
        t = jnp.asarray(global_step, jnp.float32)
        return (1.0 - self.theta_min) * jnp.exp(-self.gamma * t) \
            + self.theta_min

    def get_state(self, global_step) -> dict:
        return {"progressive_layer_drop": True,
                "pld_theta": self.theta(global_step)}
