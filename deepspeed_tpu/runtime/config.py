"""Master configuration.

One JSON/dict config is the spine of the framework, exactly as in the
reference (`/root/reference/deepspeed/runtime/config.py:810`
``_initialize_params``): every subsystem hangs its sub-config off this object.
The schema accepts DeepSpeed-style JSON so existing configs port over, plus
TPU-native blocks (``mesh``, ``sequence_parallel``) that have no reference
equivalent.

Batch-size reconciliation follows the reference's triple rule
(`runtime/config.py:921-980`):
    train_batch_size == micro_batch_per_device * gradient_accumulation_steps
                        * data_parallel_world_size
Given any two, the third is inferred; all three given must agree.
"""
from __future__ import annotations

import json
from enum import Enum
from typing import Any, Dict, Optional, Union

from pydantic import Field, model_validator

from .config_utils import ConfigModel, dict_raise_error_on_duplicate_keys
from . import constants as C


# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------
class FP16Config(ConfigModel):
    """fp16 block — dynamic loss scaling semantics follow the reference
    DynamicLossScaler (`runtime/fp16/loss_scaler.py:77`)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = C.FP16_LOSS_SCALE_DEFAULT  # 0 => dynamic
    initial_scale_power: int = C.FP16_INITIAL_SCALE_POWER_DEFAULT
    loss_scale_window: int = C.FP16_LOSS_SCALE_WINDOW_DEFAULT
    hysteresis: int = C.FP16_HYSTERESIS_DEFAULT
    min_loss_scale: float = C.FP16_MIN_LOSS_SCALE_DEFAULT

    @property
    def dynamic(self) -> bool:
        return self.enabled and self.loss_scale == 0


class BF16Config(ConfigModel):
    """bf16 block. On TPU bf16 is the native matmul dtype; fp32 master params
    are kept like the reference BF16_Optimizer (`runtime/bf16_optimizer.py:38`)."""
    enabled: bool = False
    # Keep a full-precision master copy of params (rarely worth disabling).
    master_weights: bool = True


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------
class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(ConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = int(1e8)
    max_in_cpu: int = int(1e9)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(ConfigModel):
    device: OffloadDeviceEnum = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    # host optimizer-sweep parallelism; 0 = one worker per host core
    # (capped at 8) — the reference's AVX sweep is single-threaded per
    # sub-group but a TPU-VM host has dozens of cores to put behind it
    worker_count: int = 0

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write


class ZeroConfig(ConfigModel):
    """zero_optimization block (reference: `runtime/zero/config.py`).

    TPU interpretation: stages are sharding policies over the ``data`` mesh
    axis, applied as `jax.sharding` annotations rather than runtime hooks.
      stage 0 — pure DP: params/grads/opt-state replicated, grads psum'd.
      stage 1 — optimizer state sharded over data axis.
      stage 2 — + gradients reduce-scattered (psum_scatter) over data axis.
      stage 3 — + parameters sharded (FSDP); XLA inserts just-in-time
                 all-gathers, scheduled per layer block.
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_partitions: bool = True
    allgather_bucket_size: int = int(5e8)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = int(1e9)
    cpu_offload: Optional[bool] = None  # deprecated alias
    cpu_offload_params: Optional[bool] = None  # deprecated alias
    prefetch_bucket_size: int = int(5e7)
    param_persistence_threshold: int = int(1e5)
    model_persistence_threshold: int = int(1e14)  # pydantic int bounds: keep finite
    max_live_parameters: int = int(1e9)
    max_reuse_distance: int = int(1e9)
    gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1
    # TPU-native: how many layer blocks to scan over for stage-3 gather
    # scheduling (0 = let XLA decide; >0 = lax.scan over stacked blocks).
    stage3_scan_layers: int = 0
    # ZeRO-Infinity: initialize layer slots host-side (numpy RNG) instead of
    # materializing each layer on device and fetching it. The values differ
    # from model.init's (different RNG), so use only for from-scratch runs
    # where init distribution, not init bits, matters — it removes a
    # 4-bytes/param device→host fetch at startup, which dominates init time
    # on hosts with slow D2H links.
    infinity_host_init: bool = False
    # ZeRO-Infinity D2H gradient-wire compression: 0 = off (bf16 wire),
    # 8/4/1 = grouped stochastic-rounding quantization to that many bits
    # before the device->host fetch (runtime/zero/wire_codec.py). The role
    # the reference's 1-bit error-feedback compression plays on the
    # network wire (runtime/comm/nccl.py:52), re-derived for a host
    # offload wire where persistent device error state would cost HBM
    # linear in total params: stochastic rounding is unbiased WITHOUT
    # error memory.
    offload_wire_bits: int = 0
    # ZeRO-Infinity H2D parameter-wire compression: 0 = off (bf16 uploads),
    # 8/4 = block-quantized parameter uploads (deterministic round-to-
    # nearest, per-chunk max-abs scales; runtime/zero/wire_codec.py
    # encode_params_host/decode_params). The streamed forward re-uploads
    # every layer each step (the host sweep changed them), so on slow H2D
    # links the upload wire bounds the step exactly like the reference's
    # NVMe read path bounds its stage-3 prefetch
    # (zero/partitioned_param_swapper). 8-bit halves upload bytes vs bf16
    # AND doubles the device layer cache (the cache stores the quantized
    # payload; dequant is fused into each layer's compiled program, an
    # HBM-cheap read at 1 byte/param). The forward/backward compute sees
    # the quantized weights; the f32 masters on the host stay exact.
    offload_param_bits: int = 0

    @model_validator(mode="after")
    def _resolve_deprecated(self):
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(
                device=OffloadDeviceEnum.cpu)
        if self.cpu_offload_params and self.offload_param is None:
            self.offload_param = DeepSpeedZeroOffloadParamConfig(
                device=OffloadDeviceEnum.cpu)
        if not 0 <= self.stage <= 3:
            raise ValueError(f"zero_optimization.stage must be 0..3, got {self.stage}")
        # wire-codec bit widths fail at PARSE time on every engine path
        # (offload_bench's tier-1 path consumes offload_wire_bits without
        # ever building an InfinityStepper, whose own checks these mirror)
        if self.offload_param_bits not in (0, 4, 8):
            raise ValueError(
                f"zero_optimization.offload_param_bits must be 0, 4 or 8; "
                f"got {self.offload_param_bits}")
        if self.offload_wire_bits not in (0, 1, 4, 8):
            raise ValueError(
                f"zero_optimization.offload_wire_bits must be 0, 1, 4 or "
                f"8; got {self.offload_wire_bits}")
        return self


# ---------------------------------------------------------------------------
# Optimizer / scheduler blocks
# ---------------------------------------------------------------------------
class OptimizerConfig(ConfigModel):
    type: str = "AdamW"
    params: Dict[str, Any] = Field(default_factory=dict)
    legacy_fusion: bool = False


class SchedulerConfig(ConfigModel):
    type: str = "WarmupLR"
    params: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Mesh (TPU-native block; replaces reference groups.py / mpu plumbing)
# ---------------------------------------------------------------------------
class MeshConfig(ConfigModel):
    """Named-axis device mesh over ICI/DCN.

    Replaces the reference's process-group topology
    (`deepspeed/utils/groups.py`, `runtime/pipe/topology.py:243`) with a
    declarative `jax.sharding.Mesh` spec. Axis sizes of -1 mean "absorb the
    remaining devices" (at most one axis may be -1; ``data`` defaults to -1).
    Axis order is outermost→innermost placement on the device torus; keep
    ``model``/``sequence`` innermost so their collectives ride ICI.
    """
    data: int = -1
    model: int = 1      # tensor parallel
    pipe: int = 1       # pipeline stages
    expert: int = 1     # MoE expert parallel (folded into data at runtime)
    sequence: int = 1   # context/sequence parallel
    # devices per host axis for multi-slice: "dcn_data" replicas over DCN
    dcn_data: int = 1


class PipelineConfig(ConfigModel):
    """pipeline block (reference: PipelineEngine knobs on the engine config).

    ``stages`` — "auto" (stage count = the mesh's ``pipe`` axis) or an
    explicit int the engine cross-checks against the mesh: a tuned config
    exported for one topology fails loudly on another instead of silently
    training a different 3D shape. ``micro_batches`` is an alias for the
    microbatch count M (reconciled into the batch triple as
    gradient_accumulation_steps — the reference's train_batch =
    micro * M * dp identity)."""
    stages: Union[int, str] = C.PIPE_STAGES_DEFAULT

    @model_validator(mode="after")
    def _check_stages(self):
        s = self.stages
        if isinstance(s, str) and s != "auto":
            if not s.isdigit():
                raise ValueError(
                    f"pipeline.stages must be 'auto' or a positive int, "
                    f"got {s!r}")
            self.stages = int(s)
        if isinstance(self.stages, int) and self.stages < 1:
            raise ValueError(
                f"pipeline.stages must be >= 1, got {self.stages}")
        if self.schedule not in C.PIPE_SCHEDULES:
            raise ValueError(
                f"pipeline.schedule must be one of {C.PIPE_SCHEDULES}, "
                f"got {self.schedule!r}")
        return self
    partition: str = "parameters"  # parameters | uniform | type:regex
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    pipe_partitioned: bool = True
    grad_partitioned: bool = True
    micro_batches: Optional[int] = None
    # compiled-schedule selection: auto = 1F1B for dense models, gpipe for
    # MoE (whose aux-loss plumbing lives in the gpipe loss)
    schedule: str = C.PIPE_SCHEDULE_DEFAULT   # auto | 1f1b | gpipe


class SequenceParallelConfig(ConfigModel):
    """TPU-native capability absent from the reference (SURVEY §5.7)."""
    enabled: bool = False
    mode: str = "ring"  # ring | ulysses
    axis: str = "sequence"


class TensorParallelConfig(ConfigModel):
    enabled: bool = False
    tp_size: int = 1
    # auto-TP: shard any Dense whose name matches these patterns
    autotp_size: int = 0


# ---------------------------------------------------------------------------
# Aux subsystem blocks
# ---------------------------------------------------------------------------
class ActivationCheckpointingConfig(ConfigModel):
    """Maps to jax.checkpoint/remat policies rather than the reference's
    manual activation stash (`runtime/activation_checkpointing/`)."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # remat policy name: none|full|dots_saveable|nothing_saveable|custom
    policy: str = "full"


class AioConfig(ConfigModel):
    """aio block (reference `runtime/swap_tensor/aio_config.py`)."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class FlopsProfilerConfig(ConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


class MonitorConfig(ConfigModel):
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled)


class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    # async checkpointing via a background committer thread
    async_save: bool = False


class CommsConfig(ConfigModel):
    verbose: bool = False
    prof_all: bool = False
    debug: bool = False
    prof_ops: list = Field(default_factory=list)


class ResilienceConfig(ConfigModel):
    """``resilience`` block (runtime/resilience/, docs/resilience.md).

    Governs checkpoint integrity (manifest + atomic commit + last-good
    fallback), the shared I/O retry policy, non-finite-gradient step
    skipping, worker liveness, and deterministic fault injection."""
    # -- checkpoint integrity --
    checkpoint_integrity: bool = C.RESILIENCE_CHECKPOINT_INTEGRITY_DEFAULT
    # re-read and re-fingerprint every artifact right after commit; the
    # paranoid mode that catches a lying write cache at save time
    verify_on_save: bool = C.RESILIENCE_VERIFY_ON_SAVE_DEFAULT
    # on a corrupt/partial tag at load, fall back to the newest tag that
    # still verifies instead of raising
    fallback_to_last_good: bool = C.RESILIENCE_FALLBACK_DEFAULT
    # -- retriable I/O (runtime/resilience/retry.py) --
    io_retry_attempts: int = C.RESILIENCE_IO_RETRY_ATTEMPTS_DEFAULT
    io_retry_base_delay_s: float = C.RESILIENCE_IO_RETRY_BASE_DELAY_DEFAULT
    io_retry_max_delay_s: float = C.RESILIENCE_IO_RETRY_MAX_DELAY_DEFAULT
    io_retry_jitter: float = C.RESILIENCE_IO_RETRY_JITTER_DEFAULT
    # -- training-step hygiene --
    # skip the optimizer update (and count it in state['skipped']) when
    # the global grad norm is non-finite, instead of poisoning opt state
    skip_nonfinite_grad_steps: bool = C.RESILIENCE_SKIP_NONFINITE_DEFAULT
    # -- liveness (elasticity/elastic_agent.py watchdog) --
    heartbeat_interval_s: float = C.RESILIENCE_HEARTBEAT_INTERVAL_DEFAULT
    watchdog_timeout_s: float = C.RESILIENCE_WATCHDOG_TIMEOUT_DEFAULT  # 0=off
    # -- fault injection (runtime/resilience/fault_injection.py) --
    # {"site": {"kind": "fail|fatal|truncate|delay|kill",
    #           "at": 1, "count": 1, "arg": 0}}
    # sites cover checkpoint/slot-store I/O AND the serving stack
    # (serving.allocate / append_block / admission / dispatch — see
    # docs/serving.md "Failure handling & overload")
    fault_injection: Dict[str, Any] = Field(default_factory=dict)

    @model_validator(mode="after")
    def _validate(self):
        if self.io_retry_attempts < 1:
            raise ValueError(
                f"resilience.io_retry_attempts must be >= 1, got "
                f"{self.io_retry_attempts}")
        if self.io_retry_base_delay_s < 0 or \
                self.io_retry_max_delay_s < self.io_retry_base_delay_s:
            raise ValueError(
                "resilience: need 0 <= io_retry_base_delay_s <= "
                f"io_retry_max_delay_s, got {self.io_retry_base_delay_s}/"
                f"{self.io_retry_max_delay_s}")
        if not 0.0 <= self.io_retry_jitter <= 1.0:
            raise ValueError(
                f"resilience.io_retry_jitter must be in [0, 1], got "
                f"{self.io_retry_jitter}")
        if self.watchdog_timeout_s < 0 or self.heartbeat_interval_s <= 0:
            raise ValueError(
                "resilience: watchdog_timeout_s must be >= 0 (0 disables) "
                "and heartbeat_interval_s > 0")
        if self.watchdog_timeout_s and \
                self.watchdog_timeout_s < 2 * self.heartbeat_interval_s:
            raise ValueError(
                f"resilience.watchdog_timeout_s "
                f"({self.watchdog_timeout_s}) must be at least twice "
                f"heartbeat_interval_s ({self.heartbeat_interval_s}) or a "
                f"healthy worker one beat behind gets killed")
        return self


class TracingConfig(ConfigModel):
    """``observability.tracing`` — host-side span tracer
    (deepspeed_tpu/observability/tracer.py). Spans record into a
    preallocated ring buffer and export as Chrome trace-event JSON
    (Perfetto-loadable); device syncs happen only at explicit flush
    boundaries via ``host_transfer()``."""
    enabled: bool = C.OBSERVABILITY_TRACING_ENABLED_DEFAULT
    # ring capacity in spans; oldest spans are overwritten on wraparound
    buffer_size: int = C.OBSERVABILITY_TRACE_BUFFER_DEFAULT
    # directory for per-process trace_rank<r>.json files
    output_dir: str = C.OBSERVABILITY_TRACE_DIR_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.buffer_size < 1:
            raise ValueError(
                f"observability.tracing.buffer_size must be >= 1, got "
                f"{self.buffer_size}")
        return self


class ObsMetricsConfig(ConfigModel):
    """``observability.metrics`` — counter/gauge/histogram registry with
    Prometheus-textfile and JSON exporters
    (deepspeed_tpu/observability/metrics.py). Scalars also flow into the
    MonitorMaster fan-out (TB/CSV/W&B) when a monitor is enabled."""
    enabled: bool = C.OBSERVABILITY_METRICS_ENABLED_DEFAULT
    # node_exporter textfile-collector directory (dstpu_rank<r>.prom)
    prometheus_dir: Optional[str] = C.OBSERVABILITY_PROMETHEUS_DIR_DEFAULT
    # JSON snapshot path
    json_path: Optional[str] = C.OBSERVABILITY_JSON_PATH_DEFAULT
    # export every N steps (0 = only at flush/close/atexit)
    export_interval_steps: int = C.OBSERVABILITY_EXPORT_INTERVAL_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.export_interval_steps < 0:
            raise ValueError(
                f"observability.metrics.export_interval_steps must be "
                f">= 0, got {self.export_interval_steps}")
        return self


class RequestTracingConfig(ConfigModel):
    """``observability.request_tracing`` — per-request serving timelines
    (deepspeed_tpu/observability/request_trace.py). Every request gets a
    trace id at submit; lifecycle sites stamp segments that export as a
    Perfetto waterfall track per request inside the span tracer's
    ``trace_rank<r>.json``. Requires ``tracing.enabled`` (the export
    rides the same flush)."""
    enabled: bool = C.OBSERVABILITY_REQUEST_TRACE_ENABLED_DEFAULT
    # retained request timelines; oldest completed evicted first
    capacity: int = C.OBSERVABILITY_REQUEST_TRACE_CAPACITY_DEFAULT
    # stamped segments per request before drops are counted
    max_segments: int = C.OBSERVABILITY_REQUEST_TRACE_SEGMENTS_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.capacity < 1 or self.max_segments < 1:
            raise ValueError(
                "observability.request_tracing: capacity and max_segments "
                f"must be >= 1, got {self.capacity}/{self.max_segments}")
        return self


class SloConfig(ConfigModel):
    """``observability.slo`` — per-tenant multi-window burn-rate alerting
    over the TTFT / inter-token SLOs declared in ``TenantSpec``
    (deepspeed_tpu/observability/slo.py). An alert fires when the error
    budget (``1 - objective``) burns ``burn_threshold``x faster than
    sustainable in BOTH the fast and slow windows."""
    enabled: bool = C.OBSERVABILITY_SLO_ENABLED_DEFAULT
    objective: float = C.OBSERVABILITY_SLO_OBJECTIVE_DEFAULT
    fast_window_s: float = C.OBSERVABILITY_SLO_FAST_WINDOW_DEFAULT
    slow_window_s: float = C.OBSERVABILITY_SLO_SLOW_WINDOW_DEFAULT
    burn_threshold: float = C.OBSERVABILITY_SLO_BURN_THRESHOLD_DEFAULT
    # firing -> resolved once fast burn < threshold * resolve_fraction
    resolve_fraction: float = C.OBSERVABILITY_SLO_RESOLVE_FRACTION_DEFAULT
    # fast-window observations required before an alert may fire
    min_samples: int = C.OBSERVABILITY_SLO_MIN_SAMPLES_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"observability.slo.objective must be in (0, 1), got "
                f"{self.objective}")
        if self.fast_window_s <= 0 or \
                self.slow_window_s < self.fast_window_s:
            raise ValueError(
                "observability.slo: need 0 < fast_window_s <= "
                f"slow_window_s, got {self.fast_window_s}/"
                f"{self.slow_window_s}")
        if self.burn_threshold <= 0 or self.min_samples < 1:
            raise ValueError(
                "observability.slo: burn_threshold must be > 0 and "
                f"min_samples >= 1, got {self.burn_threshold}/"
                f"{self.min_samples}")
        if not 0.0 <= self.resolve_fraction <= 1.0:
            raise ValueError(
                f"observability.slo.resolve_fraction must be in [0, 1], "
                f"got {self.resolve_fraction}")
        return self


class FlightRecorderConfig(ConfigModel):
    """``observability.flight`` — black-box flight recorder
    (deepspeed_tpu/observability/flight_recorder.py): a bounded ring of
    per-iteration engine snapshots dumped as an atomic, manifest-sealed
    post-mortem bundle on ServingError / watchdog trip / skipped-step
    burst."""
    enabled: bool = C.OBSERVABILITY_FLIGHT_ENABLED_DEFAULT
    capacity: int = C.OBSERVABILITY_FLIGHT_CAPACITY_DEFAULT
    output_dir: str = C.OBSERVABILITY_FLIGHT_DIR_DEFAULT
    max_terminal_events: int = C.OBSERVABILITY_FLIGHT_TERMINALS_DEFAULT
    # consecutive skipped train steps that trip a post-mortem dump
    skip_burst_steps: int = C.OBSERVABILITY_FLIGHT_SKIP_BURST_DEFAULT
    max_bundles: int = C.OBSERVABILITY_FLIGHT_MAX_BUNDLES_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.capacity < 1 or self.max_terminal_events < 1 \
                or self.max_bundles < 1:
            raise ValueError(
                "observability.flight: capacity, max_terminal_events and "
                "max_bundles must be >= 1, got "
                f"{self.capacity}/{self.max_terminal_events}/"
                f"{self.max_bundles}")
        if self.skip_burst_steps < 1:
            raise ValueError(
                f"observability.flight.skip_burst_steps must be >= 1, got "
                f"{self.skip_burst_steps}")
        return self


class OverlapConfig(ConfigModel):
    """``observability.overlap`` — host/device overlap profiler
    (deepspeed_tpu/observability/overlap.py): splits each serving
    iteration / training step into host-plan, dispatch-enqueue and
    device-wait from timestamps the engines already take (no new device
    syncs), exporting overlap gauges+histograms and a per-iteration
    trace track. The acceptance instrument for the async multi-step
    scheduler (ROADMAP item 4)."""
    enabled: bool = C.OBSERVABILITY_OVERLAP_ENABLED_DEFAULT
    # per-iteration records retained for the trace track
    capacity: int = C.OBSERVABILITY_OVERLAP_CAPACITY_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.capacity < 1:
            raise ValueError(
                f"observability.overlap.capacity must be >= 1, got "
                f"{self.capacity}")
        return self


class ObservabilityConfig(ConfigModel):
    """``observability`` block (deepspeed_tpu/observability/,
    docs/observability.md)."""
    tracing: TracingConfig = Field(default_factory=TracingConfig)
    metrics: ObsMetricsConfig = Field(default_factory=ObsMetricsConfig)
    request_tracing: RequestTracingConfig = Field(
        default_factory=RequestTracingConfig)
    slo: SloConfig = Field(default_factory=SloConfig)
    flight: FlightRecorderConfig = Field(
        default_factory=FlightRecorderConfig)
    overlap: OverlapConfig = Field(default_factory=OverlapConfig)

    @model_validator(mode="after")
    def _validate(self):
        if self.request_tracing.enabled and not self.tracing.enabled:
            raise ValueError(
                "observability.request_tracing.enabled requires "
                "observability.tracing.enabled — the per-request "
                "waterfall exports inside the span tracer's Chrome trace")
        return self

    @property
    def enabled(self) -> bool:
        return (self.tracing.enabled or self.metrics.enabled
                or self.request_tracing.enabled or self.slo.enabled
                or self.flight.enabled or self.overlap.enabled)


#: remat policies the model's ``_remat`` accepts (models/transformer.py);
#: kept here so the config rejects a typo'd policy at parse time, before
#: the engine rebuilds the model with it
TRAINING_REMAT_POLICIES = ("none", "full", "dots_saveable",
                           "dots_no_batch", "nothing_saveable",
                           "host_offload")


class TrainingConfig(ConfigModel):
    """``training`` block (docs/training_perf.md).

    Overrides of the model-side hot-path knobs the autotuner searches.
    Every field defaulting to None means "keep the model config's
    setting"; a non-None value makes the ENGINE rebuild the model with
    that knob at initialize time, so a tuned best-config JSON is
    self-contained — no caller-side model surgery needed to apply it."""
    # jax.checkpoint policy applied per transformer block
    remat: Optional[str] = C.TRAINING_REMAT_DEFAULT
    # analytic custom-VJP loss head (ops/transformer/fused_loss.py):
    # backward recomputes chunk logits and forms softmax−onehot in-VJP
    # instead of materializing [B,T,V] logit cotangents
    fused_loss_head: Optional[bool] = C.TRAINING_FUSED_LOSS_HEAD_DEFAULT
    # tokens per loss chunk (model config ``loss_chunk``); 0 = dense
    loss_chunk: Optional[int] = C.TRAINING_LOSS_CHUNK_DEFAULT
    # donate batch buffers into the jitted step alongside engine state
    # (runtime/engine.py _build_train_step). Off by default: bench and
    # autotune loops re-feed the same device batch, which donation
    # would invalidate.
    donate_batch: bool = C.TRAINING_DONATE_BATCH_DEFAULT

    @model_validator(mode="after")
    def _validate(self):
        if self.remat is not None and \
                self.remat not in TRAINING_REMAT_POLICIES:
            raise ValueError(
                f"training.remat must be one of "
                f"{list(TRAINING_REMAT_POLICIES)}, got {self.remat!r}")
        if self.loss_chunk is not None and self.loss_chunk < 0:
            raise ValueError(
                f"training.loss_chunk must be >= 0 (0 = dense), got "
                f"{self.loss_chunk}")
        return self

    def model_overrides(self) -> Dict[str, Any]:
        """The non-None model-config overrides this block carries."""
        out: Dict[str, Any] = {}
        for key in ("remat", "fused_loss_head", "loss_chunk"):
            val = getattr(self, key)
            if val is not None:
                out[key] = val
        return out


# ---------------------------------------------------------------------------
# Master config
# ---------------------------------------------------------------------------
class DeepSpeedConfig:
    """Parses the master dict/JSON; exposes typed sub-configs.

    Mirrors the surface of the reference `DeepSpeedConfig`
    (`runtime/config.py:679`): scalar engine knobs as attributes, each
    subsystem a typed config object.
    """

    def __init__(self, config: Any, world_size: Optional[int] = None):
        if isinstance(config, str):
            with open(config, "r") as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        else:
            raise ValueError(
                f"Expected a dict or a json path, got {type(config)}")
        self._world_size = world_size
        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # -- parsing ----------------------------------------------------------
    def _initialize_params(self, pd: dict) -> None:
        g = pd.get
        self.train_batch_size = g(C.TRAIN_BATCH_SIZE,
                                  C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = g(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = g(
            C.GRADIENT_ACCUMULATION_STEPS,
            C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = g(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = g(C.DUMP_STATE, False)
        self.gradient_clipping = g(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        # legacy DeepSpeed alias: top-level max_grad_norm == gradient_clipping
        # (previously accepted and silently IGNORED — dstpu-lint CFG001)
        mgn = g(C.MAX_GRAD_NORM)
        if mgn is not None:
            if C.GRADIENT_CLIPPING in pd and pd[C.GRADIENT_CLIPPING] != mgn:
                raise ValueError(
                    f"both {C.GRADIENT_CLIPPING} "
                    f"({pd[C.GRADIENT_CLIPPING]}) and its legacy alias "
                    f"{C.MAX_GRAD_NORM} ({mgn}) are set and disagree")
            self.gradient_clipping = mgn
        # amp is apex/CUDA mixed precision; a config that asks for it must
        # not silently train in fp32 (previously ignored — dstpu-lint CFG001)
        amp = g(C.AMP) or {}
        amp_on = (amp.get("enabled", False) if isinstance(amp, dict)
                  else bool(amp))    # "amp": true shorthand
        if amp_on:
            raise NotImplementedError(
                "amp (apex) is CUDA-specific and not supported on TPU — "
                "use bf16: {enabled: true} (native) or fp16 with dynamic "
                "loss scaling instead")
        self.prescale_gradients = g(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = g(C.GRADIENT_PREDIVIDE_FACTOR,
                                           C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = g(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.wall_clock_breakdown = g(C.WALL_CLOCK_BREAKDOWN,
                                      C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.communication_data_type = g(C.COMMUNICATION_DATA_TYPE)
        self.disable_allgather = g(C.DISABLE_ALLGATHER, False)
        self.memory_breakdown = g(C.MEMORY_BREAKDOWN, False)

        self.fp16 = FP16Config(**g(C.FP16, {}))
        self.bf16 = BF16Config(**g(C.BF16, {}))
        self.zero_config = ZeroConfig(**g(C.ZERO_OPTIMIZATION, {}))
        self.optimizer = (OptimizerConfig(**pd[C.OPTIMIZER])
                          if C.OPTIMIZER in pd else None)
        self.scheduler = (SchedulerConfig(**pd[C.SCHEDULER])
                          if C.SCHEDULER in pd else None)
        self.mesh = MeshConfig(**g(C.MESH, {}))
        self.pipeline = PipelineConfig(**g(C.PIPELINE, {}))
        self.sequence_parallel = SequenceParallelConfig(**g(C.SEQUENCE_PARALLEL, {}))
        self.tensor_parallel = TensorParallelConfig(**g(C.TENSOR_PARALLEL, {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **g(C.ACTIVATION_CHECKPOINTING, {}))
        self.aio = AioConfig(**g(C.AIO, {}))
        self.flops_profiler = FlopsProfilerConfig(**g(C.FLOPS_PROFILER, {}))
        self.monitor = MonitorConfig(
            tensorboard=TensorBoardConfig(**g(C.MONITOR_TENSORBOARD, {})),
            wandb=WandbConfig(**g(C.MONITOR_WANDB, {})),
            csv_monitor=CSVConfig(**g(C.MONITOR_CSV, {})),
        )
        self.checkpoint_config = CheckpointConfig(**g(C.CHECKPOINT, {}))
        self.comms_config = CommsConfig(**g(C.COMMS_LOGGER, {}))
        self.resilience = ResilienceConfig(**g(C.RESILIENCE, {}))
        self.observability = ObservabilityConfig(**g(C.OBSERVABILITY, {}))
        self.training = TrainingConfig(**g(C.TRAINING, {}))

        # Late imports to avoid cycles; these blocks are parsed by their
        # subsystems on first use.
        self.elasticity_dict = g(C.ELASTICITY)
        self.autotuning_dict = g(C.AUTOTUNING)
        self.compression_dict = g(C.COMPRESSION_TRAINING)
        self.data_efficiency_dict = g(C.DATA_EFFICIENCY)
        self.curriculum_learning_legacy = g(C.CURRICULUM_LEARNING_LEGACY)

    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    @property
    def precision_dtype(self) -> str:
        if self.bf16.enabled:
            return "bfloat16"
        if self.fp16.enabled:
            return "float16"
        return "float32"

    # -- batch reconciliation (reference config.py:921-980) ---------------
    def _configure_train_batch_size(self) -> None:
        if not hasattr(self, "_user_batch_triple"):
            self._user_batch_triple = (self.train_batch_size,
                                       self.train_micro_batch_size_per_gpu,
                                       self.gradient_accumulation_steps)
        tb, mb, gas = self._user_batch_triple
        ws = self._world_size  # data-parallel world size; may be None pre-mesh

        def _exact_div(num, den, what):
            if num % den != 0:
                raise ValueError(
                    f"train_batch_size ({num}) is not divisible by {what} "
                    f"({den}); the triple train_batch = micro_batch * "
                    f"gradient_accumulation_steps * dp_world must hold exactly")
            return num // den

        if ws is not None:
            if tb is not None and mb is not None and gas is not None:
                if tb != mb * gas * ws:
                    raise ValueError(
                        f"train_batch_size ({tb}) != micro_batch ({mb}) * "
                        f"gradient_accumulation_steps ({gas}) * dp_world ({ws})")
            elif tb is not None and mb is not None:
                gas = _exact_div(tb, mb * ws, "micro_batch * dp_world")
            elif tb is not None and gas is not None:
                mb = _exact_div(tb, gas * ws, "gradient_accumulation_steps * dp_world")
            elif mb is not None and gas is not None:
                tb = mb * gas * ws
            elif tb is not None:
                gas = 1
                mb = _exact_div(tb, ws, "dp_world")
            elif mb is not None:
                gas = 1
                tb = mb * ws
            else:
                raise ValueError(
                    "Need at least train_batch_size or "
                    "train_micro_batch_size_per_gpu in config")
        else:
            if gas is None:
                gas = 1
            if mb is None and tb is not None:
                mb = tb  # resolved later once mesh known
        self.train_batch_size = tb
        self.train_micro_batch_size_per_gpu = mb
        self.gradient_accumulation_steps = gas

    def resolve_batch_sizes(self, dp_world: int) -> None:
        """Re-run the triple reconciliation once the mesh is built."""
        self._world_size = dp_world
        self._configure_train_batch_size()
        self._do_sanity_check()

    def _do_sanity_check(self) -> None:
        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        for v, name in ((self.train_batch_size, C.TRAIN_BATCH_SIZE),
                        (self.train_micro_batch_size_per_gpu,
                         C.TRAIN_MICRO_BATCH_SIZE_PER_GPU),
                        (self.gradient_accumulation_steps,
                         C.GRADIENT_ACCUMULATION_STEPS)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be positive, got {v}")
        z = self.zero_config
        if z.stage < 3 and z.offload_param is not None and \
                z.offload_param.device != OffloadDeviceEnum.none:
            raise ValueError("offload_param requires ZeRO stage 3")

    def print_config(self) -> str:
        return json.dumps(self._param_dict, indent=2, sort_keys=True, default=str)
