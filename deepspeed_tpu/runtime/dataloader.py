"""Data loading.

Role of the reference ``DeepSpeedDataLoader``
(`/root/reference/deepspeed/runtime/dataloader.py:39`), single-controller
style: the reference gives each rank a DistributedSampler slice of the
dataset; here ONE host-side loader assembles the **global** batch
[gas, micro*dp_world, ...] and the engine's `shard_batch` scatters it over
the data axes of the mesh. On multi-host pods each process feeds its
addressable shard (jax.make_array_from_process_local_data path — the
per-process slice is computed from the same global index stream, which is
what DistributedSampler does with rank offsets).

Works with: numpy-array datasets (dict of arrays or (x, y) tuples),
torch-style map datasets (len/__getitem__), and python iterables yielding
dict batches.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


class RepeatingLoader:
    """Reference `runtime/dataloader.py` RepeatingLoader: wrap an iterator to
    restart on StopIteration (pipeline engines need an endless stream)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Global-batch loader with deterministic shuffling + curriculum hook.

    ``batch_size`` is the GLOBAL train batch (micro * gas * dp_world); every
    `__next__` returns one optimizer step's data shaped
    [batch_size, ...] (the engine reshapes to [gas, micro*dp, ...]).
    """

    def __init__(self,
                 dataset: Any,
                 batch_size: int,
                 shuffle: bool = True,
                 seed: int = 0,
                 drop_last: bool = True,
                 collate_fn: Optional[Callable] = None,
                 data_sampler: Optional[Iterator] = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.data_sampler = data_sampler
        self.epoch = 0
        if hasattr(dataset, "__len__"):
            n = len(dataset)
            self.len = n // batch_size if drop_last else -(-n // batch_size)
        else:
            self.len = None

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self):
        if self.len is None:
            raise TypeError("loader over an iterable dataset has no len()")
        return self.len

    def _index_stream(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            it = iter(self.data_sampler)
            if it is self.data_sampler:  # one-shot generator
                if getattr(self, "_sampler_consumed", False):
                    raise ValueError(
                        "data_sampler is a one-shot iterator already "
                        "consumed by a previous epoch; pass a re-iterable "
                        "(e.g. a sampler object with __iter__)")
                self._sampler_consumed = True
            yield from it
            return
        order = np.arange(n)
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(order)
        yield from order

    def __iter__(self):
        if not hasattr(self.dataset, "__getitem__"):
            yield from self.dataset  # iterable of ready-made batches
            return
        idxs = []
        for i in self._index_stream():
            idxs.append(i)
            if len(idxs) == self.batch_size:
                yield self._collate(idxs)
                idxs = []
        if idxs and not self.drop_last:
            yield self._collate(idxs)
        self.epoch += 1

    def _collate(self, idxs):
        items = [self.dataset[int(i)] for i in idxs]
        if self.collate_fn is not None:
            return self.collate_fn(items)
        first = items[0]
        if isinstance(first, dict):
            return {k: np.stack([it[k] for it in items]) for k in first}
        if isinstance(first, (tuple, list)):
            cols = list(zip(*items))
            return tuple(np.stack(c) for c in cols)
        return np.stack(items)


def synthetic_lm_batches(vocab_size: int, seq_len: int, global_batch: int,
                         seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Endless synthetic token stream (benchmarking / tests)."""
    rs = np.random.RandomState(seed)
    while True:
        yield {"input_ids": rs.randint(
            0, vocab_size, (global_batch, seq_len), dtype=np.int32)}
