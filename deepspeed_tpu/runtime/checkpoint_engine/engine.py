"""Checkpoint save/load.

Reference surface: ``DeepSpeedEngine.save_checkpoint``
(`/root/reference/deepspeed/runtime/engine.py:3063`) / ``load_checkpoint``
(`engine.py:2703`) plus the checkpoint-engine abstraction
(`runtime/checkpoint_engine/checkpoint_engine.py:1` — Torch vs Nebula
backends). TPU-native redesign:

  - The reference writes one model file from rank 0 plus per-rank ZeRO shard
    files (`engine.py:3398` _save_zero_checkpoint) and needs an offline
    reshape library to change topology. Here the whole train state is ONE
    sharded pytree saved via orbax/tensorstore (OCDBT): every host writes its
    shards in parallel, and restore reshards to whatever mesh/ZeRO layout the
    loading job uses — the reference's "universal checkpoint"
    (`checkpoint/universal_checkpoint.py:108`) is the default behavior, and
    elastic dp-size change (`tests/unit/checkpoint/test_zero_optimizer.py`)
    needs no special casing.
  - ``async_save`` maps to orbax AsyncCheckpointer (the NebulaCheckpointEngine
    role: commit in background, `nebula_checkpoint_engine.py:15`).
  - The ``latest`` tag file + tag-validation semantics are preserved
    (`engine.py:3045` _checkpoint_tag_validation).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...observability import get_registry, trace_span
from ...utils.logging import logger
from ..resilience import (CheckpointCorruptionError, FatalIOError,
                          atomic_write_json, atomic_write_text,
                          find_newest_verified_tag, fsync_dir,
                          get_fault_injector, has_manifest,
                          policy_from_config, retry_call, verify_manifest,
                          write_manifest)

_ASYNC_CKPTRS: Dict[int, Any] = {}


def _checkpointer(async_save: bool = False):
    import orbax.checkpoint as ocp
    if async_save:
        key = 1
        if key not in _ASYNC_CKPTRS:
            _ASYNC_CKPTRS[key] = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
        return _ASYNC_CKPTRS[key]
    key = 0
    if key not in _ASYNC_CKPTRS:
        _ASYNC_CKPTRS[key] = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    return _ASYNC_CKPTRS[key]


def _tag_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None) -> str:
    """Save engine state under save_dir/tag; update ``latest``."""
    os.makedirs(save_dir, exist_ok=True)
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    path = _tag_path(save_dir, tag)
    async_save = engine._config.checkpoint_config.async_save

    ckptr = _checkpointer(async_save)
    if async_save:
        # Publish any prior save's meta/latest now. AsyncCheckpointer.save
        # itself blocks on the previous commit, so this adds no waiting —
        # and it bounds hard-kill metadata loss to the single in-flight
        # checkpoint rather than every checkpoint since the last load.
        wait_pending()
    get_registry().counter("dstpu_checkpoint_saves_total").inc()
    state = dict(engine.state)
    scaler = state.pop("scaler", None)
    if scaler is not None:
        state["scaler"] = dict(scaler._asdict())
    with trace_span("checkpoint/save_state", tag=str(tag),
                    async_save=async_save):
        ckptr.save(os.path.join(path, "state"), state, force=True)

    if getattr(engine, "_infinity", None) is not None:
        # ZeRO-Infinity: the entire model lives in the host/NVMe stores —
        # streamed slot-by-slot into the tag dir (constant memory)
        with trace_span("checkpoint/infinity_stream", tag=str(tag)):
            engine._infinity.save_to_dir(os.path.join(path, "infinity"))

    if getattr(engine, "_host_opt", None) is not None:
        # ZeRO-Offload host state (masters + moments, numpy) — saved
        # synchronously beside the device tree (reference writes these into
        # the per-rank zero checkpoint files, engine.py:3398)
        import orbax.checkpoint as ocp
        host_sd = engine._host_opt.state_dict()
        # 0-d ndarrays, not numpy scalars: orbax >= 0.7 rejects scalar
        # types (np.int64(x)) in StandardCheckpointHandler trees
        host_tree = {"arrays": host_sd["arrays"],
                     "step_count": np.asarray(host_sd["step_count"],
                                              np.int64)}
        if engine._host_scaler is not None:
            s = engine._host_scaler
            host_tree["scaler"] = {
                "scale": np.asarray(s.scale, np.float64),
                "good_steps": np.asarray(s.good_steps, np.int64),
                "hysteresis": np.asarray(s.hysteresis, np.int64)}
        ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
            os.path.join(path, "host_opt"), host_tree, force=True)

    meta = {
        "tag": tag,
        "global_steps": engine.global_steps,
        "micro_steps": engine.micro_steps,
        "skipped_steps": engine.skipped_steps,
        "zero_stage": engine.zero_stage,
        "dp_world_size": engine.dp_world_size,
        "mesh_shape": {k: int(v) for k, v in engine.mesh.shape.items()},
        "client_state": client_state or {},
        "ds_version": "deepspeed_tpu-0.1.0",
    }
    resilience = getattr(engine._config, "resilience", None)
    if async_save:
        # A tag dir must be complete iff the state committed: defer BOTH the
        # meta.json write and the 'latest' publish until the background
        # commit finishes (wait_pending). A crash mid-commit then leaves a
        # tag dir with no meta.json — load_checkpoint(tag=...) rejects it
        # cleanly instead of failing deep inside orbax. Paths are resolved
        # NOW so a later chdir can't redirect the publish, and an atexit
        # hook guarantees the publish runs even if the caller never loads.
        global _ATEXIT_REGISTERED
        if not _ATEXIT_REGISTERED:
            import atexit
            atexit.register(wait_pending)
            _ATEXIT_REGISTERED = True
        _PENDING_TAGS.append((os.path.abspath(save_dir), tag, meta,
                              resilience))
    else:
        _publish(os.path.abspath(save_dir), tag, meta, resilience)
    logger.info(f"saved checkpoint {path}" +
                (" (async)" if async_save else ""))
    return path


_PENDING_TAGS: list = []
_ATEXIT_REGISTERED = False


def _publish(save_dir: str, tag: str, meta: dict, resilience=None) -> None:
    """Commit a tag: meta.json + integrity manifest, then point 'latest'
    at it — each file write-tmp → fsync → rename → fsync(dir), so a crash
    at any instant leaves either the previous committed checkpoint or
    this one, never a torn state. 'latest' moves only AFTER the manifest
    exists (and, with verify_on_save, re-verifies) — the Nebula commit
    contract (`nebula_checkpoint_engine.py:15`) made explicit."""
    path = _tag_path(save_dir, tag)
    os.makedirs(path, exist_ok=True)
    integrity = resilience is None or resilience.checkpoint_integrity
    verify = resilience is None or resilience.verify_on_save

    def _commit():
        get_fault_injector().check("checkpoint.publish", path=path)
        atomic_write_json(os.path.join(path, "meta.json"), meta,
                          indent=2, default=str)
        if integrity:
            write_manifest(path, extra={"tag": str(tag)})
            if verify:
                ok, problems = verify_manifest(path)
                if not ok:
                    raise FatalIOError(
                        f"checkpoint {path} failed post-commit "
                        f"verification: {'; '.join(problems[:5])}")
        atomic_write_text(os.path.join(save_dir, "latest"), str(tag))
        fsync_dir(save_dir)

    with trace_span("checkpoint/publish", tag=str(tag),
                    integrity=integrity, verify=verify):
        retry_call(_commit, policy=policy_from_config(resilience),
                   what=f"checkpoint publish '{tag}'")


def wait_pending(engine=None) -> None:
    """Block until async saves commit (orbax wait_until_finished), then
    publish their meta.json + 'latest' tags."""
    for c in _ASYNC_CKPTRS.values():
        if hasattr(c, "wait_until_finished"):
            c.wait_until_finished()
    while _PENDING_TAGS:
        _publish(*_PENDING_TAGS.pop(0))


def _validate_tag(engine, save_dir: str, tag: Optional[str]):
    """Reference tag semantics: default to the ``latest`` file
    (`engine.py:2703` load path)."""
    if tag is None:
        latest = os.path.join(save_dir, "latest")
        if not os.path.exists(latest):
            mode = engine._config.checkpoint_config.tag_validation.lower()
            msg = f"no 'latest' file in {save_dir}"
            if mode == "fail":
                raise FileNotFoundError(msg)
            logger.warning(msg)
            return None
        with open(latest) as f:
            tag = f.read().strip()
    return tag


def _resolve_verified_tag(engine, load_dir: str, tag: str,
                          explicit: bool) -> str:
    """Integrity gate on load: verify the tag's manifest; on a corrupt or
    partial tag, fall back to the newest tag that still verifies (loud
    warning) — unless the caller named the tag explicitly, in which case
    silently loading a different checkpoint would be worse than failing."""
    rz = getattr(engine._config, "resilience", None)
    if rz is not None and not rz.checkpoint_integrity:
        return tag
    path = _tag_path(load_dir, tag)
    if not os.path.isdir(path):
        # a dangling 'latest' (tag dir deleted by hand after a corruption
        # report, partial copy) is just another corruption shape — it
        # must reach the same fallback, not a bare FileNotFoundError
        ok, problems = False, [f"tag dir {path} is missing"]
    else:
        ok, problems = verify_manifest(path)
    if ok:
        return tag
    if os.path.isdir(path) and not has_manifest(path) and \
            os.path.exists(os.path.join(path, "meta.json")):
        # pre-integrity-layer save: loadable but unverifiable
        logger.warning(
            f"checkpoint tag {tag!r} has no integrity manifest "
            f"(saved before the resilience layer?) — loading unverified")
        return tag
    logger.error(
        f"checkpoint tag {tag!r} in {load_dir} FAILED integrity "
        f"verification: {'; '.join(problems[:5])}")
    if not explicit and (rz is None or rz.fallback_to_last_good):
        fb = find_newest_verified_tag(load_dir, exclude=(tag,),
                                      require_manifest=False)
        if fb is not None:
            logger.warning(
                f"FALLING BACK to newest verified checkpoint tag {fb!r} "
                f"(the run loses the steps between {fb!r} and the corrupt "
                f"{tag!r})")
            return fb
    if explicit and not os.path.isdir(path):
        # an explicitly named tag that simply is not there keeps the
        # classic error type
        raise FileNotFoundError(f"checkpoint {path} not found")
    raise CheckpointCorruptionError(
        f"checkpoint tag {tag!r} in {load_dir} is corrupt/partial "
        f"({'; '.join(problems[:5])}) and no verified fallback tag "
        f"{'was allowed' if explicit else 'exists'}")


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True,
                    load_module_only: bool = False, **_kw):
    """Restore into the engine's CURRENT shardings (topology may differ from
    the saving job — orbax reshards on read)."""
    wait_pending()
    get_registry().counter("dstpu_checkpoint_loads_total").inc()
    explicit = tag is not None
    tag = _validate_tag(engine, load_dir, tag)
    if tag is None:
        return None, {}
    with trace_span("checkpoint/verify", tag=str(tag)):
        tag = _resolve_verified_tag(engine, load_dir, tag, explicit)
    path = _tag_path(load_dir, tag)
    if not os.path.isdir(path):
        # reachable only with checkpoint_integrity disabled (the resolver
        # otherwise falls back or raises CheckpointCorruptionError)
        raise FileNotFoundError(f"checkpoint {path} not found")

    import orbax.checkpoint as ocp
    meta_path = os.path.join(path, "meta.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"checkpoint {path} exists but has no meta.json — it was never "
            f"committed (crashed mid-save?); pick a committed tag")
    with open(meta_path) as f:
        meta = json.load(f)

    shardings = engine.state_shardings()
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        engine.state, shardings)
    scaler_abs = abstract.pop("scaler", None)
    target = dict(abstract)
    if scaler_abs is not None:
        target["scaler"] = dict(scaler_abs._asdict())
    if (load_module_only or not load_optimizer_states) \
            and "params" in target:
        # partial restore: params+step only, fresh optimizer state
        params_target = {"step": target["step"], "params": target["params"]}
        restore_args = ocp.checkpoint_utils.construct_restore_args(
            params_target)
        ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        try:
            restore = ocp.args.PyTreeRestore(item=params_target,
                                             restore_args=restore_args,
                                             partial_restore=True)
        except TypeError:
            # orbax < 0.9 has no partial_restore kwarg; an empty
            # transforms dict (default-to-original) restores exactly the
            # item's keys — same partial-restore semantics
            restore = ocp.args.PyTreeRestore(item=params_target,
                                             restore_args=restore_args,
                                             transforms={})
        with trace_span("checkpoint/load_state", tag=str(tag),
                        partial=True):
            restored = ckptr.restore(os.path.join(path, "state"),
                                     args=restore)
        engine.state["params"] = restored["params"]
        engine.state["step"] = restored["step"]
    else:
        with trace_span("checkpoint/load_state", tag=str(tag),
                        partial=False):
            restored = _checkpointer().restore(
                os.path.join(path, "state"),
                ocp.args.StandardRestore(target))
        if "scaler" in restored and hasattr(engine, "loss_scaler") \
                and engine.loss_scaler is not None:
            from ..fp16 import LossScaleState
            restored["scaler"] = LossScaleState(**restored["scaler"])
        elif "scaler" in restored:
            restored.pop("scaler")
        engine.state = restored

    if getattr(engine, "_infinity", None) is not None:
        inf_path = os.path.join(path, "infinity")
        if not os.path.isdir(inf_path):
            raise FileNotFoundError(
                f"engine runs ZeRO-Infinity but {inf_path} is missing — "
                f"this checkpoint was saved by a non-infinity engine")
        engine._infinity.load_from_dir(
            inf_path,
            load_optimizer_states=(load_optimizer_states
                                   and not load_module_only))

    host_path = os.path.join(path, "host_opt")
    if getattr(engine, "_host_opt", None) is not None:
        want_opt = load_optimizer_states and not load_module_only
        if want_opt:
            if not os.path.isdir(host_path):
                raise FileNotFoundError(
                    f"engine runs with optimizer offload but {host_path} is "
                    f"missing — checkpoint was saved without offload (load "
                    f"with load_module_only=True to take params only)")
            restored_host = ocp.Checkpointer(
                ocp.StandardCheckpointHandler()).restore(host_path)
            engine._host_opt.load_state_dict(
                {"arrays": restored_host["arrays"],
                 "step_count": restored_host["step_count"]})
            if engine._host_scaler is not None and "scaler" in restored_host:
                s = restored_host["scaler"]
                engine._host_scaler.scale = float(s["scale"])
                engine._host_scaler.good_steps = int(s["good_steps"])
                engine._host_scaler.hysteresis = int(s["hysteresis"])
        else:
            # params-only load: masters re-derived from the restored device
            # params (fresh moments) — otherwise step 1 would blend new
            # params with stale masters
            engine._host_opt.reset_from_params(engine.state["params"])

    engine.global_steps = meta.get("global_steps", 0)
    engine.micro_steps = meta.get("micro_steps", 0)
    # skipped_steps lives in state["skipped"], restored with the tree
    logger.info(f"loaded checkpoint {path} (saved at dp_world="
                f"{meta.get('dp_world_size')}, now {engine.dp_world_size})")
    return path, meta.get("client_state", {})


def _infinity_fp32_state_dict(inf_path: str):
    """Rebuild the full fp32 param tree from a ZeRO-Infinity checkpoint's
    flat host-store slots, using the leaf layout recorded in its meta —
    no live engine needed (the offline half of zero_to_fp32 for the
    streamed path)."""
    with open(os.path.join(inf_path, "meta.json")) as f:
        meta = json.load(f)
    if "layer_leaves" not in meta:
        raise ValueError(
            f"{inf_path} was saved before leaf layouts were recorded — "
            f"load it through a live engine instead")
    L = meta["L"]
    rows = []
    for i in range(L):
        with np.load(os.path.join(inf_path, f"slot_{i:05d}.npz")) as z:
            rows.append(z["p"])
    slots = np.stack(rows)                       # [L, n_elems] fp32

    def nest(tree, path, arr):
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    params: dict = {}
    off = 0
    blocks: dict = {}
    for leaf in meta["layer_leaves"]:
        size = int(np.prod(leaf["shape"])) if leaf["shape"] else 1
        arr = slots[:, off:off + size].reshape([L] + leaf["shape"])
        nest(blocks, leaf["path"], arr)
        off += size
    if off != slots.shape[1]:
        raise ValueError(
            f"infinity checkpoint layout mismatch: leaf shapes cover {off} "
            f"elements but slots hold {slots.shape[1]} — meta.json was "
            f"written by a different model revision")
    params["blocks"] = blocks
    with np.load(os.path.join(inf_path, "resident.npz")) as z:
        for j, leaf in enumerate(meta["res_leaves"]):
            nest(params, leaf["path"], np.asarray(z[f"master_{j}"]))
    return params


def get_fp32_state_dict_from_zero_checkpoint(load_dir: str,
                                             tag: Optional[str] = None):
    """Offline full-precision reconstruction — role of the reference's
    `utils/zero_to_fp32.py` (482 LoC of shard-merging): with a sharded-array
    checkpoint it is a plain unsharded read of the params subtree."""
    if tag is None:
        with open(os.path.join(load_dir, "latest")) as f:
            tag = f.read().strip()
    path = _tag_path(load_dir, tag)
    inf_path = os.path.join(path, "infinity")
    if os.path.isdir(inf_path):
        return _infinity_fp32_state_dict(inf_path)
    ckptr = _checkpointer()
    restored = ckptr.restore(os.path.join(path, "state"))
    params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, dtype=np.float32), restored["params"])
    host_path = os.path.join(path, "host_opt")
    if os.path.isdir(host_path):
        # offload checkpoint: the TRUE fp32 masters live host-side; the
        # device tree's params are bf16-rounded copies
        import orbax.checkpoint as ocp
        host = ocp.Checkpointer(
            ocp.StandardCheckpointHandler()).restore(host_path)
        masters = host["arrays"]["master"]
        if isinstance(masters, dict):   # orbax may key list items "0".."N"
            masters = [masters[k] for k in
                       sorted(masters, key=lambda s: int(s))]
        leaves, treedef = jax.tree_util.tree_flatten(params)
        assert len(leaves) == len(masters), \
            f"{len(leaves)} param leaves vs {len(masters)} masters"
        params = jax.tree_util.tree_unflatten(
            treedef, [np.asarray(m, dtype=np.float32) for m in masters])
    return params
