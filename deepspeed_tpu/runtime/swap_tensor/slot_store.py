"""Fixed-stride slot stores — the storage layer of the offload tiers.

Role-equivalent of the reference swap-tensor utilities
(`/root/reference/deepspeed/runtime/swap_tensor/utils.py` SwapBuffer/
SwapBufferPool/SwapBufferManager and the file-offset bookkeeping inside
`partitioned_param_swapper.py:35`). Redesigned around the unit this
framework actually swaps: a *slot* — one scan-layer's flattened parameter
or optimizer-state vector, every slot the same size. That collapses the
reference's per-tensor offset maps into ``offset = slot * stride`` and
makes every transfer one large aligned IO.

Two backends with one API:
  - ``DramSlotStore`` — a single host allocation; acquire() is a view.
  - ``NvmeSlotStore`` — one file on the NVMe path; a ring of pinned
    4096-aligned buffers hides read/write latency behind compute
    (reference ``pipeline_read``/``pipeline_write`` double buffering,
    `pipelined_optimizer_swapper.py:55`).

Access contract (matches the streaming train loop's sequential walks):
``prefetch(slot)`` → ``acquire(slot)`` → mutate → ``release(slot,
dirty=)``. A buffer is recycled only after its writeback completes, so a
ring of K buffers tolerates a reuse distance of K-1.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...observability import get_registry, trace_span
from ...ops.aio import (ALIGN, AsyncIOHandle, PinnedBuffer, round_up)
from ..resilience import get_fault_injector, retry_call
from ...utils.logging import logger


class SlotStore:
    """Abstract fixed-stride slot store."""

    #: optional RetryPolicy for transient-I/O retries on the file-backed
    #: tiers (None = runtime/resilience DEFAULT_IO_POLICY). Set by the
    #: owner (InfinityStepper wires the config-derived policy in).
    io_policy = None

    def __init__(self, n_slots: int, slot_nbytes: int):
        self.n_slots = int(n_slots)
        self.slot_nbytes = int(slot_nbytes)

    def prefetch(self, slot: int) -> None:
        raise NotImplementedError

    def acquire(self, slot: int) -> np.ndarray:
        """uint8[slot_nbytes] view of the slot's bytes, host-resident."""
        raise NotImplementedError

    def release(self, slot: int, dirty: bool = False) -> None:
        raise NotImplementedError

    def write_slot(self, slot: int, data: np.ndarray) -> None:
        """Synchronous populate (init / checkpoint-load path)."""
        buf = self.acquire(slot)
        view = data.reshape(-1).view(np.uint8)
        buf[:view.nbytes] = view
        self.release(slot, dirty=True)

    def read_slot(self, slot: int, nbytes: Optional[int] = None) -> np.ndarray:
        """Synchronous copy-out (checkpoint-save path)."""
        buf = self.acquire(slot)
        out = buf[:nbytes if nbytes else self.slot_nbytes].copy()
        self.release(slot, dirty=False)
        return out

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    @property
    def host_bytes(self) -> int:
        return 0

    @property
    def disk_bytes(self) -> int:
        return 0


class DramSlotStore(SlotStore):
    """All slots in one host allocation (the DRAM/'cpu' offload tier)."""

    def __init__(self, n_slots: int, slot_nbytes: int):
        super().__init__(n_slots, slot_nbytes)
        self._data = np.zeros((n_slots, slot_nbytes), np.uint8)

    def prefetch(self, slot: int) -> None:
        pass

    def acquire(self, slot: int) -> np.ndarray:
        return self._data[slot]

    def release(self, slot: int, dirty: bool = False) -> None:
        pass

    @property
    def host_bytes(self) -> int:
        return self._data.nbytes


class NvmeSlotStore(SlotStore):
    """Slots in a single file on the NVMe path, accessed through a pinned
    buffer ring over the native aio handle (reference
    `partitioned_param_swapper.py` swap_in/swap_out + inflight tracking)."""

    #: seconds _free_buf blocks for a concurrent release before declaring
    #: an acquire/release imbalance (instance-settable for tests)
    PIN_WAIT_TIMEOUT = 60.0

    #: seconds close() waits for outstanding pins to drain before the
    #: dangling-pin warning — sized to the transient window it guards
    #: (a peer parked in the I/O retry backoff, bounded by the retry
    #: budget: ~3s under the default policy), NOT the full acquire
    #: budget, so teardown during exception cleanup stays fast
    CLOSE_PIN_WAIT_TIMEOUT = 3.0

    #: optional callable the store invokes (lock held, re-entrant) when no
    #: buffer is free — lets the OWNER of outstanding pins release the ones
    #: whose async consumer (e.g. an H2D transfer) has finished. Without
    #: it, a thread that holds all pins itself would wait on its own
    #: release path and time out.
    reclaim = None

    def __init__(self, n_slots: int, slot_nbytes: int, path: str,
                 aio: Optional[AsyncIOHandle] = None, buffer_count: int = 4,
                 name: str = "slots"):
        super().__init__(n_slots, slot_nbytes)
        self.stride = round_up(slot_nbytes)
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                    exist_ok=True)
        self.path = path
        self.aio = aio or AsyncIOHandle()
        self._own_aio = aio is None
        buffer_count = max(2, int(buffer_count))
        self._bufs = [PinnedBuffer(self.stride) for _ in range(buffer_count)]
        self._buf_op: List[Optional[int]] = [None] * buffer_count  # inflight
        self._buf_slot: List[Optional[int]] = [None] * buffer_count
        self._buf_pins: List[int] = [0] * buffer_count  # acquired, unreleased
        self._slot_buf: Dict[int, int] = {}   # slot currently materialized
        self._clock = 0
        # the stream-mode train loop touches the store from the main thread
        # (param uploads) and the optimizer worker concurrently
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        # preallocate the file so O_DIRECT offsets always exist
        total = self.stride * n_slots
        with open(path, "ab") as f:
            if f.tell() < total:
                f.truncate(total)
        logger.info(f"NvmeSlotStore[{name}]: {n_slots} x "
                    f"{slot_nbytes / 2**20:.1f} MiB at {path} "
                    f"({total / 2**30:.2f} GiB file, "
                    f"{buffer_count} pinned buffers)")

    # -- buffer ring ------------------------------------------------------
    def _wait_buf(self, b: int) -> None:
        if self._buf_op[b] is not None:
            with trace_span("swap/io_wait"):
                self.aio.wait_op(self._buf_op[b])
            self._buf_op[b] = None
            self._observe_depth()

    def _free_buf(self) -> int:
        """Next unpinned ring buffer, evicting its previous slot (after any
        pending IO on it has completed). When every buffer is pinned
        (main thread holding upload pins while the optimizer worker holds
        its own), block until a concurrent ``release`` frees one rather
        than aborting the step; only a full timeout — a genuine
        acquire/release imbalance — raises."""
        deadline = time.monotonic() + self.PIN_WAIT_TIMEOUT
        while True:
            for _ in range(len(self._bufs)):
                b = self._clock % len(self._bufs)
                self._clock += 1
                if self._buf_pins[b] > 0:
                    continue
                self._wait_buf(b)
                old = self._buf_slot[b]
                if old is not None and self._slot_buf.get(old) == b:
                    del self._slot_buf[old]
                self._buf_slot[b] = None
                return b
            if self.reclaim is not None:
                # release pins whose async consumer has completed — they
                # belong to THIS thread, so cond.wait could never see them
                self.reclaim()
                continue_scan = any(p == 0 for p in self._buf_pins)
                if continue_scan:
                    continue
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self._cond.wait(min(remaining, 1.0)):
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"all {len(self._bufs)} pinned buffers stayed "
                        f"acquired for {self.PIN_WAIT_TIMEOUT:.0f}s — raise "
                        f"buffer_count (acquire/release imbalance otherwise)")

    def _backoff_sleep(self, delay: float) -> None:
        """Retry backoff for in-lock submissions: waiting on the store
        condition releases the RLock (all recursion levels) for the
        duration, so the concurrent stream/optimizer thread is not
        stalled for the whole retry budget. Spurious wakeups just retry
        the submission early — harmless."""
        self._cond.wait(delay)

    def _submit_with_retry(self, b: int, submit, what: str):
        """Run one aio submission under the retry budget. The buffer is
        PINNED across the attempts: the backoff sleep releases the lock,
        and an unpinned buffer would be up for grabs to a concurrent
        _free_buf the moment it does."""
        self._buf_pins[b] += 1
        try:
            return retry_call(submit, policy=self.io_policy, what=what,
                              sleep=self._backoff_sleep)
        finally:
            self._buf_pins[b] -= 1
            if self._buf_pins[b] == 0:
                self._cond.notify_all()

    def _observe_depth(self) -> None:
        """Swap-queue-depth gauge (lock held by every caller): in-flight
        aio ops across the buffer ring — the backpressure signal for
        sizing ``buffer_count``/``queue_depth``. Gated on the registry
        flag: this runs per aio op under the store lock, so the disabled
        path must stay one attribute check."""
        reg = get_registry()
        if not reg.enabled:
            return
        reg.gauge("dstpu_swap_queue_depth").set(
            float(sum(1 for op in self._buf_op if op is not None)))

    def _submit_read(self, b: int, slot: int):
        """pread submission through the shared retry policy + the
        ``slot_store.read`` fault site. Submission failures (bad fd,
        queue full → EAGAIN/EBUSY, injected faults) are the retriable
        surface; completion errors surface in wait_op."""
        def _do():
            get_fault_injector().check("slot_store.read", path=self.path)
            return self.aio.pread(self._bufs[b].array, self.path,
                                  slot * self.stride)
        with trace_span("swap/read_submit", slot=slot):
            return self._submit_with_retry(
                b, _do, f"nvme slot read [{self.path}:{slot}]")

    def _submit_write(self, b: int, slot: int):
        def _do():
            get_fault_injector().check("slot_store.write", path=self.path)
            return self.aio.pwrite(self._bufs[b].array, self.path,
                                   slot * self.stride)
        with trace_span("swap/write_submit", slot=slot):
            return self._submit_with_retry(
                b, _do, f"nvme slot write [{self.path}:{slot}]")

    # -- API --------------------------------------------------------------
    def prefetch(self, slot: int) -> None:
        with self._lock:
            if slot in self._slot_buf:
                return
            b = self._free_buf()
            if slot in self._slot_buf:
                # _free_buf's cond.wait releases the lock — another thread
                # may have mapped this slot meanwhile; keep its mapping
                # (buffer b stays unpinned/unmapped for the next scan)
                return
            op = self._submit_read(b, slot)
            if slot in self._slot_buf:
                # the retry backoff also releases the lock: a peer mapped
                # this slot while we were sleeping. Keep theirs; register
                # our duplicate read on b (so _free_buf drains it before
                # reuse) but leave b unmapped.
                self._buf_op[b] = op
                self._observe_depth()
                return
            self._buf_op[b] = op
            self._buf_slot[b] = slot
            self._slot_buf[slot] = b
            self._observe_depth()

    def acquire(self, slot: int) -> np.ndarray:
        with self._lock:
            if slot not in self._slot_buf:
                self.prefetch(slot)
            b = self._slot_buf[slot]
            self._buf_pins[b] += 1
            self._wait_buf(b)  # finish the read (or a previous writeback)
            return self._bufs[b].array[:self.slot_nbytes]

    def release(self, slot: int, dirty: bool = False) -> None:
        with self._lock:
            b = self._slot_buf.get(slot)
            if b is None:
                return
            if self._buf_pins[b] > 0:
                self._buf_pins[b] -= 1
                if self._buf_pins[b] == 0:
                    self._cond.notify_all()
            if dirty:
                self._buf_op[b] = self._submit_write(b, slot)
                self._observe_depth()
            # buffer stays mapped (clean cache) until the ring reclaims it

    def flush(self) -> None:
        # wait + clear under ONE critical section: with the wait outside
        # the lock, a concurrent release() could submit a writeback
        # between the wait and the clear — flush would then None out an
        # op id that was never waited on, and _free_buf could recycle
        # that buffer while its write is still in flight (dstpu-lint
        # LOCK001 caught the split). Ops already submitted complete
        # independently of this lock, so holding it across the wait
        # cannot deadlock.
        with self._lock:
            self.aio.wait()
            self._buf_op = [None] * len(self._bufs)

    def close(self) -> None:
        with self._lock:
            # Teardown is ONE critical section (the RLock lets flush()
            # nest inside it): a separately-locked flush would leave an
            # unlock window where a racing release() submits a fresh
            # writeback and b.free() hands the native IO thread freed
            # memory. Before draining, WAIT (bounded) for outstanding
            # pins: a peer parked in the retry backoff (cond.wait drops
            # the lock mid-submission) still owns its buffer and will
            # resubmit into it on wake — freeing under it would be a
            # use-after-free. Its release notifies the condition. A pin
            # that never drains is an acquire/release imbalance; close
            # stays a forgiving teardown API (it may run during
            # exception cleanup) and proceeds with a loud warning.
            deadline = time.monotonic() + self.CLOSE_PIN_WAIT_TIMEOUT
            while any(p > 0 for p in self._buf_pins):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        f"NvmeSlotStore.close with "
                        f"{sum(1 for p in self._buf_pins if p > 0)} "
                        f"buffer(s) still acquired after "
                        f"{self.CLOSE_PIN_WAIT_TIMEOUT:.0f}s — "
                        f"acquire/release imbalance; outstanding views "
                        f"dangle after free")
                    break
                self._cond.wait(min(remaining, 1.0))
            self.flush()
            if self._own_aio:
                self.aio.close()
            for b in self._bufs:
                b.free()
            self._bufs = []

    @property
    def host_bytes(self) -> int:
        return sum(b.nbytes for b in self._bufs)

    @property
    def disk_bytes(self) -> int:
        return self.stride * self.n_slots


def make_slot_store(device: str, n_slots: int, slot_nbytes: int,
                    nvme_path: Optional[str] = None,
                    aio: Optional[AsyncIOHandle] = None,
                    buffer_count: int = 4, name: str = "slots",
                    io_policy=None) -> SlotStore:
    """Factory keyed on the offload device enum ('cpu' → DRAM tier,
    'nvme' → file tier).  ``io_policy`` overrides the store's transient
    retry schedule (None keeps the resilience DEFAULT_IO_POLICY) — the
    serving host cache wires the config-derived policy through here the
    same way InfinityStepper sets it on its stores."""
    if device == "nvme":
        if not nvme_path:
            raise ValueError("offload device=nvme requires nvme_path")
        store: SlotStore = NvmeSlotStore(
            n_slots, slot_nbytes, os.path.join(nvme_path, f"{name}.swp"),
            aio=aio, buffer_count=buffer_count, name=name)
    else:
        store = DramSlotStore(n_slots, slot_nbytes)
    if io_policy is not None:
        store.io_policy = io_policy
    return store
