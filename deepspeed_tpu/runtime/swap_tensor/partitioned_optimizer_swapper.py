"""Slot-partitioned host optimizer — the optimizer half of ZeRO-Infinity.

Role-equivalent of the reference optimizer swappers
(`/root/reference/deepspeed/runtime/swap_tensor/partitioned_optimizer_swapper.py`
and `pipelined_optimizer_swapper.py:55` double-buffered overlap): fp32
master + Adam moments for each scan layer live in one *slot* of a
``SlotStore`` (DRAM or NVMe), and the native ``ds_adam_step`` sweep runs
slot-at-a-time while neighbouring slots stream in/out through the store's
pinned-buffer ring. The bf16 device copy is emitted by the same sweep
directly into the parameter store's slot (the reference's fp16 param
copy-back fused into the update, `csrc/includes/cpu_adam.h` Step_AVX).

Slot layout: ``[master | m | v]`` as three contiguous fp32 spans of
``n_elems`` each, 4096-aligned total, so one aio read/write moves the whole
optimizer state of a layer.
"""
from __future__ import annotations

import ctypes
from typing import List, Optional

import numpy as np

from ...ops.adam.cpu_adam import _lib as adam_lib, _C_F32, _C_U16, _ptr
from ...ops.op_builder import BuildError
from ...utils.logging import logger
from .slot_store import SlotStore, make_slot_store


class SlotOptimizer:
    """Adam/AdamW over uniform slots of ``n_elems`` parameters each."""

    STATE_SPANS = 3   # master, m, v

    def __init__(self, n_slots: int, n_elems: int, device: str = "cpu",
                 nvme_path: Optional[str] = None, aio=None,
                 buffer_count: int = 4, lr: float = 1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 name: str = "opt"):
        self.n_slots, self.n_elems = int(n_slots), int(n_elems)
        self.lr, self.betas, self.eps = lr, tuple(betas), eps
        self.weight_decay, self.adamw_mode = weight_decay, adamw_mode
        self.step_count = 0
        slot_nbytes = self.STATE_SPANS * self.n_elems * 4
        self.store: SlotStore = make_slot_store(
            device, n_slots, slot_nbytes, nvme_path=nvme_path, aio=aio,
            buffer_count=buffer_count, name=name)
        try:
            self._lib = adam_lib()
        except BuildError as e:
            logger.warning(f"native cpu_adam unavailable ({e}); SlotOptimizer "
                           f"falls back to numpy")
            self._lib = None

    # -- views -------------------------------------------------------------
    def _spans(self, buf: np.ndarray):
        f = buf[:self.STATE_SPANS * self.n_elems * 4].view(np.float32)
        n = self.n_elems
        return f[:n], f[n:2 * n], f[2 * n:3 * n]

    # -- lifecycle ---------------------------------------------------------
    def init_slot(self, slot: int, master_f32: np.ndarray) -> None:
        buf = self.store.acquire(slot)
        p, m, v = self._spans(buf)
        p[:] = master_f32.reshape(-1)
        m[:] = 0.0
        v[:] = 0.0
        self.store.release(slot, dirty=True)

    def master(self, slot: int) -> np.ndarray:
        """Copy of the slot's fp32 master vector (checkpoint/introspection)."""
        buf = self.store.acquire(slot)
        p, _, _ = self._spans(buf)
        out = p.copy()
        self.store.release(slot, dirty=False)
        return out

    def state(self, slot: int):
        buf = self.store.acquire(slot)
        p, m, v = self._spans(buf)
        out = (p.copy(), m.copy(), v.copy())
        self.store.release(slot, dirty=False)
        return out

    def load_state(self, slot: int, p: np.ndarray, m: np.ndarray,
                   v: np.ndarray) -> None:
        buf = self.store.acquire(slot)
        sp, sm, sv = self._spans(buf)
        sp[:] = p.reshape(-1)
        sm[:] = m.reshape(-1)
        sv[:] = v.reshape(-1)
        self.store.release(slot, dirty=True)

    # -- the sweep ---------------------------------------------------------
    def prefetch(self, slot: int) -> None:
        self.store.prefetch(slot)

    def step_slot(self, slot: int, grad: np.ndarray, lr: float,
                  grad_scale: float = 1.0,
                  out_bf16: Optional[np.ndarray] = None) -> None:
        """One layer's Adam update. ``step_count`` must have been advanced
        by ``begin_step()`` for this optimizer step. ``grad`` — fp32 vector,
        or a uint16 vector of bf16 bits (the wire format of the Infinity
        grad stream — converted inline by the native sweep). ``out_bf16`` —
        uint16 view (the param store's slot) receiving the updated bf16
        params."""
        buf = self.store.acquire(slot)
        p, m, v = self._spans(buf)
        g = grad.reshape(-1)
        b1, b2 = self.betas
        if self._lib is not None and g.dtype == np.uint16:
            self._lib.ds_adam_step_g16(
                p.size, _ptr(p, _C_F32), _ptr(m, _C_F32), _ptr(v, _C_F32),
                _ptr(np.ascontiguousarray(g), _C_U16), lr, b1, b2, self.eps,
                self.weight_decay, self.step_count, grad_scale,
                int(self.adamw_mode),
                _ptr(out_bf16, _C_U16) if out_bf16 is not None else _C_U16())
        elif self._lib is not None:
            g = np.ascontiguousarray(g, dtype=np.float32)
            self._lib.ds_adam_step(
                p.size, _ptr(p, _C_F32), _ptr(m, _C_F32), _ptr(v, _C_F32),
                _ptr(g, _C_F32), lr, b1, b2, self.eps, self.weight_decay,
                self.step_count, grad_scale, int(self.adamw_mode),
                _ptr(out_bf16, _C_U16) if out_bf16 is not None else _C_U16())
        else:
            if g.dtype == np.uint16:
                import ml_dtypes
                g = g.view(ml_dtypes.bfloat16).astype(np.float32)
            gf = g.astype(np.float32) / grad_scale
            if not self.adamw_mode and self.weight_decay:
                gf = gf + self.weight_decay * p
            m *= b1
            m += (1 - b1) * gf
            v *= b2
            v += (1 - b2) * gf * gf
            c1 = 1 - b1 ** self.step_count
            c2 = 1 - b2 ** self.step_count
            u = (m / c1) / (np.sqrt(v / c2) + self.eps)
            if self.adamw_mode and self.weight_decay:
                u = u + self.weight_decay * p
            p -= lr * u
            if out_bf16 is not None:
                import ml_dtypes
                out_bf16[:] = p.astype(ml_dtypes.bfloat16).view(np.uint16)
        self.store.release(slot, dirty=True)

    def begin_step(self) -> int:
        self.step_count += 1
        return self.step_count

    def flush(self) -> None:
        self.store.flush()

    def close(self) -> None:
        self.store.close()

    @property
    def host_bytes(self) -> int:
        return self.store.host_bytes

    @property
    def disk_bytes(self) -> int:
        return self.store.disk_bytes
