"""Swap-tensor tier: slot stores + pipelined host optimizer sweeps.

Reference: `/root/reference/deepspeed/runtime/swap_tensor/` (utils,
partitioned_param_swapper, partitioned/pipelined_optimizer_swapper).
"""
from .partitioned_optimizer_swapper import SlotOptimizer
from .slot_store import (DramSlotStore, NvmeSlotStore, SlotStore,
                         make_slot_store)

__all__ = ["SlotOptimizer", "DramSlotStore", "NvmeSlotStore", "SlotStore",
           "make_slot_store"]
