from .loss_scaler import (LossScaleState, DynamicLossScaler,  # noqa: F401
                          static_loss_scaler)
