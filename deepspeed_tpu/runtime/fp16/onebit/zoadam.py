"""0/1 Adam — compressed communication AND intermittent communication.

Role-equivalent of the reference ``ZeroOneAdam``
(`/root/reference/deepspeed/runtime/fp16/onebit/zoadam.py:11`, the 0/1 Adam
paper arXiv:2202.06009). Two orthogonal savings over 1-bit Adam:

  * **variance freezing policy** (phase 1, step ≤ var_freeze_step): the
    variance (and a full-precision gradient allreduce) updates only on an
    exponentially sparsifying schedule — var_interval doubles after every
    ``var_update_scaler`` variance updates. Off-schedule steps average the
    GRADIENT through the 1-bit error-compensated collective.
  * **local step policy** (phase 2, step > var_freeze_step): replicas take
    purely LOCAL Adam steps, accumulating their updates in a momentum
    accumulator; every ``local_step_interval`` steps one 1-bit allreduce
    reconciles the accumulated update across replicas (and the interval
    itself doubles every ``local_step_scaler`` steps, clipped to
    ``local_step_clipper``) — communication becomes *intermittent*, not
    just compressed.

TPU redesign: the reference flips runtime flags
(enable_backward_allreduce, freeze_key) on a live optimizer object; here
each schedule mode is its own compiled program — "var" | "comp" | "local"
| "sync" — and the host-side ``ZeroOneSchedule`` (a deterministic replay
of the reference's var_counter/var_interval/local_step_counter state
machine) picks the program per step. Error buffers re-zero when phase 2
first activates (reference reinitial_error_buffer, `zoadam.py:324`).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from ...optimizers import _tmap, _unzip, _zeros_like_f32
from .adam import OnebitOptimizer, make_init_errors


class ZeroOneSchedule:
    """Host mirror of the reference's per-step schedule state. ``key(t)``
    must be called with 1-based consecutive steps (it fast-forwards if
    called ahead, e.g. after checkpoint resume)."""

    def __init__(self, var_freeze_step: int, var_update_scaler: int,
                 local_step_scaler: int, local_step_clipper: int):
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper
        self._t = 0
        self._last = None
        self.var_interval = 1
        self.var_counter = 0
        self.local_interval = 1
        self.local_counter = 0

    def _advance(self) -> str:
        self._t += 1
        t = self._t
        if t <= self.var_freeze_step:
            if t % self.var_interval == 0:
                # variance-update step (full-precision allreduce)
                self.var_counter += 1
                if self.var_counter == self.var_update_scaler:
                    self.var_counter = 0
                    self.var_interval *= 2
                return "var"
            return "comp"
        # phase 2: the sync decision uses the CURRENT interval; the
        # counter/doubling advances after (reference zoadam.py:233 check
        # before the :298-303 counter block)
        out = "sync" if t % self.local_interval == 0 else "local"
        self.local_counter += 1
        if self.local_counter == self.local_step_scaler:
            self.local_counter = 0
            self.local_interval = min(self.local_step_clipper,
                                      self.local_interval * 2)
        return out

    def _reset(self) -> None:
        self._t = 0
        self._last = None
        self.var_interval = 1
        self.var_counter = 0
        self.local_interval = 1
        self.local_counter = 0

    def key(self, t: int) -> str:
        if t < 1:
            raise ValueError(f"steps are 1-based, got {t}")
        if t == self._t:
            return self._last    # idempotent per step (engine may re-ask)
        if t < self._t:
            # checkpoint rollback: the schedule is pure host state —
            # re-simulate from 0
            self._reset()
        k = None
        while self._t < t:
            k = self._advance()
        self._last = k
        return k


def zero_one_adam(lr_default: float = 1e-3, betas=(0.9, 0.999),
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32678,
                  local_step_clipper: int = 16,
                  comm_axis: str = "dcn_data",
                  **unused) -> OnebitOptimizer:
    b1, b2 = betas

    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _zeros_like_f32(params),
                "v": _zeros_like_f32(params),
                # the u accumulator of the 0/1 paper (reference
                # momentum_accumulator) + the lr sum over the local window
                "u": _zeros_like_f32(params),
                "lrs": jnp.zeros((), jnp.float32)}

    init_errors = make_init_errors(comm_axis)

    def _adam_update(m, v, p, lr):
        u = m / (jnp.sqrt(v) + eps)
        p32 = p.astype(jnp.float32)
        if weight_decay:
            u = u + weight_decay * p32
        return u, (p32 - lr * u).astype(p.dtype)

    # -- phase-1 programs --------------------------------------------------
    def var_apply(grads, state, params, lr):
        """Variance-update step: full-precision pmean of grads, both
        moments update (reference zoadam.py:212-214)."""
        step = state["step"] + 1

        def upd(g, m, v, p):
            g32 = jax.lax.pmean(g.astype(jnp.float32), comm_axis)
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            _, p_new = _adam_update(m_new, v_new, p, lr)
            return p_new, m_new, v_new
        out = _tmap(upd, grads, state["m"], state["v"], params)
        new_p, new_m, new_v = _unzip(out, 3)
        return new_p, {**state, "step": step, "m": new_m, "v": new_v}

    def comp_apply(grads, state, params, lr, errors):
        """Off-schedule phase-1 step: 1-bit allreduce of the GRADIENT,
        momentum update only, variance frozen (zoadam.py:216-226)."""
        step = state["step"] + 1
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        ms = jax.tree_util.tree_leaves(state["m"])
        vs = jax.tree_util.tree_leaves(state["v"])
        ps = jax.tree_util.tree_leaves(params)
        wes = jax.tree_util.tree_leaves(errors["worker"])
        ses = jax.tree_util.tree_leaves(errors["server"])
        out_p, out_m, out_we, out_se = [], [], [], []
        for g, m, v, p, we, se in zip(flat_g, ms, vs, ps, wes, ses):
            g1, we2, se2 = compressed_allreduce(
                g.astype(jnp.float32), we[0], se[0], comm_axis)
            m_new = b1 * m + (1 - b1) * g1
            _, p_new = _adam_update(m_new, v, p, lr)
            out_p.append(p_new)
            out_m.append(m_new)
            out_we.append(we2[None])
            out_se.append(se2[None])
        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)  # noqa
        return (unf(out_p), {**state, "step": step, "m": unf(out_m)},
                {"worker": unf(out_we), "server": unf(out_se)})

    # -- phase-2 programs --------------------------------------------------
    def local_apply(grads, state, params, lr):
        """Local step: no communication; the update also accumulates into
        u (zoadam.py:228-257 freeze_key branch)."""
        step = state["step"] + 1

        def upd(g, m, v, p, u_acc):
            m_new = b1 * m + (1 - b1) * g.astype(jnp.float32)
            upd_, p_new = _adam_update(m_new, v, p, lr)
            return p_new, m_new, u_acc - lr * upd_
        out = _tmap(upd, grads, state["m"], state["v"], params, state["u"])
        new_p, new_m, new_u = _unzip(out, 3)
        return new_p, {**state, "step": step, "m": new_m, "u": new_u,
                       "lrs": state["lrs"] + lr}

    def sync_apply(grads, state, params, lr, errors):
        """Local step + reconciliation: roll back the locally-accumulated
        update, 1-bit-average the accumulator (descaled by the frozen
        denominator), reapply averaged, reconstruct momentum as -u/lrs
        (zoadam.py:257-276)."""
        step = state["step"] + 1
        lrs = state["lrs"] + lr
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        ms = jax.tree_util.tree_leaves(state["m"])
        vs = jax.tree_util.tree_leaves(state["v"])
        ps = jax.tree_util.tree_leaves(params)
        us = jax.tree_util.tree_leaves(state["u"])
        wes = jax.tree_util.tree_leaves(errors["worker"])
        ses = jax.tree_util.tree_leaves(errors["server"])
        out_p, out_m, out_u, out_we, out_se = [], [], [], [], []
        for g, m, v, p, u_acc, we, se in zip(flat_g, ms, vs, ps, us, wes,
                                             ses):
            # the step's own local update first (reference order)
            m_loc = b1 * m + (1 - b1) * g.astype(jnp.float32)
            upd_, p_loc = _adam_update(m_loc, v, p, lr)
            u_new = u_acc - lr * upd_
            denom = jnp.sqrt(v) + eps
            # roll back this window's local updates, average the window
            p32 = p_loc.astype(jnp.float32) - u_new
            u_scaled = u_new * denom
            u_avg, we2, se2 = compressed_allreduce(
                u_scaled, we[0], se[0], comm_axis)
            m_rec = -u_avg / lrs
            p_new = (p32 + u_avg / denom).astype(p.dtype)
            out_p.append(p_new)
            out_m.append(m_rec)
            out_u.append(jnp.zeros_like(u_new))
            out_we.append(we2[None])
            out_se.append(se2[None])
        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)  # noqa
        return (unf(out_p),
                {**state, "step": step, "m": unf(out_m), "u": unf(out_u),
                 "lrs": jnp.zeros((), jnp.float32)},
                {"worker": unf(out_we), "server": unf(out_se)})

    sched = ZeroOneSchedule(var_freeze_step, var_update_scaler,
                            local_step_scaler, local_step_clipper)
    return OnebitOptimizer(
        name="zerooneadam", init=init, apply=var_apply,
        hyperparams=dict(lr=lr_default, betas=betas, eps=eps,
                         weight_decay=weight_decay,
                         freeze_step=var_freeze_step, onebit=True),
        compression_apply=comp_apply, init_errors=init_errors,
        freeze_step=var_freeze_step, comm_axis=comm_axis,
        variant="zerooneadam",
        programs={"var": (var_apply, False), "comp": (comp_apply, True),
                  "local": (local_apply, False),
                  "sync": (sync_apply, True)},
        program_key=sched.key,
        reset_errors_on=("local", "sync"))
