"""1-bit Adam (and 0/1-Adam variant hooks).

Role-equivalent of the reference ``OnebitAdam``
(`/root/reference/deepspeed/runtime/fp16/onebit/adam.py:11`): exact Adam
with full-precision gradient averaging during the warmup phase
(``freeze_step`` steps); afterwards the variance term freezes and the
MOMENTUM is averaged across replicas through the error-compensated 1-bit
collective (`runtime/comm/compressed.py`) instead of the gradients —
cutting inter-replica traffic ~26x on the slow (DCN) axis.

Functional shape: both phases are pure apply functions meant to run inside
`shard_map` manual over ``comm_axis``; the engine compiles one program per
phase and switches at the freeze boundary (the reference flips a flag on
the same optimizer object; a phase here is a different compiled step).

State layout (per param leaf): m/v fp32 replicated; worker_error shaped
like the leaf and server_error shaped [numel/w] are PER-REPLICA (the engine
gives them a leading sharded axis).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from ...optimizers import Optimizer, _tmap, _unzip, _zeros_like_f32


@dataclasses.dataclass(frozen=True)
class OnebitOptimizer(Optimizer):
    """Optimizer + per-phase compiled programs and error-buffer factory.

    ``programs`` maps a phase key → ``(apply_fn, uses_errors)``; the engine
    compiles one XLA program per key and picks by ``program_key(step)`` —
    the TPU shape of the reference's runtime flag-flipping (freeze_key /
    var_interval / local_step_interval): schedule decisions are HOST
    control flow between steps, never data-dependent branches inside one.
    ``reset_errors_on`` — keys whose first activation zeroes the error
    buffers (the reference's reinitial_error_buffer on entering 0/1-Adam's
    local-step phase, `zoadam.py:324`)."""
    compression_apply: Any = None
    init_errors: Any = None
    freeze_step: int = 100
    comm_axis: str = "dcn_data"
    variant: str = "onebitadam"
    programs: Any = None          # Dict[str, Tuple[fn, uses_errors]]
    program_key: Any = None       # Callable[[int], str], step is 1-based
    reset_errors_on: Any = ()


def make_init_errors(comm_axis: str):
    """Per-replica error-feedback buffer factory (leading axis = world) —
    shared by all three 1-bit optimizers."""
    def init_errors(params, world: int):
        def we(p):
            return jnp.zeros((world,) + p.shape, jnp.float32)

        def se(p):
            n = int(p.size)
            if n % world:
                raise ValueError(
                    f"param numel {n} must divide by world {world} for "
                    f"1-bit chunking (pad or keep {comm_axis}=1)")
            return jnp.zeros((world, n // world), jnp.float32)
        return {"worker": _tmap(we, params), "server": _tmap(se, params)}
    return init_errors


def onebit_adam(lr_default: float = 1e-3, betas=(0.9, 0.999),
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100,
                comm_axis: str = "dcn_data",
                variant: str = "onebitadam") -> OnebitOptimizer:
    b1, b2 = betas

    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _zeros_like_f32(params),
                "v": _zeros_like_f32(params)}

    init_errors = make_init_errors(comm_axis)

    def _update(m, v_used, p, lr):
        u = m / (jnp.sqrt(v_used) + eps)
        p32 = p.astype(jnp.float32)
        if weight_decay:
            u = u + weight_decay * p32
        return (p32 - lr * u).astype(p.dtype)

    def warmup_apply(grads, state, params, lr):
        """Exact Adam; grads averaged across comm_axis in full precision
        (reference warmup: comm happens outside, here it's explicit)."""
        step = state["step"] + 1

        def upd(g, m, v, p):
            g32 = jax.lax.pmean(g.astype(jnp.float32), comm_axis)
            m_new = b1 * m + (1 - b1) * g32
            v_new = b2 * v + (1 - b2) * g32 * g32
            return _update(m_new, v_new, p, lr), m_new, v_new

        out = _tmap(upd, grads, state["m"], state["v"], params)
        new_params, new_m, new_v = _unzip(out, 3)
        return new_params, {"step": step, "m": new_m, "v": new_v}

    def compression_apply(grads, state, params, lr, errors):
        """Frozen-variance phase: local momentum update, then 1-bit
        error-compensated allreduce of the momentum (reference
        onebit/adam.py compression path)."""
        step = state["step"] + 1
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat = {
            "m": jax.tree_util.tree_leaves(state["m"]),
            "v": jax.tree_util.tree_leaves(state["v"]),
            "p": jax.tree_util.tree_leaves(params),
            "we": jax.tree_util.tree_leaves(errors["worker"]),
            "se": jax.tree_util.tree_leaves(errors["server"]),
        }
        out_p, out_m, out_we, out_se = [], [], [], []
        for g, m, v, p, we, se in zip(flat_g, flat["m"], flat["v"],
                                      flat["p"], flat["we"], flat["se"]):
            m_local = b1 * m + (1 - b1) * g.astype(jnp.float32)
            m_comm, we2, se2 = compressed_allreduce(
                m_local, we[0], se[0], comm_axis)
            out_m.append(m_comm)
            out_we.append(we2[None])
            out_se.append(se2[None])
            out_p.append(_update(m_comm, v, p, lr))
        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)  # noqa
        return (unf(out_p),
                {"step": step, "m": unf(out_m), "v": state["v"]},
                {"worker": unf(out_we), "server": unf(out_se)})

    return OnebitOptimizer(
        name=variant, init=init, apply=warmup_apply,
        hyperparams=dict(lr=lr_default, betas=betas, eps=eps,
                         weight_decay=weight_decay,
                         freeze_step=freeze_step, onebit=True),
        compression_apply=compression_apply, init_errors=init_errors,
        freeze_step=freeze_step, comm_axis=comm_axis, variant=variant,
        programs={"warmup": (warmup_apply, False),
                  "compress": (compression_apply, True)},
        program_key=lambda t: "warmup" if t <= freeze_step else "compress")


def get_onebit_optimizer(name: str, lr=None, betas=(0.9, 0.999), **params):
    """Registry hook for runtime/optimizers.py get_optimizer."""
    name_l = name.lower().replace("_", "")
    if name_l == "onebitadam":
        return onebit_adam(
            lr if lr is not None else 1e-3, tuple(betas),
            params.pop("eps", 1e-8), params.pop("weight_decay", 0.0),
            params.pop("freeze_step", 100),
            params.pop("comm_axis", "dcn_data"))
    if name_l == "zerooneadam":
        from .zoadam import zero_one_adam
        return zero_one_adam(lr if lr is not None else 1e-3, tuple(betas),
                             **params)
    if name_l == "onebitlamb":
        from .lamb import onebit_lamb
        return onebit_lamb(lr if lr is not None else 1e-3, tuple(betas),
                           **params)
    raise ValueError(f"unknown onebit optimizer {name}")
