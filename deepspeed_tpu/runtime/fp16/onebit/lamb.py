"""1-bit LAMB — compressed-momentum LAMB with frozen layerwise coefficients.

Role-equivalent of the reference ``OnebitLamb``
(`/root/reference/deepspeed/runtime/fp16/onebit/lamb.py:13`). Warmup is
exact LAMB (full-precision gradient averaging) while an EMA
(``coeff_beta``) of each leaf's trust ratio is recorded; at the freeze
boundary the variance is snapshotted (``exp_avg_sq_fresh``) and per-leaf
``scaling_coeff`` = united_scale / RMS(momentum) equalize momentum
magnitudes so the 1-bit collective's error feedback behaves uniformly
across layers (reference lamb.py:170-185). In the compression phase the
scaled momentum is 1-bit averaged; a *fresh* variance rebuilt from
reconstructed gradients gives the scaling ``factor`` =
max(frozen_denom / fresh_denom), clipped to [factor_min, factor_max] and
rate-limited by ``factor_threshold``, and the applied trust ratio is
``lamb_coeff_freeze * factor`` (lamb.py:330-385).

TPU redesign: per-leaf tensors through the shard_map'd error-compensated
collective (`runtime/comm/compressed.py`) instead of one flattened fused
buffer — XLA already fuses the elementwise work, and per-leaf chunking is
what the collective wants. All schedule state (scaling coeffs, EMA coeff,
last factor) lives in the optimizer state tree as scalars, so the whole
phase is one compiled program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...comm.compressed import compressed_allreduce
from ...optimizers import _tmap, _unzip, _zeros_like_f32
from .adam import OnebitOptimizer, make_init_errors


def onebit_lamb(lr_default: float = 1e-3, betas=(0.9, 0.999),
                eps: float = 1e-6, weight_decay: float = 0.0,
                freeze_step: int = 100000,
                max_coeff: float = 10.0, min_coeff: float = 0.01,
                coeff_beta: float = 0.9,
                factor_max: float = 4.0, factor_min: float = 0.5,
                factor_threshold: float = 0.1,
                comm_axis: str = "dcn_data",
                **unused) -> OnebitOptimizer:
    b1, b2 = betas

    def init(params):
        def scalar_tree(val):
            return _tmap(lambda _: jnp.asarray(val, jnp.float32), params)
        return {"step": jnp.zeros((), jnp.int32),
                "m": _zeros_like_f32(params),
                "v": _zeros_like_f32(params),
                "v_fresh": _zeros_like_f32(params),
                "coeff_freeze": scalar_tree(0.0),
                "last_factor": scalar_tree(1.0),
                "scaling_coeff": scalar_tree(1.0)}

    init_errors = make_init_errors(comm_axis)

    def _make_warmup(with_freeze: bool):
        """Exact LAMB on pmean'd grads + trust-ratio EMA (reference
        lamb.py:225-250). ``with_freeze`` is a STATIC flag — the
        freeze-boundary extras (v→v_fresh snapshot, scaling coeffs from
        momentum RMS, lamb.py:170-185) compile only into the one-shot
        'freeze' program, not into every warmup step."""
        def apply(grads, state, params, lr):
            step = state["step"] + 1

            def upd(g, m, v, p, cf):
                g32 = jax.lax.pmean(g.astype(jnp.float32), comm_axis)
                m_new = b1 * m + (1 - b1) * g32
                v_new = b2 * v + (1 - b2) * g32 * g32
                u = m_new / (jnp.sqrt(v_new) + eps)
                p32 = p.astype(jnp.float32)
                if weight_decay:
                    u = u + weight_decay * p32
                w_norm = jnp.linalg.norm(p32)
                u_norm = jnp.linalg.norm(u)
                coeff = jnp.where((w_norm > 0) & (u_norm > 0),
                                  jnp.clip(w_norm / u_norm, min_coeff,
                                           max_coeff), 1.0)
                cf_new = coeff_beta * cf + (1 - coeff_beta) * coeff
                return (p32 - lr * coeff * u).astype(p.dtype), m_new, \
                    v_new, cf_new
            out = _tmap(upd, grads, state["m"], state["v"], params,
                        state["coeff_freeze"])
            new_p, new_m, new_v, new_cf = _unzip(out, 4)
            new_state = {**state, "step": step, "m": new_m, "v": new_v,
                         "coeff_freeze": new_cf}
            if with_freeze:
                rms = _tmap(lambda m: jnp.linalg.norm(m) /
                            jnp.sqrt(jnp.asarray(m.size, jnp.float32)),
                            new_m)
                rms_leaves = jax.tree_util.tree_leaves(rms)
                united = sum(rms_leaves) / len(rms_leaves)
                new_state["scaling_coeff"] = _tmap(
                    lambda r: united / jnp.maximum(r, 1e-12), rms)
                new_state["v_fresh"] = new_v
            return new_p, new_state
        return apply

    warmup_apply = _make_warmup(False)
    freeze_apply = _make_warmup(True)

    def compress_apply(grads, state, params, lr, errors):
        """Compressed phase (reference lamb.py:251-385)."""
        step = state["step"] + 1
        treedef = jax.tree_util.tree_structure(grads)
        leaves = lambda t: jax.tree_util.tree_leaves(t)  # noqa: E731
        out_p, out_m, out_vf, out_lf, out_we, out_se = ([], [], [], [], [],
                                                        [])
        for (g, m, v, vf, cf, lf, sc, p, we, se) in zip(
                leaves(grads), leaves(state["m"]), leaves(state["v"]),
                leaves(state["v_fresh"]), leaves(state["coeff_freeze"]),
                leaves(state["last_factor"]), leaves(state["scaling_coeff"]),
                leaves(params), leaves(errors["worker"]),
                leaves(errors["server"])):
            m_last = m
            m_loc = (b1 * m + (1 - b1) * g.astype(jnp.float32)) * sc
            m_avg, we2, se2 = compressed_allreduce(
                m_loc, we[0], se[0], comm_axis)
            m_new = m_avg / sc
            g_rec = (m_new - m_last * b1) / (1 - b1)
            vf_new = b2 * vf + (1 - b2) * g_rec * g_rec
            denom = jnp.sqrt(v) + eps          # frozen variance
            denom_real = jnp.sqrt(vf_new) + eps
            update_prelim = m_new / denom
            p32 = p.astype(jnp.float32)
            if weight_decay:
                update = update_prelim + weight_decay * p32
            else:
                update = update_prelim
            factor = jnp.max(denom / denom_real)
            if weight_decay:
                ratio = jnp.minimum(
                    1.0, jnp.linalg.norm(update_prelim) /
                    jnp.maximum(jnp.linalg.norm(update), 1e-12))
                factor = factor * ratio + (1.0 - ratio)
            factor = jnp.clip(factor, factor_min, factor_max)
            factor = jnp.clip(factor, lf * (1.0 - factor_threshold),
                              lf * (1.0 + factor_threshold))
            coeff = cf * factor
            out_p.append((p32 - lr * coeff * update).astype(p.dtype))
            out_m.append(m_new)
            out_vf.append(vf_new)
            out_lf.append(factor)
            out_we.append(we2[None])
            out_se.append(se2[None])
        unf = lambda ls: jax.tree_util.tree_unflatten(treedef, ls)  # noqa
        return (unf(out_p),
                {"step": step, "m": unf(out_m), "v": state["v"],
                 "v_fresh": unf(out_vf), "coeff_freeze":
                     state["coeff_freeze"], "last_factor": unf(out_lf),
                 "scaling_coeff": state["scaling_coeff"]},
                {"worker": unf(out_we), "server": unf(out_se)})

    return OnebitOptimizer(
        name="onebitlamb", init=init, apply=warmup_apply,
        hyperparams=dict(lr=lr_default, betas=betas, eps=eps,
                         weight_decay=weight_decay,
                         freeze_step=freeze_step, onebit=True),
        compression_apply=compress_apply, init_errors=init_errors,
        freeze_step=freeze_step, comm_axis=comm_axis, variant="onebitlamb",
        programs={"warmup": (warmup_apply, False),
                  "freeze": (freeze_apply, False),
                  "compress": (compress_apply, True)},
        program_key=lambda t: ("warmup" if t < freeze_step else
                               "freeze" if t == freeze_step else
                               "compress"))
