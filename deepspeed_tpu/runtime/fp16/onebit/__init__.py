"""1-bit optimizers — counterpart of
`/root/reference/deepspeed/runtime/fp16/onebit/`."""
from .adam import OnebitOptimizer, get_onebit_optimizer, onebit_adam

__all__ = ["onebit_adam", "get_onebit_optimizer", "OnebitOptimizer"]
