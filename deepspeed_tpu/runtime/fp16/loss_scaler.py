"""Dynamic loss scaling for fp16 training.

Same semantics as the reference ``DynamicLossScaler``
(`/root/reference/deepspeed/runtime/fp16/loss_scaler.py:77`): scale doubles
after ``scale_window`` consecutive overflow-free steps, halves on overflow
(with ``delayed_shift`` hysteresis), never below ``min_scale``. Reformulated
as a pure state-transition so it lives inside the jitted train step: the
overflow check is a global `isfinite` reduction over the grad tree (the
reference's ``CheckOverflow``, `runtime/utils.py:170`) and the skip-update
becomes a `jnp.where` select rather than a Python branch.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray        # f32 scalar
    good_steps: jnp.ndarray   # i32 consecutive non-overflow steps
    hysteresis: jnp.ndarray   # i32 remaining tolerated overflows before halving


class DynamicLossScaler:
    # When False (static scaling), overflow is never detected and steps are
    # never skipped — matching the reference LossScaler whose has_overflow
    # always returns False (`fp16/loss_scaler.py:53`); non-finite grads then
    # propagate into params exactly as they would in the reference.
    detect_overflow: bool = True

    def __init__(self, initial_scale_power: int = 16, scale_window: int = 1000,
                 min_scale: float = 1.0, hysteresis: int = 2,
                 scale_factor: float = 2.0):
        self.initial_scale = 2.0 ** initial_scale_power
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.hysteresis = hysteresis
        self.scale_factor = scale_factor

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(self.initial_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(self.hysteresis, jnp.int32))

    @staticmethod
    def has_overflow(grads) -> jnp.ndarray:
        leaves = jax.tree_util.tree_leaves(grads)
        finite = jnp.asarray(True)
        for g in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        return jnp.logical_not(finite)

    def update(self, state: LossScaleState,
               overflow: jnp.ndarray) -> LossScaleState:
        hys = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0),
                        state.hysteresis)
        shrink = overflow & (state.hysteresis <= 1)
        new_scale = jnp.where(
            shrink,
            jnp.maximum(state.scale / self.scale_factor, self.min_scale),
            state.scale)
        good = jnp.where(overflow, 0, state.good_steps + 1)
        grow = (~overflow) & (good >= self.scale_window)
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        good = jnp.where(grow, 0, good)
        hys = jnp.where(grow | shrink, self.hysteresis, hys)
        return LossScaleState(scale=new_scale, good_steps=good, hysteresis=hys)


def static_loss_scaler(scale: float) -> DynamicLossScaler:
    """Fixed-scale degenerate case (reference ``LossScaler``,
    `loss_scaler.py:53`): the scale never moves AND overflow is never
    detected, so updates are never skipped — the user opted out of the
    safety net by picking a static scale."""
    s = DynamicLossScaler(initial_scale_power=0, scale_window=1 << 30,
                          min_scale=scale, hysteresis=1, scale_factor=1.0)
    s.initial_scale = scale
    s.detect_overflow = False
    return s
