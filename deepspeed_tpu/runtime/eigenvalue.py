"""Eigenvalue estimation (power iteration) for MoQ scheduling.

Role-equivalent of the reference ``Eigenvalue`` (`/root/reference/deepspeed/
runtime/eigenvalue.py:7`): estimate the top Hessian eigenvalue of the loss
w.r.t. selected params via power iteration on Hessian-vector products. The
reference differentiates twice through torch autograd; here the HVP is
`jax.jvp` of `jax.grad` — one compiled program per (loss, params) pair.
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def _normalize(tree):
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(l))
                        for l in jax.tree_util.tree_leaves(tree)))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree_util.tree_map(lambda l: l / norm, tree), norm


class Eigenvalue:
    def __init__(self, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability

    def compute_eigenvalue(self, loss_fn: Callable, params, batch,
                           rng=None) -> Tuple[jnp.ndarray, dict]:
        """Top eigenvalue of ∇²L at ``params``. Returns (eigenvalue, v)."""
        grad_fn = jax.grad(lambda p: loss_fn(p, batch))

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        if rng is None:
            rng = jax.random.PRNGKey(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])
        v, _ = _normalize(v)

        def cond(carry):
            _, prev, cur, it = carry
            return (it < self.max_iter) & \
                (jnp.abs(cur - prev) > self.tol * jnp.maximum(
                    jnp.abs(cur), 1e-12))

        def body(carry):
            v, _, cur, it = carry
            hv = hvp(v)
            v_new, norm = _normalize(hv)
            return (v_new, cur, norm, it + 1)

        v, _, eig, _ = jax.lax.while_loop(
            cond, body, (v, jnp.asarray(-1.0), jnp.zeros(()),
                         jnp.zeros((), jnp.int32)))
        return eig + self.stability, v
