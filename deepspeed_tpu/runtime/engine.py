"""Training engine.

Role-equivalent of the reference ``DeepSpeedEngine``
(`/root/reference/deepspeed/runtime/engine.py:189`), redesigned for XLA's
compilation model. The reference is an nn.Module wrapper whose
forward/backward/step each run eagerly with hand-scheduled collectives; here
the whole training step — gradient accumulation loop, mixed precision,
ZeRO collectives, gradient clipping, optimizer update, loss-scale state
machine — is ONE jitted program over a named-axis mesh. DeepSpeed's runtime
machinery maps as:

  _configure_distributed_model (engine.py:1120) → mesh build + param init
      directly into their target shardings (no broadcast needed: same program,
      same rng → identical replicated values; sharded values materialize only
      their shard)
  allreduce_gradients bucketing (engine.py:1890,2336) → grad sharding
      constraints; XLA chooses bucketing/overlap
  GAS boundary logic (engine.py:1740 scale, is_gradient_accumulation_boundary)
      → lax.scan over the microbatch axis inside the step
  FP16_Optimizer / BF16_Optimizer wrappers (engine.py:1424,1478) → fp32 master
      params in the state + cast-on-forward + loss-scale state transitions
  ZeRO stage selection (engine.py:1498) → ZeroShardingPolicy spec trees

The legacy ``forward()/backward()/step()`` triple is kept as a compatibility
surface (each call is its own jitted program, grads accumulate in a donated
device buffer); ``train_batch()``/``train_step()`` is the native path.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import get_registry, trace_span
from ..parallel import topology as topo
from ..parallel.shard_map_compat import shard_map
from ..utils.logging import logger
from . import lr_schedules
from .config import DeepSpeedConfig
from .fp16 import DynamicLossScaler, static_loss_scaler
from .optimizers import Optimizer, get_optimizer, wrap_optax
from .resilience import Heartbeat
from .utils import host_transfer
from .zero.sharding import ZeroShardingPolicy, constrain, to_named

MEM_EFFICIENT_LINEAR_DEFAULT = True


def _count_jit_build() -> None:
    """Recompile watermark: every jit program the engine constructs bumps
    this counter — a rising value mid-run means a retrace bomb."""
    get_registry().counter("dstpu_jit_programs_built_total").inc()


def _tree_zeros_f32(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, jnp.float32), tree)


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


class DeepSpeedEngine:
    """Single-controller SPMD training engine over a named mesh."""

    def __init__(self,
                 model,
                 config: Any = None,
                 mesh: Optional[Mesh] = None,
                 optimizer: Any = None,
                 lr_scheduler: Any = None,
                 loss_fn: Optional[Callable] = None,
                 param_specs: Any = None,
                 rng: Optional[jax.Array] = None,
                 dont_init: bool = False):
        self._config = (config if isinstance(config, DeepSpeedConfig)
                        else DeepSpeedConfig(config or {}))
        # the ``training`` block carries model-side hot-path knobs
        # (remat policy, fused loss head, loss chunking) — apply them by
        # rebuilding the model BEFORE anything binds model.loss, so a
        # tuned config JSON alone changes the compiled step program
        model = self._apply_training_overrides(model)
        self.model = model
        if self._config.resilience.fault_injection:
            # config-driven fault plans arm the process-global injector
            # (runtime/resilience; env DSTPU_FAULTS plans merge on top)
            from .resilience import get_fault_injector
            get_fault_injector().add_plans_from_config(
                self._config.resilience.fault_injection)
        # worker side of the elastic agent's hung-worker watchdog: beat
        # the DSTPU_HEARTBEAT_FILE the agent assigned us once per
        # interval at every train step (no-op when launched standalone)
        self._heartbeat = Heartbeat(
            interval_s=self._config.resilience.heartbeat_interval_s)
        self.mesh = mesh if mesh is not None else topo.build_mesh(
            self._config.mesh)
        self.dp_world_size = topo.dp_world_size(self.mesh)
        self.mp_world_size = topo.mp_world_size(self.mesh)
        self._config.resolve_batch_sizes(self.dp_world_size)

        self.zero_stage = self._config.zero_optimization_stage
        self.fp16_enabled = self._config.fp16.enabled
        self.bf16_enabled = self._config.bf16.enabled
        self.compute_dtype = {
            "bfloat16": jnp.bfloat16, "float16": jnp.float16,
            "float32": jnp.float32}[self._config.precision_dtype]
        self.gradient_accumulation_steps = (
            self._config.gradient_accumulation_steps or 1)
        self.train_micro_batch_size_per_gpu = \
            self._config.train_micro_batch_size_per_gpu
        self.train_batch_size = self._config.train_batch_size

        self._loss_fn = loss_fn or (
            model.loss if hasattr(model, "loss") else None)
        if self._loss_fn is None:
            raise ValueError("Need model.loss or an explicit loss_fn")
        if hasattr(model, "bind_mesh"):
            model.bind_mesh(self.mesh)

        # -- optimizer -----------------------------------------------------
        self.optimizer = self._configure_optimizer(optimizer)
        self.lr_schedule = self._configure_lr_schedule(lr_scheduler)

        # -- loss scaling --------------------------------------------------
        fp16c = self._config.fp16
        if self.fp16_enabled:
            if fp16c.dynamic:
                self.loss_scaler = DynamicLossScaler(
                    initial_scale_power=fp16c.initial_scale_power,
                    scale_window=fp16c.loss_scale_window,
                    min_scale=fp16c.min_loss_scale,
                    hysteresis=fp16c.hysteresis)
            else:
                self.loss_scaler = static_loss_scaler(fp16c.loss_scale)
        else:
            self.loss_scaler = None

        # -- sharding policy ----------------------------------------------
        if param_specs is None and hasattr(model, "partition_specs"):
            param_specs = model.partition_specs()
        self._param_shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0)))
        if param_specs is None:
            param_specs = jax.tree_util.tree_map(
                lambda s: P(*([None] * len(s.shape))), self._param_shapes)
        self.zero_policy = ZeroShardingPolicy(
            self.zero_stage, self.mesh, param_specs, self._param_shapes,
            min_partition_size=0,
            param_persistence_threshold=(
                self._config.zero_config.param_persistence_threshold
                if self.zero_stage >= 3 else 0))
        self.master_specs = self.zero_policy.master_param_specs()
        self.grad_specs = self.zero_policy.grad_specs()
        opt_shapes = jax.eval_shape(self.optimizer.init, self._param_shapes)
        self.opt_specs = self.zero_policy.opt_state_specs(opt_shapes)

        # batch leaves are [gas, global_batch, ...]; expert-parallel ranks
        # are also data ranks (reference _create_expert_and_data_parallel,
        # utils/groups.py:109), so the batch shards over 'expert' too
        batch_axes = tuple(a for a in (topo.DCN_DATA_AXIS, topo.DATA_AXIS,
                                       topo.EXPERT_AXIS)
                           if self.mesh.shape.get(a, 1) > 1)
        self._batch_dim_spec = batch_axes if batch_axes else None

        self.global_steps = 0
        self.micro_steps = 0
        self._step_times: list = []

        # -- observability (reference MonitorMaster at engine.py:287,
        #    ThroughputTimer/EngineTimers at engine.py:149; span tracer +
        #    metrics registry are TPU-native — deepspeed_tpu/observability)
        from ..monitor.monitor import MonitorMaster
        from ..observability import configure as _obs_configure
        from ..utils.timer import SynchronizedWallClockTimer, ThroughputTimer
        self.monitor = MonitorMaster(self._config.monitor)
        seq_len = getattr(getattr(model, "config", None), "max_seq_len", 0)
        self.tput_timer = ThroughputTimer(self.train_batch_size, seq_len)
        self.timers = SynchronizedWallClockTimer()
        self._analytic_flops_per_step = None
        self._tracer, self._obs = _obs_configure(
            self._config.observability, rank=jax.process_index())
        from ..observability import get_flight_recorder, get_overlap_profiler
        self._flight = get_flight_recorder()
        # host/device overlap profiler: splits the fused step into
        # enqueue vs device-wait from timestamps the step path already
        # takes (observability/overlap.py); disabled = attribute check
        self._ovl = get_overlap_profiler()
        self._skip_burst = 0
        if self._obs.enabled:
            # derived gauges refreshed at export time (plain host reads —
            # memory_stats and the comms log never sync the device)
            self._obs.set_collector("engine", self._obs_collect)

        # -- ZeRO-Offload tiers (host DRAM optimizer / Infinity streaming) -
        from .zero.offload import validate_offload_config
        offload_mode = validate_offload_config(self._config)
        self.offload_enabled = offload_mode == "optimizer"
        self.infinity_enabled = offload_mode == "infinity"
        self._host_opt = None
        self._host_scaler = None
        self._infinity = None
        if offload_mode != "none" and optimizer is not None:
            raise ValueError(
                "offload needs a config-named optimizer (Adam/AdamW/"
                "Adagrad) — the host step runs in native code, not "
                "through a user optimizer object")

        # -- state init (sharded at materialization) -----------------------
        if not dont_init:
            self.state = self.init_state(rng if rng is not None
                                         else jax.random.PRNGKey(0))
        self._train_step_fn = None
        self._grad_fn = None
        self._apply_fn = None
        self._grad_acc = None
        self._grad_acc_count = 0
        self._last_lr = float(self.optimizer.hyperparams.get("lr", 0.0))

    # ------------------------------------------------------------------
    # configuration
    # ------------------------------------------------------------------
    def _configure_optimizer(self, optimizer) -> Optimizer:
        """Reference `engine.py:1253` _configure_optimizer /
        `:1307` _configure_basic_optimizer (name-dispatch from config)."""
        if isinstance(optimizer, Optimizer):
            return optimizer
        if optimizer is not None:  # assume optax transformation
            return wrap_optax(optimizer)
        oc = self._config.optimizer
        if oc is None:
            return get_optimizer("adamw")
        return get_optimizer(oc.type, **dict(oc.params))

    def _configure_lr_schedule(self, lr_scheduler):
        sc = self._config.scheduler
        if self.optimizer.hyperparams.get("external_lr"):
            if sc is not None or callable(lr_scheduler):
                raise ValueError(
                    "an optax optimizer carries its own schedule; remove the "
                    "engine scheduler (put optax.scale_by_schedule in the "
                    "chain instead)")
            return lr_schedules.constant_lr(0.0)  # reported lr is N/A
        if callable(lr_scheduler):
            return lr_scheduler
        if sc is None:
            return lr_schedules.constant_lr(
                self.optimizer.hyperparams.get("lr", 1e-3))
        return lr_schedules.get_lr_schedule(sc.type, dict(sc.params))

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------
    def state_specs(self) -> Dict:
        if self.infinity_enabled:
            return {"step": P(), "skipped": P()}
        if self.offload_enabled:
            # device state is ONLY compute-dtype params — masters/moments
            # live on the host (runtime/zero/offload.py)
            return {"step": P(), "skipped": P(),
                    "params": self.zero_policy.model_param_specs()}
        specs = {"step": P(), "skipped": P(), "params": self.master_specs,
                 "opt": self.opt_specs}
        if self.loss_scaler is not None:
            specs["scaler"] = jax.tree_util.tree_map(lambda _: P(),
                                                     self.loss_scaler.init())
        return specs

    def state_shardings(self) -> Dict:
        return to_named(self.mesh, self.state_specs())

    def _cached_program(self, key: str, build: Callable):
        """Engine-lifetime cache for jitted programs (the TRACE003
        discipline: never construct ``jax.jit(...)`` per call — the
        compile cache is keyed on the callable object, so a fresh wrap
        retraces every time).  ``build`` runs once per ``key``."""
        if not hasattr(self, "_programs_misc"):
            self._programs_misc = {}
        if key not in self._programs_misc:
            self._programs_misc[key] = build()
        return self._programs_misc[key]

    def init_state(self, rng) -> Dict:
        """Build the train state directly into its target shardings — the
        jitted init materializes only each device's shard (replaces the
        reference's init-then-broadcast `engine.py:1083` and zero.Init
        partition-at-construction `partition_parameters.py:539`)."""
        if self.infinity_enabled:
            # ZeRO-Infinity: params/optimizer live in host stores owned by
            # the stepper; engine state carries only the counters
            from .zero.infinity import InfinityStepper
            self._infinity = InfinityStepper(self, rng)
            return {"step": jnp.zeros((), jnp.int32),
                    "skipped": jnp.zeros((), jnp.int32)}
        if self.offload_enabled:
            return self._init_state_offload(rng)

        def _init(rng):
            params = self.model.init(rng)
            if not self._config.bf16.master_weights and self.bf16_enabled:
                params = jax.tree_util.tree_map(
                    lambda p: p.astype(jnp.bfloat16), params)
            state = {"step": jnp.zeros((), jnp.int32),
                     "skipped": jnp.zeros((), jnp.int32), "params": params,
                     "opt": self.optimizer.init(params)}
            if self.loss_scaler is not None:
                state["scaler"] = self.loss_scaler.init()
            return state

        init_fn = self._cached_program(
            "init_state",
            lambda: jax.jit(_init, out_shardings=self.state_shardings()))
        with self.mesh:
            return init_fn(rng)

    def _init_state_offload(self, rng) -> Dict:
        """Offload init: fp32 params materialize sharded on device, move to
        host (masters for the CPU optimizer), device keeps the compute-dtype
        copy in the model shardings."""
        from .zero.offload import HostLossScaler, ZeroOffloadHostOptimizer
        f32_shardings = to_named(self.mesh, self.master_specs)
        init_fn = self._cached_program(
            "init_offload_f32",
            lambda: jax.jit(self.model.init, out_shardings=f32_shardings))
        with self.mesh:
            f32_params = init_fn(rng)
        host_tree = jax.device_get(f32_params)
        self._host_opt = ZeroOffloadHostOptimizer(self, host_tree)
        if self.loss_scaler is not None:
            self._host_scaler = HostLossScaler(self.loss_scaler)
        logger.info(
            f"ZeRO-Offload: {self._host_opt.host_bytes / 2**30:.2f} GiB "
            f"optimizer state in host DRAM; device holds "
            f"{'bf16' if self.compute_dtype == jnp.bfloat16 else str(self.compute_dtype)} params only")
        param_shardings = to_named(self.mesh,
                                   self.zero_policy.model_param_specs())
        # cached for the per-step upload (constant for the engine lifetime)
        self._offload_shardings = jax.tree_util.tree_leaves(
            param_shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
        cast = jax.jit(self._cast_for_compute, out_shardings=param_shardings)
        with self.mesh:
            dev_params = cast(f32_params)
        return {"step": jnp.zeros((), jnp.int32),
                "skipped": jnp.zeros((), jnp.int32), "params": dev_params}

    def _accumulate_micro_grads(self, state, batch, scale):
        """Shared GAS loop: scan the microbatch axis, sum f32 grads +
        scaled losses. Single source of the accumulation semantics for the
        fused train step AND the offload grad function."""
        gas = self.gradient_accumulation_steps

        def micro(carry, mb):
            gsum, lsum = carry
            loss, grads = jax.value_and_grad(self._micro_loss)(
                state["params"], mb, scale)
            grads = constrain(
                jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                       grads),
                self.mesh, self.grad_specs)
            gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
            return (gsum, lsum + loss), None

        zeros = _tree_zeros_f32(state["params"])
        if gas == 1:
            sq = jax.tree_util.tree_map(lambda x: x[0], batch)
            (gsum, lsum), _ = micro((zeros, jnp.zeros((), jnp.float32)), sq)
        else:
            (gsum, lsum), _ = jax.lax.scan(
                micro, (zeros, jnp.zeros((), jnp.float32)), batch)
        return gsum, lsum

    def _build_offload_grad_fn(self):
        """The jitted grads-for-offload program. With
        ``zero_optimization.offload_wire_bits`` set, the gradient leaves
        are concatenated into ONE flat vector and stochastic-rounding
        encoded ON DEVICE (runtime/zero/wire_codec.py, the same codec and
        layout ZeRO-Infinity streams per layer — chunk scales span leaf
        boundaries there too) so the D2H wire carries n/8..n bytes instead
        of 4n in a single transfer — the r4 tier-1 bottleneck was exactly
        this wire, and per-leaf transfers would pay ~n_leaves round trips
        on it. Clipping/overflow use the device-side pre-quantization
        norm: the clip factor rides the host sweep's single grad multiply
        either way, and E[decode(encode(g))] = g."""
        from .zero import wire_codec
        bits = self._offload_wire_bits

        def grad_fn(state, batch, scale, key):
            gsum, lsum = self._accumulate_micro_grads(state, batch, scale)
            gnorm = global_norm(gsum)
            if not bits:
                return lsum, gsum, gnorm
            # ONE flat vector, ONE encode, ONE D2H transfer: on a
            # high-latency wire ~100 per-leaf fetches pay ~100 round
            # trips; the concatenated form is also exactly the layout
            # Infinity streams per layer, chunk scales spanning leaf
            # boundaries and all
            flat = jnp.concatenate(
                [g.reshape(-1) for g in jax.tree_util.tree_leaves(gsum)])
            pad = (-flat.shape[0]) % wire_codec.CHUNK
            if pad:
                flat = jnp.pad(flat, (0, pad))
            return lsum, wire_codec.encode(flat, bits, key), gnorm

        with self.mesh:
            self._offload_grad_fn = jax.jit(grad_fn)
        _count_jit_build()
        return self._offload_grad_fn

    @property
    def _offload_wire_bits(self) -> int:
        return int(getattr(self._config.zero_config, "offload_wire_bits",
                           0) or 0)

    def _upload_split_fn(self, dtype):
        """One-flat-H2D upload: jitted split of the concatenated param
        vector back into master-shaped leaves (single-device fast path)."""
        key = ("upload_split", np.dtype(dtype).name)
        if not hasattr(self, "_programs_misc"):
            self._programs_misc = {}
        if key not in self._programs_misc:
            masters = self._host_opt.opt.master
            offs = np.cumsum([0] + [m.size for m in masters])
            shapes = [m.shape for m in masters]

            def split(flat):
                return [flat[offs[i]:offs[i + 1]].reshape(shapes[i])
                        for i in range(len(shapes))]
            self._programs_misc[key] = jax.jit(split)
        return self._programs_misc[key]

    def _wire_fetch_fn(self, enc):
        """Host side of the offload wire: ONE D2H of the concatenated
        payload, then chunk-aligned INCREMENTAL decode per leaf — under
        step_pipelined the decode of bucket i+1's span overlaps bucket
        i's sweep (the fetch lane's work), instead of one monolithic
        decode emptying the overlap (advisor r5)."""
        from .zero import wire_codec
        bits = self._offload_wire_bits
        masters = self._host_opt.opt.master
        payload, scales = enc
        CH = wire_codec.CHUNK
        total = sum(m.size for m in masters)
        n_chunks = -(-total // CH)
        pay_per_chunk = {8: CH, 4: CH // 2, 1: CH // 8}[bits]
        offs = np.cumsum([0] + [m.size for m in masters])
        state = {"wm": 0}                 # decoded-chunk watermark

        def fetch(k):
            if "buf" not in state:
                # persistent decode buffer: sized to the full master set,
                # allocated once per engine (a fresh multi-GB np.empty per
                # step would be recurring allocator cost on the hot path)
                if getattr(self, "_wire_buf", None) is None or \
                        self._wire_buf.shape[0] != n_chunks * CH:
                    self._wire_buf = np.empty(n_chunks * CH, np.float32)
                state["buf"] = self._wire_buf
                state["payload"] = np.asarray(payload)        # one D2H
                state["scales"] = np.asarray(scales)
            need = -(-int(offs[k + 1]) // CH)
            wm = state["wm"]
            if need > wm:
                wire_codec.decode_into(
                    state["buf"][wm * CH:need * CH],
                    state["payload"][wm * pay_per_chunk:
                                     need * pay_per_chunk],
                    state["scales"][wm:need], bits)
                state["wm"] = need
            return state["buf"][offs[k]:offs[k + 1]].reshape(
                masters[k].shape)
        return fetch

    def _offload_train_step(self, batch: Dict) -> Dict:
        """grads on device → host C++ optimizer sweep → params back.
        Reference: the cpu_offload step path of stage_1_and_2.py (grads to
        pinned host buffers, DeepSpeedCPUAdam.step, param copy-back)."""
        cfg = self._config
        if getattr(self, "_offload_grad_fn", None) is None:
            self._build_offload_grad_fn()
        gas = self.gradient_accumulation_steps
        scale = self._host_scaler.scale if self._host_scaler else 1.0
        wcb = cfg.wall_clock_breakdown
        step_i = int(self.state["step"])
        if wcb:
            self.timers("offload/grads").start()
        with trace_span("offload/grads", gas=gas):
            lsum, grads, gnorm_raw = self._offload_grad_fn(
                self.state, batch, jnp.asarray(scale, jnp.float32),
                jax.random.PRNGKey(step_i))

        # the host sweep needs loss/gnorm/lr on the host anyway — this
        # IS the step's sync boundary, so move all three over in ONE
        # batched host_transfer instead of three scattered float()
        # round trips (each a full device round trip on its own)
        stats = jnp.stack([lsum, gnorm_raw,
                           self.lr_schedule(jnp.asarray(step_i))])
        lsum_h, gnorm_h, lr_h = host_transfer(stats)
        denom = scale * gas
        gnorm = float(gnorm_h) / denom
        lr = float(lr_h)
        if wcb:
            self.timers("offload/grads").stop()  # the transfer synced
        # a non-finite norm skips the host sweep either because the fp16
        # scaler says so or because resilience hygiene does (bf16 offload
        # runs have no scaler but the same poisoned-masters failure mode)
        overflow = (not math.isfinite(gnorm)) and \
            ((self._host_scaler is not None
              and self._host_scaler.detect_overflow)
             or cfg.resilience.skip_nonfinite_grad_steps)
        if overflow:
            self.state["skipped"] = self.state["skipped"] + 1
        else:
            factor = 1.0
            if cfg.gradient_clipping and cfg.gradient_clipping > 0 \
                    and math.isfinite(gnorm):
                factor = min(1.0, cfg.gradient_clipping / max(gnorm, 1e-6))
            # overlapped sweep: bucket i+1 D2H || bucket i native Adam ||
            # bucket i-1 H2D (reference PipelinedOptimizerSwapper:55)
            fetch_fn = None
            if self._offload_wire_bits:
                grad_dev = grads                      # (payload, scales)
                fetch_fn = self._wire_fetch_fn(grads)
            else:
                grad_dev = jax.tree_util.tree_leaves(grads)
            for g in jax.tree_util.tree_leaves(grad_dev):
                try:
                    g.copy_to_host_async()
                except Exception:
                    pass
            emit_bf16 = self.compute_dtype == jnp.bfloat16
            up_dtype = (np.float16 if self.compute_dtype == jnp.float16
                        else None)
            if fetch_fn is not None and self.mesh.size == 1:
                # compressed wire + one chip = the latency-bound tunnel
                # config: per-leaf H2D uploads would pay ~n_leaves round
                # trips, so sweep everything and upload ONE flat vector,
                # split back to leaves on device. Multi-chip keeps the
                # pipelined per-bucket path (its wire is DMA, not a
                # tunnel, and the overlap wins).
                n_leaves = len(self._host_opt.opt.master)
                if wcb:
                    self.timers("offload/sweep").start()
                with trace_span("offload/host_sweep", bucketed=False):
                    outs = self._host_opt.step(
                        [fetch_fn(k) for k in range(n_leaves)], lr=lr,
                        grad_scale=denom / factor, emit_bf16=emit_bf16)
                if wcb:
                    self.timers("offload/sweep").stop()
                flat = np.concatenate(
                    [host_transfer(o).reshape(-1) for o in outs])
                if up_dtype is not None:
                    flat = flat.astype(up_dtype)
                with trace_span("offload/upload"):
                    new_leaves = self._upload_split_fn(flat.dtype)(flat)
            else:
                if wcb:
                    self.timers("offload/sweep").start()
                with trace_span("offload/host_sweep", bucketed=True):
                    new_leaves = self._host_opt.step_pipelined(
                        grad_dev, self._offload_shardings, lr=lr,
                        grad_scale=denom / factor,
                        emit_bf16=emit_bf16, upload_dtype=up_dtype,
                        fetch_fn=fetch_fn)
                if wcb:
                    self.timers("offload/sweep").stop()
            self.state["params"] = jax.tree_util.tree_unflatten(
                self._host_opt.treedef, new_leaves)
            self.state["step"] = self.state["step"] + 1
        if self._host_scaler is not None:
            self._host_scaler.update(overflow)

        metrics = {
            "loss": float(lsum_h) / denom,
            "grad_norm": gnorm,
            "lr": lr,
            "overflow": int(overflow),
            "loss_scale": scale,
        }
        self._last_metrics = metrics
        return metrics

    # ------------------------------------------------------------------
    # core step math (shared by fused train_step and compat step())
    # ------------------------------------------------------------------
    def _cast_for_compute(self, params):
        if self.compute_dtype == jnp.float32:
            return params
        return jax.tree_util.tree_map(
            lambda p: p.astype(self.compute_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)

    def _current_scale(self, state):
        """The live loss scale as a traced f32 scalar (1.0 when no scaler)."""
        if self.loss_scaler is not None:
            return state["scaler"].scale
        return jnp.asarray(1.0, jnp.float32)

    def _micro_loss(self, params, micro_batch, scale):
        loss = self._loss_fn(self._cast_for_compute(params), micro_batch)
        return loss * scale

    def _batch_spec_tree(self, batch):
        def spec(path, x):
            if path and getattr(path[-1], "key", None) == "moe_rng":
                return P(*([None] * np.ndim(x)))   # rng keys replicate
            nd = np.ndim(x)
            entries = [None] * nd
            if nd >= 2:
                entries[1] = self._batch_dim_spec
            return P(*entries)
        return jax.tree_util.tree_map_with_path(spec, batch)

    def _apply_grads(self, state, grads, n_micro: float, overflow=None):
        """Unscaled summed grads → clipped update → new state.

        Mirrors reference step path: CheckOverflow (`runtime/utils.py:170`),
        clip_grad_norm_ (`runtime/utils.py:325`), optimizer.step, loss-scale
        update, skip-on-overflow (`fp16/fused_optimizer.py`)."""
        cfg = self._config
        scale = self._current_scale(state)
        denom = scale * n_micro
        grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / denom, grads)
        grads = constrain(grads, self.mesh, self.grad_specs)

        if overflow is None:
            if self.loss_scaler is not None and \
                    self.loss_scaler.detect_overflow:
                overflow = DynamicLossScaler.has_overflow(grads)
            else:
                overflow = jnp.asarray(False)

        gnorm = global_norm(grads)
        if cfg.resilience.skip_nonfinite_grad_steps:
            # a NaN/Inf global norm means the update would poison params
            # AND optimizer moments — skip the step and count it in
            # state['skipped'] (the fp16 scaler catches this only when a
            # scaler exists; bf16/fp32 runs need the same protection)
            overflow = jnp.logical_or(jnp.asarray(overflow),
                                      jnp.logical_not(jnp.isfinite(gnorm)))
        if cfg.gradient_clipping and cfg.gradient_clipping > 0:
            clip = jnp.asarray(cfg.gradient_clipping, jnp.float32)
            factor = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * factor, grads)

        lr = self.lr_schedule(state["step"])
        new_params, new_opt = self.optimizer.apply(
            grads, state["opt"], state["params"], lr)
        new_params = constrain(new_params, self.mesh, self.master_specs)

        # skip update on overflow (fp16): keep old params/opt, still advance
        # the loss-scale state machine.
        def select(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
        new_params = select(new_params, state["params"])
        new_opt = select(new_opt, state["opt"])

        new_state = {"step": state["step"] + jnp.where(overflow, 0, 1),
                     "skipped": state.get(
                         "skipped", jnp.zeros((), jnp.int32))
                     + overflow.astype(jnp.int32),
                     "params": new_params, "opt": new_opt}
        if self.loss_scaler is not None:
            new_state["scaler"] = self.loss_scaler.update(
                state["scaler"], overflow)
        metrics = {"grad_norm": gnorm, "lr": lr,
                   "overflow": overflow.astype(jnp.int32),
                   "loss_scale": scale}
        return new_state, metrics

    def _build_train_step(self):
        if self.optimizer.hyperparams.get("onebit"):
            return self._build_onebit_train_step()
        gas = self.gradient_accumulation_steps

        def step_fn(state, batch):
            scale = self._current_scale(state)
            gsum, lsum = self._accumulate_micro_grads(state, batch, scale)
            new_state, metrics = self._apply_grads(state, gsum, float(gas))
            metrics["loss"] = lsum / (scale * gas)
            return new_state, metrics

        # Donated-buffer audit (ISSUE 11): state in / state out aliases the
        # params + opt leaves — always safe and always donated (the step
        # would otherwise hold 2x model state live across the update).
        # The BATCH is only donatable when the caller feeds fresh device
        # buffers every step; bench/autotune loops re-feed one batch, so
        # it is opt-in via training.donate_batch. The offload grad fn
        # (_build_offload_grad_fn) donates NOTHING: its state stays live
        # for the host optimizer sweep and its batch is reused.
        donate = (0, 1) if self._config.training.donate_batch else (0,)
        with self.mesh:
            self._train_step_fn = jax.jit(step_fn, donate_argnums=donate)
        _count_jit_build()
        return self._train_step_fn

    def _apply_training_overrides(self, model):
        """Rebuild ``model`` with the ``training`` block's model-side
        overrides (remat / fused_loss_head / loss_chunk). Mirrors
        Autotuner.apply_best: dataclass-config models are reconstructed
        via dataclasses.replace; models without one reject overrides
        loudly instead of silently ignoring a tuned config."""
        overrides = self._config.training.model_overrides()
        if not overrides:
            return model
        import dataclasses as _dc
        mcfg = getattr(model, "config", None)
        if mcfg is None or not _dc.is_dataclass(mcfg):
            raise ValueError(
                f"config has training overrides {sorted(overrides)} but "
                f"{type(model).__name__} has no dataclass .config to "
                f"rebuild from")
        applicable = {k: v for k, v in overrides.items()
                      if hasattr(mcfg, k)}
        missing = set(overrides) - set(applicable)
        if missing:
            raise ValueError(
                f"training overrides {sorted(missing)} have no matching "
                f"field on {type(mcfg).__name__}")
        if all(getattr(mcfg, k) == v for k, v in applicable.items()):
            return model
        return type(model)(_dc.replace(mcfg, **applicable),
                           getattr(model, "constrain", None))

    # ------------------------------------------------------------------
    # 1-bit Adam: shard_map'd step over the compression axis
    # ------------------------------------------------------------------
    def _onebit_program_key(self) -> str:
        """Phase key for the step ABOUT to run (1-based step index).
        OnebitAdam/Lamb: warmup|compress at the freeze boundary; 0/1-Adam:
        var|comp|local|sync from its host schedule."""
        opt = self.optimizer
        t = self.global_steps + 1
        if getattr(opt, "program_key", None) is not None:
            return opt.program_key(t)
        return "warmup" if t <= opt.freeze_step else "compress"

    def _build_onebit_train_step(self, key: Optional[str] = None):
        """Compiled step for 1-bit optimizers. Grads stay LOCAL to each
        ``comm_axis`` replica (partial-manual shard_map; other axes remain
        GSPMD-auto); the optimizer owns the cross-replica reduction —
        full-precision pmean in warmup/var phases, error-compensated 1-bit
        collectives elsewhere (reference fp16/onebit/{adam,zoadam,lamb}.py;
        nothing reduces grads twice). ONE program per phase key, cached —
        phase switches are host decisions between steps."""
        from jax.sharding import PartitionSpec as P
        opt = self.optimizer
        axis = opt.comm_axis
        gas = self.gradient_accumulation_steps
        w = self.mesh.shape.get(axis, 1)
        if self._config.gradient_clipping:
            logger.warning(
                "gradient_clipping is ignored by the 1-bit optimizer "
                "(momentum, not gradients, is communicated — same "
                "restriction as the reference)")
        if key is None:
            key = self._onebit_program_key()
        self._onebit_key = key
        if getattr(self, "_onebit_errors", None) is None:
            def espec(leaf):
                return P(axis, *([None] * (leaf.ndim - 1)))
            err_init = self._cached_program(
                "onebit_init_errors",
                lambda: jax.jit(
                    lambda: opt.init_errors(self._param_shapes, w)))
            with self.mesh:
                errs = err_init()
            shardings = jax.tree_util.tree_map(
                lambda l: NamedSharding(self.mesh, espec(l)), errs)
            self._onebit_errors = jax.device_put(errs, shardings)
        if getattr(self, "_onebit_compiled", None) is None:
            self._onebit_compiled = {}

        if key not in self._onebit_compiled:
            programs = getattr(opt, "programs", None) or {
                "warmup": (opt.apply, False),
                "compress": (opt.compression_apply, True)}
            apply_fn, uses_errors = programs[key]

            def core(state, errors, batch):
                # fp16 x 1-bit (reference fp16/onebit/adam.py under
                # FP16_Optimizer): scale the loss, unscale the local
                # grads, skip-on-overflow EVERYWHERE (the apply is a
                # collective, so overflow anywhere must skip all
                # replicas), advance the loss-scale state machine.
                scale = self._current_scale(state)
                gsum, lsum = self._accumulate_micro_grads(
                    state, batch, scale)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) / (gas * scale), gsum)
                if self.loss_scaler is not None and \
                        self.loss_scaler.detect_overflow:
                    local_over = DynamicLossScaler.has_overflow(grads)
                    overflow = jax.lax.pmax(
                        local_over.astype(jnp.int32), axis) > 0
                else:
                    overflow = jnp.asarray(False)
                lr = self.lr_schedule(state["step"])
                if uses_errors:
                    new_params, new_opt, new_errors = apply_fn(
                        grads, state["opt"], state["params"], lr, errors)
                else:
                    new_params, new_opt = apply_fn(
                        grads, state["opt"], state["params"], lr)
                    new_errors = errors

                def select(new, old):
                    return jax.tree_util.tree_map(
                        lambda n, o: jnp.where(overflow, o, n), new, old)
                new_params = select(new_params, state["params"])
                new_opt = select(new_opt, state["opt"])
                new_errors = select(new_errors, errors)
                new_state = {"step": state["step"]
                             + jnp.where(overflow, 0, 1),
                             "skipped": state["skipped"]
                             + overflow.astype(jnp.int32),
                             "params": new_params, "opt": new_opt}
                if self.loss_scaler is not None:
                    new_state["scaler"] = self.loss_scaler.update(
                        state["scaler"], overflow)
                loss = jax.lax.pmean(lsum, axis) / (gas * scale)
                # observability must not reintroduce the traffic 1-bit
                # removes: report the mean of per-replica local norms (one
                # scalar on the wire) — an upper bound on the norm of the
                # averaged gradient, documented as such.
                gnorm = jax.lax.pmean(global_norm(grads), axis)
                return new_state, new_errors, {
                    "loss": loss, "grad_norm": gnorm, "lr": lr,
                    "overflow": overflow.astype(jnp.int32),
                    "loss_scale": scale}

            state_specs = jax.tree_util.tree_map(lambda _: P(),
                                                 self.state_specs())
            err_in = jax.tree_util.tree_map(
                lambda l: P(axis), self._onebit_errors)

            def step_fn(state, errors, batch):
                bspec = jax.tree_util.tree_map(lambda _: P(None, axis),
                                               batch)
                sharded = shard_map(
                    core, mesh=self.mesh,
                    in_specs=(state_specs, err_in, bspec),
                    out_specs=(state_specs, err_in,
                               jax.tree_util.tree_map(
                                   lambda _: P(),
                                   {"loss": 0, "grad_norm": 0, "lr": 0,
                                    "overflow": 0, "loss_scale": 0})),
                    axis_names={axis})
                return sharded(state, errors, batch)

            with self.mesh:
                self._onebit_compiled[key] = jax.jit(step_fn,
                                                     donate_argnums=(0, 1))
            _count_jit_build()

        # error buffers re-zero when a reset-marked phase first activates
        # (reference reinitial_error_buffer, zoadam.py:324)
        if key in getattr(opt, "reset_errors_on", ()) and \
                not getattr(self, "_onebit_errors_reset", False):
            zero_fn = self._cached_program(
                "onebit_zero_errors",
                lambda: jax.jit(
                    lambda e: jax.tree_util.tree_map(jnp.zeros_like, e),
                    donate_argnums=(0,)))
            with self.mesh:
                self._onebit_errors = zero_fn(self._onebit_errors)
            self._onebit_errors_reset = True

        compiled = self._onebit_compiled[key]

        def run(state, batch):
            new_state, self._onebit_errors, metrics = compiled(
                state, self._onebit_errors, batch)
            return new_state, metrics

        self._train_step_fn = run
        return self._train_step_fn

    # ------------------------------------------------------------------
    # native API
    # ------------------------------------------------------------------
    def shard_batch(self, batch: Dict) -> Dict:
        """Host numpy batch [gas*micro*dp, ...] or [gas, B, ...] →
        device arrays sharded over the data axes."""
        gas = self.gradient_accumulation_steps
        global_b = self.train_batch_size
        # multi-host: each process supplies its LOCAL slice of the global
        # batch (launcher/dataloader contract, reference deepspeed.runtime
        # dataloader sharding)
        nproc = jax.process_count()
        local_b = global_b // nproc if nproc > 1 else global_b

        def prep(k, x):
            # deliberate host materialization: batches normally arrive
            # as host arrays (train_step only calls shard_batch when the
            # leaves are NOT jax.Array), so this is a coercion, not a
            # device round trip — and when a caller DOES hand a device
            # leaf, the sync is the documented contract of this helper
            x = host_transfer(x)
            if k == "moe_rng":
                # a single PRNG key: split into one key per microbatch so
                # gate randomness (RTS / RSample) differs across the GAS scan
                if x.shape == (2,):
                    x = host_transfer(jax.random.split(
                        jnp.asarray(x, jnp.uint32), gas))
                if x.shape != (gas, 2):
                    raise ValueError(
                        f"moe_rng must be a PRNG key (2,) or per-microbatch "
                        f"keys ({gas}, 2); got {x.shape}")
                return x.astype(np.uint32)
            if x.ndim >= 1 and x.shape[0] == local_b:
                return x.reshape((gas, local_b // gas) + x.shape[1:])
            if x.ndim >= 2 and x.shape[0] == gas:
                return x  # already [gas, micro*dp(_local), ...]
            raise ValueError(
                f"batch leading dim {x.shape[0]} matches neither the "
                f"process-local batch ({local_b}"
                f"{f' = {global_b}/{nproc} procs' if nproc > 1 else ''}) "
                f"nor [gas={gas}, ...] layout")
        batch = {k: prep(k, v) for k, v in batch.items()}
        shardings = to_named(self.mesh, self._batch_spec_tree(batch))
        if nproc > 1:
            # assemble global arrays from per-process shards — device_put
            # cannot write non-addressable shards
            def to_global(x, sharding):
                x = np.asarray(x)
                spec = sharding.spec
                gshape = list(x.shape)
                if len(spec) > 1 and spec[1] is not None:
                    gshape[1] = gshape[1] * nproc
                return jax.make_array_from_process_local_data(
                    sharding, x, tuple(gshape))
            return jax.tree_util.tree_map(to_global, batch, shardings)
        return jax.device_put(batch, shardings)

    def train_step(self, batch: Dict) -> Dict:
        """One full optimizer step (gas microbatches). Returns metrics dict
        of device scalars."""
        self._heartbeat.maybe_beat()
        if self.infinity_enabled:
            self.tput_timer.start()
            with trace_span("engine/train_step", mode="infinity",
                            step=self.global_steps):
                metrics = self._infinity.train_step(batch)
            self.tput_timer.stop()  # streamed step is synchronous
            self.global_steps += 1
            self.micro_steps += self.gradient_accumulation_steps
            if self._config.wall_clock_breakdown:
                self._step_times.append(metrics["step_time"])
            # on the ENGINE (the stepper keeps its own copy) — this is
            # what get_global_grad_norm() reads
            self._last_metrics = metrics
            self._post_step_observe(metrics, batch)
            return metrics
        if self.offload_enabled:
            if any(not isinstance(v, jax.Array) for v in
                   jax.tree_util.tree_leaves(batch)):
                with trace_span("engine/shard_batch"):
                    batch = self.shard_batch(batch)
            t0 = time.perf_counter()
            self.tput_timer.start()
            with trace_span("engine/train_step", mode="offload",
                            step=self.global_steps):
                metrics = self._offload_train_step(batch)
            self.tput_timer.stop()  # host step is synchronous already
            self.global_steps += 1
            self.micro_steps += self.gradient_accumulation_steps
            if self._config.wall_clock_breakdown:
                self._step_times.append(time.perf_counter() - t0)
            self._post_step_observe(metrics, batch)
            return metrics
        if self.optimizer.hyperparams.get("onebit"):
            key = self._onebit_program_key()
            if key != getattr(self, "_onebit_key", None) or \
                    self._train_step_fn is None:
                self._build_onebit_train_step(key)
        if self._train_step_fn is None:
            self._build_train_step()
        if any(not isinstance(v, jax.Array) for v in
               jax.tree_util.tree_leaves(batch)):
            with trace_span("engine/shard_batch"):
                batch = self.shard_batch(batch)
        else:
            gas = self.gradient_accumulation_steps
            for leaf in jax.tree_util.tree_leaves(batch):
                if leaf.ndim < 2 or leaf.shape[0] != gas:
                    raise ValueError(
                        f"device batch leaves must be [gas={gas}, "
                        f"micro*dp, ...]; got {leaf.shape} — pass host "
                        f"arrays or use engine.shard_batch()")
        t0 = time.perf_counter()
        self.tput_timer.start()
        # the fused step is ONE jitted program — fwd/bwd/allreduce/clip/
        # optimizer phases live inside XLA (the device profiler's job);
        # host-side the span pair splits enqueue from device wait
        with trace_span("engine/train_step", mode="fused",
                        step=self.global_steps):
            self.state, metrics = self._train_step_fn(self.state, batch)
        ovl_on = self._ovl.enabled
        # step_fn returned = async dispatch enqueued; the overlap
        # profiler's enqueue/device-wait boundary (no extra sync — the
        # wait end reuses the step_sync join below)
        t_enq = time.perf_counter() if ovl_on else 0.0
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps
        # sync whenever anything CONSUMES the timing (monitor, breakdown,
        # metrics registry, or the periodic print) — unsynced stop() would
        # time async-dispatch enqueue, inflating tok/s and MFU by orders
        # of magnitude
        sync = (self.monitor.enabled or self._config.wall_clock_breakdown
                or bool(self._config.steps_per_print) or self._obs.enabled
                or self._flight.enabled or self._ovl.enabled)
        if sync:
            with trace_span("engine/step_sync", step=self.global_steps):
                self.tput_timer.stop(sync=metrics["loss"])
            if ovl_on:
                # total = t0 -> after the sync join; wait = enqueue
                # boundary -> join.  Recorded only on synced steps — an
                # unsynced step has no join to measure against and the
                # profiler never adds one
                t_end = time.perf_counter()
                self._ovl.observe("train", total_s=t_end - t0,
                                  enqueue_s=t_enq - t0,
                                  wait_s=t_end - t_enq)
        else:
            self.tput_timer.stop()
        if self._config.wall_clock_breakdown:
            host_transfer(metrics["loss"], block=True)
            self._step_times.append(time.perf_counter() - t0)
        # keep get_global_grad_norm() current: the compat step() path and
        # the offload/infinity paths set this too
        self._last_metrics = metrics
        self._post_step_observe(metrics, batch)
        return metrics

    def _post_step_observe(self, metrics: Dict, batch) -> None:
        """Monitor events at the GAS boundary + periodic log line
        (reference engine.py:1938 loss writes, :2270 _write_monitor).
        Also the metrics-registry feed point: the step already synced
        (train_step's sync flag includes the registry), so the float()
        materializations below are cheap."""
        cfg = self._config
        do_print = cfg.steps_per_print and \
            self.global_steps % cfg.steps_per_print == 0
        obs = self._obs
        fr = self._flight
        if not (do_print or self.monitor.enabled or obs.enabled
                or fr.enabled):
            return
        m = {k: float(v) for k, v in metrics.items()}
        step = self.global_steps
        if fr.enabled:
            # black-box snapshot per optimizer step; a burst of
            # consecutive overflow-skipped steps dumps a post-mortem
            # bundle (the run is diverging or the scale is thrashing —
            # capture the evidence while the ring still holds it)
            fr.record({
                "kind": "train_step", "step": step, "t": time.time(),
                "loss": m.get("loss"), "grad_norm": m.get("grad_norm"),
                "loss_scale": m.get("loss_scale"),
                "overflow": bool(m.get("overflow")),
            })
            if m.get("overflow"):
                self._skip_burst += 1
                if self._skip_burst >= fr.skip_burst_steps:
                    fr.dump("skipped_step_burst",
                            f"{self._skip_burst} consecutive skipped "
                            f"steps ending at step {step}",
                            extra={"loss_scale": m.get("loss_scale"),
                                   "grad_norm": m.get("grad_norm")})
                    self._skip_burst = 0
            else:
                self._skip_burst = 0
        if obs.enabled:
            obs.counter("dstpu_train_steps_total").inc()
            if m.get("overflow"):
                obs.counter("dstpu_train_skipped_steps_total").inc()
            dt = self.tput_timer.last_step_time
            if dt is not None:
                obs.histogram("dstpu_step_time_seconds").observe(dt)
        if self.monitor.enabled:
            events = [("Train/loss", m["loss"], step),
                      ("Train/lr", m["lr"], step),
                      ("Train/grad_norm", m["grad_norm"], step),
                      ("Train/loss_scale", m.get("loss_scale", 1.0), step)]
            if self.tput_timer.timed_steps > 0:
                events.append(("Train/samples_per_sec",
                               self.tput_timer.samples_per_sec, step))
                if self.tput_timer.seq_length:
                    events.append(("Train/tokens_per_sec",
                                   self.tput_timer.tokens_per_sec, step))
                mfu = self._try_mfu(batch)
                if mfu is not None:
                    events.append(("Train/mfu", mfu, step))
            if obs.enabled:
                # registry scalars ride the existing fan-out — TB/CSV/W&B
                # get every counter/gauge/histogram-mean for free
                obs.collect()
                events.extend(obs.to_events(step))
            self.monitor.write_events(events)
            self.monitor.flush()
        if obs.enabled:
            from ..observability import (export_interval_steps,
                                         export_metrics)
            ivl = export_interval_steps()
            if ivl and step % ivl == 0:
                export_metrics()
        if do_print:
            extra = ""
            if self.tput_timer.timed_steps > 0:
                extra = f" tok/s={self.tput_timer.tokens_per_sec:,.0f}"
                mfu = self._try_mfu(batch)
                if mfu is not None:
                    extra += f" mfu={100 * mfu:.1f}%"
            logger.info(
                f"step={self.global_steps} loss={m['loss']:.4f} "
                f"lr={m['lr']:.3e} grad_norm={m['grad_norm']:.3f} "
                f"loss_scale={m.get('loss_scale', 1.0):.0f}{extra}")
            if cfg.wall_clock_breakdown and self.timers.timers:
                # named-timer breakdown; memory_breakdown (the config key)
                # appends the device/host memory snapshot to the line
                self.timers.log(sorted(self.timers.timers),
                                memory_breakdown=cfg.memory_breakdown)

    def _obs_collect(self) -> None:
        """Export-time refresh of derived gauges: device-memory watermark
        and comms wire volume. Host-side reads only — ``memory_stats``
        and the trace-time comms log never block on the device."""
        obs = self._obs
        try:
            stats = jax.devices()[0].memory_stats() or {}
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                obs.gauge("dstpu_device_peak_memory_bytes").set(float(peak))
        except Exception:
            pass
        from ..comm.comms_logging import get_comms_logger
        from ..observability import sanitize_name
        cl = get_comms_logger()
        if cl is not None:
            for op_name, sizes in cl.comms_dict.items():
                vol = sum(rec["volume"] for rec in sizes.values())
                obs.gauge(
                    f"dstpu_comm_volume_bytes_{sanitize_name(op_name)}",
                    help="trace-time comms payload volume (CommsLogger)",
                ).set(float(vol))

    def flush_observability(self, sync: bool = True):
        """Flush the span trace and metric exports
        (docs/observability.md). ``sync=True`` first joins the last
        step's loss via ``host_transfer(block=True)`` — the explicit
        flush-boundary device sync, so the trace covers fully-executed
        work. Returns the list of files written."""
        from ..observability import flush_all
        val = None
        if sync:
            last = getattr(self, "_last_metrics", None)
            if last:
                val = last.get("loss")
        return flush_all(sync=val)

    def _try_mfu(self, batch) -> Optional[float]:
        """Engine-reported MFU from ANALYTIC flops (6N + attention) — the
        bench script no longer owns this number (VERDICT missing #7).
        Deliberately not XLA cost analysis here: that would lower+compile a
        second copy of the train step mid-loop; the explicit FlopsProfiler
        API is where users pay that cost knowingly."""
        del batch
        if self.offload_enabled or self.infinity_enabled:
            return None  # offload step is host-bound; MFU is not the metric
        if self.tput_timer.timed_steps == 0:
            return None
        try:
            if self._analytic_flops_per_step is None:
                from ..profiling.flops_profiler.profiler import (
                    chip_peak_flops, transformer_flops_per_token)
                mcfg = getattr(self.model, "config", None)
                if mcfg is None or not hasattr(mcfg, "d_model"):
                    return None
                seq = self.tput_timer.seq_length or mcfg.max_seq_len
                self._analytic_flops_per_step = (
                    self.train_batch_size * seq *
                    transformer_flops_per_token(
                        self.num_parameters(), mcfg.num_layers,
                        mcfg.d_model, seq))
                self._peak_flops = chip_peak_flops() * max(
                    jax.device_count(), 1)
            return (self._analytic_flops_per_step /
                    self.tput_timer.avg_step_time / self._peak_flops)
        except Exception as e:  # observability must never kill training
            logger.debug(f"mfu unavailable: {e}")
            return None

    def train_batch(self, data_iter: Optional[Iterable] = None,
                    batch: Optional[Dict] = None) -> Dict:
        """Reference `PipelineEngine.train_batch`-style surface for plain DP:
        pull one global batch from the iterator and step."""
        if batch is None:
            if not hasattr(data_iter, "__next__"):
                # cache the iterator per loader so successive calls advance
                # through the data instead of restarting at batch 0
                if getattr(self, "_data_iter_src", None) is not data_iter:
                    self._data_iter_src = data_iter
                    self._data_iter = iter(data_iter)
                try:
                    batch = next(self._data_iter)
                except StopIteration:
                    self._data_iter = iter(data_iter)  # next epoch
                    batch = next(self._data_iter)
            else:
                batch = next(data_iter)
        return self.train_step(batch)

    def eval_loss(self, batch: Dict) -> jnp.ndarray:
        if self.infinity_enabled:
            return jnp.asarray(self._infinity.eval_loss(batch))
        if any(not isinstance(v, jax.Array)
               for v in jax.tree_util.tree_leaves(batch)):
            batch = self.shard_batch(batch)
        sq = jax.tree_util.tree_map(lambda x: x.reshape((-1,) + x.shape[2:]),
                                    batch)
        if not hasattr(self, "_eval_fn"):
            with self.mesh:
                self._eval_fn = jax.jit(lambda p, b: self._loss_fn(
                    self._cast_for_compute(p), b))
            _count_jit_build()
        return self._eval_fn(self.state["params"], sq)

    # ------------------------------------------------------------------
    # compat API: forward / backward / step  (reference engine.py:1761,
    # 1910, 2121). Each call is an independent jitted program.
    # ------------------------------------------------------------------
    def forward(self, batch: Dict) -> jnp.ndarray:
        if self.offload_enabled or self.infinity_enabled:
            raise NotImplementedError(
                "the compat forward/backward/step surface is not wired for "
                "offload — use train_step()/train_batch()")
        self._last_batch = batch if isinstance(
            next(iter(jax.tree_util.tree_leaves(batch))), jax.Array) \
            else jax.device_put(batch, to_named(
                self.mesh, jax.tree_util.tree_map(
                    lambda x: P(self._batch_dim_spec,), batch)))
        if self._grad_fn is None:
            def gfn(params, mb, scale):
                return jax.value_and_grad(self._micro_loss)(params, mb, scale)
            with self.mesh:
                self._grad_fn = jax.jit(gfn)
            _count_jit_build()
        scale = (self.state["scaler"].scale
                 if self.loss_scaler is not None else 1.0)
        with trace_span("engine/forward", micro_step=self.micro_steps):
            self._last_loss, self._last_grads = self._grad_fn(
                self.state["params"], self._last_batch, scale)
        return self._last_loss / scale if self.fp16_enabled else self._last_loss

    def backward(self, loss=None) -> None:
        """Accumulate the grads of the last forward into the GAS buffer."""
        del loss  # grads were produced alongside forward (jit has no tape)
        with trace_span("engine/backward", micro_step=self.micro_steps):
            grads = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                           self._last_grads)
            if self._grad_acc is None:
                self._grad_acc = grads
            else:
                # cache the jitted adder: jax.jit keys its compile cache on
                # the callable object, so a fresh lambda here meant a fresh
                # trace+compile EVERY microbatch (dstpu-lint TRACE003)
                if getattr(self, "_grad_acc_add_fn", None) is None:
                    with self.mesh:
                        self._grad_acc_add_fn = jax.jit(
                            lambda a, b: jax.tree_util.tree_map(jnp.add,
                                                                a, b),
                            donate_argnums=(0,))
                    _count_jit_build()
                with self.mesh:
                    self._grad_acc = self._grad_acc_add_fn(self._grad_acc,
                                                           grads)
        self._grad_acc_count += 1
        self.micro_steps += 1

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._grad_acc_count >= self.gradient_accumulation_steps

    def step(self) -> None:
        self._heartbeat.maybe_beat()
        if self._grad_acc is None:
            return
        if self._apply_fn is None:
            with self.mesh:
                self._apply_fn = jax.jit(
                    lambda st, g, n: self._apply_grads(st, g, n),
                    donate_argnums=(0, 1))
            _count_jit_build()
        with trace_span("engine/optimizer_step", step=self.global_steps):
            self.state, metrics = self._apply_fn(
                self.state, self._grad_acc,
                jnp.asarray(float(self._grad_acc_count), jnp.float32))
        self._grad_acc = None
        self._grad_acc_count = 0
        self.global_steps += 1
        self._last_metrics = metrics

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def get_lr(self) -> float:
        return float(self.lr_schedule(self.state["step"]))

    def get_global_grad_norm(self) -> Optional[float]:
        m = getattr(self, "_last_metrics", None)
        return float(m["grad_norm"]) if m else None

    @property
    def skipped_steps(self) -> int:
        return int(self.state.get("skipped", 0))

    @property
    def loss_scale(self) -> float:
        if self._host_scaler is not None:
            return self._host_scaler.scale
        if self.loss_scaler is None:
            return 1.0
        return float(self.state["scaler"].scale)

    def num_parameters(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self._param_shapes))

    # checkpointing lives in runtime/checkpoint_engine (wired by __init__.py)
    def save_checkpoint(self, save_dir, tag=None, client_state=None):
        from .checkpoint_engine.engine import save_checkpoint as _save
        # a multi-GB checkpoint write is the longest legitimate gap
        # between train steps — bracket it with beats so the elastic
        # agent's watchdog doesn't read it as a hang
        self._heartbeat.beat_now()
        try:
            return _save(self, save_dir, tag=tag,
                         client_state=client_state or {})
        finally:
            self._heartbeat.beat_now()

    def load_checkpoint(self, load_dir, tag=None, **kw):
        from .checkpoint_engine.engine import load_checkpoint as _load
        self._heartbeat.maybe_beat()
        return _load(self, load_dir, tag=tag, **kw)
