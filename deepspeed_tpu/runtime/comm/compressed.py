"""Error-feedback sign-compressed allreduce.

Role-equivalent of the reference 1-bit compression backends
(`/root/reference/deepspeed/runtime/comm/nccl.py:52-204`
NcclBackend.compressed_allreduce, `mpi.py` MpiBackend): the two-stage
worker/server scheme of 1-bit Adam —

  1. worker compensates its tensor with local error feedback, compresses to
     sign + per-chunk scale, keeps the new compression error;
  2. all_to_all moves chunk i of every worker to device i (the "server"
     for that chunk);
  3. the server averages the decompressed worker chunks, adds ITS error
     feedback, recompresses (sign + scale), keeps the server error;
  4. all_gather broadcasts the recompressed chunks back.

TPU-native shape: a pure function usable inside `shard_map` manual over the
compression axis (meant for ``dcn_data`` — ICI is fast enough that exact
reduction wins there; DCN is where 1-bit pays). Signs travel as int8, so
wire volume per direction is n/w bytes + one f32 scale per chunk vs 4n
bytes for fp32 allreduce — the reference's ~26x compression.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def compression_ratio(numel: int, world: int) -> float:
    """Per-device wire bytes, compressed / exact fp32 allreduce.

    Exact ring allreduce moves 2·4·n·(w-1)/w bytes per device. Compressed:
    the all_to_all ships n·(w-1)/w int8 sign bytes + (w-1) f32 scales out,
    and the all_gather returns the same — int8 instead of fp32 in each
    direction = 1/4 wire cost (the reference packs signs to 1 BIT via cupy
    packbits for ~26x; int8 is the TPU-collective-friendly format)."""
    exact = 2 * 4.0 * numel * (world - 1) / world
    compressed = 2 * (numel * (world - 1) / world + 4.0 * (world - 1))
    return compressed / max(exact, 1e-9)


def compressed_allreduce(
        x: jnp.ndarray, worker_error: jnp.ndarray,
        server_error: jnp.ndarray, axis: str
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Inside shard_map (manual over ``axis``). x: the local tensor (same
    shape on every device, values differ); errors: local error-feedback
    buffers shaped like x (worker) and x/w (server). Returns the averaged
    tensor (identical on all devices) + updated error buffers.
    """
    w = jax.lax.psum(1, axis)
    n = x.size
    if n % w:
        raise ValueError(f"tensor size {n} must divide by axis size {w}")
    chunk = n // w
    flat = x.reshape(-1).astype(jnp.float32)

    # -- stage 1: worker compression (reference nccl.py:86-117) ----------
    buf = flat + worker_error.reshape(-1)
    chunks = buf.reshape(w, chunk)
    scales = jnp.linalg.norm(chunks, axis=1) / jnp.sqrt(float(chunk))
    signs = jnp.where(chunks >= 0, 1.0, -1.0)
    decompressed = signs * scales[:, None]
    new_worker_error = (buf - decompressed.reshape(-1)).reshape(x.shape)

    # -- stage 2: all_to_all signs+scales to chunk servers ----------------
    # row j of the result is worker j's chunk destined for THIS device
    signs_i8 = signs.astype(jnp.int8)                      # wire format
    recv_signs = jax.lax.all_to_all(signs_i8, axis, split_axis=0,
                                    concat_axis=0).reshape(w, chunk)
    recv_scales = jax.lax.all_to_all(scales, axis, split_axis=0,
                                     concat_axis=0).reshape(w)

    # -- stage 3: server average + recompression (nccl.py:141-171) --------
    avg = jnp.mean(recv_signs.astype(jnp.float32)
                   * recv_scales[:, None], axis=0)          # [chunk]
    sbuf = avg + server_error.reshape(-1)
    sscale = jnp.linalg.norm(sbuf) / jnp.sqrt(float(chunk))
    ssign = jnp.where(sbuf >= 0, 1.0, -1.0)
    new_server_error = (sbuf - ssign * sscale).reshape(server_error.shape)

    # -- stage 4: all_gather the recompressed chunks ----------------------
    out_signs = jax.lax.all_gather(ssign.astype(jnp.int8), axis)  # [w,chunk]
    out_scales = jax.lax.all_gather(sscale, axis)                 # [w]
    out = (out_signs.astype(jnp.float32)
           * out_scales[:, None]).reshape(x.shape)
    return out.astype(x.dtype), new_worker_error.astype(x.dtype), \
        new_server_error.astype(jnp.float32)
