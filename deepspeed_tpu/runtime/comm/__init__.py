"""Compressed communication backends — counterpart of
`/root/reference/deepspeed/runtime/comm/`."""
from .compressed import compressed_allreduce, compression_ratio

__all__ = ["compressed_allreduce", "compression_ratio"]
