"""Memory-mapped indexed dataset (.bin + .idx).

Role-equivalent of the reference's Megatron-format ``indexed_dataset``
(`/root/reference/deepspeed/runtime/data_pipeline/data_sampling/
indexed_dataset.py`, 645 LoC): token sequences in one flat binary file with
an index of per-document offsets, read zero-copy via numpy memmap. The
format here is self-describing and little-endian:

  .idx: magic b'DSTPUIDX', version u32, dtype code u32, doc count u64,
        then u64 offsets[count + 1] (in elements, prefix-sum style)
  .bin: the concatenated token values
"""
from __future__ import annotations

import os
import struct
from typing import Iterable, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.uint16, 7: np.uint32}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class IndexedDatasetBuilder:
    """Streaming writer (reference IndexedDatasetBuilder)."""

    def __init__(self, path_prefix: str, dtype=np.uint16):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(path_prefix + ".bin", "wb")
        self._offsets = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._bin.write(arr.tobytes())
        self._offsets.append(self._offsets[-1] + arr.size)

    def finalize(self) -> None:
        self._bin.close()
        with open(self.prefix + ".idx", "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<II", _VERSION, _CODES[self.dtype]))
            f.write(struct.pack("<Q", len(self._offsets) - 1))
            f.write(np.asarray(self._offsets, np.uint64).tobytes())


class MMapIndexedDataset:
    """Zero-copy reader: ds[i] → np array of document i's tokens."""

    def __init__(self, path_prefix: str):
        idx_path = path_prefix + ".idx"
        with open(idx_path, "rb") as f:
            if f.read(8) != _MAGIC:
                raise ValueError(f"{idx_path}: bad magic")
            version, code = struct.unpack("<II", f.read(8))
            if version != _VERSION:
                raise ValueError(f"{idx_path}: version {version}")
            (count,) = struct.unpack("<Q", f.read(8))
            self._offsets = np.frombuffer(
                f.read(8 * (count + 1)), dtype=np.uint64)
        self.dtype = np.dtype(_DTYPES[code])
        self._data = np.memmap(path_prefix + ".bin", dtype=self.dtype,
                               mode="r")

    def __len__(self) -> int:
        return len(self._offsets) - 1

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(f"document {i} out of range [0, {n})")
        lo, hi = int(self._offsets[i]), int(self._offsets[i + 1])
        return self._data[lo:hi]

    @property
    def sizes(self) -> np.ndarray:
        return np.diff(self._offsets).astype(np.int64)


def write_dataset(path_prefix: str, documents: Iterable[Sequence[int]],
                  dtype=np.uint16) -> None:
    b = IndexedDatasetBuilder(path_prefix, dtype)
    for doc in documents:
        b.add_item(doc)
    b.finalize()
