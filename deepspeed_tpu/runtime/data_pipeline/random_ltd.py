"""Random layerwise token dropping (random-LTD).

Role-equivalent of the reference random-LTD
(`/root/reference/deepspeed/runtime/data_pipeline/data_routing/
basic_layer.py:117` RandomLayerTokenDrop + the gather/scatter CUDA kernels
in `csrc/random_ltd/`): during training, middle layers process a random
subset of tokens; the dropped tokens bypass the layer and are scattered
back afterwards. On TPU the kernels collapse to `jnp.take_along_axis` /
scatter — gather/scatter of [B, keep, D] is XLA-native.

The kept-token count follows a linear schedule from ``start_ratio`` to 1.0
over ``schedule_steps`` (the reference's seq-length schedule), snapped to
``granularity`` for shape reuse.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RandomLTDConfig:
    enabled: bool = False
    start_ratio: float = 0.5       # fraction of tokens kept at step 0
    schedule_steps: int = 10000
    granularity: int = 16          # kept-count rounded to a multiple
    # first/last layers always see all tokens (reference keeps the ends)
    skip_first_layers: int = 1
    skip_last_layers: int = 1


def kept_tokens_at(cfg: RandomLTDConfig, seq_len: int, step: int) -> int:
    """Host-side schedule: kept token count for this step (static per
    compiled program — a new count recompiles, so granularity matters)."""
    frac = min(max(step, 0) / max(cfg.schedule_steps, 1), 1.0)
    ratio = cfg.start_ratio + frac * (1.0 - cfg.start_ratio)
    keep = int(seq_len * ratio) // cfg.granularity * cfg.granularity
    return min(max(keep, cfg.granularity), seq_len)


def sample_indices(rng, batch: int, seq_len: int,
                   keep: int) -> jnp.ndarray:
    """[B, keep] sorted random token indices (reference token_sort.cu)."""
    def one(key):
        return jnp.sort(jax.random.permutation(key, seq_len)[:keep])
    return jax.vmap(one)(jax.random.split(rng, batch))


def gather_tokens(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """x [B, T, D], idx [B, keep] → [B, keep, D]
    (reference gather_scatter.cu gather path)."""
    return jnp.take_along_axis(x, idx[..., None], axis=1)


def scatter_tokens(full: jnp.ndarray, part: jnp.ndarray,
                   idx: jnp.ndarray) -> jnp.ndarray:
    """Write the processed kept tokens back into the full stream."""
    return jax.vmap(lambda f, p, i: f.at[i].set(p))(full, part, idx)


def random_ltd_layer(layer_fn, x: jnp.ndarray, rng,
                     keep: int) -> jnp.ndarray:
    """Run ``layer_fn`` on a random token subset; dropped tokens pass
    through unchanged (the residual identity of the reference)."""
    b, t = x.shape[0], x.shape[1]
    if keep >= t:
        return layer_fn(x)
    idx = sample_indices(rng, b, t, keep)
    part = layer_fn(gather_tokens(x, idx))
    return scatter_tokens(x, part, idx)
