"""Curriculum learning scheduler.

Role-equivalent of the reference ``CurriculumScheduler``
(`/root/reference/deepspeed/runtime/data_pipeline/curriculum_scheduler.py`):
difficulty (e.g. sequence length) ramps from ``min_difficulty`` to
``max_difficulty`` under a schedule — fixed_linear, fixed_root,
fixed_discrete, or custom — and the value is snapped down to a multiple of
``difficulty_step`` (TPU-relevant: keeps seqlen tile-aligned so XLA reuses
compiled programs).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, Optional


class CurriculumScheduler:
    def __init__(self, config: Dict):
        self.enabled = bool(config.get("enabled", True))
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        sc = config.get("schedule_config", {})
        self.total_steps = int(sc.get("total_curriculum_step", 1)) or 1
        self.difficulty_step = int(sc.get("difficulty_step", 1)) or 1
        self.root_degree = int(sc.get("root_degree", 2))
        self.discrete_difficulties = sc.get("difficulty", [])
        self.discrete_steps = sc.get("max_step", [])
        self._custom: Optional[Callable[[int], int]] = config.get(
            "custom_get_difficulty")
        if self.schedule_type == "fixed_discrete" and \
                len(self.discrete_difficulties) != \
                len(self.discrete_steps) + 1:
            raise ValueError(
                "fixed_discrete needs len(difficulty) == len(max_step) + 1")
        if self.schedule_type == "custom" and self._custom is None:
            raise ValueError("custom schedule needs custom_get_difficulty")

    def _snap(self, d: float) -> int:
        d = int(d // self.difficulty_step * self.difficulty_step)
        return max(self.min_difficulty,
                   min(d, self.max_difficulty))

    def get_difficulty(self, global_step: int) -> int:
        if not self.enabled:
            return self.max_difficulty
        t = min(max(global_step, 0), self.total_steps)
        frac = t / self.total_steps
        if self.schedule_type == "fixed_linear":
            d = self.min_difficulty + frac * (self.max_difficulty
                                              - self.min_difficulty)
        elif self.schedule_type == "fixed_root":
            d = self.min_difficulty + (frac ** (1.0 / self.root_degree)) * \
                (self.max_difficulty - self.min_difficulty)
        elif self.schedule_type == "fixed_discrete":
            d = self.discrete_difficulties[-1]
            for diff, step in zip(self.discrete_difficulties,
                                  self.discrete_steps):
                if global_step <= step:
                    d = diff
                    break
            return int(d)   # discrete values are used verbatim
        elif self.schedule_type == "custom":
            return int(self._custom(global_step))
        else:
            raise ValueError(f"unknown schedule {self.schedule_type}")
        return self._snap(math.ceil(d))

    def truncate_batch(self, batch: Dict, global_step: int,
                       seq_keys=("input_ids", "labels", "loss_mask")):
        """Apply the current difficulty as a sequence-length truncation
        (the reference's legacy curriculum seqlen path, engine.py:1800)."""
        d = self.get_difficulty(global_step)
        out = dict(batch)
        for k in seq_keys:
            if k in out and out[k].ndim >= 2 and out[k].shape[-1] > d:
                out[k] = out[k][..., :d]
        return out
