"""Data efficiency — counterpart of
`/root/reference/deepspeed/runtime/data_pipeline/`."""
from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import (DataAnalyzer, DeepSpeedDataSampler,
                           curriculum_batches)
from .indexed_dataset import (IndexedDatasetBuilder, MMapIndexedDataset,
                              write_dataset)
from .random_ltd import (RandomLTDConfig, gather_tokens, kept_tokens_at,
                         random_ltd_layer, sample_indices, scatter_tokens)

__all__ = ["CurriculumScheduler", "DataAnalyzer", "DeepSpeedDataSampler",
           "curriculum_batches", "IndexedDatasetBuilder",
           "MMapIndexedDataset", "write_dataset", "RandomLTDConfig",
           "kept_tokens_at", "sample_indices", "gather_tokens",
           "scatter_tokens", "random_ltd_layer"]
