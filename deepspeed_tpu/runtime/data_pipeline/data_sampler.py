"""Difficulty-indexed data analysis + curriculum SAMPLING.

Role-equivalent of the reference data-efficiency pair
(`/root/reference/deepspeed/runtime/data_pipeline/data_sampling/
data_analyzer.py:18` DataAnalyzer — an offline pass computing per-sample
difficulty metrics and writing index maps — and `data_sampler.py:33`
DeepSpeedDataSampler — a sampler that, each step, draws the batch only
from samples whose difficulty is within the curriculum's current bound,
deterministically and sharded across data-parallel ranks).

The round-2 curriculum here only TRUNCATED batches (sequence-length
curriculum); this module adds the reference's stronger capability: the
curriculum *selects data*. Redesign notes:

  - The analyzer stores, per metric: a ``<name>_values.npy`` (metric per
    sample) and ``<name>_order.npy`` (sample ids sorted by metric) — the
    reference's index-to-sample map collapses to a prefix of the sorted
    order, found by binary search on the sorted values.
  - Sampling is a pure function of (seed, step): every rank computes the
    same global batch and takes its contiguous slice — no broadcast, same
    determinism contract as the reference's deterministic shuffle.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, Iterator, Optional, Sequence

import numpy as np

from ...utils.logging import logger


class DataAnalyzer:
    """Offline metric pass (reference data_analyzer.py:18).

    ``metric_functions``: name → fn(sample) -> scalar difficulty. Built-in
    name "seqlen" needs no function (uses len(sample))."""

    def __init__(self, dataset, save_path: str,
                 metric_functions: Optional[Dict[str, Callable]] = None):
        self.dataset = dataset
        self.save_path = save_path
        self.metric_functions = dict(metric_functions or {})
        if not self.metric_functions:
            self.metric_functions = {"seqlen": len}

    def run(self) -> Dict[str, str]:
        """Compute every metric over the dataset; write value + order
        files. Returns {metric: path_prefix}."""
        os.makedirs(self.save_path, exist_ok=True)
        n = len(self.dataset)
        out = {}
        for name, fn in self.metric_functions.items():
            values = np.empty(n, np.float64)
            for i in range(n):
                values[i] = float(fn(self.dataset[i]))
            order = np.argsort(values, kind="stable").astype(np.int64)
            vpath = os.path.join(self.save_path, f"{name}_values.npy")
            opath = os.path.join(self.save_path, f"{name}_order.npy")
            np.save(vpath, values)
            np.save(opath, order)
            out[name] = os.path.join(self.save_path, name)
            logger.info(f"DataAnalyzer: metric '{name}' over {n} samples "
                        f"-> [{values.min():.3g}, {values.max():.3g}]")
        return out

    @staticmethod
    def load(save_path: str, metric: str):
        values = np.load(os.path.join(save_path, f"{metric}_values.npy"))
        order = np.load(os.path.join(save_path, f"{metric}_order.npy"))
        return values, order


class DeepSpeedDataSampler:
    """Curriculum-bounded deterministic sampler (reference
    data_sampler.py:33).

    Each ``sample_batch(step)`` draws ``global_batch_size`` sample ids
    uniformly from the pool {i : metric[i] <= difficulty(step)} (value
    mode) or the easiest ``difficulty(step)`` PERCENT of samples
    (percentile mode), then returns this rank's contiguous shard. The draw
    is a pure function of (seed, step) — identical on every rank, across
    restarts, and after checkpoint resume."""

    def __init__(self, values: np.ndarray, order: np.ndarray,
                 curriculum: "CurriculumScheduler",
                 global_batch_size: int,
                 difficulty_type: str = "value",
                 dp_rank: int = 0, dp_world: int = 1, seed: int = 1234):
        from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
        if difficulty_type not in ("value", "percentile"):
            raise ValueError(
                f"difficulty_type must be value|percentile, got "
                f"{difficulty_type}")
        if global_batch_size % dp_world:
            raise ValueError(f"global batch {global_batch_size} must "
                             f"divide by dp_world {dp_world}")
        self.values = np.asarray(values)
        self.order = np.asarray(order)
        self.sorted_values = self.values[self.order]
        self.curriculum = curriculum
        self.global_batch_size = int(global_batch_size)
        self.difficulty_type = difficulty_type
        self.dp_rank, self.dp_world = int(dp_rank), int(dp_world)
        self.seed = int(seed)

    def pool_size(self, step: int) -> int:
        d = self.curriculum.get_difficulty(step)
        n = len(self.order)
        if self.difficulty_type == "percentile":
            k = int(np.ceil(n * min(max(d, 0), 100) / 100.0))
        else:
            k = int(np.searchsorted(self.sorted_values, d, side="right"))
        return max(k, 1)   # never an empty pool: easiest sample qualifies

    def sample_batch(self, step: int) -> np.ndarray:
        """Global-batch sample ids for ``step``, this rank's shard."""
        k = self.pool_size(step)
        rng = np.random.default_rng((self.seed, step))
        pool = self.order[:k]
        replace = k < self.global_batch_size
        picks = rng.choice(k, size=self.global_batch_size, replace=replace)
        batch = pool[picks]
        per = self.global_batch_size // self.dp_world
        return batch[self.dp_rank * per:(self.dp_rank + 1) * per]

    def __iter__(self) -> Iterator[np.ndarray]:
        step = 0
        while True:
            yield self.sample_batch(step)
            step += 1


def curriculum_batches(dataset, sampler: DeepSpeedDataSampler,
                       collate: Optional[Callable] = None
                       ) -> Iterator:
    """Convenience: sample ids → actual batches from the dataset.
    ``collate`` defaults to right-padding token sequences with 0 into
    [B, max_len] int32 (the indexed-dataset document shape)."""
    def default_collate(samples):
        mx = max(len(s) for s in samples)
        out = np.zeros((len(samples), mx), np.int32)
        mask = np.zeros((len(samples), mx), np.float32)
        for i, s in enumerate(samples):
            out[i, :len(s)] = s
            mask[i, :len(s)] = 1.0
        return {"input_ids": out, "loss_mask": mask}

    collate = collate or default_collate
    for ids in sampler:
        yield collate([dataset[int(i)] for i in ids])
