"""Pipeline-parallel runtime package.

Exports the light bookkeeping surface only: the stage/replica/shard
grid and the schedule instruction set. ``PipelineEngine`` itself stays
a deliberate deep import (``runtime.pipe.engine``) — it pulls the full
training-engine stack, and ``ds.initialize`` already dispatches to it
whenever the mesh's ``pipe`` axis is >= 2.
"""
from . import schedule  # noqa: F401
from .topology import (PipelineParallelGrid,  # noqa: F401
                       grid_sizes_from_mesh)

__all__ = ["PipelineParallelGrid", "grid_sizes_from_mesh", "schedule"]
