"""Pipeline module expression.

Reference surface: ``LayerSpec`` (`/root/reference/deepspeed/runtime/pipe/
module.py:24`), ``TiedLayerSpec``, ``PipelineModule`` (`module.py:86`) with
layer partitioning by 'parameters' | 'uniform' | 'type:regex'
(`_partition_layers` :365, balancing via `partition_balanced`
`runtime/utils.py:639`).

TPU redesign: a pipeline stage is not a set of processes executing a module
shard — it is a slice of a **stage-stacked parameter pytree** (leaves carry a
leading ``[S, layers_per_stage, ...]`` axis, sharded over the ``pipe`` mesh
axis) driven by one compiled microbatch loop (see `pipe/engine.py`). This
module computes the partition (which layer goes to which stage) and builds
the stacked pytree; tied layers (`TiedLayerSpec`) stay replicated over
``pipe`` — shard_map's transpose then produces exactly the reference's
tied-gradient all-reduce (`pipe/engine.py:233` _exec_reduce_tied_grads).
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..utils import partition_balanced, partition_uniform, tree_param_count


class LayerSpec:
    """Deferred layer: build params with ``init(rng)``, run with
    ``apply(params, x)``. Reference `pipe/module.py:24` (defers nn.Module
    construction so only the owning stage materializes weights — here
    materialization is sharded by jit, so the deferral is just structure)."""

    def __init__(self, init_fn: Callable, apply_fn: Callable,
                 typename: str = "Layer"):
        self.init_fn = init_fn
        self.apply_fn = apply_fn
        self.typename = typename

    def build(self, rng):
        return self.init_fn(rng)

    def param_count(self) -> int:
        shapes = jax.eval_shape(lambda: self.init_fn(jax.random.PRNGKey(0)))
        return tree_param_count(shapes)


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages by key (reference
    `pipe/module.py:56` — e.g. tied input/output embeddings)."""

    def __init__(self, key: str, init_fn, apply_fn, typename="TiedLayer",
                 forward_fn: Optional[Callable] = None):
        super().__init__(init_fn, apply_fn, typename)
        self.key = key
        self.forward_fn = forward_fn or apply_fn


def partition_layers(layer_specs: Sequence[LayerSpec], num_stages: int,
                     method: str = "parameters") -> List[int]:
    """Stage boundaries over the layer list. Reference
    `pipe/module.py:365` _partition_layers."""
    n = len(layer_specs)
    method = method.lower()
    if method == "uniform":
        return partition_uniform(n, num_stages)
    if method == "parameters":
        weights = [max(1, s.param_count()) for s in layer_specs]
        return partition_balanced(weights, num_stages)
    if method.startswith("type:"):
        pat = re.compile(method[5:], re.IGNORECASE)
        weights = [1 if pat.search(s.typename) else 0 for s in layer_specs]
        return partition_balanced([max(w, 0) + 1e-9 for w in weights],
                                  num_stages)
    raise ValueError(f"Unknown partition method {method}")


class PipelineModule:
    """A model expressed as a flat layer list, to be executed by the
    pipeline engine. Reference `pipe/module.py:86`.

    The engine currently requires homogeneous stages (equal layer counts and
    matching layer param structures) so stages stack into one scanned pytree
    — the partition method still decides WHICH layers group together, and
    `boundaries` is exposed for inspection/tests.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int,
                 partition_method: str = "parameters",
                 loss_fn: Optional[Callable] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        self.boundaries = partition_layers(self.layer_specs, num_stages,
                                           partition_method)
        self.tied_keys = sorted({s.key for s in self.layer_specs
                                 if isinstance(s, TiedLayerSpec)})

    def stage_layers(self, stage_id: int) -> List[LayerSpec]:
        lo, hi = self.boundaries[stage_id], self.boundaries[stage_id + 1]
        return self.layer_specs[lo:hi]

    def init(self, rng) -> Dict[str, Any]:
        """Build {"tied": {key: params}, "stages": [per-stage layer param
        lists]} — the engine stacks homogeneous stages afterwards."""
        keys = jax.random.split(rng, len(self.layer_specs) + 1)
        tied: Dict[str, Any] = {}
        stages = []
        for sid in range(self.num_stages):
            lo, hi = self.boundaries[sid], self.boundaries[sid + 1]
            layer_params = []
            for li in range(lo, hi):
                spec = self.layer_specs[li]
                if isinstance(spec, TiedLayerSpec):
                    if spec.key not in tied:
                        tied[spec.key] = spec.build(keys[li])
                    layer_params.append({"__tied__": spec.key})
                else:
                    layer_params.append(spec.build(keys[li]))
            stages.append(layer_params)
        return {"tied": tied, "stages": stages}
