"""Pipeline-parallel engine.

Reference: ``PipelineEngine`` (`/root/reference/deepspeed/runtime/pipe/
engine.py:37`, 1376 LoC) — an instruction interpreter that exchanges
activations over NCCL p2p (`pipe/p2p.py:49,70`) with a meta-shape handshake
(`engine.py:827`), executes 1F1B instruction lists, reduces tied grads
(`engine.py:233`) and DP grads per boundary.

TPU-native redesign: the whole schedule is a single compiled program.

  - stages = slices of a stage-stacked param pytree, sharded over the
    ``pipe`` mesh axis (see `pipe/module.py`);
  - activation exchange = `lax.ppermute` shift-by-one inside a `lax.scan`
    over schedule ticks (fill-drain/GPipe dataflow; the scan carry IS the
    reference's pipe buffer);
  - microbatch loop memory = scan residuals, bounded by the model's remat
    policy (reference couples this to activation checkpointing the same way);
  - tied-weight grad all-reduce = automatic: tied params enter `shard_map`
    replicated over ``pipe``, so its transpose emits the psum
    (reference's _exec_reduce_tied_grads);
  - 3D composition (dense models): the region is manual over the FULL
    ``(pipe, model, data)`` product. Each stage's forward/backward is a
    tensor-parallel program over ``model`` (per-shard head counts via
    ``tp_train_view``, exact gradients via the ``copy_to``/``reduce_from``
    pair in `parallel/collectives.py`, vocab-parallel embed + CE), the
    microbatch dim is sharded over the ``data`` product, and gradients
    leave the region through ONE collective per axis family: stage
    boundaries ride ``ppermute`` on ``pipe``, per-layer TP psums stay on
    ``model``, and the DP gradient reduction is a psum — or a ZeRO-2
    ``psum_scatter`` straight into the policy's grad layout
    (`zero/sharding.grad_reduce_plan`) — on ``data``. Three collective
    families, three axes, zero contention.
  - MoE models keep the previous region (manual over ``pipe`` only,
    gpipe schedule) byte-for-byte — their expert/data axes stay auto.

Bubble math matches TrainSchedule: M microbatches over S stages run
M + S - 1 ticks (forward); backward retraces the same ticks in reverse.
``measure_bubble_fraction`` turns that from arithmetic into a measured
gauge (``dstpu_train_bubble_frac``) via a two-point slope fit.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...models import layers as L
from ...observability import trace_span
from ...parallel import collectives as C
from ...parallel import topology as topo
from ...parallel.shard_map_compat import shard_map
from ..engine import DeepSpeedEngine, _count_jit_build, global_norm
from ..zero.sharding import constrain, grad_reduce_plan


def chunked_ce(proj, norm_fn, ln_params, y, tok, chunk, onehot,
               tp_axis=None):
    """Shared head loss of BOTH pipeline schedules: final norm + chunked
    cross-entropy over `chunk`-token slices (the [mb, chunk, V] logits
    block is the only live vocab tensor). Returns (sum_nll, token_count).

    ``proj``: x → logits; ``onehot``: extract the target logit via a
    one-hot product instead of take_along_axis (gathers along a
    vocab-sharded dim crash the SPMD partitioner under manual axes).

    ``tp_axis``: vocab-parallel mode for the 3D engine — ``proj`` maps
    shard-local ``x`` to LOCAL ``[.., V/mp]`` logits and the softmax
    statistics reduce over the model axis (Megatron's vocab-parallel CE:
    shard-max via pmax on a stop_gradient'd copy, log-sum-exp and the
    target logit via ``reduce_from`` so backward stays exact; the
    one-hot of ``label - lo`` is all-zero off-shard).  The full [.., V]
    logits tensor never materializes."""
    mb, t = tok.shape
    x = norm_fn(ln_params, y)
    labels = jnp.concatenate([tok[:, 1:], jnp.zeros_like(tok[:, :1])],
                             axis=1)
    mask = jnp.ones((mb, t), jnp.float32).at[:, -1].set(0.0)
    n_chunks = t // chunk
    if tp_axis is not None:
        fin = C.copy_to(tp_axis)
        red = C.reduce_from(tp_axis)

    def to_chunks(a):
        return a.reshape(mb, n_chunks, chunk, *a.shape[2:]).swapaxes(0, 1)

    def body(carry, xs):
        xc, yc, mc = xs
        if tp_axis is not None:
            logits = proj(fin(xc))             # local [mb, chunk, V/mp]
            vloc = logits.shape[-1]
            lo = jax.lax.axis_index(tp_axis) * vloc
            # stop_gradient INSIDE the pmax: pmax has no JVP rule, so a
            # tangent-carrying operand fails to trace
            m = jax.lax.pmax(
                jnp.max(jax.lax.stop_gradient(logits), axis=-1), tp_axis)
            se = jnp.sum(jnp.exp(logits - m[..., None]), axis=-1)
            lse = m + jnp.log(red(se))
            tgt = red(jnp.sum(logits * jax.nn.one_hot(
                yc - lo, vloc, dtype=logits.dtype), -1))
        else:
            logits = proj(xc)
            lse = jax.scipy.special.logsumexp(logits, axis=-1)
            if onehot:
                tgt = jnp.sum(logits * jax.nn.one_hot(
                    yc, logits.shape[-1], dtype=logits.dtype), -1)
            else:
                tgt = jnp.take_along_axis(logits, yc[..., None],
                                          axis=-1)[..., 0]
        tot, cnt = carry
        return (tot + jnp.sum((lse - tgt) * mc), cnt + jnp.sum(mc)), None

    (tot, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (to_chunks(x), to_chunks(labels), to_chunks(mask)))
    return tot, cnt


class PipelinedLM:
    """Adapter: stage-stack a TransformerLM's params for pipeline execution.

    blocks leaves [L, ...] → [S, L/S, ...] (dim 0 sharded over ``pipe``);
    embeddings / final norm replicated over ``pipe`` (tied first/last-stage
    usage, reference PipelineModule TiedLayerSpec)."""

    def __init__(self, model, num_stages: int):
        cfg = model.config
        n_scan = getattr(cfg, "scan_length", cfg.num_layers)
        if n_scan % num_stages != 0:
            raise ValueError(
                f"scanned blocks ({n_scan}) must divide evenly into "
                f"{num_stages} pipeline stages")
        self.model = model
        self.config = cfg
        self.num_stages = num_stages
        self.layers_per_stage = n_scan // num_stages

    def init(self, rng):
        params = self.model.init(rng)
        return self._stack(params)

    def _stack(self, params):
        s, lps = self.num_stages, self.layers_per_stage
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((s, lps) + x.shape[1:]), params["blocks"])
        return params

    def unstack(self, params):
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), params["blocks"])
        return params

    # set by PipelineEngine: vocab-sharded embeddings via one-hot matmuls
    # (gather on a sharded table crashes the SPMD partitioner inside the
    # partial-manual shard_map; the matmul form partitions cleanly)
    use_onehot_embed = False

    def partition_specs(self):
        specs = dict(self.model.partition_specs())
        specs["blocks"] = jax.tree_util.tree_map(
            lambda sp: P("pipe", *sp), specs["blocks"],
            is_leaf=lambda x: isinstance(x, P))
        if not self.use_onehot_embed:
            # no TP: replicate embed/head over `model` (nothing to shard)
            specs["embed"] = jax.tree_util.tree_map(
                lambda sp: P(*([None] * len(sp))), specs["embed"],
                is_leaf=lambda x: isinstance(x, P))
            if "lm_head" in specs:
                specs["lm_head"] = jax.tree_util.tree_map(
                    lambda sp: P(*([None] * len(sp))), specs["lm_head"],
                    is_leaf=lambda x: isinstance(x, P))
        return specs

    def pipe_specs(self):
        """shard_map in_specs over the manual ``pipe`` axis only."""
        shapes = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        specs = jax.tree_util.tree_map(lambda x: P(), shapes)
        specs["blocks"] = jax.tree_util.tree_map(
            lambda x: P("pipe"), shapes["blocks"])
        return specs

    # engine-protocol loss (single-stage fallback / eval)
    def loss(self, params, batch):
        return self.model.loss(self.unstack(params), batch)


class PipelineEngine(DeepSpeedEngine):
    """Engine whose train step runs the compiled pipeline schedule.

    ``gradient_accumulation_steps`` is the microbatch count M (same meaning
    as the reference's engine: train_batch = micro * M * dp).

    Two compiled schedules:
      - ``1f1b`` (default, dense models): the reference TrainSchedule
        (`schedule.py:182`) as ONE scan over 2(M+S-1) combined ticks —
        forward at tick 2m+s, backward at tick 2m+2S-1-s (closed forms of
        the even/odd instruction math, pinned by a validation test).
        Backward is hand-orchestrated jax.vjp per stage from a ring buffer
        of ≤ S+1 stored stage inputs, so activation memory is bounded by
        the in-flight microbatch count — the point of 1F1B — instead of
        the full schedule length.
      - ``gpipe``: fill-drain forward scan with autodiff backward (kept
        for MoE models, whose aux-loss plumbing lives there).
    """

    def __init__(self, model, config=None, mesh=None, **kw):
        from ..config import DeepSpeedConfig
        config = (config if isinstance(config, DeepSpeedConfig)
                  else DeepSpeedConfig(config or {}))
        if mesh is None:
            mesh = topo.build_mesh(config.mesh)
        if topo.pp_world_size(mesh) < 2:
            raise ValueError("PipelineEngine needs a mesh with pipe>=2")
        self.num_stages = topo.pp_world_size(mesh)
        adapter = model if isinstance(model, PipelinedLM) else PipelinedLM(
            model, self.num_stages)
        adapter.use_onehot_embed = topo.mp_world_size(mesh) > 1
        self.adapter = adapter
        self.schedule = config.pipeline.schedule
        if self.schedule == "auto":
            # MoE aux-loss plumbing lives in the gpipe loss; dense → 1F1B
            self.schedule = ("gpipe" if getattr(adapter.config,
                                                "moe_enabled", False)
                             else "1f1b")
        if self.schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"pipeline.schedule must be auto|1f1b|gpipe, "
                             f"got {self.schedule}")
        if self.schedule == "1f1b" and getattr(adapter.config,
                                               "moe_enabled", False):
            raise NotImplementedError(
                "1f1b schedule does not carry the MoE aux loss yet; use "
                "pipeline.schedule=gpipe for MoE models")
        mcfg = adapter.config
        if getattr(mcfg, "attn_impl", None) in ("ring", "ulysses"):
            raise NotImplementedError(
                "ring/ulysses attention (sequence parallel) inside the "
                "compiled pipeline loop would nest manual collectives over "
                "pipe+sequence — not supported yet; use sequence "
                "parallelism without PP")
        if getattr(mcfg, "moe_enabled", False) and \
                mcfg.moe_noisy_gate_policy == "RSample":
            raise NotImplementedError(
                "RSample noisy gating has no rng path in the compiled "
                "pipeline loop yet; use deterministic gating under "
                "PipelineEngine")
        ps = config.pipeline.stages
        if ps != "auto" and int(ps) != self.num_stages:
            raise ValueError(
                f"pipeline.stages ({ps}) != mesh pipe axis "
                f"({self.num_stages}): this config was exported for a "
                f"different topology")
        pmb = config.pipeline.micro_batches
        if pmb:
            tb, mb, gas = getattr(
                config, "_user_batch_triple",
                (config.train_batch_size,
                 config.train_micro_batch_size_per_gpu,
                 config.gradient_accumulation_steps))
            if gas is not None and gas != pmb:
                raise ValueError(
                    f"pipeline.micro_batches ({pmb}) conflicts with "
                    f"gradient_accumulation_steps ({gas})")
            # micro_batches IS the accumulation count M; rebalance the
            # batch triple around it (the per-device micro batch
            # re-derives from train_batch_size when that is pinned)
            config._user_batch_triple = (
                tb, None if tb is not None else mb, pmb)
        # -- 3D region setup (dense models) ----------------------------
        self._mp = topo.mp_world_size(mesh)
        dense = not getattr(mcfg, "moe_enabled", False)
        if dense and dict(mesh.shape).get(topo.EXPERT_AXIS, 1) > 1:
            raise NotImplementedError(
                "expert mesh axis > 1 under a dense pipeline model: the "
                "3D region reduces gradients over (dcn_data, data) only — "
                "drop the expert axis or use an MoE model")
        if dense and self._mp > 1:
            if mcfg.vocab_size % self._mp:
                raise ValueError(
                    f"model mesh axis ({self._mp}) must divide vocab_size "
                    f"({mcfg.vocab_size}) for vocab-parallel embed/CE")
            # per-shard head-count view with the exact-backward collective
            # pair armed; raises on indivisible heads
            self._tview = adapter.model.tp_train_view(
                self._mp, topo.MODEL_AXIS)
        else:
            self._tview = adapter.model
        self._plan = None            # grad-reduce plan, set at region build
        super().__init__(model=adapter, config=config, mesh=mesh, **kw)

    @property
    def micro_batches(self) -> int:
        return self.gradient_accumulation_steps

    def _stage_windows(self, model, sid):
        """This stage's slice of the per-layer attention-window vector
        (TransformerConfig.attention_layers — the GPT-Neo family), or None
        when the model has none. ``sid`` is the traced stage index, so the
        slice is dynamic while its length (layers per stage) is static."""
        wins = getattr(model, "_layer_windows", lambda: None)()
        if wins is None:
            return None
        lps = model.config.scan_length // self.num_stages
        return jax.lax.dynamic_slice(wins, (sid * lps,), (lps,))

    # -- 3D region plumbing ------------------------------------------------
    def _data_axes(self):
        """Size>1 data-parallel mesh axes, in mesh order (the ``data``
        leg of the 3D product; expert is guarded off for dense models)."""
        ms = dict(self.mesh.shape)
        return tuple(a for a in (topo.DCN_DATA_AXIS, topo.DATA_AXIS)
                     if ms.get(a, 1) > 1)

    def _dp_prod(self) -> int:
        ms = dict(self.mesh.shape)
        return int(np.prod([ms[a] for a in self._data_axes()] or [1]))

    def _region_param_specs(self):
        """shard_map in_specs for the 3D region: the adapter's partition
        specs (``pipe`` on the blocks stack dim, ``model`` on the TP
        dims), with ``model`` stripped from the fused-qkv leaves — the
        global ``[q|k|v]`` packing cannot tile contiguously over the
        model axis, so qkv enters REPLICATED and each shard gathers its
        own permuted columns inside the differentiated region
        (`collectives.qkv_shard_columns`)."""
        specs = self.adapter.partition_specs()
        if self._mp <= 1:
            return specs

        def strip(path, sp):
            keys = tuple(getattr(p, "key", None) for p in path)
            if keys[-2:] in (("qkv", "kernel"), ("qkv", "bias")):
                return P(*[None if e == topo.MODEL_AXIS else e
                           for e in sp])
            return sp
        return jax.tree_util.tree_map_with_path(
            strip, specs, is_leaf=lambda x: isinstance(x, P))

    def _qkv_cols(self):
        """This model shard's fused-qkv column gather (traced row pick)."""
        c0 = self.adapter.model.config
        cols = jnp.asarray(C.qkv_shard_columns(
            c0.num_heads, c0.kv_heads, c0.hdim, self._mp))
        return cols[jax.lax.axis_index(topo.MODEL_AXIS)]

    def _tp_localize_fn(self, cols):
        """Block-param localizer applied INSIDE the differentiated
        functions: fused-qkv column gather (vjp scatters partial grads
        back into the global layout) and row-parallel bias pre-division
        (the reduce_from restores the bias exactly). Identity when the
        model axis is trivial."""
        if self._mp <= 1:
            return lambda bl: bl
        mp = self._mp

        def localize(bl):
            bl = dict(bl)
            attn = dict(bl["attn"])
            qkv = dict(attn["qkv"])
            qkv["kernel"] = jnp.take(qkv["kernel"], cols, axis=-1)
            if "bias" in qkv:
                qkv["bias"] = jnp.take(qkv["bias"], cols, axis=-1)
            attn["qkv"] = qkv
            out = dict(attn["out"])
            if "bias" in out:
                out["bias"] = out["bias"] / mp
            attn["out"] = out
            bl["attn"] = attn
            mlp = dict(bl["mlp"])
            fco = dict(mlp["fc_out"])
            if "bias" in fco:
                fco["bias"] = fco["bias"] / mp
            mlp["fc_out"] = fco
            bl["mlp"] = mlp
            return bl
        return localize

    def _tp_embed_fn(self, cfg, t):
        """Token+position embed for the region. mp>1: vocab-parallel
        masked take (off-shard rows zeroed, reduce_from over ``model``
        rejoins the replicated stream — identity backward, so the local
        table grad is exact); the positional embed adds AFTER the
        reduction, on the replicated stream (full grads every shard)."""
        tp = self._mp > 1
        red = C.reduce_from(topo.MODEL_AXIS) if tp else None
        onehot = getattr(self.adapter, "use_onehot_embed", False)

        def embed_fn(ep, tok):
            if tp:
                emb = ep["embed"]["embedding"].astype(cfg.dtype)
                vloc = emb.shape[0]
                lo = jax.lax.axis_index(topo.MODEL_AXIS) * vloc
                mine = (tok >= lo) & (tok < lo + vloc)
                x = jnp.take(emb, jnp.where(mine, tok - lo, 0), axis=0)
                x = red(jnp.where(mine[..., None], x, jnp.zeros_like(x)))
            else:
                embed = (L.embedding_apply_onehot if onehot
                         else L.embedding_apply)
                x = embed(ep["embed"], tok, cfg.dtype)
            if cfg.pos_embedding == "learned":
                pos = jnp.arange(t)[None, :]
                x = x + L.embedding_apply(ep["pos_embed"], pos, cfg.dtype)
            return x
        return embed_fn

    def _grad_exit_reduce(self, grads):
        """The per-axis exit collectives of the 3D region: one psum over
        ``model`` for the partial-gradient leaf set, then one psum — or
        ZeRO-2 ``psum_scatter`` per the precomputed plan — over the data
        product for every leaf. (``pipe`` reductions stay at the call
        sites: blocks are pipe-local, embed/head psum over pipe.)"""
        if self._mp > 1:
            grads = C.psum_tp_partials(grads, topo.MODEL_AXIS)
        daxes = self._data_axes()
        if daxes:
            plan_sub = {k: self._plan[k] for k in grads}
            grads = jax.tree_util.tree_map(
                lambda g, pl: C.reduce_over_data(g, pl, daxes),
                grads, plan_sub)
        return grads

    # -- the pipeline loss program (runs inside shard_map over 'pipe') -----
    def _pipeline_loss(self, params, ids):
        """ids: [M, mb, T] (replicated over pipe; 'data' handled by GSPMD).
        Returns global mean token loss."""
        cfg = self.adapter.config
        model = self.adapter.model
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m = ids.shape[0]
        mb, t = ids.shape[1], ids.shape[2]
        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)

        onehot = getattr(self.adapter, "use_onehot_embed", False)

        def embed_fn(tok):
            embed = (L.embedding_apply_onehot if onehot
                     else L.embedding_apply)
            x = embed(params["embed"], tok, cfg.dtype)
            if cfg.pos_embedding == "learned":
                pos = jnp.arange(t)[None, :]
                x = x + L.embedding_apply(params["pos_embed"], pos, cfg.dtype)
            return x

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_loss(y, tok):
            return chunked_ce(lambda xc: model._project(params, xc),
                              partial(norm, eps=cfg.layernorm_eps),
                              params["ln_f"], y, tok, chunk, onehot)

        def sb_fn(sp, x, win=None):
            y, _, la = model._superblock(sp, x, None, None, None, True, win)
            return y, la
        sb = model._remat(sb_fn)
        # per-layer attention windows (GPT-Neo family): this stage's slice
        # of the window vector rides the stage scan like the params do;
        # None (the common case) keeps the scan structure window-free
        win_local = self._stage_windows(model, sid)
        xs_local = (blocks_local if win_local is None
                    else (blocks_local, win_local))

        def stage_fn(x):
            def f(c, xs):
                sp, win = (xs, None) if win_local is None else xs
                y, la = sb(sp, c[0], win)
                return (y, c[1] + la), None
            (y, laux), _ = jax.lax.scan(
                f, (x, jnp.zeros((), jnp.float32)), xs_local)
            return y, laux

        perm = [(i, (i + 1) % s) for i in range(s)]

        def tick(carry, tt):
            state, lsum, cnt, lauxsum = carry
            recv = jax.lax.ppermute(state, topo.PIPE_AXIS, perm)
            tok_in = ids[jnp.clip(tt, 0, m - 1)]
            x = jnp.where(sid == 0, embed_fn(tok_in), recv)
            y, laux = stage_fn(x)
            # this stage holds a real microbatch only for ticks in
            # [sid, sid + m); outside that window its input is pipeline
            # bubble garbage and the aux loss must not count
            valid_data = jnp.logical_and(tt >= sid, tt < sid + m).astype(
                jnp.float32)
            lauxsum = lauxsum + laux * valid_data
            tok_out = ids[jnp.clip(tt - (s - 1), 0, m - 1)]
            # Only the last stage at ticks >= S-1 holds a real microbatch
            # output; every other (stage, tick) skips the vocab projection
            # entirely (cond, not select — the head is the single most
            # expensive op in the loop). Safe under manual 'pipe': the
            # predicate is uniform within a stage, so 'model'-axis (auto)
            # collectives inside the branch stay consistent per stage.
            valid = jnp.logical_and(sid == s - 1, tt >= s - 1)
            ls, ct = jax.lax.cond(
                valid, lambda: head_loss(y, tok_out),
                lambda: (jnp.zeros((), jnp.float32),
                         jnp.zeros((), jnp.float32)))
            return (y, lsum + ls, cnt + ct, lauxsum), None

        state0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        zero = jnp.zeros((), jnp.float32)
        (_, lsum, cnt, lauxsum), _ = jax.lax.scan(
            tick, (state0, zero, zero, zero), jnp.arange(m + s - 1))
        lsum = jax.lax.psum(lsum, topo.PIPE_AXIS)
        cnt = jax.lax.psum(cnt, topo.PIPE_AXIS)
        loss = lsum / jnp.maximum(cnt, 1.0)
        if getattr(cfg, "moe_enabled", False):
            # per-stage aux summed over stages, averaged over microbatches —
            # same normalization as the DP path (one laux per micro, meaned)
            laux = jax.lax.psum(lauxsum, topo.PIPE_AXIS) / m
            loss = loss + cfg.moe_aux_loss_coef * laux
        return loss

    def _pipeline_loss_3d(self, params, ids):
        """Dense gpipe loss, manual over the ``(pipe, model, data)``
        product. ids [M, mb_local, T] — microbatch dim sharded over the
        data product; params are the region-local views of
        `_region_param_specs` (TP-sharded kernels, replicated qkv).

        Returns the GLOBAL mean token loss, identical on every shard:
        the loss-sum / token-count pair reduces via ``reduce_from`` over
        ``(pipe,) + data`` so in-region autodiff sees an identity
        backward — each shard's grads come out as its exact partial
        contribution, and `_grad_exit_reduce` assembles them with one
        collective per axis family. (The raw-psum transpose would
        over-count by the shard count — masked by AdamW's scale
        invariance in the MoE path, exposed by SGD.)"""
        cfg = self.adapter.config
        model = self._tview
        tp = self._mp > 1
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m, mb, t = ids.shape
        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)
        tied = "lm_head" not in params

        embed_raw = self._tp_embed_fn(cfg, t)
        embed_fn = lambda tok: embed_raw(params, tok)    # noqa: E731
        localize = self._tp_localize_fn(self._qkv_cols() if tp else None)

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_loss(y, tok):
            if tp:
                def proj(xc):
                    if tied:
                        return L.embedding_attend(params["embed"], xc)
                    return jnp.einsum(
                        "...d,dv->...v", xc,
                        params["lm_head"]["kernel"].astype(xc.dtype),
                        preferred_element_type=jnp.float32)
            else:
                def proj(xc):
                    return model._project(params, xc)
            return chunked_ce(proj, partial(norm, eps=cfg.layernorm_eps),
                              params["ln_f"], y, tok, chunk, False,
                              tp_axis=topo.MODEL_AXIS if tp else None)

        def sb_fn(sp, x, win=None):
            y, _, _ = model._superblock(localize(sp), x, None, None, None,
                                        True, win)
            return y
        sb = model._remat(sb_fn)
        win_local = self._stage_windows(model, sid)
        xs_local = (blocks_local if win_local is None
                    else (blocks_local, win_local))

        def stage_fn(x):
            def f(c, xs):
                sp, win = (xs, None) if win_local is None else xs
                return sb(sp, c, win), None
            y, _ = jax.lax.scan(f, x, xs_local)
            return y

        perm = [(i, (i + 1) % s) for i in range(s)]

        def tick(carry, tt):
            state, lsum, cnt = carry
            recv = jax.lax.ppermute(state, topo.PIPE_AXIS, perm)
            tok_in = ids[jnp.clip(tt, 0, m - 1)]
            x = jnp.where(sid == 0, embed_fn(tok_in), recv)
            y = stage_fn(x)
            tok_out = ids[jnp.clip(tt - (s - 1), 0, m - 1)]
            # head only where it's real work (see _pipeline_loss); the
            # predicate depends on the pipe index alone, so the model-
            # axis collectives inside the branch stay uniform per stage
            valid = jnp.logical_and(sid == s - 1, tt >= s - 1)
            ls, ct = jax.lax.cond(
                valid, lambda: head_loss(y, tok_out),
                lambda: (jnp.zeros((), jnp.float32),
                         jnp.zeros((), jnp.float32)))
            return (y, lsum + ls, cnt + ct), None

        state0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        zero = jnp.zeros((), jnp.float32)
        (_, lsum, cnt), _ = jax.lax.scan(
            tick, (state0, zero, zero), jnp.arange(m + s - 1))
        red = C.reduce_from((topo.PIPE_AXIS,) + self._data_axes())
        return red(lsum) / jnp.maximum(red(cnt), 1.0)

    # ------------------------------------------------------------------
    # 1F1B: one compiled scan over combined fwd/bwd ticks
    # ------------------------------------------------------------------
    def _pipeline_value_and_grad(self, params, ids, scale):
        """Manual over the full ``(pipe, model, data)`` product (dense
        models — 1F1B rejects MoE at init). ids [M, mb_local, T] with
        the microbatch dim sharded over the data product; params in
        compute dtype, per the region specs (`_region_param_specs`).
        Returns (loss summed over microbatches AND data shards, grads
        summed the same way x ``scale``) — backward is hand-driven
        jax.vjp per stage, activations bounded by a ring of S+1 stored
        stage inputs; each stage body is the tensor-parallel program of
        ``tp_train_view`` (exact-gradient copy_to/reduce_from seams).

        Tick timing (validated against TrainSchedule, test_pipeline.py):
            forward  of microbatch m at stage s: tick 2m + s
            backward of microbatch m at stage s: tick 2m + 2S - 1 - s
        Activations ppermute forward each tick, cotangents backward; both
        are consumed exactly one tick after production.
        """
        cfg = self.adapter.config
        model = self._tview
        tp = self._mp > 1
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m, mb, t = ids.shape
        cap = s + 1                      # ring capacity ≥ in-flight bound
        onehot = getattr(self.adapter, "use_onehot_embed", False)
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)
        norm = partial(norm, eps=cfg.layernorm_eps)

        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        eparams = {"embed": params["embed"]}
        if "pos_embed" in params:
            eparams["pos_embed"] = params["pos_embed"]
        tied = "lm_head" not in params
        hparams = {"ln_f": params["ln_f"],
                   ("embed" if tied else "lm_head"):
                       params["embed" if tied else "lm_head"]}

        embed_fn = self._tp_embed_fn(cfg, t)
        localize = self._tp_localize_fn(self._qkv_cols() if tp else None)
        win_local = self._stage_windows(model, sid)

        def stage_fn(bl, x):
            bl = localize(bl)   # inside the vjp: qkv grads scatter back
            def f(c, xs):
                bp, win = (xs, None) if win_local is None else xs
                y, _ = model._block(bp, c, None, None, win)
                return y, None
            y, _ = jax.lax.scan(
                f, x, bl if win_local is None else (bl, win_local))
            return y

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_fn(hp, y, tok):
            """Per-microbatch MEAN CE via the shared chunked_ce head (the
            gpipe path consumes the same helper as (sum, count)). Under
            TP the projection is shard-local ([.., V/mp] logits) and
            chunked_ce runs Megatron's vocab-parallel CE over ``model``."""
            def proj(xc):
                if tied:
                    return L.embedding_attend(hp["embed"], xc)
                return jnp.einsum("...d,dv->...v", xc,
                                  hp["lm_head"]["kernel"].astype(xc.dtype),
                                  preferred_element_type=jnp.float32)
            tot, cnt = chunked_ce(proj, norm, hp["ln_f"], y, tok, chunk,
                                  onehot,
                                  tp_axis=topo.MODEL_AXIS if tp else None)
            return tot / jnp.maximum(cnt, 1.0)

        perm_f = [(i, (i + 1) % s) for i in range(s)]
        perm_b = [(i, (i - 1) % s) for i in range(s)]
        f32 = jnp.float32

        def zeros_f32(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, f32), tree)

        def tick(carry, tt):
            act, cot, buf, g_bl, g_e, g_h, lsum = carry
            recv_act = jax.lax.ppermute(act, topo.PIPE_AXIS, perm_f)
            recv_cot = jax.lax.ppermute(cot, topo.PIPE_AXIS, perm_b)

            # ---- forward part: microbatch (tt - sid)/2 ------------------
            mf2 = tt - sid
            mf = jnp.clip(mf2 // 2, 0, m - 1)
            fvalid = (mf2 % 2 == 0) & (mf2 >= 0) & (mf2 // 2 < m)
            # embed only where it's real work: stage 0's valid fwd ticks
            # (under TP the one-hot embed is an mb·t·V·d matmul)
            x_in = jax.lax.cond(
                fvalid & (sid == 0),
                lambda: embed_fn(eparams, ids[mf]), lambda: recv_act)
            slot = mf % cap
            old = jax.lax.dynamic_index_in_dim(buf, slot, 0,
                                               keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(fvalid, x_in, old), slot, 0)
            # last stage never forwards its output anywhere; skip compute
            new_act = jax.lax.cond(
                fvalid & (sid < s - 1),
                lambda: stage_fn(blocks_local, x_in), lambda: act)

            # ---- backward part: microbatch (tt - (2S-1-sid))/2 ----------
            mb2 = tt - (2 * s - 1 - sid)
            mbk = jnp.clip(mb2 // 2, 0, m - 1)
            bvalid = (mb2 % 2 == 0) & (mb2 >= 0) & (mb2 // 2 < m)
            x_st = jax.lax.dynamic_index_in_dim(buf, mbk % cap, 0,
                                                keepdims=False)
            tok_b = ids[mbk]

            def bwd_last():
                lossv, vjp = jax.vjp(
                    lambda x, bl, hp: head_fn(hp, stage_fn(bl, x), tok_b),
                    x_st, blocks_local, hparams)
                dx, dbl, dhp = vjp(jnp.asarray(scale, f32))
                return dx, dbl, dhp, lossv

            def bwd_mid():
                _, vjp = jax.vjp(lambda x, bl: stage_fn(bl, x),
                                 x_st, blocks_local)
                dx, dbl = vjp(recv_cot)
                return (dx, dbl,
                        jax.tree_util.tree_map(jnp.zeros_like, hparams),
                        jnp.zeros((), f32))

            def bwd_skip():
                return (jnp.zeros_like(act),
                        jax.tree_util.tree_map(jnp.zeros_like,
                                               blocks_local),
                        jax.tree_util.tree_map(jnp.zeros_like, hparams),
                        jnp.zeros((), f32))

            dx, dbl, dhp, lossv = jax.lax.cond(
                bvalid,
                lambda: jax.lax.cond(sid == s - 1, bwd_last, bwd_mid),
                bwd_skip)

            dep = jax.lax.cond(
                bvalid & (sid == 0),
                lambda: jax.vjp(lambda ep: embed_fn(ep, tok_b),
                                eparams)[1](dx)[0],
                lambda: jax.tree_util.tree_map(jnp.zeros_like, eparams))

            g_bl = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32)[None], g_bl, dbl)
            g_e = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32), g_e, dep)
            g_h = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32), g_h, dhp)
            return (new_act, dx, buf, g_bl, g_e, g_h, lsum + lossv), None

        act0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        buf0 = jnp.zeros((cap, mb, t, cfg.d_model), cfg.dtype)
        carry0 = (act0, act0, buf0,
                  zeros_f32(params["blocks"]), zeros_f32(eparams),
                  zeros_f32(hparams), jnp.zeros((), f32))
        (_, _, _, g_bl, g_e, g_h, lsum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(2 * (m + s - 1)))

        psum = partial(jax.lax.psum, axis_name=topo.PIPE_AXIS)
        loss = jax.lax.psum(                   # last stage only; summed
            lsum, (topo.PIPE_AXIS,) + self._data_axes())
        grads = {"blocks": g_bl}               # stays pipe-local
        g_e = jax.tree_util.tree_map(psum, g_e)     # stage 0 only
        g_h = jax.tree_util.tree_map(psum, g_h)     # last stage only
        grads["ln_f"] = g_h["ln_f"]
        if tied:
            grads["embed"] = jax.tree_util.tree_map(
                jnp.add, g_e["embed"], g_h["embed"])
        else:
            grads["embed"] = g_e["embed"]
            grads["lm_head"] = g_h["lm_head"]
        if "pos_embed" in g_e:
            grads["pos_embed"] = g_e["pos_embed"]
        return loss, self._grad_exit_reduce(grads)

    def _build_train_step(self):
        # the schedule itself runs inside ONE jitted program (per-tick
        # stage work is the device profiler's domain); the host-side span
        # marks which schedule was compiled, for how many stages/micros
        with trace_span("pipe/build_schedule", schedule=self.schedule,
                        stages=self.num_stages,
                        micro_batches=self.micro_batches):
            return self._build_train_step_traced()

    def _pipeline_gpipe_value_and_grad(self, params, ids, scale):
        """Autodiff runs INSIDE the region: legacy jax (0.4.x) cannot
        transpose the shard_map primitive itself (scalar residuals trip
        ``_SpecError`` in the partial-eval / transpose pipeline), so the
        gpipe path mirrors 1F1B's structure — grads are taken per stage
        and the cross-stage contributions of the replicated leaves
        (embed/head/ln_f) psummed here, while block grads stay
        pipe-local like the params themselves. Dense models run the 3D
        loss (`_pipeline_loss_3d`, manual over pipe x model x data, exit
        reductions via `_grad_exit_reduce`); MoE keeps the pipe-only
        region and loss unchanged.
        fp16: loss is scaled BEFORE autodiff so small grads survive the
        half-precision backward (reference FP16_Optimizer.backward,
        fp16/fused_optimizer.py); the caller divides the loss back out.
        """
        moe = getattr(self.adapter.config, "moe_enabled", False)

        def loss_fn(p):
            inner = (self._pipeline_loss if moe else self._pipeline_loss_3d)
            return inner(self._cast_for_compute(p), ids) * scale
        loss, grads = jax.value_and_grad(loss_fn)(params)
        psum = partial(jax.lax.psum, axis_name=topo.PIPE_AXIS)
        grads = {k: (v if k == "blocks"
                     else jax.tree_util.tree_map(psum, v))
                 for k, v in grads.items()}
        if not moe:
            grads = self._grad_exit_reduce(grads)
        return loss, grads

    def _build_loss_grad_region(self):
        """The shard_map'd ``(params, ids, scale) -> (loss, grads)``
        program — shared by the train-step builders and the bubble
        probe. Dense models get the 3D region (manual over pipe, model
        and the data product, ZeRO grad plan precomputed); MoE keeps the
        pipe-only manual region with every other axis left auto."""
        if getattr(self.adapter.config, "moe_enabled", False):
            pipe_specs = self.adapter.pipe_specs()
            return shard_map(
                self._pipeline_gpipe_value_and_grad, mesh=self.mesh,
                in_specs=(pipe_specs, P(), P()),
                out_specs=(P(), pipe_specs),
                axis_names={topo.PIPE_AXIS})
        daxes = self._data_axes()
        region_specs = self._region_param_specs()
        self._plan, gout = grad_reduce_plan(region_specs, self.grad_specs,
                                            daxes)
        ids_spec = (P(None, daxes if len(daxes) > 1 else daxes[0])
                    if daxes else P())
        names = {topo.PIPE_AXIS} | set(daxes)
        if self._mp > 1:
            names.add(topo.MODEL_AXIS)
        if self.schedule == "1f1b":
            fn = self._pipeline_value_and_grad
            # 1F1B assembles exactly the head/embed/blocks grads; subset
            # the out-spec tree to match (tied embeds have no lm_head key)
            gout = {k: gout[k] for k in
                    ("blocks", "ln_f", "embed", "lm_head", "pos_embed")
                    if k in gout}
        else:
            fn = self._pipeline_gpipe_value_and_grad
        return shard_map(
            fn, mesh=self.mesh,
            in_specs=(region_specs, ids_spec, P()),
            out_specs=(P(), gout),
            axis_names=names)

    def _build_train_step_traced(self):
        sharded = self._build_loss_grad_region()
        if self.schedule == "1f1b":
            # grads and per-micro mean losses are SUMS over microbatches
            # and data shards — normalize by both
            n_eff = float(self.micro_batches * self._dp_prod())

            def step_fn(state, batch):
                ids = batch["input_ids"]        # [M, micro*dp, T]
                scale = self._current_scale(state)
                loss_sum, grads = sharded(
                    self._cast_for_compute(state["params"]), ids, scale)
                new_state, metrics = self._apply_grads(state, grads, n_eff)
                metrics["loss"] = loss_sum / n_eff
                return new_state, metrics
        else:
            # gpipe: the loss is already the global mean (normalized
            # inside the region), so only the fp16 scale divides out
            def step_fn(state, batch):
                ids = batch["input_ids"]        # [M, micro*dp, T]
                scale = self._current_scale(state)
                loss, grads = sharded(state["params"], ids, scale)
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads)
                new_state, metrics = self._apply_grads(state, grads, 1.0)
                metrics["loss"] = loss / scale
                return new_state, metrics

        with self.mesh:
            self._train_step_fn = jax.jit(step_fn, donate_argnums=(0,))
        _count_jit_build()
        return self._train_step_fn

    # ------------------------------------------------------------------
    # measured bubble fraction
    # ------------------------------------------------------------------
    def measure_bubble_fraction(self, micro_counts=None, repeats: int = 2,
                                seq_len: Optional[int] = None) -> Dict:
        """Measure the schedule's pipeline-bubble fraction on this
        engine's compiled loss+grad program (two-point slope fit).

        Timing the full program at two microbatch counts M1 < M gives
        the per-microbatch steady-state cost as the slope; the intercept
        is the fill/drain bubble:

            bubble = (t(M) - M * slope) / t(M)

        1F1B's ticks cond-skip the bubble slots' compute, so its
        intercept is small; gpipe's fill-drain loop runs every stage on
        every tick, so its measured fraction lands near the analytic
        (S-1)/(M+S-1). Records the ``dstpu_train_bubble_frac`` gauge and
        returns the fit. Device-syncing — a profiling call, not a train
        step."""
        m_full = self.micro_batches
        if micro_counts is not None:
            m_small, m_full = micro_counts
        else:
            m_small = max(1, m_full // 2)
        if not m_small < m_full:
            raise ValueError(
                f"bubble fit needs two distinct microbatch counts, got "
                f"({m_small}, {m_full}) — run with "
                f"gradient_accumulation_steps >= 2")
        import time as _time
        cfg = self.adapter.config
        t_len = int(seq_len or cfg.max_seq_len)
        mb_global = self.train_batch_size // self.micro_batches
        with trace_span("pipe/bubble_probe", schedule=self.schedule,
                        stages=self.num_stages, m_small=m_small,
                        m_full=m_full):
            region = self._build_loss_grad_region()
            with self.mesh:
                probe = jax.jit(region)   # no donation: params are live
            _count_jit_build()
            params = self._cast_for_compute(self.state["params"])
            scale = jnp.asarray(1.0, jnp.float32)
            times = {}
            for m in (m_small, m_full):
                ids = jnp.zeros((m, mb_global, t_len), jnp.int32)
                jax.block_until_ready(probe(params, ids, scale))  # compile
                best = float("inf")
                for _ in range(max(1, repeats)):
                    t0 = _time.perf_counter()
                    jax.block_until_ready(probe(params, ids, scale))
                    best = min(best, _time.perf_counter() - t0)
                times[m] = best
            slope = (times[m_full] - times[m_small]) / (m_full - m_small)
            frac = 0.0
            if times[m_full] > 0:
                frac = (times[m_full] - m_full * slope) / times[m_full]
            frac = min(1.0, max(0.0, frac))
        self._ovl.record_bubble(frac)
        return {"bubble_frac": frac, "schedule": self.schedule,
                "stages": self.num_stages,
                "micro_counts": (m_small, m_full),
                "step_time_s": times[m_full], "per_micro_s": max(slope, 0.0)}
