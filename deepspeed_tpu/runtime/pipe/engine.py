"""Pipeline-parallel engine.

Reference: ``PipelineEngine`` (`/root/reference/deepspeed/runtime/pipe/
engine.py:37`, 1376 LoC) — an instruction interpreter that exchanges
activations over NCCL p2p (`pipe/p2p.py:49,70`) with a meta-shape handshake
(`engine.py:827`), executes 1F1B instruction lists, reduces tied grads
(`engine.py:233`) and DP grads per boundary.

TPU-native redesign: the whole schedule is a single compiled program.

  - stages = slices of a stage-stacked param pytree, sharded over the
    ``pipe`` mesh axis (see `pipe/module.py`);
  - activation exchange = `lax.ppermute` shift-by-one inside a `lax.scan`
    over schedule ticks (fill-drain/GPipe dataflow; the scan carry IS the
    reference's pipe buffer);
  - microbatch loop memory = scan residuals, bounded by the model's remat
    policy (reference couples this to activation checkpointing the same way);
  - tied-weight grad all-reduce = automatic: tied params enter `shard_map`
    replicated over ``pipe``, so its transpose emits the psum
    (reference's _exec_reduce_tied_grads);
  - DP gradient reduction + ZeRO sharding compose unchanged — the ``data``
    axis stays an auto axis handled by GSPMD outside the manual ``pipe``
    collectives.

Bubble math matches TrainSchedule: M microbatches over S stages run
M + S - 1 ticks (forward); backward retraces the same ticks in reverse.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...models import layers as L
from ...observability import trace_span
from ...parallel import topology as topo
from ...parallel.shard_map_compat import shard_map
from ..engine import DeepSpeedEngine, _count_jit_build, global_norm
from ..zero.sharding import constrain


def chunked_ce(proj, norm_fn, ln_params, y, tok, chunk, onehot):
    """Shared head loss of BOTH pipeline schedules: final norm + chunked
    cross-entropy over `chunk`-token slices (the [mb, chunk, V] logits
    block is the only live vocab tensor). Returns (sum_nll, token_count).

    ``proj``: x → logits; ``onehot``: extract the target logit via a
    one-hot product instead of take_along_axis (gathers along a
    vocab-sharded dim crash the SPMD partitioner under manual axes)."""
    mb, t = tok.shape
    x = norm_fn(ln_params, y)
    labels = jnp.concatenate([tok[:, 1:], jnp.zeros_like(tok[:, :1])],
                             axis=1)
    mask = jnp.ones((mb, t), jnp.float32).at[:, -1].set(0.0)
    n_chunks = t // chunk

    def to_chunks(a):
        return a.reshape(mb, n_chunks, chunk, *a.shape[2:]).swapaxes(0, 1)

    def body(carry, xs):
        xc, yc, mc = xs
        logits = proj(xc)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        if onehot:
            tgt = jnp.sum(logits * jax.nn.one_hot(
                yc, logits.shape[-1], dtype=logits.dtype), -1)
        else:
            tgt = jnp.take_along_axis(logits, yc[..., None],
                                      axis=-1)[..., 0]
        tot, cnt = carry
        return (tot + jnp.sum((lse - tgt) * mc), cnt + jnp.sum(mc)), None

    (tot, cnt), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (to_chunks(x), to_chunks(labels), to_chunks(mask)))
    return tot, cnt


class PipelinedLM:
    """Adapter: stage-stack a TransformerLM's params for pipeline execution.

    blocks leaves [L, ...] → [S, L/S, ...] (dim 0 sharded over ``pipe``);
    embeddings / final norm replicated over ``pipe`` (tied first/last-stage
    usage, reference PipelineModule TiedLayerSpec)."""

    def __init__(self, model, num_stages: int):
        cfg = model.config
        n_scan = getattr(cfg, "scan_length", cfg.num_layers)
        if n_scan % num_stages != 0:
            raise ValueError(
                f"scanned blocks ({n_scan}) must divide evenly into "
                f"{num_stages} pipeline stages")
        self.model = model
        self.config = cfg
        self.num_stages = num_stages
        self.layers_per_stage = n_scan // num_stages

    def init(self, rng):
        params = self.model.init(rng)
        return self._stack(params)

    def _stack(self, params):
        s, lps = self.num_stages, self.layers_per_stage
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((s, lps) + x.shape[1:]), params["blocks"])
        return params

    def unstack(self, params):
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), params["blocks"])
        return params

    # set by PipelineEngine: vocab-sharded embeddings via one-hot matmuls
    # (gather on a sharded table crashes the SPMD partitioner inside the
    # partial-manual shard_map; the matmul form partitions cleanly)
    use_onehot_embed = False

    def partition_specs(self):
        specs = dict(self.model.partition_specs())
        specs["blocks"] = jax.tree_util.tree_map(
            lambda sp: P("pipe", *sp), specs["blocks"],
            is_leaf=lambda x: isinstance(x, P))
        if not self.use_onehot_embed:
            # no TP: replicate embed/head over `model` (nothing to shard)
            specs["embed"] = jax.tree_util.tree_map(
                lambda sp: P(*([None] * len(sp))), specs["embed"],
                is_leaf=lambda x: isinstance(x, P))
            if "lm_head" in specs:
                specs["lm_head"] = jax.tree_util.tree_map(
                    lambda sp: P(*([None] * len(sp))), specs["lm_head"],
                    is_leaf=lambda x: isinstance(x, P))
        return specs

    def pipe_specs(self):
        """shard_map in_specs over the manual ``pipe`` axis only."""
        shapes = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        specs = jax.tree_util.tree_map(lambda x: P(), shapes)
        specs["blocks"] = jax.tree_util.tree_map(
            lambda x: P("pipe"), shapes["blocks"])
        return specs

    # engine-protocol loss (single-stage fallback / eval)
    def loss(self, params, batch):
        return self.model.loss(self.unstack(params), batch)


class PipelineEngine(DeepSpeedEngine):
    """Engine whose train step runs the compiled pipeline schedule.

    ``gradient_accumulation_steps`` is the microbatch count M (same meaning
    as the reference's engine: train_batch = micro * M * dp).

    Two compiled schedules:
      - ``1f1b`` (default, dense models): the reference TrainSchedule
        (`schedule.py:182`) as ONE scan over 2(M+S-1) combined ticks —
        forward at tick 2m+s, backward at tick 2m+2S-1-s (closed forms of
        the even/odd instruction math, pinned by a validation test).
        Backward is hand-orchestrated jax.vjp per stage from a ring buffer
        of ≤ S+1 stored stage inputs, so activation memory is bounded by
        the in-flight microbatch count — the point of 1F1B — instead of
        the full schedule length.
      - ``gpipe``: fill-drain forward scan with autodiff backward (kept
        for MoE models, whose aux-loss plumbing lives there).
    """

    def __init__(self, model, config=None, mesh=None, **kw):
        from ..config import DeepSpeedConfig
        config = (config if isinstance(config, DeepSpeedConfig)
                  else DeepSpeedConfig(config or {}))
        if mesh is None:
            mesh = topo.build_mesh(config.mesh)
        if topo.pp_world_size(mesh) < 2:
            raise ValueError("PipelineEngine needs a mesh with pipe>=2")
        self.num_stages = topo.pp_world_size(mesh)
        adapter = model if isinstance(model, PipelinedLM) else PipelinedLM(
            model, self.num_stages)
        adapter.use_onehot_embed = topo.mp_world_size(mesh) > 1
        self.adapter = adapter
        self.schedule = config.pipeline.schedule
        if self.schedule == "auto":
            # MoE aux-loss plumbing lives in the gpipe loss; dense → 1F1B
            self.schedule = ("gpipe" if getattr(adapter.config,
                                                "moe_enabled", False)
                             else "1f1b")
        if self.schedule not in ("1f1b", "gpipe"):
            raise ValueError(f"pipeline.schedule must be auto|1f1b|gpipe, "
                             f"got {self.schedule}")
        if self.schedule == "1f1b" and getattr(adapter.config,
                                               "moe_enabled", False):
            raise NotImplementedError(
                "1f1b schedule does not carry the MoE aux loss yet; use "
                "pipeline.schedule=gpipe for MoE models")
        mcfg = adapter.config
        if getattr(mcfg, "attn_impl", None) in ("ring", "ulysses"):
            raise NotImplementedError(
                "ring/ulysses attention (sequence parallel) inside the "
                "compiled pipeline loop would nest manual collectives over "
                "pipe+sequence — not supported yet; use sequence "
                "parallelism without PP")
        if getattr(mcfg, "moe_enabled", False) and \
                mcfg.moe_noisy_gate_policy == "RSample":
            raise NotImplementedError(
                "RSample noisy gating has no rng path in the compiled "
                "pipeline loop yet; use deterministic gating under "
                "PipelineEngine")
        super().__init__(model=adapter, config=config, mesh=mesh, **kw)

    @property
    def micro_batches(self) -> int:
        return self.gradient_accumulation_steps

    def _stage_windows(self, model, sid):
        """This stage's slice of the per-layer attention-window vector
        (TransformerConfig.attention_layers — the GPT-Neo family), or None
        when the model has none. ``sid`` is the traced stage index, so the
        slice is dynamic while its length (layers per stage) is static."""
        wins = getattr(model, "_layer_windows", lambda: None)()
        if wins is None:
            return None
        lps = model.config.scan_length // self.num_stages
        return jax.lax.dynamic_slice(wins, (sid * lps,), (lps,))

    # -- the pipeline loss program (runs inside shard_map over 'pipe') -----
    def _pipeline_loss(self, params, ids):
        """ids: [M, mb, T] (replicated over pipe; 'data' handled by GSPMD).
        Returns global mean token loss."""
        cfg = self.adapter.config
        model = self.adapter.model
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m = ids.shape[0]
        mb, t = ids.shape[1], ids.shape[2]
        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)

        onehot = getattr(self.adapter, "use_onehot_embed", False)

        def embed_fn(tok):
            embed = (L.embedding_apply_onehot if onehot
                     else L.embedding_apply)
            x = embed(params["embed"], tok, cfg.dtype)
            if cfg.pos_embedding == "learned":
                pos = jnp.arange(t)[None, :]
                x = x + L.embedding_apply(params["pos_embed"], pos, cfg.dtype)
            return x

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_loss(y, tok):
            return chunked_ce(lambda xc: model._project(params, xc),
                              partial(norm, eps=cfg.layernorm_eps),
                              params["ln_f"], y, tok, chunk, onehot)

        def sb_fn(sp, x, win=None):
            y, _, la = model._superblock(sp, x, None, None, None, True, win)
            return y, la
        sb = model._remat(sb_fn)
        # per-layer attention windows (GPT-Neo family): this stage's slice
        # of the window vector rides the stage scan like the params do;
        # None (the common case) keeps the scan structure window-free
        win_local = self._stage_windows(model, sid)
        xs_local = (blocks_local if win_local is None
                    else (blocks_local, win_local))

        def stage_fn(x):
            def f(c, xs):
                sp, win = (xs, None) if win_local is None else xs
                y, la = sb(sp, c[0], win)
                return (y, c[1] + la), None
            (y, laux), _ = jax.lax.scan(
                f, (x, jnp.zeros((), jnp.float32)), xs_local)
            return y, laux

        perm = [(i, (i + 1) % s) for i in range(s)]

        def tick(carry, tt):
            state, lsum, cnt, lauxsum = carry
            recv = jax.lax.ppermute(state, topo.PIPE_AXIS, perm)
            tok_in = ids[jnp.clip(tt, 0, m - 1)]
            x = jnp.where(sid == 0, embed_fn(tok_in), recv)
            y, laux = stage_fn(x)
            # this stage holds a real microbatch only for ticks in
            # [sid, sid + m); outside that window its input is pipeline
            # bubble garbage and the aux loss must not count
            valid_data = jnp.logical_and(tt >= sid, tt < sid + m).astype(
                jnp.float32)
            lauxsum = lauxsum + laux * valid_data
            tok_out = ids[jnp.clip(tt - (s - 1), 0, m - 1)]
            # Only the last stage at ticks >= S-1 holds a real microbatch
            # output; every other (stage, tick) skips the vocab projection
            # entirely (cond, not select — the head is the single most
            # expensive op in the loop). Safe under manual 'pipe': the
            # predicate is uniform within a stage, so 'model'-axis (auto)
            # collectives inside the branch stay consistent per stage.
            valid = jnp.logical_and(sid == s - 1, tt >= s - 1)
            ls, ct = jax.lax.cond(
                valid, lambda: head_loss(y, tok_out),
                lambda: (jnp.zeros((), jnp.float32),
                         jnp.zeros((), jnp.float32)))
            return (y, lsum + ls, cnt + ct, lauxsum), None

        state0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        zero = jnp.zeros((), jnp.float32)
        (_, lsum, cnt, lauxsum), _ = jax.lax.scan(
            tick, (state0, zero, zero, zero), jnp.arange(m + s - 1))
        lsum = jax.lax.psum(lsum, topo.PIPE_AXIS)
        cnt = jax.lax.psum(cnt, topo.PIPE_AXIS)
        loss = lsum / jnp.maximum(cnt, 1.0)
        if getattr(cfg, "moe_enabled", False):
            # per-stage aux summed over stages, averaged over microbatches —
            # same normalization as the DP path (one laux per micro, meaned)
            laux = jax.lax.psum(lauxsum, topo.PIPE_AXIS) / m
            loss = loss + cfg.moe_aux_loss_coef * laux
        return loss

    # ------------------------------------------------------------------
    # 1F1B: one compiled scan over combined fwd/bwd ticks
    # ------------------------------------------------------------------
    def _pipeline_value_and_grad(self, params, ids, scale):
        """Manual over 'pipe'. ids [M, mb, T]; params in compute dtype.
        Returns (loss summed over microbatches, grads summed over
        microbatches x ``scale``) — backward is hand-driven jax.vjp per
        stage, activations bounded by a ring of S+1 stored stage inputs.

        Tick timing (validated against TrainSchedule, test_pipeline.py):
            forward  of microbatch m at stage s: tick 2m + s
            backward of microbatch m at stage s: tick 2m + 2S - 1 - s
        Activations ppermute forward each tick, cotangents backward; both
        are consumed exactly one tick after production.
        """
        cfg = self.adapter.config
        model = self.adapter.model
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m, mb, t = ids.shape
        cap = s + 1                      # ring capacity ≥ in-flight bound
        onehot = getattr(self.adapter, "use_onehot_embed", False)
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)
        norm = partial(norm, eps=cfg.layernorm_eps)

        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        eparams = {"embed": params["embed"]}
        if "pos_embed" in params:
            eparams["pos_embed"] = params["pos_embed"]
        tied = "lm_head" not in params
        hparams = {"ln_f": params["ln_f"],
                   ("embed" if tied else "lm_head"):
                       params["embed" if tied else "lm_head"]}

        def embed_fn(ep, tok):
            embed = (L.embedding_apply_onehot if onehot
                     else L.embedding_apply)
            x = embed(ep["embed"], tok, cfg.dtype)
            if cfg.pos_embedding == "learned":
                pos = jnp.arange(t)[None, :]
                x = x + L.embedding_apply(ep["pos_embed"], pos, cfg.dtype)
            return x

        win_local = self._stage_windows(model, sid)

        def stage_fn(bl, x):
            def f(c, xs):
                bp, win = (xs, None) if win_local is None else xs
                y, _ = model._block(bp, c, None, None, win)
                return y, None
            y, _ = jax.lax.scan(
                f, x, bl if win_local is None else (bl, win_local))
            return y

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_fn(hp, y, tok):
            """Per-microbatch MEAN CE via the shared chunked_ce head (the
            gpipe path consumes the same helper as (sum, count))."""
            def proj(xc):
                if tied:
                    return L.embedding_attend(hp["embed"], xc)
                return jnp.einsum("...d,dv->...v", xc,
                                  hp["lm_head"]["kernel"].astype(xc.dtype),
                                  preferred_element_type=jnp.float32)
            tot, cnt = chunked_ce(proj, norm, hp["ln_f"], y, tok, chunk,
                                  onehot)
            return tot / jnp.maximum(cnt, 1.0)

        perm_f = [(i, (i + 1) % s) for i in range(s)]
        perm_b = [(i, (i - 1) % s) for i in range(s)]
        f32 = jnp.float32

        def zeros_f32(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape, f32), tree)

        def tick(carry, tt):
            act, cot, buf, g_bl, g_e, g_h, lsum = carry
            recv_act = jax.lax.ppermute(act, topo.PIPE_AXIS, perm_f)
            recv_cot = jax.lax.ppermute(cot, topo.PIPE_AXIS, perm_b)

            # ---- forward part: microbatch (tt - sid)/2 ------------------
            mf2 = tt - sid
            mf = jnp.clip(mf2 // 2, 0, m - 1)
            fvalid = (mf2 % 2 == 0) & (mf2 >= 0) & (mf2 // 2 < m)
            # embed only where it's real work: stage 0's valid fwd ticks
            # (under TP the one-hot embed is an mb·t·V·d matmul)
            x_in = jax.lax.cond(
                fvalid & (sid == 0),
                lambda: embed_fn(eparams, ids[mf]), lambda: recv_act)
            slot = mf % cap
            old = jax.lax.dynamic_index_in_dim(buf, slot, 0,
                                               keepdims=False)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(fvalid, x_in, old), slot, 0)
            # last stage never forwards its output anywhere; skip compute
            new_act = jax.lax.cond(
                fvalid & (sid < s - 1),
                lambda: stage_fn(blocks_local, x_in), lambda: act)

            # ---- backward part: microbatch (tt - (2S-1-sid))/2 ----------
            mb2 = tt - (2 * s - 1 - sid)
            mbk = jnp.clip(mb2 // 2, 0, m - 1)
            bvalid = (mb2 % 2 == 0) & (mb2 >= 0) & (mb2 // 2 < m)
            x_st = jax.lax.dynamic_index_in_dim(buf, mbk % cap, 0,
                                                keepdims=False)
            tok_b = ids[mbk]

            def bwd_last():
                lossv, vjp = jax.vjp(
                    lambda x, bl, hp: head_fn(hp, stage_fn(bl, x), tok_b),
                    x_st, blocks_local, hparams)
                dx, dbl, dhp = vjp(jnp.asarray(scale, f32))
                return dx, dbl, dhp, lossv

            def bwd_mid():
                _, vjp = jax.vjp(lambda x, bl: stage_fn(bl, x),
                                 x_st, blocks_local)
                dx, dbl = vjp(recv_cot)
                return (dx, dbl,
                        jax.tree_util.tree_map(jnp.zeros_like, hparams),
                        jnp.zeros((), f32))

            def bwd_skip():
                return (jnp.zeros_like(act),
                        jax.tree_util.tree_map(jnp.zeros_like,
                                               blocks_local),
                        jax.tree_util.tree_map(jnp.zeros_like, hparams),
                        jnp.zeros((), f32))

            dx, dbl, dhp, lossv = jax.lax.cond(
                bvalid,
                lambda: jax.lax.cond(sid == s - 1, bwd_last, bwd_mid),
                bwd_skip)

            dep = jax.lax.cond(
                bvalid & (sid == 0),
                lambda: jax.vjp(lambda ep: embed_fn(ep, tok_b),
                                eparams)[1](dx)[0],
                lambda: jax.tree_util.tree_map(jnp.zeros_like, eparams))

            g_bl = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32)[None], g_bl, dbl)
            g_e = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32), g_e, dep)
            g_h = jax.tree_util.tree_map(
                lambda a, b: a + b.astype(f32), g_h, dhp)
            return (new_act, dx, buf, g_bl, g_e, g_h, lsum + lossv), None

        act0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        buf0 = jnp.zeros((cap, mb, t, cfg.d_model), cfg.dtype)
        carry0 = (act0, act0, buf0,
                  zeros_f32(params["blocks"]), zeros_f32(eparams),
                  zeros_f32(hparams), jnp.zeros((), f32))
        (_, _, _, g_bl, g_e, g_h, lsum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(2 * (m + s - 1)))

        psum = partial(jax.lax.psum, axis_name=topo.PIPE_AXIS)
        loss = psum(lsum)                      # last stage only
        grads = {"blocks": g_bl}               # stays pipe-local
        g_e = jax.tree_util.tree_map(psum, g_e)     # stage 0 only
        g_h = jax.tree_util.tree_map(psum, g_h)     # last stage only
        grads["ln_f"] = g_h["ln_f"]
        if tied:
            grads["embed"] = jax.tree_util.tree_map(
                jnp.add, g_e["embed"], g_h["embed"])
        else:
            grads["embed"] = g_e["embed"]
            grads["lm_head"] = g_h["lm_head"]
        if "pos_embed" in g_e:
            grads["pos_embed"] = g_e["pos_embed"]
        return loss, grads

    def _build_1f1b_train_step(self):
        pipe_specs = self.adapter.pipe_specs()
        grad_out_specs = pipe_specs   # same tree/layout as the params
        sharded = shard_map(
            self._pipeline_value_and_grad, mesh=self.mesh,
            in_specs=(pipe_specs, P(), P()),
            out_specs=(P(), grad_out_specs),
            axis_names={topo.PIPE_AXIS})
        n_micro = float(self.micro_batches)

        def step_fn(state, batch):
            ids = batch["input_ids"]        # [M, mb, T]
            scale = self._current_scale(state)
            loss_sum, grads = sharded(
                self._cast_for_compute(state["params"]), ids, scale)
            new_state, metrics = self._apply_grads(state, grads, n_micro)
            metrics["loss"] = loss_sum / n_micro
            return new_state, metrics

        with self.mesh:
            self._train_step_fn = jax.jit(step_fn, donate_argnums=(0,))
        _count_jit_build()
        return self._train_step_fn

    def _build_train_step(self):
        # the schedule itself runs inside ONE jitted program (per-tick
        # stage work is the device profiler's domain); the host-side span
        # marks which schedule was compiled, for how many stages/micros
        with trace_span("pipe/build_schedule", schedule=self.schedule,
                        stages=self.num_stages,
                        micro_batches=self.micro_batches):
            return self._build_train_step_traced()

    def _pipeline_gpipe_value_and_grad(self, params, ids, scale):
        """Manual over 'pipe'. Autodiff runs INSIDE the region: legacy
        jax (0.4.x) cannot transpose the shard_map primitive itself
        (scalar residuals trip ``_SpecError`` in the partial-eval /
        transpose pipeline), so the gpipe path mirrors 1F1B's structure
        — grads are taken per stage and the cross-stage contributions
        of the replicated leaves (embed/head/ln_f) psummed here, while
        block grads stay pipe-local like the params themselves.
        fp16: loss is scaled BEFORE autodiff so small grads survive the
        half-precision backward (reference FP16_Optimizer.backward,
        fp16/fused_optimizer.py); the caller divides the loss back out.
        """
        def loss_fn(p):
            return self._pipeline_loss(self._cast_for_compute(p),
                                       ids) * scale
        loss, grads = jax.value_and_grad(loss_fn)(params)
        psum = partial(jax.lax.psum, axis_name=topo.PIPE_AXIS)
        grads = {k: (v if k == "blocks"
                     else jax.tree_util.tree_map(psum, v))
                 for k, v in grads.items()}
        return loss, grads

    def _build_train_step_traced(self):
        if self.schedule == "1f1b":
            return self._build_1f1b_train_step()
        pipe_specs = self.adapter.pipe_specs()
        sharded = shard_map(
            self._pipeline_gpipe_value_and_grad, mesh=self.mesh,
            in_specs=(pipe_specs, P(), P()),
            out_specs=(P(), pipe_specs),
            axis_names={topo.PIPE_AXIS})

        def step_fn(state, batch):
            ids = batch["input_ids"]        # [M, mb, T]
            scale = self._current_scale(state)
            loss, grads = sharded(state["params"], ids, scale)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            new_state, metrics = self._apply_grads(state, grads, 1.0)
            metrics["loss"] = loss / scale
            return new_state, metrics

        with self.mesh:
            self._train_step_fn = jax.jit(step_fn, donate_argnums=(0,))
        _count_jit_build()
        return self._train_step_fn
