"""Pipeline-parallel engine.

Reference: ``PipelineEngine`` (`/root/reference/deepspeed/runtime/pipe/
engine.py:37`, 1376 LoC) — an instruction interpreter that exchanges
activations over NCCL p2p (`pipe/p2p.py:49,70`) with a meta-shape handshake
(`engine.py:827`), executes 1F1B instruction lists, reduces tied grads
(`engine.py:233`) and DP grads per boundary.

TPU-native redesign: the whole schedule is a single compiled program.

  - stages = slices of a stage-stacked param pytree, sharded over the
    ``pipe`` mesh axis (see `pipe/module.py`);
  - activation exchange = `lax.ppermute` shift-by-one inside a `lax.scan`
    over schedule ticks (fill-drain/GPipe dataflow; the scan carry IS the
    reference's pipe buffer);
  - microbatch loop memory = scan residuals, bounded by the model's remat
    policy (reference couples this to activation checkpointing the same way);
  - tied-weight grad all-reduce = automatic: tied params enter `shard_map`
    replicated over ``pipe``, so its transpose emits the psum
    (reference's _exec_reduce_tied_grads);
  - DP gradient reduction + ZeRO sharding compose unchanged — the ``data``
    axis stays an auto axis handled by GSPMD outside the manual ``pipe``
    collectives.

Bubble math matches TrainSchedule: M microbatches over S stages run
M + S - 1 ticks (forward); backward retraces the same ticks in reverse.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...models import layers as L
from ...parallel import topology as topo
from ..engine import DeepSpeedEngine, global_norm
from ..zero.sharding import constrain


class PipelinedLM:
    """Adapter: stage-stack a TransformerLM's params for pipeline execution.

    blocks leaves [L, ...] → [S, L/S, ...] (dim 0 sharded over ``pipe``);
    embeddings / final norm replicated over ``pipe`` (tied first/last-stage
    usage, reference PipelineModule TiedLayerSpec)."""

    def __init__(self, model, num_stages: int):
        cfg = model.config
        n_scan = getattr(cfg, "scan_length", cfg.num_layers)
        if n_scan % num_stages != 0:
            raise ValueError(
                f"scanned blocks ({n_scan}) must divide evenly into "
                f"{num_stages} pipeline stages")
        self.model = model
        self.config = cfg
        self.num_stages = num_stages
        self.layers_per_stage = n_scan // num_stages

    def init(self, rng):
        params = self.model.init(rng)
        return self._stack(params)

    def _stack(self, params):
        s, lps = self.num_stages, self.layers_per_stage
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((s, lps) + x.shape[1:]), params["blocks"])
        return params

    def unstack(self, params):
        params = dict(params)
        params["blocks"] = jax.tree_util.tree_map(
            lambda x: x.reshape((-1,) + x.shape[2:]), params["blocks"])
        return params

    def partition_specs(self):
        specs = dict(self.model.partition_specs())
        specs["blocks"] = jax.tree_util.tree_map(
            lambda sp: P("pipe", *sp), specs["blocks"],
            is_leaf=lambda x: isinstance(x, P))
        # Embedding gathers on a vocab-sharded table inside the partial-manual
        # shard_map trip an XLA SPMD-partitioner crash (gather partitioning);
        # replicate the (tied) embedding over `model` under pipeline until a
        # one-hot-matmul TP embedding lands.
        specs["embed"] = jax.tree_util.tree_map(
            lambda sp: P(*([None] * len(sp))), specs["embed"],
            is_leaf=lambda x: isinstance(x, P))
        if "lm_head" in specs:
            specs["lm_head"] = jax.tree_util.tree_map(
                lambda sp: P(*([None] * len(sp))), specs["lm_head"],
                is_leaf=lambda x: isinstance(x, P))
        return specs

    def pipe_specs(self):
        """shard_map in_specs over the manual ``pipe`` axis only."""
        shapes = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        specs = jax.tree_util.tree_map(lambda x: P(), shapes)
        specs["blocks"] = jax.tree_util.tree_map(
            lambda x: P("pipe"), shapes["blocks"])
        return specs

    # engine-protocol loss (single-stage fallback / eval)
    def loss(self, params, batch):
        return self.model.loss(self.unstack(params), batch)


class PipelineEngine(DeepSpeedEngine):
    """Engine whose train step runs the compiled pipeline schedule.

    ``gradient_accumulation_steps`` is the microbatch count M (same meaning
    as the reference's engine: train_batch = micro * M * dp)."""

    def __init__(self, model, config=None, mesh=None, **kw):
        if mesh is None:
            from ..config import DeepSpeedConfig
            cfg = (config if isinstance(config, DeepSpeedConfig)
                   else DeepSpeedConfig(config or {}))
            config = cfg
            mesh = topo.build_mesh(cfg.mesh)
        if topo.pp_world_size(mesh) < 2:
            raise ValueError("PipelineEngine needs a mesh with pipe>=2")
        self.num_stages = topo.pp_world_size(mesh)
        adapter = model if isinstance(model, PipelinedLM) else PipelinedLM(
            model, self.num_stages)
        self.adapter = adapter
        mcfg = adapter.config
        if getattr(mcfg, "attn_impl", None) == "ring":
            raise NotImplementedError(
                "ring attention (sequence parallel) inside the compiled "
                "pipeline loop would nest manual collectives over "
                "pipe+sequence — not supported yet; use ring without PP")
        if getattr(mcfg, "moe_enabled", False) and \
                mcfg.moe_noisy_gate_policy == "RSample":
            raise NotImplementedError(
                "RSample noisy gating has no rng path in the compiled "
                "pipeline loop yet; use deterministic gating under "
                "PipelineEngine")
        super().__init__(model=adapter, config=config, mesh=mesh, **kw)

    @property
    def micro_batches(self) -> int:
        return self.gradient_accumulation_steps

    # -- the pipeline loss program (runs inside shard_map over 'pipe') -----
    def _pipeline_loss(self, params, ids):
        """ids: [M, mb, T] (replicated over pipe; 'data' handled by GSPMD).
        Returns global mean token loss."""
        cfg = self.adapter.config
        model = self.adapter.model
        s = self.num_stages
        sid = jax.lax.axis_index(topo.PIPE_AXIS)
        m = ids.shape[0]
        mb, t = ids.shape[1], ids.shape[2]
        blocks_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params["blocks"])
        norm = (L.layernorm_apply if cfg.norm_type == "layernorm"
                else L.rmsnorm_apply)

        def embed_fn(tok):
            x = L.embedding_apply(params["embed"], tok, cfg.dtype)
            if cfg.pos_embedding == "learned":
                pos = jnp.arange(t)[None, :]
                x = x + L.embedding_apply(params["pos_embed"], pos, cfg.dtype)
            return x

        chunk = cfg.loss_chunk if (cfg.loss_chunk and
                                   t % max(cfg.loss_chunk, 1) == 0 and
                                   t > cfg.loss_chunk) else t

        def head_loss(y, tok):
            """Chunked-CE head (same dataflow as TransformerLM.loss: the
            [mb, chunk, V] logits block is the only live vocab tensor)."""
            x = norm(params["ln_f"], y, eps=cfg.layernorm_eps)
            labels = jnp.concatenate(
                [tok[:, 1:], jnp.zeros_like(tok[:, :1])], axis=1)
            mask = jnp.ones((mb, t), jnp.float32).at[:, -1].set(0.0)
            n_chunks = t // chunk

            def to_chunks(a):
                return a.reshape(mb, n_chunks, chunk,
                                 *a.shape[2:]).swapaxes(0, 1)

            def body(carry, xs):
                xc, yc, mc = xs
                logits = model._project(params, xc)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                tgt = jnp.take_along_axis(logits, yc[..., None],
                                          axis=-1)[..., 0]
                tot, cnt2 = carry
                return (tot + jnp.sum((lse - tgt) * mc),
                        cnt2 + jnp.sum(mc)), None

            (tot, cnt2), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                (to_chunks(x), to_chunks(labels), to_chunks(mask)))
            return tot, cnt2

        def sb_fn(sp, x):
            y, _, la = model._superblock(sp, x)
            return y, la
        sb = model._remat(sb_fn)

        def stage_fn(x):
            def f(c, sp):
                y, la = sb(sp, c[0])
                return (y, c[1] + la), None
            (y, laux), _ = jax.lax.scan(
                f, (x, jnp.zeros((), jnp.float32)), blocks_local)
            return y, laux

        perm = [(i, (i + 1) % s) for i in range(s)]

        def tick(carry, tt):
            state, lsum, cnt, lauxsum = carry
            recv = jax.lax.ppermute(state, topo.PIPE_AXIS, perm)
            tok_in = ids[jnp.clip(tt, 0, m - 1)]
            x = jnp.where(sid == 0, embed_fn(tok_in), recv)
            y, laux = stage_fn(x)
            # this stage holds a real microbatch only for ticks in
            # [sid, sid + m); outside that window its input is pipeline
            # bubble garbage and the aux loss must not count
            valid_data = jnp.logical_and(tt >= sid, tt < sid + m).astype(
                jnp.float32)
            lauxsum = lauxsum + laux * valid_data
            tok_out = ids[jnp.clip(tt - (s - 1), 0, m - 1)]
            # Only the last stage at ticks >= S-1 holds a real microbatch
            # output; every other (stage, tick) skips the vocab projection
            # entirely (cond, not select — the head is the single most
            # expensive op in the loop). Safe under manual 'pipe': the
            # predicate is uniform within a stage, so 'model'-axis (auto)
            # collectives inside the branch stay consistent per stage.
            valid = jnp.logical_and(sid == s - 1, tt >= s - 1)
            ls, ct = jax.lax.cond(
                valid, lambda: head_loss(y, tok_out),
                lambda: (jnp.zeros((), jnp.float32),
                         jnp.zeros((), jnp.float32)))
            return (y, lsum + ls, cnt + ct, lauxsum), None

        state0 = jnp.zeros((mb, t, cfg.d_model), cfg.dtype)
        zero = jnp.zeros((), jnp.float32)
        (_, lsum, cnt, lauxsum), _ = jax.lax.scan(
            tick, (state0, zero, zero, zero), jnp.arange(m + s - 1))
        lsum = jax.lax.psum(lsum, topo.PIPE_AXIS)
        cnt = jax.lax.psum(cnt, topo.PIPE_AXIS)
        loss = lsum / jnp.maximum(cnt, 1.0)
        if getattr(cfg, "moe_enabled", False):
            # per-stage aux summed over stages, averaged over microbatches —
            # same normalization as the DP path (one laux per micro, meaned)
            laux = jax.lax.psum(lauxsum, topo.PIPE_AXIS) / m
            loss = loss + cfg.moe_aux_loss_coef * laux
        return loss

    def _build_train_step(self):
        auto_axes = frozenset(a for a in self.mesh.axis_names
                              if a != topo.PIPE_AXIS)
        pipe_specs = self.adapter.pipe_specs()
        sharded_loss = jax.shard_map(
            self._pipeline_loss, mesh=self.mesh,
            in_specs=(pipe_specs, P()), out_specs=P(),
            axis_names={topo.PIPE_AXIS}, check_vma=False)

        def step_fn(state, batch):
            ids = batch["input_ids"]        # [M, mb, T]
            # fp16: scale the loss BEFORE autodiff so small grads survive the
            # half-precision backward; _apply_grads divides the sum back out
            # (reference FP16_Optimizer.backward, fp16/fused_optimizer.py).
            scale = self._current_scale(state)

            def loss_of(params):
                return sharded_loss(self._cast_for_compute(params),
                                    ids) * scale

            loss, grads = jax.value_and_grad(loss_of)(state["params"])
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            new_state, metrics = self._apply_grads(state, grads, 1.0)
            metrics["loss"] = loss / scale
            return new_state, metrics

        with self.mesh:
            self._train_step_fn = jax.jit(step_fn, donate_argnums=(0,))
        return self._train_step_fn
