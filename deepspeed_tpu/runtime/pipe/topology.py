"""Pipeline-parallel grid: rank bookkeeping over the 3D topology.

Role-equivalent of the reference ``PipelineParallelGrid``
(`/root/reference/deepspeed/runtime/pipe/topology.py:249`): given the
(pipe, data, model) process topology, answer "which stage / data replica
/ model shard is rank r, and which ranks form each communicator group".

TPU-native redesign: at runtime there are no process groups to build —
the single `jax.sharding.Mesh` (owned by ``parallel/topology.py``, the
only module that constructs one) already IS the communicator, and the
compiled 3D region addresses it by axis name (``ppermute`` on ``pipe``,
``psum`` on ``model``, ``psum_scatter`` on ``data``). What remains
grid-shaped is the *bookkeeping*: checkpoint reshape, bench reporting,
and the stage-boundary ring permutation the pipeline engine's docs and
tests pin. This module therefore consumes an existing mesh (or explicit
axis sizes) and never constructs one.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...parallel.topology import (DATA_AXIS, DCN_DATA_AXIS, EXPERT_AXIS,
                                  MODEL_AXIS, PIPE_AXIS,
                                  PipeModelDataParallelTopology,
                                  ProcessTopology)


def grid_sizes_from_mesh(mesh) -> Tuple[int, int, int]:
    """(pipe, data, model) axis sizes of a built mesh; the data leg is
    the full data-parallel product (dcn_data x data x expert), matching
    the gradient-reduce axis set of the 3D region."""
    ms = dict(mesh.shape)
    dp = (ms.get(DCN_DATA_AXIS, 1) * ms.get(DATA_AXIS, 1)
          * ms.get(EXPERT_AXIS, 1))
    return ms.get(PIPE_AXIS, 1), dp, ms.get(MODEL_AXIS, 1)


class PipelineParallelGrid:
    """Stage/replica/shard coordinates over a (pipe, data, model) grid.

    Rank order is the topology's row-major enumeration — the same order
    `jax.devices()` flattens the mesh axes, so rank r here is device r
    of the mesh whose sizes built this grid.
    """

    def __init__(self, topology: Optional[ProcessTopology] = None,
                 mesh=None):
        if topology is None:
            if mesh is None:
                raise ValueError(
                    "PipelineParallelGrid needs a topology or a mesh")
            pp, dp, mp = grid_sizes_from_mesh(mesh)
            topology = PipeModelDataParallelTopology(pp, dp, mp)
        self._topo = topology
        self.pipe_parallel_size = topology.get_dim("pipe") \
            if "pipe" in topology.axes else 1
        self.data_parallel_size = topology.get_dim("data") \
            if "data" in topology.axes else 1
        self.model_parallel_size = topology.get_dim("model") \
            if "model" in topology.axes else 1

    @property
    def topology(self) -> ProcessTopology:
        return self._topo

    @property
    def world_size(self) -> int:
        return self._topo.world_size

    def _coord(self, rank: int) -> Dict[str, int]:
        return self._topo.get_coord(rank)

    # -- per-rank coordinates (reference get_stage_id / _id family) --------
    def get_stage_id(self, rank: int) -> int:
        return self._coord(rank).get("pipe", 0)

    def get_data_parallel_id(self, rank: int) -> int:
        return self._coord(rank).get("data", 0)

    def get_model_parallel_id(self, rank: int) -> int:
        return self._coord(rank).get("model", 0)

    def is_first_stage(self, rank: int) -> bool:
        return self.get_stage_id(rank) == 0

    def is_last_stage(self, rank: int) -> bool:
        return self.get_stage_id(rank) == self.pipe_parallel_size - 1

    # -- communicator groups (reference p2p/pipe/data group builders) ------
    def pipe_groups(self) -> List[List[int]]:
        """Rank groups that differ only along ``pipe`` — each is one
        pipeline (the ppermute ring's members)."""
        return self._topo.get_axis_comm_lists("pipe")

    def data_groups(self) -> List[List[int]]:
        return self._topo.get_axis_comm_lists("data")

    def model_groups(self) -> List[List[int]]:
        return self._topo.get_axis_comm_lists("model")

    def stage_to_ranks(self, stage: int) -> List[int]:
        """All ranks holding the given pipeline stage."""
        return self._topo.get_axis_list("pipe", stage)

    # -- stage-boundary ring ------------------------------------------------
    def ppermute_ring(self, shift: int = 1) -> List[Tuple[int, int]]:
        """(src_stage, dst_stage) pairs of the stage-boundary activation
        rotation — the permutation the compiled schedule hands
        ``jax.lax.ppermute`` on the ``pipe`` axis each tick."""
        s = self.pipe_parallel_size
        return [(i, (i + shift) % s) for i in range(s)]

    def stage_neighbors(self, stage: int) -> Tuple[Optional[int],
                                                   Optional[int]]:
        """(prev, next) stage ids along the dataflow; None past the ends
        (the schedule masks the wrap-around recv at stage 0)."""
        prev = stage - 1 if stage > 0 else None
        nxt = stage + 1 if stage < self.pipe_parallel_size - 1 else None
        return prev, nxt

    def __str__(self):
        return (f"PipelineParallelGrid(pipe={self.pipe_parallel_size}, "
                f"data={self.data_parallel_size}, "
                f"model={self.model_parallel_size})")
