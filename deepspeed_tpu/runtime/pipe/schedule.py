"""Pipeline instruction schedules.

Surface-parity with the reference schedule ISA
(`/root/reference/deepspeed/runtime/pipe/schedule.py`): ``PipeSchedule``
subclasses generate per-step instruction lists (`steps` :317-476 define the
instruction vocabulary — OptimizerStep, ReduceGrads, LoadMicroBatch,
ForwardPass, BackwardPass, Send/RecvActivation, Send/RecvGrad).

On TPU the *executor* is not an interpreter over these instructions — the
microbatch loop compiles into one XLA program (`runtime/pipe/engine.py`).
The schedule objects remain authoritative for (a) semantics documentation,
(b) bubble/step-count math the engine uses, and (c) host-driven execution
tests that validate the compiled loop against the instruction-level
simulation.
"""
from __future__ import annotations

from typing import Iterator, List

from ..utils import call_to_str


class PipeInstruction:
    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return (self.__class__ is other.__class__
                and self.kwargs == other.kwargs)


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class ForwardPass(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class BackwardPass(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class SendActivation(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class RecvActivation(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class SendGrad(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class RecvGrad(PipeInstruction):
    def __init__(self, buffer_id: int):
        super().__init__(buffer_id=buffer_id)


class PipeSchedule:
    """Base: yields lists of instructions per step.
    Reference `schedule.py:7`."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    @property
    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Fill-drain forward-only schedule (reference `schedule.py:129`)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id
            if 0 <= micro_batch_id < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(micro_batch_id % 2))
                else:
                    cmds.append(RecvActivation(micro_batch_id % 2))
                cmds.append(ForwardPass(micro_batch_id % 2))
                if not self.is_last_stage:
                    cmds.append(SendActivation(micro_batch_id % 2))
            yield cmds

    @property
    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B (reference `schedule.py:182`): warmup forwards, steady-state
    alternating fwd/bwd, cooldown backwards, then reduce + step."""

    def steps(self):
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)
            cmds = []
            if self._valid_micro_batch(micro_batch_id):
                buf = self._buffer_idx(micro_batch_id)
                if is_forward:
                    if self._valid_stage(self.prev_stage):
                        cmds.append(RecvActivation(buf))
                    if self.is_first_stage or self.is_last_stage:
                        cmds.append(LoadMicroBatch(buf))
                    cmds.append(ForwardPass(buf))
                    if self._valid_stage(self.next_stage):
                        cmds.append(SendActivation(buf))
                else:
                    if self._valid_stage(self.next_stage):
                        cmds.append(RecvGrad(buf))
                    cmds.append(BackwardPass(buf))
                    if self._valid_stage(self.prev_stage):
                        cmds.append(SendGrad(buf))
            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())
            yield cmds

    def _valid_micro_batch(self, mb: int) -> bool:
        return 0 <= mb < self.micro_batches

    def _valid_stage(self, stage: int) -> bool:
        return 0 <= stage < self.stages

    @property
    def num_pipe_buffers(self):
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id):
        def _is_even(x):
            return x % 2 == 0

        if _is_even(step_id) and _is_even(self.stage_id):
            return self._even_step_forward_id(step_id), True
        if not _is_even(step_id) and not _is_even(self.stage_id):
            return self._odd_step_forward_id(step_id), True
        if _is_even(step_id) and not _is_even(self.stage_id):
            return self._even_step_backward_id(step_id), False
        return self._odd_step_backward_id(step_id), False

    def _even_step_forward_id(self, step_id):
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id):
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id):
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id):
        return (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2

    def _buffer_idx(self, micro_batch_id):
        return micro_batch_id % self.num_pipe_buffers
