"""Shared machinery for config models.

Equivalent in role to the reference's ``DeepSpeedConfigModel``
(`/root/reference/deepspeed/runtime/config_utils.py`): a pydantic base class
with support for deprecated fields, "auto" placeholder values, and dict-style
construction from a sub-block of the master JSON config.
"""
from __future__ import annotations

from typing import Any

from pydantic import BaseModel, ConfigDict

AUTO = "auto"


class ConfigModel(BaseModel):
    """Base for all sub-config blocks.

    - Unknown keys are rejected (catches typos the way the reference's
      ``error on unrecognized`` behavior does).
    - ``"auto"`` is tolerated for fields that declare it; resolution happens in
      the engine once the mesh/model is known.
    """

    model_config = ConfigDict(extra="forbid", validate_assignment=True,
                              populate_by_name=True, protected_namespaces=())

    def __init__(self, strict: bool = False, **data: Any) -> None:
        if not strict:  # drop None values so defaults apply
            data = {k: v for k, v in data.items() if v is not None}
        super().__init__(**data)


def get_scalar_param(d: dict, key: str, default):
    return d.get(key, default)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load object_pairs_hook that rejects duplicate keys."""
    d = dict(ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter: dict = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        dupes = [k for k, n in counter.items() if n > 1]
        raise ValueError(f"Duplicate keys in config: {dupes}")
    return d
