"""Failure taxonomy for the resilience layer.

The reference stack surfaces storage failures as whatever the backend
throws (aio retcodes, torch.save IOError, Nebula commit errors); callers
cannot tell a retriable blip from a lost device. Here every I/O failure is
classified into exactly one of two types before it crosses a subsystem
boundary:

  ``TransientIOError`` — the operation may succeed if repeated (EIO on a
      flaky NVMe queue, EAGAIN/EINTR, a timed-out host-store write). The
      retry layer (`retry.py`) eats these up to the policy budget.
  ``FatalIOError``     — repeating cannot help (corrupt data, layout
      mismatch, permission denied, disk gone). Never retried; propagate
      loudly.

``classify_errno`` is the single source of which OS errnos count as
transient, shared by the retry predicate and the fault injector.
"""
from __future__ import annotations

import errno


class TransientIOError(OSError):
    """An I/O failure that is expected to succeed on retry."""


class FatalIOError(OSError):
    """An I/O failure that retrying cannot fix — propagate, never loop."""


class CheckpointCorruptionError(FatalIOError):
    """A checkpoint tag failed integrity verification (bad checksum,
    truncated artifact, missing manifest entry) and no verified fallback
    tag exists."""


class ServingError(RuntimeError):
    """The serving stack (inference/serving/) cannot make progress or
    detected an invariant violation: no-progress watchdog trips,
    preemption-thrash pin-or-fail, fatal dispatch faults, and the block
    pool's own :class:`BlockPoolError` all branch here — a serving bug
    or an undersized deployment surfaces loudly, never as a silent
    spin or a corrupted KV cache.  Deliberately NOT an ``OSError``:
    nothing in this family is retriable I/O (``is_transient`` is never
    True for it) — the remedies are scheduling decisions (shed, fail
    the request, raise to the operator), not the retry layer."""


#: OS errnos worth retrying: device/queue blips and interrupted syscalls.
#: Deliberately excludes ENOSPC/EROFS/EACCES/ENOENT — repeating those
#: just repeats the failure.
TRANSIENT_ERRNOS = frozenset({
    errno.EIO, errno.EAGAIN, errno.EINTR, errno.EBUSY, errno.ETIMEDOUT,
})


def is_transient(exc: BaseException) -> bool:
    """True if ``exc`` is worth retrying under the shared taxonomy."""
    if isinstance(exc, FatalIOError):
        return False
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, OSError):
        return exc.errno in TRANSIENT_ERRNOS
    return False
