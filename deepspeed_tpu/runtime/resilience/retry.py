"""Retriable I/O: exponential backoff + jitter over the shared taxonomy.

One policy object, one call path, used by every I/O site in the stack
(infinity slot streams, NVMe slot stores, checkpoint commit). The
reference DeepSpeed has no equivalent — a single EIO on an aio submit
kills the run; Nebula-style committed checkpoints motivate the same
discipline for the TPU-native engine (SURVEY: nebula_checkpoint_engine
commit semantics).

Usage::

    retry_call(lambda: store.pwrite(buf, path, off),
               policy=policy, what="nvme slot write")

    @retriable(what="manifest write")
    def _write(): ...

Only exceptions passing ``is_transient`` (TransientIOError / transient
OSError errnos) are retried; ``FatalIOError`` and everything else
propagate on the first throw. Exhausting the budget re-raises the LAST
transient error so the caller sees the real failure, with the attempt
count in the log.
"""
from __future__ import annotations

import dataclasses
import functools
import random
import time
from typing import Callable, Optional, TypeVar

from ...utils.logging import logger
from .errors import is_transient

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff schedule: attempt k (0-based retry index)
    sleeps ``min(base * multiplier**k, max_delay)``, scaled by a uniform
    jitter in ``[1 - jitter, 1 + jitter]`` so a fleet of workers hitting
    the same flaky store does not retry in lockstep."""
    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.25

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError(
                f"need 0 <= base_delay_s <= max_delay_s, got "
                f"{self.base_delay_s}/{self.max_delay_s}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {self.jitter}")

    def delay(self, retry_index: int) -> float:
        d = min(self.base_delay_s * (self.multiplier ** retry_index),
                self.max_delay_s)
        if self.jitter and d > 0:
            d *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return d


#: Default for I/O sites not configured through the ``resilience`` block.
DEFAULT_IO_POLICY = RetryPolicy()


def policy_from_config(resilience_cfg) -> RetryPolicy:
    """Build the shared I/O policy from a ``ResilienceConfig``
    (runtime/config.py ``resilience`` block)."""
    if resilience_cfg is None:
        return DEFAULT_IO_POLICY
    return RetryPolicy(
        max_attempts=resilience_cfg.io_retry_attempts,
        base_delay_s=resilience_cfg.io_retry_base_delay_s,
        max_delay_s=resilience_cfg.io_retry_max_delay_s,
        jitter=resilience_cfg.io_retry_jitter)


def retry_call(fn: Callable[[], T], *,
               policy: Optional[RetryPolicy] = None,
               what: str = "operation",
               sleep: Callable[[float], None] = time.sleep) -> T:
    """Run ``fn`` with the policy's transient-retry budget.

    ``sleep`` is injectable for tests (no real waiting in unit suites).
    """
    from ...observability import get_registry
    policy = policy or DEFAULT_IO_POLICY
    last: Optional[BaseException] = None
    for attempt in range(policy.max_attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 — classify then decide
            if not is_transient(e):
                raise
            last = e
            # rare-event metric, fed unconditionally: the retry history
            # must exist the moment an operator turns export on
            get_registry().counter("dstpu_io_retries_total").inc()
            if attempt + 1 >= policy.max_attempts:
                break
            d = policy.delay(attempt)
            logger.warning(
                f"transient I/O failure in {what} "
                f"(attempt {attempt + 1}/{policy.max_attempts}): {e} — "
                f"retrying in {d * 1e3:.0f} ms")
            sleep(d)
    get_registry().counter("dstpu_io_retry_giveups_total").inc()
    logger.error(f"{what} failed after {policy.max_attempts} attempts: "
                 f"{last}")
    assert last is not None
    raise last


def retriable(policy: Optional[RetryPolicy] = None,
              what: Optional[str] = None):
    """Decorator form of ``retry_call``."""
    def deco(fn):
        label = what or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(lambda: fn(*args, **kwargs),
                              policy=policy, what=label)
        return wrapper
    return deco
