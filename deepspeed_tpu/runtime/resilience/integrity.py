"""Checkpoint integrity: atomic publishes, per-artifact checksums, and
last-good-tag discovery.

Role-equivalent of the reference's checkpoint tag validation
(`runtime/engine.py:3045` _checkpoint_tag_validation — which only checks
that every rank AGREES on the tag string) plus the commit semantics of
the Nebula engine (`checkpoint_engine/nebula_checkpoint_engine.py` —
a tag is only visible once fully persisted). Here both are strengthened:

  - every artifact file under a tag dir is fingerprinted (size + crc32)
    into ``manifest.json``, written atomically AFTER the artifacts;
  - ``latest`` is updated only after the manifest (optionally verified
    back) exists, via write-tmp → fsync → rename → fsync(dir), so a
    crash at any instant leaves either the old or the new committed
    state, never a torn one;
  - loads verify the manifest and can walk back to the newest tag that
    still verifies (`find_newest_verified_tag`).

crc32 (zlib) rather than sha256: the threat model is torn writes and
bit-rot detection, not adversarial tampering, and checkpoint artifacts
are GBs — checksum throughput matters.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

from ...utils.logging import logger
from .fault_injection import get_fault_injector

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1

#: files never listed in a manifest (the manifest itself; 'latest' lives
#: one level up in the save dir)
_MANIFEST_EXCLUDE = frozenset({MANIFEST_NAME})

_CHUNK = 1 << 20


# ---------------------------------------------------------------------------
# atomic filesystem primitives
# ---------------------------------------------------------------------------
def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss.
    Best-effort on filesystems that refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """write-tmp → fsync → rename → fsync(dir): readers see the old
    content or the new content, never a prefix."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_json(path: str, obj, **json_kw) -> None:
    atomic_write_bytes(path, json.dumps(obj, **json_kw).encode("utf-8"))


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------
def file_checksum(path: str) -> Tuple[int, int]:
    """(size_bytes, crc32) of a file, streamed."""
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            size += len(chunk)
            crc = zlib.crc32(chunk, crc)
    return size, crc & 0xFFFFFFFF


def _walk_artifacts(tag_dir: str) -> List[str]:
    """Relative (posix) paths of every regular file under the tag dir,
    manifest excluded, sorted for a stable manifest."""
    out = []
    for root, _dirs, files in os.walk(tag_dir):
        for fn in files:
            rel = os.path.relpath(os.path.join(root, fn), tag_dir)
            rel = rel.replace(os.sep, "/")
            if rel in _MANIFEST_EXCLUDE or fn.startswith(".tmp") or \
                    ".tmp." in fn:
                continue
            out.append(rel)
    return sorted(out)


def write_manifest(tag_dir: str, extra: Optional[Dict] = None) -> Dict:
    """Fingerprint every artifact currently under ``tag_dir`` into an
    atomically-written ``manifest.json``; returns the manifest dict."""
    fi = get_fault_injector()
    entries = {}
    for rel in _walk_artifacts(tag_dir):
        full = os.path.join(tag_dir, rel)
        fi.check("checkpoint.artifact", path=full)
        size, crc = file_checksum(full)
        entries[rel] = {"size": size, "crc32": crc}
    manifest = {"version": MANIFEST_VERSION, "files": entries}
    if extra:
        manifest.update(extra)
    atomic_write_json(os.path.join(tag_dir, MANIFEST_NAME), manifest,
                      indent=2, sort_keys=True)
    return manifest


def verify_manifest(tag_dir: str) -> Tuple[bool, List[str]]:
    """Re-fingerprint the tag dir against its manifest.

    Returns (ok, problems). A tag with no manifest is NOT ok (either it
    predates the integrity layer — the caller may choose leniency — or
    the commit never finished); the problem list says which.
    """
    mpath = os.path.join(tag_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return False, [f"no {MANIFEST_NAME} in {tag_dir} (uncommitted or "
                       f"pre-integrity checkpoint)"]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, [f"unreadable manifest {mpath}: {e}"]
    problems = []
    files = manifest.get("files", {})
    if not isinstance(files, dict):
        return False, [f"malformed manifest {mpath}: 'files' is "
                       f"{type(files).__name__}, not a dict"]
    for rel, want in files.items():
        try:
            want_size, want_crc = int(want["size"]), int(want["crc32"])
        except (TypeError, KeyError, ValueError):
            # bit-rot that kept the JSON valid: report, don't crash — a
            # damaged manifest is exactly what the fallback path is for
            problems.append(f"{rel}: malformed manifest entry {want!r}")
            continue
        full = os.path.join(tag_dir, rel)
        if not os.path.exists(full):
            problems.append(f"missing artifact {rel}")
            continue
        size, crc = file_checksum(full)
        if size != want_size:
            problems.append(f"{rel}: size {size} != recorded {want_size} "
                            f"(truncated/partial write)")
        elif crc != want_crc:
            problems.append(f"{rel}: crc32 {crc:#010x} != recorded "
                            f"{want_crc:#010x} (corrupt)")
    # artifacts that appeared after the commit are suspicious but not
    # corruption — the recorded set is what the load will read
    return not problems, problems


def has_manifest(tag_dir: str) -> bool:
    return os.path.exists(os.path.join(tag_dir, MANIFEST_NAME))


# ---------------------------------------------------------------------------
# tag discovery
# ---------------------------------------------------------------------------
def _tag_sort_key(save_dir: str, tag: str):
    """Newest-first ordering: recorded global_steps, then meta mtime."""
    meta = os.path.join(save_dir, tag, "meta.json")
    steps = -1
    try:
        with open(meta) as f:
            steps = int(json.load(f).get("global_steps", -1))
    except (OSError, ValueError, TypeError):
        pass
    try:
        mtime = os.path.getmtime(meta)
    except OSError:
        mtime = 0.0
    return (steps, mtime)


def list_tags(save_dir: str) -> List[str]:
    """Tag dirs under save_dir that at least have a meta.json, newest
    first by recorded step then mtime."""
    if not os.path.isdir(save_dir):
        return []
    tags = [d for d in os.listdir(save_dir)
            if os.path.exists(os.path.join(save_dir, d, "meta.json"))]
    return sorted(tags, key=lambda t: _tag_sort_key(save_dir, t),
                  reverse=True)


def find_newest_verified_tag(save_dir: str,
                             exclude: Tuple[str, ...] = (),
                             require_manifest: bool = True
                             ) -> Optional[str]:
    """Walk tags newest-first, return the first that verifies.

    Two passes: manifest-VERIFIED tags always win, even over newer
    manifest-less ones — a tag with meta.json but no manifest is either
    a pre-integrity legacy save or a commit that crashed between the
    meta and manifest writes, and the two are indistinguishable, so an
    unverifiable tag must never shadow an older verified one. With
    ``require_manifest=False`` a second pass accepts the newest
    manifest-less tag when NO tag verifies (legacy-only save dirs)."""
    candidates = [t for t in list_tags(save_dir) if t not in exclude]
    for tag in candidates:
        tag_dir = os.path.join(save_dir, tag)
        ok, problems = verify_manifest(tag_dir)
        if ok:
            return tag
        logger.warning(f"checkpoint tag {tag!r} failed verification "
                       f"({'; '.join(problems[:3])}) — continuing search")
    if not require_manifest:
        for tag in candidates:
            if not has_manifest(os.path.join(save_dir, tag)):
                logger.warning(
                    f"no tag in {save_dir} verifies; accepting "
                    f"manifest-less tag {tag!r} (legacy save) unverified")
                return tag
    return None
