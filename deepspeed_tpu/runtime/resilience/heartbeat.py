"""Worker liveness: heartbeat files + a watchdog, and a thread-based
timeout guard for device syncs.

The elastic agent's monitor loop (elasticity/elastic_agent.py) can see a
DEAD worker (poll() returns a code) but not a HUNG one — a worker wedged
in a collective or a device sync keeps its process alive forever, and the
reference's torch-elastic monitor has the same blind spot. The contract
here: each worker touches a per-rank heartbeat file on a cadence; the
agent treats a running worker whose heartbeat is older than the watchdog
timeout as hung and kills it, which feeds the normal re-rendezvous path.

``run_with_timeout`` is the in-process cousin: bound a possibly-wedged
blocking call (e.g. ``block_until_ready`` on a sick device) and turn it
into a logged error instead of a hang.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Sequence

#: env var the elastic agent sets for each worker: path of the heartbeat
#: file that worker must touch (`beat`) on its training cadence.
ENV_HEARTBEAT_FILE = "DSTPU_HEARTBEAT_FILE"


def beat(path: str) -> None:
    """Touch the heartbeat file (create if missing, bump mtime)."""
    with open(path, "a"):
        pass
    os.utime(path, None)


def heartbeat_age(path: str, now: Optional[float] = None) -> float:
    """Seconds since the last beat; +inf if the file does not exist
    (a worker that never checked in is indistinguishable from hung)."""
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return float("inf")
    return (now if now is not None else time.time()) - mtime


def is_stale(path: str, timeout_s: float,
             now: Optional[float] = None) -> bool:
    return heartbeat_age(path, now=now) > timeout_s


class Heartbeat:
    """Worker-side rate-limited beater: call ``maybe_beat()`` every
    iteration; it touches the file at most once per interval. Reads the
    target path from ``DSTPU_HEARTBEAT_FILE`` when not given one —
    workers launched outside an elastic agent become no-ops."""

    def __init__(self, path: Optional[str] = None,
                 interval_s: float = 1.0):
        self.path = path or os.environ.get(ENV_HEARTBEAT_FILE)
        self.interval_s = interval_s
        self._last = 0.0

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def maybe_beat(self) -> None:
        if self.path is None:
            return
        now = time.monotonic()
        if now - self._last >= self.interval_s:
            self._last = now
            beat(self.path)

    def beat_now(self) -> None:
        """Unconditional beat (bracketing a long operation like a
        checkpoint write, where the next regular beat may be far away)."""
        if self.path is None:
            return
        self._last = time.monotonic()
        beat(self.path)


class Watchdog:
    """Agent-side staleness check over a set of heartbeat files."""

    def __init__(self, timeout_s: float):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s

    def stale(self, paths: Sequence[str]) -> List[int]:
        now = time.time()
        return [i for i, p in enumerate(paths)
                if is_stale(p, self.timeout_s, now=now)]


def run_with_timeout(fn: Callable[[], None], timeout_s: float,
                     ) -> bool:
    """Run a blocking call on a daemon thread; True iff it finished
    within ``timeout_s``. Exceptions from ``fn`` re-raise in the caller;
    on timeout the thread is abandoned (daemon — a truly wedged device
    sync cannot be cancelled, only contained) and False returned."""
    err: list = []
    done = threading.Event()

    def _run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name="resilience-timeout-guard")
    t.start()
    if not done.wait(timeout_s):
        return False
    if err:
        raise err[0]
    return True
