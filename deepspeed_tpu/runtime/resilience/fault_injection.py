"""Deterministic fault injection for the resilience layer.

The integrity/retry machinery is only trustworthy if its failure paths
run in CI. ``FaultInjector`` lets tests (and brave operators) make the
Nth call at a named site fail deterministically — no monkeypatching the
I/O stack, no flaky timing. Sites are plain strings checked by the
instrumented code paths:

    checkpoint.artifact    each artifact file as a tag commit fingerprints it
    checkpoint.publish     the meta/manifest/'latest' publish of a tag
    infinity.slot_write    one ZeRO-Infinity slot .npz write
    infinity.slot_read     one ZeRO-Infinity slot .npz open
    slot_store.write       one NVMe slot-store pwrite submission
    slot_store.read        one NVMe slot-store pread submission
    serving.allocate       one paged-KV block-table allocation (admission)
    serving.append_block   one paged-KV block-table growth (decode boundary)
    serving.admission      one serving-scheduler admission attempt
    serving.dispatch       one mixed-step program dispatch
    serving.spill          one eviction demoted into the host tier
    serving.promote        one host-tier block scatter back to the pool
    serving.fleet.route    one fleet placement decision
    serving.fleet.replica_step  one fleet replica's engine iteration
    serving.fabric.publish one prefill-worker KV-fabric chain-block publish
    serving.fabric.claim   one decode-replica KV-fabric claim
    serving.fleet.scale    one autoscaler join/drain actuation

The serving sites feed the continuous-batching chaos suite
(tests/unit/test_serving_chaos.py, docs/serving.md "Failure handling"):
``fail`` there exercises the retry-next-step / hold-this-iteration
paths, ``fatal`` the per-request FAILED terminal path.

Fault kinds:

    fail      raise TransientIOError (the retry layer should absorb it)
    fatal     raise FatalIOError (must NOT be retried)
    truncate  truncate the site's file to ``arg`` bytes (torn write)
    delay     sleep ``arg`` seconds (slow device)
    kill      SIGKILL the pid passed by the site (dead worker slot)

Activation is env-driven (``DSTPU_FAULTS``) or config-driven
(``resilience.fault_injection`` block) or programmatic (tests call
``add_plan``). Env grammar, ';'-separated::

    DSTPU_FAULTS="site=kind:at[:count[:arg]];site2=kind:at"
    # e.g. fail the 2nd and 3rd infinity slot writes:
    DSTPU_FAULTS="infinity.slot_write=fail:2:2"

``at`` is the 1-based call index at which the fault first fires; ``count``
is how many consecutive calls fire (-1 = forever). With no plans the
check is one dict lookup — safe to leave in production paths.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Dict, Optional

from ...utils.logging import logger
from .errors import FatalIOError, TransientIOError

ENV_FAULTS = "DSTPU_FAULTS"

_KINDS = ("fail", "fatal", "truncate", "delay", "kill")


@dataclasses.dataclass
class FaultPlan:
    kind: str
    at: int = 1          # 1-based call index of the first firing
    count: int = 1       # consecutive firings; -1 = every call from ``at``
    arg: float = 0.0     # truncate size (bytes) / delay (seconds)

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {_KINDS}")
        if self.at < 1:
            raise ValueError(f"fault 'at' is a 1-based index, got {self.at}")

    def active(self, n: int) -> bool:
        if n < self.at:
            return False
        return self.count < 0 or n < self.at + self.count


class FaultInjector:
    """Per-site call counters + plans. Thread-compatible for the store
    threads that hit it (counter bumps are GIL-atomic dict ops and exact
    ordering across racing sites is not part of the contract)."""

    def __init__(self, plans: Optional[Dict[str, FaultPlan]] = None):
        self.plans: Dict[str, FaultPlan] = dict(plans or {})
        self.calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}

    # -- construction ------------------------------------------------------
    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None
                 ) -> "FaultInjector":
        spec = (env if env is not None else os.environ).get(ENV_FAULTS, "")
        fi = cls()
        for entry in filter(None, (s.strip() for s in spec.split(";"))):
            try:
                site, rest = entry.split("=", 1)
                parts = rest.split(":")
                fi.add_plan(site.strip(), parts[0],
                            at=int(parts[1]) if len(parts) > 1 else 1,
                            count=int(parts[2]) if len(parts) > 2 else 1,
                            arg=float(parts[3]) if len(parts) > 3 else 0.0)
            except (ValueError, IndexError) as e:
                raise ValueError(
                    f"bad {ENV_FAULTS} entry {entry!r} "
                    f"(grammar: site=kind:at[:count[:arg]]): {e}") from e
        return fi

    def add_plans_from_config(self, cfg: Dict[str, dict]) -> None:
        """``resilience.fault_injection`` block:
        {"site": {"kind": ..., "at": ..., "count": ..., "arg": ...}}."""
        for site, spec in (cfg or {}).items():
            self.add_plan(site, spec["kind"], at=int(spec.get("at", 1)),
                          count=int(spec.get("count", 1)),
                          arg=float(spec.get("arg", 0.0)))

    def add_plan(self, site: str, kind: str, at: int = 1, count: int = 1,
                 arg: float = 0.0) -> None:
        self.plans[site] = FaultPlan(kind, at=at, count=count, arg=arg)

    def reset(self) -> None:
        self.plans.clear()
        self.calls.clear()
        self.fired.clear()

    # -- the hook ----------------------------------------------------------
    def check(self, site: str, path: Optional[str] = None,
              pid: Optional[int] = None) -> None:
        """Instrumented sites call this once per operation. Raises /
        truncates / delays / kills per the active plan, else no-ops."""
        plan = self.plans.get(site)
        if plan is None:
            return
        n = self.calls.get(site, 0) + 1
        self.calls[site] = n
        if not plan.active(n):
            return
        self.fired[site] = self.fired.get(site, 0) + 1
        logger.warning(f"FaultInjector: firing {plan.kind!r} at {site} "
                       f"(call {n})")
        if plan.kind == "fail":
            raise TransientIOError(
                f"injected transient fault at {site} (call {n})")
        if plan.kind == "fatal":
            raise FatalIOError(
                f"injected fatal fault at {site} (call {n})")
        if plan.kind == "truncate":
            if path is None:
                raise ValueError(
                    f"truncate fault at {site} needs a file path")
            self.truncate_file(path, int(plan.arg))
            return
        if plan.kind == "delay":
            time.sleep(plan.arg)
            return
        if plan.kind == "kill":
            if pid is None:
                raise ValueError(f"kill fault at {site} needs a pid")
            os.kill(pid, signal.SIGKILL)

    @staticmethod
    def truncate_file(path: str, nbytes: int = 0) -> None:
        """Simulate a torn write: keep the first ``nbytes`` bytes."""
        with open(path, "r+b") as f:
            f.truncate(max(0, int(nbytes)))

    def fire_count(self, site: str) -> int:
        return self.fired.get(site, 0)


_INJECTOR: Optional[FaultInjector] = None


def get_fault_injector() -> FaultInjector:
    """Process-global injector, built from ``DSTPU_FAULTS`` on first use."""
    global _INJECTOR
    if _INJECTOR is None:
        _INJECTOR = FaultInjector.from_env()
    return _INJECTOR


def install_fault_injector(fi: Optional[FaultInjector]) -> FaultInjector:
    """Replace the global injector (tests); None reinstalls from env."""
    global _INJECTOR
    _INJECTOR = fi if fi is not None else FaultInjector.from_env()
    return _INJECTOR
