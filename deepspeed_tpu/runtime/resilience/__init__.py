"""Fault-tolerance layer: failure taxonomy, retriable I/O, checkpoint
integrity, fault injection, and worker liveness.

The production story this subsystem exists for (ROADMAP north star —
serving/training at fleet scale): a torn checkpoint write must not
poison a run, a transient NVMe/host-store error must not kill it, and a
hung worker must be detected, not just a dead one. The reference ships
tag validation and Nebula committed checkpoints for the same reasons;
this is the TPU-native equivalent plus the fault-injection harness that
keeps the failure paths tested.

Wired into: runtime/checkpoint_engine (atomic commit + manifest +
last-good fallback), runtime/zero/infinity + runtime/swap_tensor
(retriable slot I/O), elasticity/elastic_agent (heartbeat watchdog),
runtime/engine (non-finite grad-norm skip-step), inference/engine
(device-sync timeout guard). Config: the ``resilience`` block
(runtime/config.py); docs: docs/resilience.md.
"""
from .errors import (CheckpointCorruptionError, FatalIOError,
                     ServingError, TRANSIENT_ERRNOS, TransientIOError,
                     is_transient)
from .fault_injection import (ENV_FAULTS, FaultInjector, FaultPlan,
                              get_fault_injector, install_fault_injector)
from .heartbeat import (ENV_HEARTBEAT_FILE, Heartbeat, Watchdog, beat,
                        heartbeat_age, is_stale, run_with_timeout)
from .integrity import (MANIFEST_NAME, atomic_write_bytes,
                        atomic_write_json, atomic_write_text,
                        file_checksum, find_newest_verified_tag, fsync_dir,
                        has_manifest, list_tags, verify_manifest,
                        write_manifest)
from .retry import (DEFAULT_IO_POLICY, RetryPolicy, policy_from_config,
                    retriable, retry_call)

__all__ = [
    "CheckpointCorruptionError", "FatalIOError", "ServingError",
    "TRANSIENT_ERRNOS", "TransientIOError", "is_transient",
    "ENV_FAULTS", "FaultInjector", "FaultPlan", "get_fault_injector",
    "install_fault_injector",
    "ENV_HEARTBEAT_FILE", "Heartbeat", "Watchdog", "beat", "heartbeat_age",
    "is_stale", "run_with_timeout",
    "MANIFEST_NAME", "atomic_write_bytes", "atomic_write_json",
    "atomic_write_text", "file_checksum", "find_newest_verified_tag",
    "fsync_dir", "has_manifest", "list_tags", "verify_manifest",
    "write_manifest",
    "DEFAULT_IO_POLICY", "RetryPolicy", "policy_from_config", "retriable",
    "retry_call",
]
