"""Optimizer family (functional, pytree-native).

Role-equivalent of the reference's optimizer zoo — FusedAdam
(`/root/reference/csrc/adam/multi_tensor_adam.cu`), FusedLamb
(`csrc/lamb/fused_lamb_cuda_kernel.cu`), CPU Adam/Adagrad (`csrc/adam/
cpu_adam.cpp`) and the selection logic in `runtime/engine.py:1307`
``_configure_basic_optimizer``. On TPU the "fused multi-tensor apply" trick is
unnecessary: each update is a pure elementwise pytree map that XLA fuses into
a handful of kernels, and sharded optimizer state (ZeRO-1/2) is expressed by
partition specs on the state tree, not by bucketing code.

API (optax-flavored so user optax optimizers also slot in):
    opt = get_optimizer("adamw", weight_decay=0.01)
    state = opt.init(params)
    new_params, new_state = opt.apply(grads, state, params, lr)

All states store fp32 moments regardless of param dtype.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp


def _tmap(f, *trees, **kw):
    return jax.tree_util.tree_map(f, *trees, **kw)


def _unzip(out, n):
    """Split a pytree whose leaves are n-tuples into n pytrees."""
    is_leaf = lambda x: isinstance(x, tuple)  # noqa: E731
    return tuple(_tmap(lambda o, i=i: o[i], out, is_leaf=is_leaf)
                 for i in range(n))


@dataclasses.dataclass(frozen=True)
class Optimizer:
    name: str
    init: Callable
    apply: Callable          # (grads, state, params, lr) -> (params, state)
    hyperparams: Dict[str, Any] = dataclasses.field(default_factory=dict)


def _zeros_like_f32(params):
    return _tmap(lambda p: jnp.zeros(p.shape, jnp.float32), params)


# ---------------------------------------------------------------------------
# Adam / AdamW   (reference: ops/adam/fused_adam.py, multi_tensor_adam.cu)
# ---------------------------------------------------------------------------
def adam(lr_default: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
         weight_decay: float = 0.0, adamw_mode: bool = True,
         bias_correction: bool = True) -> Optimizer:
    b1, b2 = betas

    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _zeros_like_f32(params),
                "v": _zeros_like_f32(params)}

    def apply(grads, state, params, lr):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        if bias_correction:
            c1 = 1.0 - b1 ** t
            c2 = 1.0 - b2 ** t
        else:
            c1 = c2 = 1.0

        def upd(g, m, v, p):
            g32 = g.astype(jnp.float32)
            m = b1 * m + (1 - b1) * g32
            v = b2 * v + (1 - b2) * g32 * g32
            mhat = m / c1
            vhat = v / c2
            u = mhat / (jnp.sqrt(vhat) + eps)
            p32 = p.astype(jnp.float32)
            if weight_decay and adamw_mode:
                u = u + weight_decay * p32
            return (p32 - lr * u).astype(p.dtype), m, v

        if weight_decay and not adamw_mode:
            grads = _tmap(lambda g, p: g + weight_decay * p.astype(g.dtype),
                          grads, params)
        out = _tmap(upd, grads, state["m"], state["v"], params)
        new_params, new_m, new_v = _unzip(out, 3)
        return new_params, {"step": step, "m": new_m, "v": new_v}

    return Optimizer("adamw" if adamw_mode else "adam", init, apply,
                     dict(lr=lr_default, betas=betas, eps=eps,
                          weight_decay=weight_decay))


# ---------------------------------------------------------------------------
# LAMB   (reference: ops/lamb/fused_lamb.py)
# ---------------------------------------------------------------------------
def lamb(lr_default: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
         weight_decay: float = 0.0, max_coeff: float = 10.0,
         min_coeff: float = 0.01) -> Optimizer:
    b1, b2 = betas

    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "m": _zeros_like_f32(params),
                "v": _zeros_like_f32(params)}

    def apply(grads, state, params, lr):
        step = state["step"] + 1

        def upd(g, m, v, p):
            g32 = g.astype(jnp.float32)
            m = b1 * m + (1 - b1) * g32
            v = b2 * v + (1 - b2) * g32 * g32
            u = m / (jnp.sqrt(v) + eps)
            p32 = p.astype(jnp.float32)
            if weight_decay:
                u = u + weight_decay * p32
            # layerwise trust ratio
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(u)
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, min_coeff, max_coeff), 1.0)
            return (p32 - lr * ratio * u).astype(p.dtype), m, v

        out = _tmap(upd, grads, state["m"], state["v"], params)
        new_params, new_m, new_v = _unzip(out, 3)
        return new_params, {"step": step, "m": new_m, "v": new_v}

    return Optimizer("lamb", init, apply,
                     dict(lr=lr_default, betas=betas, eps=eps,
                          weight_decay=weight_decay))


# ---------------------------------------------------------------------------
# SGD / Adagrad  (reference: csrc/adagrad/cpu_adagrad.cpp)
# ---------------------------------------------------------------------------
def sgd(lr_default: float = 1e-2, momentum: float = 0.0,
        weight_decay: float = 0.0, nesterov: bool = False) -> Optimizer:
    def init(params):
        if momentum:
            return {"step": jnp.zeros((), jnp.int32),
                    "mom": _zeros_like_f32(params)}
        return {"step": jnp.zeros((), jnp.int32)}

    def apply(grads, state, params, lr):
        step = state["step"] + 1

        def upd(g, p, buf=None):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay:
                g32 = g32 + weight_decay * p32
            if buf is not None:
                buf = momentum * buf + g32
                g32 = (g32 + momentum * buf) if nesterov else buf
                return (p32 - lr * g32).astype(p.dtype), buf
            return (p32 - lr * g32).astype(p.dtype)

        if momentum:
            out = _tmap(upd, grads, params, state["mom"])
            new_params, new_mom = _unzip(out, 2)
            return new_params, {"step": step, "mom": new_mom}
        return _tmap(upd, grads, params), {"step": step}

    return Optimizer("sgd", init, apply, dict(lr=lr_default, momentum=momentum))


def adagrad(lr_default: float = 1e-2, eps: float = 1e-10,
            weight_decay: float = 0.0) -> Optimizer:
    def init(params):
        return {"step": jnp.zeros((), jnp.int32),
                "sq": _zeros_like_f32(params)}

    def apply(grads, state, params, lr):
        step = state["step"] + 1

        def upd(g, sq, p):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if weight_decay:
                g32 = g32 + weight_decay * p32
            sq = sq + g32 * g32
            return (p32 - lr * g32 / (jnp.sqrt(sq) + eps)).astype(p.dtype), sq

        out = _tmap(upd, grads, state["sq"], params)
        new_params, new_sq = _unzip(out, 2)
        return new_params, {"step": step, "sq": new_sq}

    return Optimizer("adagrad", init, apply, dict(lr=lr_default, eps=eps))


# ---------------------------------------------------------------------------
# Registry — mirrors _configure_basic_optimizer name dispatch
# (reference runtime/engine.py:1307: adam, adamw, lamb, onebit_adam,
#  onebit_lamb, zero_one_adam; cpu variants collapse to the same math here —
#  host placement is an offload concern, see runtime/zero/offload).
# ---------------------------------------------------------------------------
def get_optimizer(name: str, **params) -> Optimizer:
    name_l = name.lower()
    lr = params.pop("lr", None)
    betas = params.pop("betas", (0.9, 0.999))
    if isinstance(betas, list):
        betas = tuple(betas)

    def _done(opt):
        if params:  # reject typos/unsupported keys like the reference's
            raise ValueError(   # torch optimizer ctors do
                f"Unknown parameter(s) for optimizer {name}: {sorted(params)}")
        return opt

    if name_l in ("adam", "adamw", "fusedadam", "cpuadam", "deepspeedcpuadam"):
        return _done(adam(
            lr if lr is not None else 1e-3, betas,
            params.pop("eps", 1e-8), params.pop("weight_decay", 0.0),
            adamw_mode=params.pop("adam_w_mode", name_l != "adam"),
            bias_correction=params.pop("bias_correction", True)))
    if name_l in ("lamb", "fusedlamb"):
        return _done(lamb(
            lr if lr is not None else 1e-3, betas,
            params.pop("eps", 1e-6), params.pop("weight_decay", 0.0),
            params.pop("max_coeff", 10.0), params.pop("min_coeff", 0.01)))
    if name_l == "sgd":
        return _done(sgd(
            lr if lr is not None else 1e-2, params.pop("momentum", 0.0),
            params.pop("weight_decay", 0.0), params.pop("nesterov", False)))
    if name_l in ("adagrad", "cpuadagrad"):
        return _done(adagrad(
            lr if lr is not None else 1e-2, params.pop("eps", 1e-10),
            params.pop("weight_decay", 0.0)))
    if name_l in ("onebitadam", "onebitlamb", "zerooneadam"):
        try:
            from .fp16.onebit import get_onebit_optimizer
        except ImportError as e:
            raise NotImplementedError(
                f"{name} requires the onebit module (not built yet)") from e
        return get_onebit_optimizer(name_l, lr=lr, betas=betas, **params)
    raise ValueError(f"Unknown optimizer: {name}")


def wrap_optax(tx, name: str = "optax") -> Optimizer:
    """Adapt a user-supplied optax GradientTransformation. The engine's LR
    schedule does NOT apply — schedules must live inside the optax chain
    (the engine refuses a config scheduler for wrapped optimizers)."""
    import optax

    def init(params):
        return {"step": jnp.zeros((), jnp.int32), "optax": tx.init(params)}

    def apply(grads, state, params, lr):
        del lr  # schedule lives inside the optax chain
        updates, opt_state = tx.update(grads, state["optax"], params)
        return (optax.apply_updates(params, updates),
                {"step": state["step"] + 1, "optax": opt_state})

    return Optimizer(name, init, apply, {"external_lr": True})
