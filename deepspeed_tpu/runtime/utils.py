"""Runtime utilities.

Reference: `/root/reference/deepspeed/runtime/utils.py` — the pieces that
survive the move to SPMD are the partitioning math (`partition_uniform`
:573, `partition_balanced` :639, used for pipeline stage balancing) and the
memory-report helper (`see_memory_usage` :819). Overflow checking and
MP-aware grad-norm clipping live in the engine's jitted step instead.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import numpy as np

from ..utils.logging import logger


def host_transfer(value, block: bool = False):
    """THE deliberate device→host sync point.

    Every blocking transfer on a hot path must either route through here
    or carry a ``# dstpu: ignore[SYNC00x]`` marker — ``dstpu-lint``
    (tools/lint, SYNC family) flags bare ``np.asarray``/``device_get``/
    ``block_until_ready`` reachable from jit/step paths, so accidental
    syncs can't hide among deliberate ones (docs/lint.md).

    ``block=False`` (default): materialize ``value`` on the host as a
    numpy array. ``block=True``: wait for ``value``'s async computation
    /transfer to complete and return it unchanged (the
    ``block_until_ready`` form — e.g. joining an H2D upload before
    recycling its pinned source buffer).
    """
    if block:
        return jax.block_until_ready(value)
    return np.asarray(value)


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries [p0..pN] splitting num_items as evenly as possible.
    Reference `runtime/utils.py:573`."""
    parts = [0] * (num_parts + 1)
    base, extra = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + base + (1 if p < extra else 0)
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out = list(weights)
    for i in range(1, len(out)):
        out[i] += out[i - 1]
    return out


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Boundaries minimizing the heaviest part (binary search over the
    bottleneck, same contract as reference `runtime/utils.py:639`
    ``partition_balanced``)."""
    n = len(weights)
    if num_parts >= n:
        return partition_uniform(n, num_parts)
    prefix = [0.0] + prefix_sum_inc(weights)

    def parts_needed(cap: float) -> int:
        count, start = 0, 0
        for _ in range(num_parts + 1):
            # furthest end with sum(start..end) <= cap
            end = start
            while end < n and prefix[end + 1] - prefix[start] <= cap:
                end += 1
            if end == start:  # single item exceeds cap
                return num_parts + 1
            count += 1
            start = end
            if start == n:
                return count
        return num_parts + 1

    lo = max(weights)
    hi = prefix[-1]
    for _ in range(64):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= num_parts:
            hi = mid
        else:
            lo = mid
        if hi - lo <= 1e-6 * max(1.0, hi):
            break
    cap = hi * (1 + 1e-9)
    bounds = [0]
    start = 0
    for p in range(num_parts):
        remaining_parts = num_parts - p - 1
        end = start
        while end < n and prefix[end + 1] - prefix[start] <= cap and \
                (n - end) > remaining_parts:
            end += 1
        end = max(end, start + 1)
        bounds.append(end)
        start = end
    bounds[-1] = n
    return bounds


def see_memory_usage(message: str, force: bool = False) -> None:
    """Reference `runtime/utils.py:819` — device + host memory snapshot."""
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        used = stats.get("bytes_in_use", 0) / 2**30
        peak = stats.get("peak_bytes_in_use", 0) / 2**30
    except Exception:
        used = peak = 0.0
    import resource
    host_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20
    logger.info(f"{message} | device used {used:.2f}GB peak {peak:.2f}GB | "
                f"host rss {host_gb:.2f}GB")


def tree_param_count(tree) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def call_to_str(base: str, *args, **kwargs) -> str:
    """Reference `runtime/utils.py` call_to_str (used by pipe schedule repr)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    return name + ")"
