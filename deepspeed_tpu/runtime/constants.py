"""Config keys and defaults for the master JSON config.

Mirrors the configuration surface of the reference
(`/root/reference/deepspeed/runtime/constants.py`) so a DeepSpeed user can
bring their JSON config over unchanged; values are interpreted TPU-natively.
"""

#############################################
# Batch-size triple
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
SCHEDULER = "scheduler"
MAX_GRAD_NORM = "max_grad_norm"

#############################################
# Precision
#############################################
FP16 = "fp16"
BF16 = "bf16"
AMP = "amp"

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Misc engine knobs
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
MEMORY_BREAKDOWN = "memory_breakdown"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"
SPARSE_GRADIENTS = "sparse_gradients"
COMMUNICATION_DATA_TYPE = "communication_data_type"
DISABLE_ALLGATHER = "disable_allgather"

#############################################
# Subsystem config blocks
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
AIO = "aio"
FLOPS_PROFILER = "flops_profiler"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
PIPELINE = "pipeline"
SEQUENCE_PARALLEL = "sequence_parallel"
MESH = "mesh"
CHECKPOINT = "checkpoint"
TENSOR_PARALLEL = "tensor_parallel"
RESILIENCE = "resilience"
COMMS_LOGGER = "comms_logger"
OBSERVABILITY = "observability"
TRAINING = "training"

#############################################
# Defaults
#############################################
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
STEPS_PER_PRINT_DEFAULT = 10
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
WALL_CLOCK_BREAKDOWN_DEFAULT = False
SPARSE_GRADIENTS_DEFAULT = False

# Loss-scaling defaults (fp16 block), same semantics as the reference
# DynamicLossScaler (`runtime/fp16/loss_scaler.py:77`).
FP16_LOSS_SCALE_DEFAULT = 0  # 0 => dynamic
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE_DEFAULT = 1.0

# Pipeline block defaults (runtime/pipe/engine.py, docs/training_perf.md
# "3D parallelism"): stages "auto" = the mesh pipe-axis size (an int is
# cross-checked against it at engine build); schedule "auto" = 1F1B for
# dense models, gpipe for MoE.
PIPE_STAGES_DEFAULT = "auto"
PIPE_SCHEDULE_DEFAULT = "auto"
PIPE_SCHEDULES = ("auto", "1f1b", "gpipe")

# Resilience block defaults (runtime/resilience/, docs/resilience.md).
RESILIENCE_CHECKPOINT_INTEGRITY_DEFAULT = True
RESILIENCE_VERIFY_ON_SAVE_DEFAULT = True
RESILIENCE_FALLBACK_DEFAULT = True
RESILIENCE_IO_RETRY_ATTEMPTS_DEFAULT = 3
RESILIENCE_IO_RETRY_BASE_DELAY_DEFAULT = 0.05   # seconds
RESILIENCE_IO_RETRY_MAX_DELAY_DEFAULT = 2.0     # seconds
RESILIENCE_IO_RETRY_JITTER_DEFAULT = 0.25       # fraction of each delay
RESILIENCE_SKIP_NONFINITE_DEFAULT = True
RESILIENCE_HEARTBEAT_INTERVAL_DEFAULT = 1.0     # seconds
RESILIENCE_WATCHDOG_TIMEOUT_DEFAULT = 0.0       # seconds; 0 disables

# Observability block defaults (deepspeed_tpu/observability/,
# docs/observability.md). Tracing/metrics are opt-in: the disabled path
# must stay a no-op attribute check on the step hot path.
OBSERVABILITY_TRACING_ENABLED_DEFAULT = False
OBSERVABILITY_TRACE_BUFFER_DEFAULT = 65536      # ring capacity, spans
OBSERVABILITY_TRACE_DIR_DEFAULT = "traces"
OBSERVABILITY_METRICS_ENABLED_DEFAULT = False
OBSERVABILITY_EXPORT_INTERVAL_DEFAULT = 0       # steps; 0 = flush-only
OBSERVABILITY_PROMETHEUS_DIR_DEFAULT = None     # textfile-collector dir
OBSERVABILITY_JSON_PATH_DEFAULT = None          # JSON snapshot path
# request-scoped tracing (observability/request_trace.py): per-request
# serving timelines exported as extra Perfetto tracks in the span trace
OBSERVABILITY_REQUEST_TRACE_ENABLED_DEFAULT = False
OBSERVABILITY_REQUEST_TRACE_CAPACITY_DEFAULT = 512   # retained timelines
OBSERVABILITY_REQUEST_TRACE_SEGMENTS_DEFAULT = 256   # stamps per request
# SLO burn-rate alerting (observability/slo.py): multi-window burn of
# each tenant's TTFT / inter-token error budget from TenantSpec
OBSERVABILITY_SLO_ENABLED_DEFAULT = False
OBSERVABILITY_SLO_OBJECTIVE_DEFAULT = 0.9       # met-target fraction
OBSERVABILITY_SLO_FAST_WINDOW_DEFAULT = 30.0    # seconds
OBSERVABILITY_SLO_SLOW_WINDOW_DEFAULT = 300.0   # seconds
OBSERVABILITY_SLO_BURN_THRESHOLD_DEFAULT = 2.0  # x budget, both windows
OBSERVABILITY_SLO_RESOLVE_FRACTION_DEFAULT = 0.5  # hysteresis on resolve
OBSERVABILITY_SLO_MIN_SAMPLES_DEFAULT = 5       # fast-window floor
# flight recorder (observability/flight_recorder.py): bounded ring of
# per-iteration engine snapshots + post-mortem bundles on failure
OBSERVABILITY_FLIGHT_ENABLED_DEFAULT = False
OBSERVABILITY_FLIGHT_CAPACITY_DEFAULT = 256     # snapshot ring slots
OBSERVABILITY_FLIGHT_DIR_DEFAULT = "flight_recorder"
OBSERVABILITY_FLIGHT_TERMINALS_DEFAULT = 64     # terminal-event ring
OBSERVABILITY_FLIGHT_SKIP_BURST_DEFAULT = 8     # skipped-step trigger
OBSERVABILITY_FLIGHT_MAX_BUNDLES_DEFAULT = 4    # bundles kept per rank
# host/device overlap profiler (observability/overlap.py): per-iteration
# host-plan / dispatch-enqueue / device-wait split — the acceptance
# instrument for the async multi-step scheduler (ROADMAP item 4)
OBSERVABILITY_OVERLAP_ENABLED_DEFAULT = False
OBSERVABILITY_OVERLAP_CAPACITY_DEFAULT = 2048   # iteration ring slots

# Serving (continuous batching) block defaults — the ``serving`` block
# of the INFERENCE config (inference/config.py ServingConfig,
# inference/serving/, docs/serving.md). Declared here so the whole JSON
# schema stays in one file (dstpu-lint CFG rules).
SERVING_ENABLED_DEFAULT = False         # serving engine is opt-in
SERVING_KV_BLOCK_SIZE_DEFAULT = 16      # tokens per paged KV block
SERVING_NUM_KV_BLOCKS_DEFAULT = 512     # pool blocks (block 0 reserved)
SERVING_MAX_BATCH_SLOTS_DEFAULT = 8     # compiled decode-batch width
# chunked prefill (Sarathi-Serve): prompt tokens processed per scheduler
# iteration alongside the live decode slots — also the compiled chunk
# width of the single mixed-batch program
SERVING_PREFILL_CHUNK_TOKENS_DEFAULT = 256
# content-addressed prefix caching over the paged pool (RadixAttention-
# style block reuse): hit full blocks skip prefill
SERVING_PREFIX_CACHE_DEFAULT = True
# overload control: submit() sheds (terminal SHED status, never queued)
# beyond this many waiting requests — bounded backpressure instead of an
# unbounded deque; 0 = unbounded (the pre-robustness behavior)
SERVING_MAX_QUEUE_DEPTH_DEFAULT = 1024
# preemption-thrash guard: a request preempted this many times becomes
# PINNED (never chosen as a victim again, runs to completion); when every
# running request is pinned and the pool still cannot grow, the growing
# request FAILS with a clear error instead of livelocking; 0 = no cap
SERVING_MAX_PREEMPTIONS_DEFAULT = 8
# serving watchdog: this many consecutive scheduler iterations with zero
# progress (no tokens, no prefill chunks, no admissions, no terminal
# transitions while work remains) raise a loud ServingError with full
# scheduler diagnostics; 0 disables
SERVING_NO_PROGRESS_STEPS_DEFAULT = 64
# speculative decoding draft depth: the draft model proposes this many
# tokens per speculating slot per iteration (plus one KV-only step);
# the target verifies them in ONE batched dispatch and emits
# 1..spec_k+1 tokens, token-exact vs plain decode under the same key.
# Only read when serving_engine(draft_model=...) arms a draft.
SERVING_SPEC_K_DEFAULT = 3
# default per-request TTL (submit -> terminal), swept every step() for
# WAITING and RUNNING requests; 0 = no deadline. submit(deadline_s=...)
# overrides per request.
SERVING_DEFAULT_DEADLINE_S_DEFAULT = 0.0
# quantized KV cache: store the paged pool at this many bits per value
# (0 = the engine dtype, byte-identical to the pre-quantization path;
# 8 = int8; 4 = packed int4, two values per byte) with per-row per-head
# f32 scales alongside — decode moves ~2x/~3.8x fewer HBM bytes and the
# same pool HBM budget holds that many more tokens (docs/serving.md
# "Quantized KV cache")
SERVING_KV_CACHE_BITS_DEFAULT = 0
# serving mesh (docs/serving.md "Tensor-parallel serving"): the decode /
# chunked-prefill program shards over a (data, model) submesh —
# ``model`` splits attention heads, the paged KV pool (+ scale planes)
# and the MLP column/row-wise (per-chip pool bytes / model); ``data``
# partitions the decode slots (model * data chips serve data x the
# slots).  1 x 1 keeps the single-device program byte-identical to the
# pre-TP path.
SERVING_MESH_DATA_DEFAULT = 1
SERVING_MESH_MODEL_DEFAULT = 1
# tiered host prefix cache (docs/serving.md "Tiered prefix cache"):
# refcount-0 blocks the pool LRU evicts spill (encoded at
# ``wire_bits``; a quantized pool spills its own int8/int4 bytes
# verbatim) into a host DRAM store, overflowing to an NVMe-backed store
# when budgeted, keyed by the same chained content digest as the radix
# index; a prefix hit on a spilled chain promotes blocks back during
# the admission/prefill window instead of recomputing them.
SERVING_HOST_CACHE_ENABLED_DEFAULT = False
SERVING_HOST_CACHE_DRAM_BUDGET_BYTES_DEFAULT = 0   # 0 = DRAM tier off
SERVING_HOST_CACHE_NVME_BUDGET_BYTES_DEFAULT = 0   # 0 = NVMe tier off
SERVING_HOST_CACHE_NVME_PATH_DEFAULT = None        # dir for the .swp file
# block promotions (host -> pool scatters) serviced per engine step —
# bounds the per-iteration promote stall the decode lanes ride behind
SERVING_HOST_CACHE_PROMOTE_PARALLELISM_DEFAULT = 4
# wire/at-rest bits for spilling an UNQUANTIZED pool (8 = int8 with f32
# per-row scales, 4 = packed int4, 0 = raw dtype bytes); ignored when
# serving.kv_cache_bits already quantizes the pool (spill is then the
# pool's own bytes, a lossless round-trip)
SERVING_HOST_CACHE_WIRE_BITS_DEFAULT = 8
# Resilient serving fleet (``serving.fleet`` — inference/serving/fleet/,
# docs/serving.md "Fleet serving & failover"): many ServingEngine
# replicas behind a router that places by queue depth and cached-prefix
# affinity, declares replicas dead on missed heartbeats / ServingError,
# and replays every in-flight request on a healthy replica with its
# original fold_in key — the stream is bit-identical and a high-water
# deduplicator makes delivery exactly-once.
SERVING_FLEET_ENABLED_DEFAULT = False
SERVING_FLEET_REPLICAS_DEFAULT = 2          # engines behind the router
# heartbeat stamped at every serving iteration boundary; a replica whose
# beat file goes stale past the timeout is declared DEAD (threaded
# replicas only — cooperative stepping surfaces death synchronously)
SERVING_FLEET_HEARTBEAT_INTERVAL_S_DEFAULT = 1.0
SERVING_FLEET_HEARTBEAT_TIMEOUT_S_DEFAULT = 0.0    # 0 disables staleness
# placement score = affinity_weight * covered-prefix tokens - queue cost
# per waiting request; higher weight chases warm prefixes harder at the
# price of queue imbalance
SERVING_FLEET_AFFINITY_WEIGHT_DEFAULT = 1.0
# failover attempts per request before the fleet gives up and FAILs it
# (each resubmission replays the original key — token-exact)
SERVING_FLEET_MAX_FAILOVERS_DEFAULT = 3
# jittered backoff for honoring SHED retry_after_s hints when every
# routable replica is saturated (retry_call-shaped schedule)
SERVING_FLEET_RETRY_BASE_DELAY_S_DEFAULT = 0.05
SERVING_FLEET_RETRY_MAX_DELAY_S_DEFAULT = 2.0
# disaggregated serving (docs/serving.md "Disaggregated fleet &
# autoscaling"): the first K replicas become prefill workers that
# publish finished chains into the shared host tier (the KV fabric) and
# the rest decode replicas that claim-and-promote them; 0 keeps the
# uniform fleet.  Requires serving.host_cache.enabled when > 0.
SERVING_FLEET_PREFILL_REPLICAS_DEFAULT = 0
# affinity credit for a host/fabric-resident prefix token relative to a
# device-resident one: it saves the recompute but pays claim + promote
SERVING_FLEET_PROMOTE_DISCOUNT_DEFAULT = 0.5
# autoscaler policy (fleet/autoscaler.py): burn-rate alerts + per-class
# queue depth -> join/drain, bounded by cooldowns and the chip budget
SERVING_FLEET_CHIP_BUDGET_DEFAULT = 8       # alive replicas x chips each
SERVING_FLEET_SCALE_UP_COOLDOWN_S_DEFAULT = 5.0
SERVING_FLEET_SCALE_DOWN_COOLDOWN_S_DEFAULT = 30.0
SERVING_FLEET_QUEUE_HIGH_DEFAULT = 8.0      # per-replica depth -> scale up
SERVING_FLEET_QUEUE_LOW_DEFAULT = 1.0       # below this the class is quiet
SERVING_FLEET_QUIET_S_DEFAULT = 10.0        # quiet this long -> scale down

# Training hot-path block (``training`` — runtime/config.py
# TrainingConfig, docs/training_perf.md): per-run overrides of the model
# knobs the autotuner searches, so a tuned config JSON is self-contained
# and the engine — not the caller — rebuilds the model with the winning
# remat/loss-head settings.  None = keep whatever the model config says.
TRAINING_REMAT_DEFAULT = None          # none|full|dots_saveable|...
TRAINING_FUSED_LOSS_HEAD_DEFAULT = None   # True/False; None = model's
TRAINING_LOSS_CHUNK_DEFAULT = None     # tokens per loss chunk; 0 = dense
# donate the batch buffers into the jitted train step in addition to the
# engine state. Off by default: benches and the autotuner re-feed the
# same device batch across steps, which donation would invalidate.
TRAINING_DONATE_BATCH_DEFAULT = False

# The reference's inference-route keys (ROUTE_TRAIN/EVAL/PREDICT/ENCODE)
# and a top-level MOE block key were carried here for five PRs without a
# consumer — keys nobody reads are schema lies users trip over, so they
# were DELETED (dstpu-lint CFG001) rather than grandfathered.  MoE
# configuration lives in the model config; routes are not part of this
# repo's inference API.
