"""Universal checkpoint tools.

Role-equivalent of the reference checkpoint reshape library
(`/root/reference/deepspeed/checkpoint/`: DeepSpeedCheckpoint,
`universal_checkpoint.py:108`, reshape_3d_utils) and the offline
`ds_to_universal` flow. Design note: the native checkpoint is ALREADY
topology-free (one sharded pytree, orbax reshards on read — SURVEY §5.4),
so the "universal" format here serves portability OUTSIDE the framework:
a directory of plain ``.npy`` files + a JSON manifest, importable with
nothing but numpy. The reference needs this machinery to merge per-rank
shard files; here export/import is a flatten/unflatten.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


def _flatten_paths(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_paths(v, f"{prefix}{k}/"))
        return out
    out[prefix.rstrip("/")] = tree
    return out


def export_universal(ckpt_dir: str, out_dir: str,
                     tag: Optional[str] = None) -> str:
    """deepspeed_tpu checkpoint → universal dir of npy files + manifest.
    The fp32 masters are used when the checkpoint carries offload state
    (via get_fp32_state_dict_from_zero_checkpoint)."""
    from ..runtime.checkpoint_engine.engine import (
        get_fp32_state_dict_from_zero_checkpoint)
    params = get_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)
    flat = _flatten_paths(params)
    os.makedirs(out_dir, exist_ok=True)
    manifest: Dict[str, Any] = {"format": "dstpu_universal_v1",
                                "tensors": {}}
    for name, arr in flat.items():
        arr = np.asarray(arr)
        fname = name.replace("/", ".") + ".npy"
        np.save(os.path.join(out_dir, fname), arr)
        manifest["tensors"][name] = {
            "file": fname, "shape": list(arr.shape), "dtype": str(arr.dtype)}
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return out_dir


def load_universal(universal_dir: str) -> Dict[str, np.ndarray]:
    """universal dir → flat {path: array} dict."""
    with open(os.path.join(universal_dir, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest.get("format") != "dstpu_universal_v1":
        raise ValueError(f"not a universal checkpoint: {universal_dir}")
    return {name: np.load(os.path.join(universal_dir, meta["file"]))
            for name, meta in manifest["tensors"].items()}


def unflatten(flat: Dict[str, np.ndarray]) -> Dict:
    """Flat path dict → nested params pytree."""
    tree: Dict = {}
    for path, arr in flat.items():
        node = tree
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return tree


def import_universal(universal_dir: str, engine) -> None:
    """Load universal params into a live engine (any topology — the
    device_put reshards; the reference needs reshape_meg_2d for this)."""
    import jax
    params = unflatten(load_universal(universal_dir))

    def put(arr, cur):
        arr = np.asarray(arr)
        if arr.shape != cur.shape:
            raise ValueError(f"shape mismatch {arr.shape} vs {cur.shape}")
        return jax.device_put(arr.astype(cur.dtype), cur.sharding)

    engine.state["params"] = jax.tree_util.tree_map(
        put, params, engine.state["params"])
    if getattr(engine, "_host_opt", None) is not None:
        # offload: fp32 masters re-derived from the imported params
        engine._host_opt.reset_from_params(engine.state["params"])
