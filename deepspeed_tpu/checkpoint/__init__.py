"""Checkpoint tools — counterpart of `/root/reference/deepspeed/checkpoint/`."""
from .universal import (export_universal, import_universal, load_universal,
                        unflatten)

__all__ = ["export_universal", "import_universal", "load_universal",
           "unflatten"]
