"""Checkpoint tools — counterpart of `/root/reference/deepspeed/checkpoint/`."""
from .megatron import (load_megatron_checkpoint, megatron_gpt_config,
                       megatron_to_params, merge_megatron_state_dicts,
                       split_megatron_state_dict)
from .universal import (export_universal, import_universal, load_universal,
                        unflatten)

__all__ = ["export_universal", "import_universal", "load_universal",
           "unflatten", "load_megatron_checkpoint", "megatron_gpt_config",
           "megatron_to_params", "merge_megatron_state_dicts",
           "split_megatron_state_dict"]
