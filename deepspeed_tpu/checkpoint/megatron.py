"""Megatron-LM checkpoint ingestion: mp-sharded state dicts → params pytree.

Role-equivalent of the reference's ``MegatronSDLoader``
(`/root/reference/deepspeed/runtime/state_dict_factory.py:215`) and the
Megatron inference policy (`module_inject/containers/megatron_gpt.py:29`).
The reference merges/splits torch shard FILES to the serving mp degree,
because each GPU must load exactly its slice. The TPU-native design needs
none of that file surgery: shards are merged once into the canonical
(tp=1) params pytree, and serving at ANY target TP degree is what
`device_put` into the mesh's NamedShardings already does — GSPMD is the
reshard. A format-level splitter (`split_megatron_state_dict`) is still
provided for re-export to Megatron tooling, with the same index math the
reference's split path uses.

Format facts (reference `state_dict_factory.py:224-247` + sanity_check):
- one state dict per mp rank, module under ``model``/``module``, with
  ``checkpoint_version`` ∈ {0, 1.0, 2.0} and optionally ``mp_world_size``;
- column-parallel tensors (merge on torch OUT axis 0):
  ``attention.query_key_value``, ``mlp.dense_h_to_4h`` (weight AND bias),
  ``word_embeddings.weight``;
- row-parallel tensors (merge on torch IN axis 1):
  ``attention.dense.weight``, ``mlp.dense_4h_to_h.weight``;
- everything else is replicated — shard 0 wins;
- per-shard qkv row layout by version (np = heads per shard, hn = head
  dim; reference `merge_query_key_value`, `state_dict_factory.py:247`):
    v0:   [3, np, hn]   v1.0: [np, hn, 3]   v2.0: [np, 3, hn]
  The canonical target is [3, nh, hn] (q all heads | k | v) — exactly the
  fused-qkv order ``TransformerLM`` reshapes (models/transformer.py).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.transformer import TransformerConfig
from ..utils.interop import to_numpy as _np
from ..utils.logging import logger

_COL_PARALLEL = ("attention.query_key_value", "mlp.dense_h_to_4h",
                 "word_embeddings.weight")
_ROW_PARALLEL = ("attention.dense.weight", "mlp.dense_4h_to_h.weight")
_VERSIONS = (0, 1.0, 2.0)


def _get_module(sd: Dict[str, Any]) -> Dict[str, Any]:
    """The client weights live under 'model' or 'module' (reference
    `_choose_module_key`); bare dicts of weights pass through."""
    has_model, has_module = "model" in sd, "module" in sd
    if has_model and has_module:
        raise ValueError("checkpoint has both 'model' and 'module' keys")
    if has_model or has_module:
        inner = sd["model" if has_model else "module"]
        # Megatron-LM nests once more: model.language_model.{embedding,
        # transformer}; flatten to the transformer/embedding namespace
        if "language_model" in inner:
            inner = _flatten_language_model(inner["language_model"])
        return inner
    return sd


def _flatten_language_model(lm: Dict[str, Any]) -> Dict[str, Any]:
    flat: Dict[str, Any] = {}
    emb = lm.get("embedding", {})
    for name, sub in (("word_embeddings", emb.get("word_embeddings", {})),
                      ("position_embeddings",
                       emb.get("position_embeddings", {}))):
        for k, v in sub.items():
            flat[f"{name}.{k}"] = v
    for k, v in lm.get("transformer", {}).items():
        flat[f"transformer.{k}"] = v
    return flat


def _qkv_to_canonical(w: np.ndarray, version, np_heads: int) -> np.ndarray:
    """One shard's qkv rows → [3, np, hn]-major rows (leading axis only;
    works for [rows, h] weights and [rows] biases)."""
    rows = w.shape[0]
    if rows % (3 * np_heads):
        raise ValueError(f"qkv rows {rows} not divisible by 3*heads "
                         f"{3 * np_heads}")
    hn = rows // (3 * np_heads)
    rest = w.shape[1:]
    if version == 0:
        return w                                        # already [3,np,hn]
    if version == 1.0:
        v = w.reshape((np_heads, hn, 3) + rest)
        return np.moveaxis(v, 2, 0).reshape((rows,) + rest)
    if version == 2.0:
        v = w.reshape((np_heads, 3, hn) + rest)
        return np.swapaxes(v, 0, 1).reshape((rows,) + rest)
    raise ValueError(f"checkpoint version {version!r} not in {_VERSIONS}")


def _qkv_from_canonical(w: np.ndarray, version, np_heads: int) -> np.ndarray:
    """Inverse of `_qkv_to_canonical` (used by the re-export splitter)."""
    rows = w.shape[0]
    hn = rows // (3 * np_heads)
    rest = w.shape[1:]
    if version == 0:
        return w
    if version == 1.0:
        v = w.reshape((3, np_heads, hn) + rest)
        return np.moveaxis(v, 0, 2).reshape((rows,) + rest)
    if version == 2.0:
        v = w.reshape((3, np_heads, hn) + rest)
        return np.swapaxes(v, 0, 1).reshape((rows,) + rest)
    raise ValueError(f"checkpoint version {version!r} not in {_VERSIONS}")


def _load_file(path_or_sd):
    if isinstance(path_or_sd, dict):
        return path_or_sd
    import torch                      # Megatron checkpoints are torch pickles
    return torch.load(path_or_sd, map_location="cpu", weights_only=False)


def merge_megatron_state_dicts(shards: Sequence[Any], num_heads: int,
                               version: Optional[float] = None
                               ) -> Tuple[Dict[str, np.ndarray], float]:
    """mp-rank shard list (paths or loaded dicts, rank order) → one merged
    client state dict with qkv rows in canonical [q|k|v] order.

    Returns (merged, version). Mirrors the reference `merge_state_dict`
    (`state_dict_factory.py:324`) including the per-version qkv handling —
    but always merges to tp=1; the mesh reshards from there."""
    raw = [_load_file(s) for s in shards]
    if version is None:
        version = raw[0].get("checkpoint_version", 0)
    if version not in _VERSIONS:
        raise ValueError(f"checkpoint version {version!r} not in {_VERSIONS}")
    declared = raw[0].get("mp_world_size")
    if declared is not None and int(declared) != len(raw):
        raise ValueError(f"checkpoint declares mp_world_size={declared} but "
                         f"{len(raw)} shards were given")
    mods = [_get_module(sd) for sd in raw]
    keys = list(mods[0].keys())
    for i, m in enumerate(mods[1:], 1):
        if set(m.keys()) != set(keys):
            raise ValueError(f"shard {i} key set differs from shard 0")
    if num_heads % len(mods):
        raise ValueError(f"num_heads {num_heads} not divisible by "
                         f"{len(mods)} shards")
    np_heads = num_heads // len(mods)

    merged: Dict[str, np.ndarray] = {}
    for key in keys:
        vals = [_np(m[key]) for m in mods]
        if "attention.query_key_value" in key:
            canon = [_qkv_to_canonical(v, version, np_heads) for v in vals]
            # [3, np, hn] per shard → concat shards inside each of q/k/v
            parts = []
            for i in range(3):
                size = canon[0].shape[0] // 3
                parts.append(np.concatenate(
                    [c[i * size:(i + 1) * size] for c in canon], axis=0))
            merged[key] = np.concatenate(parts, axis=0)
        elif any(t in key for t in _COL_PARALLEL):
            merged[key] = np.concatenate(vals, axis=0)
        elif any(t in key for t in _ROW_PARALLEL):
            merged[key] = np.concatenate(vals, axis=1)
        else:
            merged[key] = vals[0]
    return merged, version


def split_megatron_state_dict(client_sd: Dict[str, np.ndarray],
                              mp_world_size: int, num_heads: int,
                              version: float = 2.0
                              ) -> List[Dict[str, np.ndarray]]:
    """Canonical merged client sd → ``mp_world_size`` Megatron-format
    shards (reference `split_state_dict`, `state_dict_factory.py:387`).
    Provided for re-export to Megatron tooling — serving at a target TP
    degree does NOT go through here (GSPMD reshards the pytree)."""
    if num_heads % mp_world_size:
        raise ValueError(f"num_heads {num_heads} not divisible by mp "
                         f"{mp_world_size}")
    np_heads = num_heads // mp_world_size
    out: List[Dict[str, np.ndarray]] = []
    for r in range(mp_world_size):
        shard: Dict[str, np.ndarray] = {}
        for key, val in client_sd.items():
            val = np.asarray(val)
            if "attention.query_key_value" in key:
                size = val.shape[0] // 3
                if size % mp_world_size:
                    raise ValueError(f"{key}: {size} rows per q/k/v not "
                                     f"divisible by mp {mp_world_size}")
                per = size // mp_world_size
                mine = np.concatenate(
                    [val[i * size + r * per: i * size + (r + 1) * per]
                     for i in range(3)], axis=0)
                shard[key] = _qkv_from_canonical(mine, version, np_heads)
            elif any(t in key for t in _COL_PARALLEL):
                if val.shape[0] % mp_world_size:
                    raise ValueError(f"{key}: dim0 {val.shape[0]} not "
                                     f"divisible by mp {mp_world_size}")
                shard[key] = np.split(val, mp_world_size, axis=0)[r]
            elif any(t in key for t in _ROW_PARALLEL):
                if val.shape[1] % mp_world_size:
                    raise ValueError(f"{key}: dim1 {val.shape[1]} not "
                                     f"divisible by mp {mp_world_size}")
                shard[key] = np.split(val, mp_world_size, axis=1)[r]
            else:
                shard[key] = val
        out.append({"model": shard, "checkpoint_version": version,
                    "mp_world_size": mp_world_size})
    return out


_LAYER_RE = re.compile(r"transformer\.layers\.(\d+)\.")


def megatron_gpt_config(client_sd: Dict[str, np.ndarray], num_heads: int,
                        **overrides) -> TransformerConfig:
    """Infer a TransformerConfig from a merged Megatron GPT state dict.
    Head count is not recorded in the format — the caller supplies it
    (the reference reads it off the live module instead,
    `containers/megatron_gpt.py:54`)."""
    n_layers = 1 + max(int(m.group(1)) for k in client_sd
                       if (m := _LAYER_RE.match(k)))
    vocab, d_model = client_sd["word_embeddings.weight"].shape
    max_seq = client_sd["position_embeddings.weight"].shape[0]
    d_ff = client_sd["transformer.layers.0.mlp.dense_h_to_4h.weight"].shape[0]
    kw = dict(
        vocab_size=vocab, max_seq_len=max_seq, num_layers=n_layers,
        num_heads=num_heads, d_model=d_model, d_ff=d_ff,
        pos_embedding="learned", parallel_residual=False,
        norm_type="layernorm",
        # Megatron-LM defaults to the erf GeLU (torch F.gelu)
        activation="gelu_exact",
        use_bias=True, tie_embeddings=True)
    kw.update(overrides)
    return TransformerConfig(**kw)


def megatron_to_params(client_sd: Dict[str, np.ndarray],
                       config: TransformerConfig) -> Dict:
    """Merged Megatron GPT client sd → params pytree. Torch [out, in]
    linear weights transpose to this framework's [in, out] kernels; the
    qkv rows are already canonical [q|k|v] from the merge."""
    n = config.num_layers
    sd = client_sd

    def blk_t(name):
        return np.stack([_np(sd[f"transformer.layers.{i}.{name}"]).T
                         for i in range(n)])

    def blk(name):
        return np.stack([_np(sd[f"transformer.layers.{i}.{name}"])
                         for i in range(n)])

    consumed = set()
    for i in range(n):
        for nm in ("input_layernorm.weight", "input_layernorm.bias",
                   "attention.query_key_value.weight",
                   "attention.query_key_value.bias",
                   "attention.dense.weight", "attention.dense.bias",
                   "post_attention_layernorm.weight",
                   "post_attention_layernorm.bias",
                   "mlp.dense_h_to_4h.weight", "mlp.dense_h_to_4h.bias",
                   "mlp.dense_4h_to_h.weight", "mlp.dense_4h_to_h.bias"):
            consumed.add(f"transformer.layers.{i}.{nm}")
    consumed |= {"word_embeddings.weight", "position_embeddings.weight",
                 "transformer.final_layernorm.weight",
                 "transformer.final_layernorm.bias"}
    extra = set(sd) - consumed
    if extra:
        # loud, like the diffusion loaders: a silently-dropped tensor is a
        # wrong model
        raise ValueError(f"unconsumed Megatron keys: {sorted(extra)[:8]}"
                         f"{'...' if len(extra) > 8 else ''}")
    missing = consumed - set(sd)
    if missing:
        raise ValueError(f"missing Megatron keys: {sorted(missing)[:8]}"
                         f"{'...' if len(missing) > 8 else ''}")

    params = {
        "embed": {"embedding": _np(sd["word_embeddings.weight"])},
        "pos_embed": {"embedding": _np(sd["position_embeddings.weight"])},
        "blocks": {
            "ln1": {"scale": blk("input_layernorm.weight"),
                    "bias": blk("input_layernorm.bias")},
            "attn": {
                "qkv": {"kernel": blk_t("attention.query_key_value.weight"),
                        "bias": blk("attention.query_key_value.bias")},
                "out": {"kernel": blk_t("attention.dense.weight"),
                        "bias": blk("attention.dense.bias")},
            },
            "ln2": {"scale": blk("post_attention_layernorm.weight"),
                    "bias": blk("post_attention_layernorm.bias")},
            "mlp": {
                "fc_in": {"kernel": blk_t("mlp.dense_h_to_4h.weight"),
                          "bias": blk("mlp.dense_h_to_4h.bias")},
                "fc_out": {"kernel": blk_t("mlp.dense_4h_to_h.weight"),
                           "bias": blk("mlp.dense_4h_to_h.bias")},
            },
        },
        "ln_f": {"scale": _np(sd["transformer.final_layernorm.weight"]),
                 "bias": _np(sd["transformer.final_layernorm.bias"])},
    }
    return params


def load_megatron_checkpoint(shards: Sequence[Any], num_heads: int,
                             version: Optional[float] = None,
                             **config_overrides
                             ) -> Tuple[TransformerConfig, Dict]:
    """The one-call surface: mp shard list → (config, params), ready for
    ``TransformerLM``/``init_inference`` at ANY target TP degree (the
    engine's shardings do the resharding the reference does with file
    merge/split)."""
    merged, ver = merge_megatron_state_dicts(shards, num_heads, version)
    cfg = megatron_gpt_config(merged, num_heads, **config_overrides)
    logger.info(f"megatron checkpoint: {len(list(shards))} shard(s), "
                f"version {ver}, {cfg.num_layers}L d{cfg.d_model} "
                f"h{cfg.num_heads} vocab {cfg.vocab_size}")
    return cfg, megatron_to_params(merged, cfg)
