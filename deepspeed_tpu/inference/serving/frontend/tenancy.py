"""Tenant registry: who shares the serving engine, and on what terms.

Each tenant carries a fairness ``weight`` (its share of served tokens
under contention), a strict ``priority`` tier (higher admits first
regardless of counters — the latency tier above the fair pool), TTFT /
inter-token SLO targets (the frontend boosts a tenant whose oldest
waiting request is about to blow its TTFT target, and the per-tenant
histograms make attainment measurable), and a ``max_queue_share`` that
bounds how much of the bounded waiting queue one tenant may hog before
the shed policy picks ITS requests as overload victims.

Fairness is the virtual-token-counter scheme of "Fairness in Serving
Large Language Models" (Sheng et al., OSDI '24): every served token
charges its tenant ``1 / weight`` virtual tokens; admission prefers the
smallest counter; a tenant going idle->active lifts its counter to the
minimum of the active tenants, so idle time banks NO credit and a
returning tenant cannot starve the ones that kept the engine busy.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, Optional


@dataclass(frozen=True)
class TenantSpec:
    """Per-tenant serving terms (immutable — re-register to change)."""
    name: str
    #: weighted-fair share: under contention tenant i receives
    #: weight_i / sum(weights) of the served tokens (the VTC bound)
    weight: float = 1.0
    #: strict tier: higher-priority tenants admit before lower,
    #: regardless of virtual counters (use sparingly — priority
    #: bypasses fairness by design)
    priority: int = 0
    #: TTFT SLO target in seconds (0 = none): a tenant whose oldest
    #: waiting request has burned >70% of this budget is boosted to the
    #: front of its priority tier
    ttft_slo_s: float = 0.0
    #: inter-token SLO target in seconds (0 = none) — recorded next to
    #: the per-tenant histogram; advisory (decode pace is batch-wide)
    itl_slo_s: float = 0.0
    #: max fraction of the bounded waiting queue this tenant may hold
    #: before the shed policy victimizes it (0 = its fair weight share)
    max_queue_share: float = 0.0

    def __post_init__(self) -> None:
        if not isinstance(self.name, str) or not self.name:
            raise ValueError(
                f"tenant name must be a non-empty string, got "
                f"{self.name!r}")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}")
        if not 0 <= self.max_queue_share <= 1:
            raise ValueError(
                f"tenant {self.name!r}: max_queue_share must be in "
                f"[0, 1], got {self.max_queue_share}")
        if self.ttft_slo_s < 0 or self.itl_slo_s < 0:
            raise ValueError(
                f"tenant {self.name!r}: SLO targets must be >= 0")


class TenantRegistry:
    """Tenant specs + their live virtual-token counters.

    Unknown tenants resolve to the ``default`` spec (weight 1, no
    priority, no SLOs) so the frontend never rejects traffic for
    lacking a registration — fairness just treats it as one more
    unit-weight tenant.
    """

    def __init__(self, tenants: Iterable[TenantSpec] = ()) -> None:
        self._specs: Dict[str, TenantSpec] = {}
        #: virtual token counters (Sheng et al.): tokens / weight
        self.vtc: Dict[str, float] = {}
        self.register(TenantSpec("default"))
        for spec in tenants:
            self.register(spec)

    def register(self, spec: TenantSpec) -> TenantSpec:
        self._specs[spec.name] = spec
        self.vtc.setdefault(spec.name, 0.0)
        return spec

    def get(self, name: str) -> TenantSpec:
        spec = self._specs.get(name)
        if spec is None:
            spec = TenantSpec(name)
            self.register(spec)
        return spec

    def names(self):
        return list(self._specs)

    # -- virtual token counters ------------------------------------------
    def charge(self, name: str, tokens: float) -> None:
        """Serve-time charge: ``tokens / weight`` virtual tokens."""
        self.vtc[name] = self.vtc.get(name, 0.0) \
            + tokens / self.get(name).weight

    def lift(self, name: str, active: Iterable[str]) -> None:
        """Idle->active counter lift: entering tenant starts at the
        minimum counter of the currently active tenants (no banked
        credit from idle time)."""
        floor = min((self.vtc.get(t, 0.0) for t in active if t != name),
                    default=None)
        if floor is not None:
            self.vtc[name] = max(self.vtc.get(name, 0.0), floor)

    def fair_share(self, name: str, among: Optional[Iterable[str]] = None
                   ) -> float:
        """This tenant's weight fraction among ``among`` (default: all
        registered tenants)."""
        names = list(among) if among is not None else self.names()
        total = sum(self.get(t).weight for t in names) or 1.0
        return self.get(name).weight / total
