"""SLO-grade multi-tenant serving front-end (docs/serving.md
"Sampling, streaming & multi-tenant SLOs").

Three composable pieces over the serving engine:

  * :mod:`streaming` — per-token :class:`TokenEvent` delivery at
    iteration boundaries (in-program sampling means the token IS the
    dispatch output; no host-side sampling pass);
  * :mod:`tenancy` — tenant specs (weight / priority / SLO targets)
    and their live virtual-token counters (Sheng et al., OSDI '24);
  * :mod:`frontend` — :class:`ServingFrontend`, wiring the registry
    into the scheduler's admission / prefill / shed policy hooks and
    the per-tenant ``dstpu_serving_tenant_*`` metrics.
"""
from .frontend import ServingFrontend  # noqa: F401
from .streaming import (StreamCollector, StreamDeduper,  # noqa: F401
                        StreamReplayError, TokenEvent)
from .tenancy import TenantRegistry, TenantSpec  # noqa: F401

__all__ = ["ServingFrontend", "StreamCollector", "StreamDeduper",
           "StreamReplayError", "TokenEvent",
           "TenantRegistry", "TenantSpec"]
