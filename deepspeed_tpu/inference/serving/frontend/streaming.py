"""Token streaming: the event type and a small collection helper.

The serving engine delivers tokens to callers AT ITERATION BOUNDARIES
(the continuous-batching loop is single-threaded; callbacks run on the
serving thread between dispatches, never concurrently with one).  Each
emitted token — and each non-OK terminal transition — becomes one
:class:`TokenEvent`; a request's stream therefore always ends with an
event whose ``final`` is True, carrying the terminal
:class:`~..scheduler.RequestStatus`.

Exceptions raised by a callback disable THAT stream (logged once); the
request keeps generating and every other stream is untouched — a slow
or broken consumer must never stall the batch.
"""
from __future__ import annotations

from typing import Any, List, NamedTuple, Optional


class TokenEvent(NamedTuple):
    """One streamed token (or terminal marker) of one request.

    ``token`` is None for a tokenless terminal event (shed / cancelled
    / timed-out / failed before any token).  ``index`` is the token's
    OUTPUT index (0 = first generated token).  ``status`` is the
    request's lifecycle status AT FLUSH TIME — None while in flight,
    the terminal :class:`RequestStatus` on the stream's last event
    (``final`` True).  ``time_s``/``prev_time_s`` are perf-counter
    stamps of this and the previous token (inter-token latency =
    ``time_s - prev_time_s``)."""
    request: Any
    token: Optional[int]
    index: int
    status: Any
    final: bool
    tenant: str
    time_s: float
    prev_time_s: Optional[float]


class StreamReplayError(RuntimeError):
    """A replayed stream diverged from what was already delivered —
    the fold-in key schedule's bit-identical replay contract was
    violated (wrong key on resubmit, or a non-deterministic sampler)."""


class StreamDeduper:
    """Fleet-level exactly-once filter over a (possibly replayed)
    token stream (docs/serving.md "Fleet serving & failover").

    Token-exact failover resubmits a dead replica's request from token
    0 — the fold-in key schedule makes the replayed stream bit-identical
    — so the client-facing stream must forward only tokens past the
    high-water mark already delivered.  ``admit`` returns the event to
    forward, or None for a replayed duplicate (counted in
    ``duplicates``); a duplicate whose token differs from what was
    delivered at that index raises :class:`StreamReplayError` — better
    a loud failover bug than a silently forked stream.  Tokenless
    terminal events pass through untouched (they carry no index to
    deduplicate)."""

    def __init__(self) -> None:
        self.delivered: List[int] = []
        self.duplicates = 0

    @property
    def high_water(self) -> int:
        """Number of tokens already forwarded to the client."""
        return len(self.delivered)

    def admit(self, ev: TokenEvent) -> Optional[TokenEvent]:
        if ev.token is None:
            return ev
        if ev.index < len(self.delivered):
            self.duplicates += 1
            if self.delivered[ev.index] != ev.token:
                raise StreamReplayError(
                    f"replayed stream diverged at index {ev.index}: "
                    f"delivered {self.delivered[ev.index]}, replay "
                    f"emitted {ev.token}")
            return None
        if ev.index > len(self.delivered):
            raise StreamReplayError(
                f"stream gap: expected index {len(self.delivered)}, "
                f"got {ev.index}")
        self.delivered.append(ev.token)
        return ev


class StreamCollector:
    """Minimal ``on_token`` sink: records tokens and events in arrival
    order (tests and the replay bench read ``tokens`` / ``events``
    after the drain)."""

    def __init__(self) -> None:
        self.tokens: List[int] = []
        self.events: List[TokenEvent] = []

    def __call__(self, ev: TokenEvent) -> None:
        self.events.append(ev)
        if ev.token is not None:
            self.tokens.append(ev.token)

    @property
    def finished(self) -> bool:
        return bool(self.events) and self.events[-1].final
